"""Sort-free MXU histogram + routing kernels.

Profiling on TPU v5e via the axon tunnel showed per-row memory ops (gather,
scatter, sort) running at ~10M rows/s — the argsort+regroup prologue of the
grouped Pallas histogram (histogram_pallas.py) and the per-row table gathers
of the routing step dominated tree time (~250 ms + ~130 ms per growth pass
at 1M rows), while dense matmuls run at full MXU rate. These kernels remove
every per-row memory op from the growth pass:

- `build_histograms_mxu`: hist[s, f, b, c] = slotOH^T @ (binOH * data_c) —
  both one-hot matrices are built in VMEM per row-block (never hitting HBM)
  and contracted on the MXU with bf16 inputs / f32 accumulation. Gradients
  and hessians are split hi/lo into two bf16 matmuls (double-bf16), giving
  ~2e-6 relative error vs exact f32 scatter — well inside the reference's
  own f32-histogram option (hist_t, USE_SINGLE_PRECISION).
  This is the TPU answer to the CUDA shared-memory scatter kernels
  (cuda_histogram_constructor.cu:18-307): on a systolic-array machine the
  histogram is reformulated as matrix multiplication instead of scatter.

- `route_rows_mxu`: one pass over the binned matrix that advances every
  row through the splits applied this pass (cuda_data_partition.cu:288's
  GenDataToLeftBitVector equivalent). All per-node lookups (split feature,
  threshold bin, children, categorical bitsets, next-pass slot) go through
  ONE [rows, nodes] one-hot f32 matmul against a packed node table —
  no gathers. Categorical bitset words are carried as two 16-bit halves so
  every table value stays exactly representable in f32.

HBM traffic per pass: one read of the binned matrix + small blocks;
flops: nchan * S * N * F * B MACs (bf16; nchan = 5 with double-precision
sums, 4 with single-bf16 hessians) for the histogram, negligible for
routing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.log import Log

__all__ = ["build_histograms_mxu", "build_histograms_mxu_v2",
           "build_histograms_mxu_auto", "route_rows_mxu",
           "pack_route_tables", "node_values_mxu", "node_sums_mxu",
           "quantize_gradients", "pack_bins_4bit", "unpack_bins_4bit"]

# v5e has 128 MB VMEM; the default 16 MB scoped limit starves the
# accumulate-in-VMEM histogram output on small row counts.
# jax < 0.5 names the params class TPUCompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
_COMPILER_PARAMS = _CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)

# features per accumulating dot in the v2/fused kernels: batching widens
# the MXU output tile (a [nb, C*S] x [nb, G*B] dot instead of G narrow
# ones), measured ~15% faster at small S on v5e
_FGROUP = 4


def _round_up(x: int, k: int) -> int:
    return ((x + k - 1) // k) * k


# ---------------------------------------------------------------------------
# 4-bit packed bin storage (reference 4-bit DenseBin, src/io/dense_bin.hpp:42)
# ---------------------------------------------------------------------------

def pack_bins_4bit(bins):
    """Pack a [N, F] bin matrix whose values all fit 4 bits (max_bin <= 15
    incl. the NaN bin) into [N, ceil(F/2)] uint8: feature j < Fh rides
    column j's LOW nibble, feature Fh+j its HIGH nibble. The split layout
    (features [0..Fh) low, [Fh..F) high — NOT interleaved nibbles) keeps
    per-feature extraction a static column pick + shift/mask inside the
    kernels, with no lane interleave. Accepts numpy or jax input; exact:
    training on packed storage grows bit-identical trees.

    Any bin id above 15 (a caller configuring more bins than a nibble
    holds — the NaN bin counts) makes packing lossy, so it is refused:
    returns None with a logged warning and the caller keeps the uint8
    storage path instead of training on silently truncated bins."""
    xp = jnp if isinstance(bins, jax.Array) else _np
    vmax = int(bins.max()) if bins.size else 0
    if vmax > 15:
        Log.warning(
            "pack_bins_4bit: bin id %d exceeds the 4-bit limit of 15 "
            "(max_bin incl. the NaN bin must be <= 15); keeping uint8 "
            "bin storage", vmax)
        return None
    n, f = bins.shape
    fh = (f + 1) // 2
    lo = bins[:, :fh].astype(xp.uint8)
    hi = xp.zeros((n, fh), xp.uint8)
    if f > fh:
        if xp is jnp:
            hi = hi.at[:, :f - fh].set(bins[:, fh:].astype(xp.uint8))
        else:
            hi[:, :f - fh] = bins[:, fh:].astype(xp.uint8)
    return lo | (hi << 4)


def unpack_bins_4bit(packed, num_features: int):
    """Inverse of pack_bins_4bit -> [N, num_features] uint8."""
    xp = jnp if isinstance(packed, jax.Array) else _np
    fh = packed.shape[1]
    lo = packed & xp.uint8(15)
    hi = packed >> 4
    return xp.concatenate([lo, hi], axis=1)[:, :num_features]


def _packed_cols(bins_i, js, fh: int):
    """Per-feature [nb, 1] i32 bin values from a packed i32 block for the
    static feature ids `js` (kernel-side unpack: column pick + nibble)."""
    out = []
    for j in js:
        if j < fh:
            out.append(jnp.bitwise_and(bins_i[:, j:j + 1], 15))
        else:
            c = j - fh
            out.append(jnp.right_shift(bins_i[:, c:c + 1], 4) & 15)
    return out


# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------

def _hist_kernel(nb: int, fc: int, b: int, s: int, flane: int,
                 mm_dtype=jnp.bfloat16, nchan: int = 5):
    fcb = fc * b

    def kernel(block_any_ref, slot_ref, bins_ref, data_ref, out_ref):
        ci = pl.program_id(0)
        ri = pl.program_id(1)

        @pl.when(ri == 0)
        def _():
            out_ref[0] = jnp.zeros_like(out_ref[0])

        # late growth passes have most rows parked in finished leaves
        # (slot -1); blocks with no active row skip all compute
        @pl.when(block_any_ref[ri] != 0)
        def _():
            slot = slot_ref[:, 0]                            # [nb] i32
            iota_s = jax.lax.broadcasted_iota(jnp.int32, (nb, s), 1)
            slot_oh = (slot[:, None] == iota_s)              # [nb, S] bool

            # chunk-extract without lane slicing: a [flane, fc*B] 0/1
            # selector copies feature ci*fc+j//B into one-hot column space
            # via the MXU (bin values <= 255 are exact in bf16)
            bins_f = bins_ref[:].astype(jnp.int32) \
                .astype(jnp.bfloat16)                        # [nb, flane]
            frow = jax.lax.broadcasted_iota(jnp.int32, (flane, fcb), 0)
            jcol = jax.lax.broadcasted_iota(jnp.int32, (flane, fcb), 1)
            sel = (frow == ci * fc + jcol // b).astype(jnp.bfloat16)
            ext = jax.lax.dot_general(
                bins_f, sel, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # [nb, fc*B]
            binidx = jax.lax.broadcasted_iota(jnp.int32, (nb, fcb), 1) % b
            bin_oh = (ext == binidx.astype(jnp.float32)) \
                .astype(mm_dtype)                            # [nb, fc*B]

            data = data_ref[:]                               # [nb, 8] f32
            for c in range(nchan):  # hi/lo pairs + cnt, or g/h/cnt
                lhs = jnp.where(slot_oh, data[:, c:c + 1],
                                jnp.float32(0.0)).astype(mm_dtype)
                part = jax.lax.dot_general(
                    lhs, bin_oh,
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)      # [S, fc*B]
                out_ref[0, c * s:(c + 1) * s, :] += part

    return kernel


def _hist_channels(grad, hess, cnt, double_prec: bool,
                   quantized: bool = False, const_hess: float = 0.0):
    """Channel matrix [N, 8] for the histogram kernels (hi/lo bf16 pairs
    + count, or grad-hi/lo + single-bf16 hessian + count).

    quantized=True: the caller passes stochastically-rounded INTEGER
    gradients/hessians in [-127, 127] (quantize_gradients) — bf16-exact,
    so each rides a single channel with no hi/lo split: 3 channels
    instead of 5, the flop lever of quantized GBDT training adapted to
    the MXU formulation. f32 accumulation is integer-exact to 2^24 and
    ~1e-7-relative beyond, far inside the stochastic-rounding noise.

    const_hess != 0 drops the hessian channel entirely (the reference's
    IsConstantHessian fast path, objective_function.h:42): per-row
    hessians are const_hess x the count weight, so the hessian histogram
    is reconstructed as const_hess * count in _combine_hist — EXACT (no
    quantization noise on hessians) and one fewer MXU channel
    (quantized 3 -> 2, exact 5 -> 3)."""
    g = grad.astype(jnp.float32)
    h = hess.astype(jnp.float32)
    if const_hess:
        if quantized:
            chans = [g, cnt.astype(jnp.float32)]
        else:
            g_hi = jax.lax.reduce_precision(g, exponent_bits=8,
                                            mantissa_bits=7)
            chans = [g_hi, g - g_hi, cnt.astype(jnp.float32)]
        nchan = len(chans)
        data = jnp.stack(chans + [jnp.zeros_like(g)] * (8 - nchan),
                         axis=1)
        return data, nchan
    if quantized:
        chans = [g, h, cnt.astype(jnp.float32)]
        data = jnp.stack(chans + [jnp.zeros_like(g)] * 5, axis=1)
        return data, 3
    # reduce_precision (not a bf16 round-trip, which XLA elides under
    # --xla_allow_excess_precision) keeps the hi/lo split honest
    g_hi = jax.lax.reduce_precision(g, exponent_bits=8, mantissa_bits=7)
    if double_prec:
        h_hi = jax.lax.reduce_precision(h, exponent_bits=8, mantissa_bits=7)
        chans = [g_hi, g - g_hi, h_hi, h - h_hi, cnt.astype(jnp.float32)]
    else:
        # mixed precision: gradient sums (the squared gain numerator) stay
        # hi/lo-exact, hessian sums ride single bf16 — the denominator is
        # smoothed by lambda_l2/min_hessian and tolerates ~2^-9 error
        chans = [g_hi, g - g_hi, h, cnt.astype(jnp.float32)]
    nchan = len(chans)
    data = jnp.stack(chans + [jnp.zeros_like(g)] * (8 - nchan),
                     axis=1)                                 # [N, 8]
    return data, nchan


def quantize_gradients(grad, hess, key, *, pmax_axis=None):
    """Stochastically-rounded integer gradients for the 3-channel
    histogram mode: g_q = floor(g/gs + u), gs = max|g|/127 (and likewise
    hessians). Unbiased (E[g_q]*gs = g); per-tree scales. Returns
    (g_q, h_q, gscale, hscale) with g_q/h_q integer-valued f32.

    hess=None (the constant-hessian fast path): skip hessian
    quantization entirely — returns (g_q, None, gscale, 1.0), saving
    the hessian PRNG draw and keeping hessian sums exact.

    pmax_axis: shard_map axis name for distributed training — scales must
    agree across shards so every rank bins identical integers."""
    g = grad.astype(jnp.float32)
    gmax = jnp.max(jnp.abs(g))
    if pmax_axis:
        gmax = jax.lax.pmax(gmax, pmax_axis)
    gscale = jnp.maximum(gmax, 1e-30) / 127.0
    ku, kv = jax.random.split(key)
    ug = jax.random.uniform(ku, g.shape)
    # clip: f32 rounding at the band edge (127 + u -> 128.0) can escape
    # the documented [-127, 127] contract a few times per billion rows
    g_q = jnp.clip(jnp.floor(g / gscale + ug), -127.0, 127.0)
    if hess is None:
        return g_q, None, gscale, jnp.float32(1.0)
    h = hess.astype(jnp.float32)
    # abs: custom objectives may hand back negative hessians; scaling by
    # max|h| keeps h_q inside the bf16-exact [-127, 127] band either way
    hmax = jnp.max(jnp.abs(h))
    if pmax_axis:
        hmax = jax.lax.pmax(hmax, pmax_axis)
    hscale = jnp.maximum(hmax, 1e-30) / 127.0
    uh = jax.random.uniform(kv, h.shape)
    h_q = jnp.clip(jnp.floor(h / hscale + uh), -127.0, 127.0)
    return g_q, h_q, gscale, hscale


def _combine_hist(out, *, nchan: int, s: int, f: int, b: int, bmax: int,
                  double_prec: bool, const_hess: float = 0.0) -> jax.Array:
    """Kernel output [*, nchan*s, f*b] -> [S, F, bmax, 3] with the hi/lo
    channel recombination (shared postlude of the v2/fused kernels).
    const_hess != 0: the hessian channel was dropped by _hist_channels;
    reconstruct it exactly as const_hess * count."""
    out = out.reshape(nchan, s, f, b)[..., :bmax]
    out = jnp.transpose(out, (1, 0, 2, 3))                   # [S, C, F, B]
    if const_hess:
        if nchan == 2:   # quantized: [g_int, cnt]
            g, c = out[:, 0], out[:, 1]
        else:            # exact: [g_hi, g_lo, cnt]
            g, c = out[:, 0] + out[:, 1], out[:, 2]
        return jnp.stack([g, c * jnp.float32(const_hess), c], axis=-1)
    if nchan == 3:  # quantized: integer g/h sums ride single channels
        return jnp.stack([out[:, 0], out[:, 1], out[:, 2]], axis=-1)
    if double_prec:
        return jnp.stack([out[:, 0] + out[:, 1], out[:, 2] + out[:, 3],
                          out[:, 4]], axis=-1)               # [S, F, B, 3]
    return jnp.stack([out[:, 0] + out[:, 1], out[:, 2], out[:, 3]],
                     axis=-1)


def _hist_accumulate(hist_ref, slot, bins_i, data, *, nb: int, f: int,
                     b: int, s: int, nchan: int, mm_dtype, fh: int = 0):
    """Shared accumulation body of the v2/fused kernels: slot-masked
    channel operand, per-feature-group bin one-hots, accumulating dots.
    slot: [nb, 1] i32 (-1 = no slot); bins_i: [nb, lanes] i32 (fh > 0:
    4-bit packed columns, feature j at column j % fh, nibble j // fh)."""
    iota_s = jax.lax.broadcasted_iota(jnp.int32, (nb, s), 1)
    slot_oh = (slot == iota_s)                               # [nb, S] bool
    lhs = jnp.concatenate(
        [jnp.where(slot_oh, data[:, c:c + 1], jnp.float32(0.0))
         for c in range(nchan)], axis=1).astype(mm_dtype)    # [nb, C*S]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, b), 1)
    for gj in range(0, f, _FGROUP):
        js = range(gj, min(gj + _FGROUP, f))
        cols = _packed_cols(bins_i, js, fh) if fh else \
            [bins_i[:, j:j + 1] for j in js]
        oh = jnp.concatenate(
            [(c == iota_b) for c in cols],
            axis=1).astype(mm_dtype)                         # [nb, G*B]
        part = jax.lax.dot_general(
            lhs, oh, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [C*S, G*B]
        hist_ref[0, :, gj * b:(gj + len(js)) * b] += part


def _route_decide(node, gath, bins_blk, ftbl, memb, *, nb: int,
                  fh: int = 0, loc=None, efb_range: bool = False):
    """Shared split-decision math of the route/fused kernels: numerical
    thresholds, NaN-bin default direction, categorical bitset membership.
    gath: [nb, K] node-table row per row; bins_blk: [nb, lanes] f32
    (fh > 0: 4-bit packed byte columns, feature j at column j % fh,
    nibble j // fh — byte values <= 255 stay f32-exact, the nibble is
    recovered arithmetically after the column pick);
    loc is not None: bins_blk holds EFB bundle columns; the split
    feature's bundle column (_COL_BCOL) is selected, then the original
    local bin is decoded through the [F, Bb] loc_table (efb.py: default
    bin folded in for out-of-segment positions) — the decision math
    below then runs on original bins unchanged;
    memb: [nb, Bpad] categorical left-set membership or None when the
    table holds no categorical splits. Returns (new node ids, next-pass
    kernel slot) as [nb, 1] f32 pairs — rows of unsplit nodes keep
    their node and their own slot; routed rows take the chosen child's
    slot, carried in the PARENT row (_COL_SLOTL/_COL_SLOTR) so no
    second node-table lookup is needed."""

    def col(c):
        return gath[:, c:c + 1]                              # [nb, 1] f32

    split = col(_COL_SPLIT)
    pf = col(_COL_FEAT_Q) * 256.0 + col(_COL_FEAT_R)
    thr = col(_COL_THR)
    defl = col(_COL_DEFLEFT) > 0.5
    child_l = col(_COL_LEFT_Q) * 256.0 + col(_COL_LEFT_R)
    child_r = col(_COL_RIGHT_Q) * 256.0 + col(_COL_RIGHT_R)

    # predicates as 0/1 f32 (Mosaic lacks i1-valued selects)
    one = jnp.float32(1.0)
    zero = jnp.float32(0.0)
    defl_f = jnp.where(defl, one, zero)
    if efb_range:
        # EFB bundle-RANGE decision: the row's bundle bin compared to
        # per-node position constants (pack_route_tables efb columns).
        # In-segment rows go left iff pos <= P(t); the NaN position goes
        # by default_left; out-of-segment rows (the split feature sits
        # at its default bin) go by the precomputed default side. No
        # original-bin decode, no [rows, F]-wide work — identity columns
        # (dense numerics, categoricals) reduce to the plain bin compare
        # because their segment spans the whole column.
        bcol = col(_COL_BCOL_Q) * 256.0 + col(_COL_BCOL_R)
        iota_c = jax.lax.broadcasted_iota(
            jnp.int32, (nb, bins_blk.shape[1]), 1).astype(jnp.float32)
        pval = jnp.sum(jnp.where(bcol == iota_c, bins_blk, 0.0),
                       axis=1, keepdims=True)                # [nb, 1] f32
        seg_lo = col(_COL_SEG_LO)
        seg_hi = col(_COL_SEG_HI)
        pt = col(_COL_PT)
        dbl = col(_COL_DBLEFT)
        pnan = col(_COL_PNAN)
        in_f = jnp.where((pval >= seg_lo) & (pval <= seg_hi), one, zero)
        nanp_f = jnp.where(pval == pnan, one, zero)
        le_f = jnp.where(pval <= pt, one, zero)
        num_gl = in_f * (nanp_f * defl_f + (one - nanp_f) * le_f) + \
            (one - in_f) * dbl
        binv = pval  # categorical columns are identity: bin == position
    else:
        if fh:
            # packed storage: pick byte column pf % fh, then the nibble
            fh_f = jnp.float32(fh)
            is_hi = jnp.where(pf >= fh_f, jnp.float32(1.0),
                              jnp.float32(0.0))
            pcol = pf - is_hi * fh_f
            iota_p = jax.lax.broadcasted_iota(
                jnp.int32, (nb, bins_blk.shape[1]), 1).astype(jnp.float32)
            pbyte = jnp.sum(jnp.where(pcol == iota_p, bins_blk, 0.0),
                            axis=1, keepdims=True)           # [nb, 1] f32
            hi_val = jnp.floor(pbyte * jnp.float32(1.0 / 16.0))
            binv = is_hi * hi_val + (1.0 - is_hi) * \
                (pbyte - 16.0 * hi_val)
        # per-feature flags (num_bins, missing_is_nan) index the
        # full-width feature table regardless of bin packing/bundling
        iota_f = jax.lax.broadcasted_iota(
            jnp.int32, (nb, ftbl.shape[0]), 1).astype(jnp.float32)
        feat_oh = (pf == iota_f)                             # [nb, L] bool
        if loc is not None:
            # EFB expansion fallback: bundle-column select, then
            # original-local-bin decode through the [F, Bb] loc table
            bcol = col(_COL_BCOL_Q) * 256.0 + col(_COL_BCOL_R)
            iota_c = jax.lax.broadcasted_iota(
                jnp.int32, (nb, bins_blk.shape[1]), 1).astype(jnp.float32)
            pval = jnp.sum(jnp.where(bcol == iota_c, bins_blk, 0.0),
                           axis=1, keepdims=True)            # [nb, 1] f32
            # loc row of the split feature: one MXU dot (entries <= 256,
            # bf16-exact; 0/1 lhs keeps the accumulation a selection)
            loc_row = jax.lax.dot_general(
                feat_oh.astype(jnp.bfloat16), loc.astype(jnp.bfloat16),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # [nb, Bb]
            iota_b2 = jax.lax.broadcasted_iota(
                jnp.int32, (nb, loc.shape[1]), 1).astype(jnp.float32)
            binv = jnp.sum(jnp.where(pval == iota_b2, loc_row, 0.0),
                           axis=1, keepdims=True)            # [nb, 1] f32
        elif not fh:
            # column select: binv[r] = bins[r, pf[r]] via one-hot sum
            binv = jnp.sum(jnp.where(feat_oh, bins_blk, 0.0), axis=1,
                           keepdims=True)                    # [nb, 1] f32
        nbins = jnp.sum(jnp.where(feat_oh, ftbl[:, 0][None, :], 0.0),
                        axis=1, keepdims=True)
        mnan = jnp.sum(jnp.where(feat_oh, ftbl[:, 1][None, :], 0.0),
                       axis=1, keepdims=True) > 0.5
        is_nan_bin = mnan & (binv == nbins - 1.0)
        nan_f = jnp.where(is_nan_bin, one, zero)
        le_f = jnp.where(binv <= thr, one, zero)
        num_gl = nan_f * defl_f + (one - nan_f) * le_f
    if memb is not None:
        iscat_f = jnp.where(col(_COL_ISCAT) > 0.5, one, zero)
        bpad = memb.shape[1]
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, bpad), 1) \
            .astype(jnp.float32)
        in_set_f = jnp.sum(jnp.where(binv == iota_b, memb, 0.0),
                           axis=1, keepdims=True)            # 0/1 f32
        gl_f = iscat_f * in_set_f + (one - iscat_f) * num_gl
    else:
        gl_f = num_gl
    child_f = gl_f * child_l + (one - gl_f) * child_r
    slot_own = col(_COL_SLOT_Q) * 256.0 + col(_COL_SLOT_R)
    slot_l = col(_COL_SLOTL_Q) * 256.0 + col(_COL_SLOTL_R)
    slot_r = col(_COL_SLOTR_Q) * 256.0 + col(_COL_SLOTR_R)
    slot_child = gl_f * slot_l + (one - gl_f) * slot_r
    new_node = split * child_f + (one - split) * node.astype(jnp.float32)
    new_slot = split * slot_child + (one - split) * slot_own
    return new_node, new_slot


def _hist_kernel_v2(nb: int, f: int, b: int, s: int,
                    mm_dtype=jnp.bfloat16, nchan: int = 5, fh: int = 0):
    """Extraction-free histogram kernel: the [flane, fc*B] selector matmul
    of _hist_kernel (whose cost scales with the 128-lane padding, ~4.6x
    waste at F=28 and the S-independent floor of every pass) is replaced
    by per-feature static lane slices + a VPU broadcast-compare. One grid
    pass over rows, one [nb, nchan*S] x [nb, B] dot per feature."""

    def kernel(block_any_ref, slot_ref, bins_ref, data_ref, out_ref):
        ri = pl.program_id(0)

        @pl.when(ri == 0)
        def _():
            out_ref[0] = jnp.zeros_like(out_ref[0])

        @pl.when(block_any_ref[ri] != 0)
        def _():
            _hist_accumulate(out_ref, slot_ref[:],
                             bins_ref[:].astype(jnp.int32), data_ref[:],
                             nb=nb, f=f, b=b, s=s, nchan=nchan,
                             mm_dtype=mm_dtype, fh=fh)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("num_slots", "bmax", "row_block", "fchunk",
                              "interpret", "use_f32", "double_prec",
                              "quantized", "const_hess"))
def build_histograms_mxu(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                         cnt: jax.Array, row_slot: jax.Array, *,
                         num_slots: int, bmax: int, row_block: int = 1024,
                         fchunk: int = 4, use_f32: bool = False,
                         double_prec: bool = True, quantized: bool = False,
                         const_hess: float = 0.0,
                         interpret: bool = False) -> jax.Array:
    """Per-slot histograms without sorting or gathering.

    Args mirror build_histograms; row_slot < 0 routes to no slot.
    Returns [num_slots, F, bmax, 3] f32 (grad, hess, count).

    double_prec=True splits gradients AND hessians into hi/lo bf16 pairs
    (~f32-accurate sums, 5 matmul channels). False keeps gradient sums
    hi/lo-exact but sums hessians as single bf16 (~2^-9 relative error;
    4 channels, ~1.3x faster) — the TPU analog of the reference GPU
    backend's gpu_use_dp switch.
    """
    n, f = bins.shape
    nb = row_block
    s = num_slots
    b = ((bmax + 127) // 128) * 128          # lane-aligned bin axis
    fc = fchunk
    nchunks = (f + fc - 1) // fc
    fpad = nchunks * fc
    flane = ((max(fpad, f) + 127) // 128) * 128

    npad = (-n) % nb
    if npad:
        bins = jnp.pad(bins, ((0, npad), (0, 0)))
    if flane != f:
        # padded feature columns always bin to 255 (a bin id real features
        # can also hit, but their chunks are sliced away below)
        bins = jnp.pad(bins, ((0, 0), (0, flane - f)),
                       constant_values=255)
    slot = jnp.where((row_slot < 0) | (row_slot >= s), -1, row_slot) \
        .astype(jnp.int32)
    if npad:
        slot = jnp.pad(slot, (0, npad), constant_values=-1)

    data, nchan = _hist_channels(grad, hess, cnt, double_prec, quantized,
                                 const_hess)
    if npad:
        data = jnp.pad(data, ((0, npad), (0, 0)))

    nblocks = (n + npad) // nb
    block_any = jnp.max(
        (slot >= 0).astype(jnp.int32).reshape(nblocks, nb), axis=1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nchunks, nblocks),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda ci, ri, ba: (ri, 0)),
            pl.BlockSpec((nb, flane), lambda ci, ri, ba: (ri, 0)),
            pl.BlockSpec((nb, 8), lambda ci, ri, ba: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((1, nchan * s, fc * b),
                               lambda ci, ri, ba: (ci, 0, 0)))
    out = pl.pallas_call(
        _hist_kernel(nb, fc, b, s, flane,
                     jnp.float32 if use_f32 else jnp.bfloat16,
                     nchan=nchan),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nchunks, nchan * s, fc * b),
                                       jnp.float32),
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(block_any, slot[:, None], bins, data)

    # [nchunks, C*S, fc*B] -> [S, F, B, 3]
    out = out.reshape(nchunks, nchan, s, fc, b)
    out = jnp.transpose(out, (2, 1, 0, 3, 4)).reshape(s, nchan, fpad, b)
    out = out[:, :, :f, :bmax]
    if const_hess:
        if nchan == 2:
            g, c = out[:, 0], out[:, 1]
        else:
            g, c = out[:, 0] + out[:, 1], out[:, 2]
        hist = jnp.stack([g, c * jnp.float32(const_hess), c], axis=-1)
    elif nchan == 3:
        hist = jnp.stack([out[:, 0], out[:, 1], out[:, 2]], axis=-1)
    elif double_prec:
        hist = jnp.stack([out[:, 0] + out[:, 1], out[:, 2] + out[:, 3],
                          out[:, 4]], axis=-1)               # [S, F, B, 3]
    else:
        hist = jnp.stack([out[:, 0] + out[:, 1], out[:, 2], out[:, 3]],
                         axis=-1)
    return hist


# VMEM budget for the v2/fused kernels: resident histogram output block
# plus the per-row-block input working set (binned lanes in i32/f32 and
# the bin one-hot scratch). Beyond it the chunked v1 kernel takes over
# (wide-feature datasets) — without the input term, wide-F data at tiny
# frontiers passed the output check and then failed scoped-VMEM
# allocation inside the fused kernel (observed at F=1000, bmax=64).
_V2_BUDGET_BYTES = 80 * 1024 * 1024
_V2_ROW_BLOCK = 4096  # worst-case block the grower/dispatcher may pick


def fits_v2(num_slots: int, num_features: int, bmax: int,
            double_prec: bool = True, quantized: bool = False,
            route_width: int = 0,
            row_block: int = _V2_ROW_BLOCK,
            const_hess: float = 0.0) -> bool:
    """Whether the extraction-free v2/fused kernels' working set fits
    the VMEM budget for this shape (single owner of the predicate — the
    grower and the auto dispatcher must agree). route_width: the
    original-feature table width when it differs from the bins width
    (EFB: bins hold bundle columns but routing gathers original-feature
    one-hots + the loc_table decode); row_block: the block the caller
    will actually use."""
    b = ((bmax + 127) // 128) * 128
    if const_hess:
        # _hist_channels: [g, cnt] quantized, [g_hi, g_lo, cnt] exact
        # (regardless of double_prec — the dropped channel is hessian)
        nchan = 2 if quantized else 3
    else:
        nchan = 3 if quantized else (5 if double_prec else 4)
    out = nchan * num_slots * num_features * b * 4
    plane = ((num_features + 127) // 128) * 128
    flane_r = ((max(route_width, num_features) + 127) // 128) * 128
    # bins block in i32 + f32 (~3 lane buffers) + the route decide's
    # iota/one-hot/where mask chain over the route width (~6 f32
    # temporaries, more under the EFB loc decode), plus the [nb, G*B]
    # bin one-hot scratch
    route_cost = 36 if route_width and route_width != num_features else 24
    inputs = row_block * (12 * plane + route_cost * flane_r +
                          2 * _FGROUP * b)
    return out + inputs <= _V2_BUDGET_BYTES


@functools.partial(
    jax.jit, static_argnames=("num_slots", "bmax", "row_block",
                              "interpret", "use_f32", "double_prec",
                              "quantized", "num_features", "const_hess"))
def build_histograms_mxu_v2(bins: jax.Array, grad: jax.Array,
                            hess: jax.Array, cnt: jax.Array,
                            row_slot: jax.Array, *, num_slots: int,
                            bmax: int, row_block: int = 4096,
                            use_f32: bool = False,
                            double_prec: bool = True,
                            quantized: bool = False,
                            num_features: int = 0,
                            const_hess: float = 0.0,
                            interpret: bool = False) -> jax.Array:
    """Extraction-free variant of build_histograms_mxu (same contract):
    one grid pass over rows, per-feature static lane slices instead of
    the selector matmul, all channels in a single dot per feature.

    num_features > 0 marks `bins` as 4-bit packed storage
    (pack_bins_4bit) with that many logical features; the kernel unpacks
    nibbles in VMEM, halving the bin matrix's HBM traffic."""
    n, fcols = bins.shape
    f = num_features if num_features else fcols
    fh = fcols if num_features else 0
    nb = row_block
    s = num_slots
    b = ((bmax + 127) // 128) * 128
    flane = ((fcols + 127) // 128) * 128

    npad = (-n) % nb
    if npad:
        bins = jnp.pad(bins, ((0, npad), (0, 0)))
    if flane != fcols:
        # padded lanes are never sliced by the kernel (j < f); the value
        # only needs to be in-range for the int cast
        bins = jnp.pad(bins, ((0, 0), (0, flane - fcols)))
    slot = jnp.where((row_slot < 0) | (row_slot >= s), -1, row_slot) \
        .astype(jnp.int32)
    if npad:
        slot = jnp.pad(slot, (0, npad), constant_values=-1)
    data, nchan = _hist_channels(grad, hess, cnt, double_prec, quantized,
                                 const_hess)
    if npad:
        data = jnp.pad(data, ((0, npad), (0, 0)))

    nblocks = (n + npad) // nb
    block_any = jnp.max(
        (slot >= 0).astype(jnp.int32).reshape(nblocks, nb), axis=1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda ri, ba: (ri, 0)),
            pl.BlockSpec((nb, flane), lambda ri, ba: (ri, 0)),
            pl.BlockSpec((nb, 8), lambda ri, ba: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((1, nchan * s, f * b),
                               lambda ri, ba: (0, 0, 0)))
    out = pl.pallas_call(
        _hist_kernel_v2(nb, f, b, s,
                        jnp.float32 if use_f32 else jnp.bfloat16,
                        nchan=nchan, fh=fh),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, nchan * s, f * b), jnp.float32),
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(block_any, slot[:, None], bins, data)

    return _combine_hist(out, nchan=nchan, s=s, f=f, b=b, bmax=bmax,
                         double_prec=double_prec, const_hess=const_hess)


def build_histograms_mxu_auto(bins, grad, hess, cnt, row_slot, *,
                              num_slots, bmax, double_prec=True,
                              quantized=False, num_features=0,
                              const_hess=0.0,
                              interpret=False, **v1_cfg):
    """v2 kernel when its per-feature output block fits VMEM, else the
    chunked v1 kernel (wide-feature datasets). num_features > 0 marks
    `bins` as 4-bit packed (the v1 fallback unpacks on device — packed
    storage targets small-bmax shapes, which always fit v2)."""
    f = num_features if num_features else bins.shape[1]
    if fits_v2(num_slots, f, bmax, double_prec, quantized,
               const_hess=const_hess):
        return build_histograms_mxu_v2(
            bins, grad, hess, cnt, row_slot, num_slots=num_slots,
            bmax=bmax, double_prec=double_prec, quantized=quantized,
            num_features=num_features, const_hess=const_hess,
            interpret=interpret)
    if num_features:
        bins = unpack_bins_4bit(bins, num_features)
    return build_histograms_mxu(
        bins, grad, hess, cnt, row_slot, num_slots=num_slots, bmax=bmax,
        double_prec=double_prec, quantized=quantized,
        const_hess=const_hess, interpret=interpret,
        **v1_cfg)


def _fused_kernel(nb: int, f: int, flane: int, b: int, s: int, m: int,
                  bpad: int, mm_dtype=jnp.bfloat16, nchan: int = 5,
                  has_cat: bool = True, fh: int = 0,
                  has_efb: bool = False, efb_range: bool = False):
    """Route + histogram in ONE sweep over the binned matrix: advance each
    row through the splits committed by the previous pass (the
    _route_kernel math) and immediately scatter-accumulate it into its new
    slot's histogram (the _hist_kernel_v2 math). Saves a full second read
    of bins + a kernel launch per growth pass. Blocks whose rows all sit
    in unsplit nodes skip everything except the cheap node-table gather
    (their rows keep their node and contribute to no slot)."""

    def kernel(node_ref, bins_ref, data_ref, tbl_ref, member_ref,
               feat_tbl_ref, loc_ref, hist_ref, node_out_ref):
        ri = pl.program_id(0)

        @pl.when(ri == 0)
        def _():
            hist_ref[0] = jnp.zeros_like(hist_ref[0])

        node = node_ref[:]                                   # [nb, 1] i32
        iota_m = jax.lax.broadcasted_iota(jnp.int32, (nb, m), 1)
        # bf16 operands: the node table was designed around base-256
        # digits (every entry <= 256, bf16-exact), and one-hot rows make
        # the f32 accumulation a pure selection — bit-exact at 1/4 the
        # MXU passes of an f32 dot
        node_oh = (node == iota_m).astype(jnp.bfloat16)      # [nb, M]
        tbl_bf = tbl_ref[:].astype(jnp.bfloat16)
        gath = jax.lax.dot_general(
            node_oh, tbl_bf, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [nb, K]

        def col(c):
            return gath[:, c:c + 1]                          # [nb, 1] f32

        split = col(_COL_SPLIT)
        block_has_split = jnp.sum(split) > 0.5

        def own_slot():
            return (gath[:, _COL_SLOT_Q:_COL_SLOT_Q + 1] * 256.0 +
                    gath[:, _COL_SLOT_R:_COL_SLOT_R + 1])

        @pl.when(~block_has_split)
        def _():
            node_out_ref[:] = jnp.concatenate(
                [node.astype(jnp.float32), own_slot()],
                axis=1).astype(jnp.int32)

        @pl.when(block_has_split)
        def _():
            memb = jax.lax.dot_general(
                node_oh, member_ref[:].astype(jnp.bfloat16),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) if has_cat else None
            new_node_f, new_slot_f = _route_decide(
                node, gath, bins_ref[:].astype(jnp.int32)
                .astype(jnp.float32), feat_tbl_ref[:], memb,
                nb=nb, fh=fh, efb_range=efb_range,
                loc=loc_ref[:] if has_efb else None)
            node_out_ref[:] = jnp.concatenate(
                [new_node_f, new_slot_f], axis=1).astype(jnp.int32)

        # ---- histogram accumulation for every block holding slotted
        # rows. The slot rode along with the route (child slots live in
        # the parent's table row; unsplit nodes carry their own slot,
        # -1 outside the initial root pass) — no second node lookup.
        slot = node_out_ref[:, 1:2]                          # [nb, 1] i32
        block_any_slot = jnp.max(slot) >= 0

        @pl.when(block_any_slot)
        def _():
            _hist_accumulate(hist_ref, slot,
                             bins_ref[:].astype(jnp.int32), data_ref[:],
                             nb=nb, f=f, b=b, s=s, nchan=nchan,
                             mm_dtype=mm_dtype, fh=fh)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("num_slots", "bmax", "row_block", "has_cat",
                              "double_prec", "quantized", "num_features",
                              "efb_range", "const_hess", "interpret"))
def fused_route_hist_mxu(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                         cnt: jax.Array, row_node: jax.Array,
                         tbl: jax.Array, member: jax.Array,
                         feat_tbl: jax.Array, *, num_slots: int, bmax: int,
                         row_block: int = 4096, has_cat: bool = True,
                         double_prec: bool = True, quantized: bool = False,
                         num_features: int = 0, loc_table=None,
                         efb_range: bool = False,
                         const_hess: float = 0.0,
                         interpret: bool = False):
    """One sweep: route rows through the previous pass's packed split
    tables (pack_route_tables) AND build the per-slot histograms of the
    resulting frontier. Returns (hist [S, F, bmax, 3], new row_node [N]).

    Rows whose node did not split keep their node and land in no slot
    (slot -1), matching route_rows_mxu + build_histograms_mxu. Routing is
    idempotent: a second sweep through the same tables is the identity
    (children are not split in the table), which the grower uses to flush
    the final pass's routing after its loops.

    num_features > 0 marks `bins` as 4-bit packed (pack_bins_4bit) with
    that many logical features; nibbles unpack in VMEM.

    loc_table ([F_orig, Bb] i32/f32) marks `bins` as EFB bundle columns:
    histograms build in bundle space (f = bundle columns, bmax = Bb) and
    routing decodes the original local bin through loc_table (efb.py);
    feat_tbl stays original-feature-indexed. efb_range=True routes by
    the bundle-RANGE table columns instead — no loc table, no
    original-feature-width work (pack_route_tables efb=)."""
    n, fcols = bins.shape
    has_efb = loc_table is not None and not efb_range
    f = num_features if num_features else fcols
    fh = fcols if num_features else 0
    nb = row_block
    s = num_slots
    b = ((bmax + 127) // 128) * 128
    plane = ((fcols + 127) // 128) * 128     # bins block width (packed)
    # route tables are original-feature-indexed under decode-mode EFB
    f_route = loc_table.shape[0] if has_efb else f
    flane = ((f_route + 127) // 128) * 128
    m, kcols = tbl.shape
    bpad = member.shape[1]

    npad = (-n) % nb
    if npad:
        bins = jnp.pad(bins, ((0, npad), (0, 0)))
        row_node = jnp.pad(row_node, (0, npad))
    if plane != fcols:
        bins = jnp.pad(bins, ((0, 0), (0, plane - fcols)))
    if feat_tbl.shape[0] > flane:
        feat_tbl = feat_tbl[:flane]   # range mode: ftbl is unused
    elif feat_tbl.shape[0] < flane:
        feat_tbl = jnp.pad(feat_tbl,
                           ((0, flane - feat_tbl.shape[0]), (0, 0)))
    if has_efb:
        bb_lane = ((loc_table.shape[1] + 127) // 128) * 128
        loc = jnp.pad(loc_table.astype(jnp.float32),
                      ((0, flane - loc_table.shape[0]),
                       (0, bb_lane - loc_table.shape[1])))
    else:
        loc = jnp.zeros((8, 128), jnp.float32)  # unused placeholder
    data, nchan = _hist_channels(grad, hess, cnt, double_prec, quantized,
                                 const_hess)
    if npad:
        data = jnp.pad(data, ((0, npad), (0, 0)))

    nblocks = (n + npad) // nb
    hist, node_out = pl.pallas_call(
        _fused_kernel(nb, f, flane, b, s, m, bpad, nchan=nchan,
                      has_cat=has_cat, fh=fh, has_efb=has_efb,
                      efb_range=efb_range),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda ri: (ri, 0)),
            pl.BlockSpec((nb, plane), lambda ri: (ri, 0)),
            pl.BlockSpec((nb, 8), lambda ri: (ri, 0)),
            pl.BlockSpec((m, kcols), lambda ri: (0, 0)),
            pl.BlockSpec((m, bpad), lambda ri: (0, 0)),
            pl.BlockSpec((flane, 2), lambda ri: (0, 0)),
            pl.BlockSpec(loc.shape, lambda ri: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, nchan * s, f * b), lambda ri: (0, 0, 0)),
            pl.BlockSpec((nb, 2), lambda ri: (ri, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, nchan * s, f * b), jnp.float32),
            jax.ShapeDtypeStruct((n + npad, 2), jnp.int32),
        ],
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(row_node.astype(jnp.int32)[:, None], bins, data, tbl, member,
      feat_tbl, loc)

    h3 = _combine_hist(hist, nchan=nchan, s=s, f=f, b=b, bmax=bmax,
                       double_prec=double_prec, const_hess=const_hess)
    return h3, node_out[:n, 0]


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

# packed node-table column layout. The MXU truncates f32 operands to
# bf16, whose integers are exact only up to 256 — node/child ids can reach
# 2*num_leaves, so they are stored as (quotient, remainder) base-256 pairs
# and reassembled after the contraction. Every other column is <= 256.
_COL_SPLIT = 0     # 1.0 if the node was split this pass
_COL_FEAT_R = 1    # split feature % 256 (used-feature idx)
_COL_THR = 2       # threshold bin (mxu path requires max_bin <= 256)
_COL_DEFLEFT = 3   # NaN-direction default_left
_COL_ISCAT = 4     # categorical decision
_COL_LEFT_Q = 5    # left child id // 256
_COL_LEFT_R = 6    # left child id % 256
_COL_RIGHT_Q = 7   # right child id // 256
_COL_RIGHT_R = 8   # right child id % 256
_COL_SLOT_Q = 9    # next-pass slot // 256 (-1 encodes as (-1, 255))
_COL_SLOT_R = 10   # next-pass slot % 256
_COL_FEAT_Q = 11   # split feature // 256 (wide datasets)
_COL_SLOTL_Q = 12  # left child's next-pass slot // 256 (-1 = (-1, 255))
_COL_SLOTL_R = 13  # left child's next-pass slot % 256
_COL_SLOTR_Q = 14  # right child's next-pass slot // 256
_COL_SLOTR_R = 15  # right child's next-pass slot % 256
_COL_BCOL_Q = 16   # split feature's EFB bundle column // 256
_COL_BCOL_R = 17   # split feature's EFB bundle column % 256
# EFB bundle-RANGE routing (efb.EfbScan route tables): the split decision
# becomes position compares on the row's bundle bin — no original-bin
# decode, no [rows, F]-wide work. All values <= 256 (bf16-exact).
_COL_SEG_LO = 18   # first bundle position of the split feature's segment
_COL_SEG_HI = 19   # last bundle position of the segment
_COL_PT = 20       # last LEFT position for this threshold (seg_lo-1: none)
_COL_DBLEFT = 21   # default-bin side goes left (out-of-segment rows)
_COL_PNAN = 22     # NaN-bin position (-1: none); routes by default_left
_N_COLS = 23


def pack_route_tables(split_mask, feat, thr, default_left, is_cat,
                      child_l, child_r, slot_of_node, cat_bitset,
                      m_pad: int, bmax: int, bcol=None, efb=None):
    """Node tables for route_rows_mxu: ([m_pad, _N_COLS] f32 scalars,
    [m_pad, Bpad] 0/1 categorical left-set membership per bin).
    bcol: per-node EFB bundle column of the split feature (defaults to
    the feature id itself — identity when bins are unbundled).
    efb (EfbDev with .scan tables): fills the bundle-RANGE routing
    columns (_COL_SEG_LO.._COL_PNAN) from its static tables so the
    kernels can run the efb_range decision; zeros otherwise."""
    m1 = split_mask.shape[0]
    w = cat_bitset.shape[1]
    bpad = ((bmax + 127) // 128) * 128
    bits = jnp.arange(bpad, dtype=jnp.uint32)
    words = cat_bitset if w * 32 >= bpad else jnp.pad(
        cat_bitset, ((0, 0), (0, (bpad + 31) // 32 - w)))
    member = ((words[:, bits // 32] >> (bits % 32)[None, :]) &
              jnp.uint32(1)).astype(jnp.float32)      # [m1, Bpad]
    def qr(v):
        v = v.astype(jnp.int32)
        return ((v // 256).astype(jnp.float32)[:, None],
                (v % 256).astype(jnp.float32)[:, None])

    cl_q, cl_r = qr(child_l)
    cr_q, cr_r = qr(child_r)
    sl_q, sl_r = qr(slot_of_node)
    f_q, f_r = qr(feat)
    # children's kernel slots carried in the PARENT row so routing picks
    # the destination slot without a second node-table lookup
    cl_i = jnp.clip(child_l.astype(jnp.int32), 0, m1 - 1)
    cr_i = jnp.clip(child_r.astype(jnp.int32), 0, m1 - 1)
    slot_l = jnp.where(split_mask, slot_of_node[cl_i], -1)
    slot_r = jnp.where(split_mask, slot_of_node[cr_i], -1)
    slq_q, slq_r = qr(slot_l)
    srq_q, srq_r = qr(slot_r)
    bc_q, bc_r = qr(feat if bcol is None else bcol)
    if efb is not None and getattr(efb, "scan", None) is not None:
        er = efb.scan
        fr = feat.astype(jnp.int32)
        th = jnp.clip(thr.astype(jnp.int32), 0,
                      er.pos_thresh.shape[1] - 1)
        seg_lo_n = efb.seg_lo[fr].astype(jnp.float32)[:, None]
        seg_hi_n = efb.seg_hi[fr].astype(jnp.float32)[:, None]
        pt_n = er.pos_thresh[fr, th].astype(jnp.float32)[:, None]
        dbl_n = jnp.where(er.nan_is_default[fr], default_left,
                          er.db_le_t[fr, th]) \
            .astype(jnp.float32)[:, None]
        pnan_n = er.p_nan_f[fr].astype(jnp.float32)[:, None]
    else:
        z = jnp.zeros((m1, 1), jnp.float32)
        seg_lo_n = seg_hi_n = pt_n = dbl_n = z
        pnan_n = z - 1.0
    tbl = jnp.concatenate([
        split_mask.astype(jnp.float32)[:, None],
        f_r,
        thr.astype(jnp.float32)[:, None],
        default_left.astype(jnp.float32)[:, None],
        is_cat.astype(jnp.float32)[:, None],
        cl_q, cl_r, cr_q, cr_r,
        sl_q, sl_r,
        f_q,
        slq_q, slq_r, srq_q, srq_r,
        bc_q, bc_r,
        seg_lo_n, seg_hi_n, pt_n, dbl_n, pnan_n], axis=1)
    if m_pad > m1:
        tbl = jnp.pad(tbl, ((0, m_pad - m1), (0, 0)))
        member = jnp.pad(member, ((0, m_pad - m1), (0, 0)))
    return tbl, member


def _route_kernel(nb: int, f: int, m: int, bpad: int,
                  has_cat: bool = True, fh: int = 0,
                  has_efb: bool = False, efb_range: bool = False,
                  counts_spad: int = 0, valid_rows: int = 0):
    # every per-row quantity is kept [nb, 1] (2-D) — Mosaic lowers 2-D
    # masks/selects cleanly where 1-D bool vectors hit unsupported i1 casts.
    # counts_spad > 0: the same sweep also accumulates per-slot row counts
    # ([8, counts_spad] f32 broadcast rows, exact to 2^24) — routing AND
    # the partition metadata of the scatter histogram in one pass.
    def kernel(node_ref, bins_ref, tbl_ref, member_ref, feat_tbl_ref,
               loc_ref, out_ref, *counts_refs):
        node = node_ref[:]                                   # [nb, 1] i32
        iota_m = jax.lax.broadcasted_iota(jnp.int32, (nb, m), 1)
        # bf16 operands are exact here: table entries <= 256 by design
        node_oh = (node == iota_m).astype(jnp.bfloat16)      # [nb, M]
        tbl_bf = tbl_ref[:].astype(jnp.bfloat16)
        gath = jax.lax.dot_general(
            node_oh, tbl_bf, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [nb, K]

        # blocks whose rows all sit in unsplit nodes (the common case in
        # late, narrow growth passes) skip the decision math entirely
        block_has_split = jnp.sum(gath[:, _COL_SPLIT:_COL_SPLIT + 1]) > 0.5

        def own_slot():
            return (gath[:, _COL_SLOT_Q:_COL_SLOT_Q + 1] * 256.0 +
                    gath[:, _COL_SLOT_R:_COL_SLOT_R + 1])

        @pl.when(~block_has_split)
        def _():
            node_f = node.astype(jnp.float32)
            out_ref[:] = jnp.concatenate(
                [node_f, own_slot()], axis=1).astype(jnp.int32)

        @pl.when(block_has_split)
        def _():
            memb = jax.lax.dot_general(
                node_oh, member_ref[:].astype(jnp.bfloat16),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) if has_cat else None
            new_node_f, new_slot_f = _route_decide(
                node, gath, bins_ref[:].astype(jnp.int32)
                .astype(jnp.float32), feat_tbl_ref[:], memb,
                nb=nb, fh=fh, efb_range=efb_range,
                loc=loc_ref[:] if has_efb else None)
            out_ref[:] = jnp.concatenate(
                [new_node_f, new_slot_f], axis=1).astype(jnp.int32)

        if counts_spad:
            counts_ref, = counts_refs
            ri = pl.program_id(0)

            @pl.when(ri == 0)
            def _():
                counts_ref[0] = jnp.zeros_like(counts_ref[0])

            # read the routed slot back (same trick as the fused kernel:
            # child slots rode along in the parent's table row)
            slot = out_ref[:, 1:2]                       # [nb, 1] i32
            iota_s = jax.lax.broadcasted_iota(
                jnp.int32, (nb, counts_spad), 1)
            rid = ri * nb + jax.lax.broadcasted_iota(
                jnp.int32, (nb, counts_spad), 0)
            ohc = ((slot == iota_s) & (rid < valid_rows)) \
                .astype(jnp.float32)                     # [nb, spad]
            csum = jnp.sum(ohc, axis=0, keepdims=True)   # [1, spad]
            counts_ref[0] += jnp.broadcast_to(csum, (8, counts_spad))

    return kernel


@functools.partial(
    jax.jit, static_argnames=("row_block", "num_features", "efb_range",
                              "interpret", "emit_counts", "num_slots"))
def route_rows_mxu(bins: jax.Array, row_node: jax.Array, tbl: jax.Array,
                   member: jax.Array, feat_tbl: jax.Array, *,
                   row_block: int = 0, num_features: int = 0,
                   loc_table=None, efb_range: bool = False,
                   emit_counts: bool = False, num_slots: int = 0,
                   interpret: bool = False):
    """Advance rows one level and emit (new row_node, new row_slot).

    tbl/member: from pack_route_tables (M_pad lane-friendly).
    feat_tbl: [F, 2] f32: (num_bins, missing_is_nan).
    num_features > 0 marks `bins` as 4-bit packed (pack_bins_4bit).
    loc_table marks `bins` as EFB bundle columns decoded per row
    (expansion fallback); efb_range=True instead runs the bundle-RANGE
    decision off the packed table columns — no loc table, no
    original-feature-width work (pack_route_tables efb=).

    emit_counts=True (requires num_slots > 0): the on-device parallel
    partition mode — the same sweep additionally returns per-slot row
    counts [num_slots] i32 (rows whose new slot is s; parked rows
    excluded), the exact metadata the scatter histogram's
    partition_rows needs, so routing stops being a count-only second
    pass. Returns (row_node, row_slot, counts) instead of 2-tuple.
    Both partition implementations consume these counts: 'scan'
    derives its exclusive prefix-sum slot bases from them directly
    (routing + counting + partitioning = one sweep, no O(N log N)
    sort), 'argsort' uses them only for the slot-base offsets while
    re-deriving order via the stable sort (the bit-parity oracle).
    """
    n, fcols = bins.shape
    has_efb = loc_table is not None and not efb_range
    f = num_features if num_features else fcols
    f_route = loc_table.shape[0] if has_efb else f
    fh = fcols if num_features else 0
    m, kcols = tbl.shape
    # row_block 0 = auto: 4096 measured fastest at the flagship shape
    # (6.6 vs 8.0 ms at m=896, docs/PerfNotes.md round 5), but ONLY for
    # narrow-input dense routing — wide tables ([nb, m] one-hot), wide
    # bins blocks, and both EFB modes (the expansion decode OOM'd at a
    # 2048 block on 250-column bundles, grower_mxu.py sweep note) keep
    # the conservative 1024. The table cutoff is m <= 1024: the one-hot
    # route tensor is [nb, m] f32, so nb=4096 at m=2048 is a 32 MiB
    # operand (4096*2048*4) before the matmul's output — past the
    # ~16 MiB/core VMEM budget the measured case (m=896, 14 MiB) stays
    # inside, and exactly the fits_v2-style bound the histogram side
    # enforces for its own scan tensors.
    if row_block:
        nb = row_block
    elif m <= 1024 and fcols <= 128 and loc_table is None \
            and not efb_range:
        nb = 4096
    else:
        nb = 1024
    bpad = member.shape[1]
    npad = (-n) % nb
    if npad:
        bins = jnp.pad(bins, ((0, npad), (0, 0)))
        row_node = jnp.pad(row_node, (0, npad))
    if feat_tbl.shape[0] > f_route:
        feat_tbl = feat_tbl[:f_route]  # range mode: ftbl is unused
    elif feat_tbl.shape[0] < f_route:
        feat_tbl = jnp.pad(feat_tbl,
                           ((0, f_route - feat_tbl.shape[0]), (0, 0)))
    loc = loc_table.astype(jnp.float32) if has_efb else \
        jnp.zeros((8, 128), jnp.float32)
    nblocks = (n + npad) // nb
    spad = ((max(num_slots, 1) + 127) // 128) * 128 if emit_counts else 0
    out_specs = pl.BlockSpec((nb, 2), lambda ri: (ri, 0))
    out_shape = jax.ShapeDtypeStruct((n + npad, 2), jnp.int32)
    if emit_counts:
        out_specs = [out_specs,
                     pl.BlockSpec((1, 8, spad), lambda ri: (0, 0, 0))]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((1, 8, spad), jnp.float32)]
    out = pl.pallas_call(
        _route_kernel(nb, f, m, bpad, fh=fh, has_efb=has_efb,
                      efb_range=efb_range, counts_spad=spad,
                      valid_rows=n),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda ri: (ri, 0)),
            pl.BlockSpec((nb, fcols), lambda ri: (ri, 0)),
            pl.BlockSpec((m, kcols), lambda ri: (0, 0)),
            pl.BlockSpec((m, bpad), lambda ri: (0, 0)),
            pl.BlockSpec((f_route, 2), lambda ri: (0, 0)),
            pl.BlockSpec(loc.shape, lambda ri: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(row_node.astype(jnp.int32)[:, None], bins, tbl, member, feat_tbl,
      loc)
    if emit_counts:
        out, counts = out
        return (out[:n, 0], out[:n, 1],
                counts[0, 0, :num_slots].astype(jnp.int32))
    return out[:n, 0], out[:n, 1]


# ---------------------------------------------------------------------------
# exact per-node sums (leaf-value recomputation)
# ---------------------------------------------------------------------------

def _node_sums_kernel(nb: int, m: int):
    def kernel(node_ref, data_ref, out_ref):
        ri = pl.program_id(0)

        @pl.when(ri == 0)
        def _():
            out_ref[0] = jnp.zeros_like(out_ref[0])

        node = node_ref[:]                                   # [nb, 1] i32
        iota_m = jax.lax.broadcasted_iota(jnp.int32, (nb, m), 1)
        # full-f32 contraction: only 8 output columns, so unlike the
        # histogram dots this one is cheap enough to keep exact — the
        # "exact leaf refit" contract of node_sums_mxu depends on it
        oh = (node == iota_m).astype(jnp.float32)            # [nb, M]
        data = data_ref[:]                                   # [nb, 8] f32
        out_ref[0] += jax.lax.dot_general(
            oh, data, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [M, 8]

    return kernel


@functools.partial(jax.jit, static_argnames=("num_nodes", "row_block",
                                             "interpret"))
def node_sums_mxu(row_node: jax.Array, grad: jax.Array, hess: jax.Array,
                  cnt: jax.Array, *, num_nodes: int, row_block: int = 4096,
                  interpret: bool = False) -> jax.Array:
    """Exact per-node (grad, hess, count) sums from the row->node vector —
    a full-f32 one-hot contraction, gather-free. Used to recompute
    leaf values exactly after quantized growth (quantization then only
    ever perturbs the split SEARCH, never the fitted outputs; the
    reference's leaf output closed form gbdt.cpp:412 stays exact).
    Returns [num_nodes, 3] f32. Rows with node < 0 or >= num_nodes are
    ignored."""
    n = row_node.shape[0]
    m = _round_up(num_nodes, 128)
    nb = row_block
    data, _ = _hist_channels(grad, hess, cnt, double_prec=True)
    npad = (-n) % nb
    node = row_node.astype(jnp.int32)
    if npad:
        node = jnp.pad(node, (0, npad), constant_values=-1)
        data = jnp.pad(data, ((0, npad), (0, 0)))
    out = pl.pallas_call(
        _node_sums_kernel(nb, m),
        grid=((n + npad) // nb,),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda ri: (ri, 0)),
            pl.BlockSpec((nb, 8), lambda ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((1, m, 8), lambda ri: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, m, 8), jnp.float32),
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(node[:, None], data)[0, :num_nodes]
    return jnp.stack([out[:, 0] + out[:, 1], out[:, 2] + out[:, 3],
                      out[:, 4]], axis=-1)                   # [M, 3]


# ---------------------------------------------------------------------------
# per-row node-value lookup (score updates)
# ---------------------------------------------------------------------------

def _values_kernel(nb: int, m: int):
    def kernel(node_ref, tbl_ref, out_ref):
        node = node_ref[:]                                   # [nb, 1] i32
        iota_m = jax.lax.broadcasted_iota(jnp.int32, (nb, m), 1)
        node_oh = (node == iota_m).astype(jnp.float32)
        # the MXU truncates f32 operands to bf16, so the table carries a
        # (hi, lo) split; summing the two product columns restores ~f32
        # accuracy (boosting scores drift and stall trees otherwise)
        got = jax.lax.dot_general(
            node_oh, tbl_ref[:], dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [nb, 2]
        out_ref[:] = got[:, 0:1] + got[:, 1:2]

    return kernel


@functools.partial(jax.jit, static_argnames=("row_block", "interpret"))
def node_values_mxu(row_node: jax.Array, values: jax.Array, *,
                    row_block: int = 0,
                    interpret: bool = False) -> jax.Array:
    """values[row_node] without a gather: [N] <- [M] table via one-hot
    matmul (score updates, reference score_updater.hpp:21-110).
    row_block 0 = auto: 8192 measured fastest at the common table sizes
    (3.0 vs 4.4 ms at m=896, docs/PerfNotes.md round 5); narrower for
    very wide tables (the [nb, m] f32 one-hot lives in VMEM)."""
    n = row_node.shape[0]
    m1 = values.shape[0]
    m = _round_up(m1, 128)
    if not row_block:
        row_block = 8192 if m <= 1024 else 2048
    # unlike a gather, the one-hot contraction touches EVERY table entry
    # (0 * NaN = NaN would poison all rows); never-referenced rows such as
    # the grower's scratch node can hold NaN, so sanitize first
    v = values.astype(jnp.float32)
    v = jnp.where(jnp.isfinite(v), v, 0.0)
    v_hi = jax.lax.reduce_precision(v, exponent_bits=8, mantissa_bits=7)
    tbl = jnp.stack([v_hi, v - v_hi], axis=1)                # [m1, 2]
    if m > m1:
        tbl = jnp.pad(tbl, ((0, m - m1), (0, 0)))
    nb = row_block
    npad = (-n) % nb
    node = row_node.astype(jnp.int32)
    if npad:
        node = jnp.pad(node, (0, npad))
    out = pl.pallas_call(
        _values_kernel(nb, m),
        grid=((n + npad) // nb,),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda ri: (ri, 0)),
            pl.BlockSpec((m, 2), lambda ri: (0, 0)),
        ],
        out_specs=pl.BlockSpec((nb, 1), lambda ri: (ri, 0)),
        out_shape=jax.ShapeDtypeStruct((n + npad, 1), jnp.float32),
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(node[:, None], tbl)
    return out[:n, 0]

