"""Sort-free MXU histogram + routing kernels.

Profiling on TPU v5e via the axon tunnel showed per-row memory ops (gather,
scatter, sort) running at ~10M rows/s — the argsort+regroup prologue of the
grouped Pallas histogram (histogram_pallas.py) and the per-row table gathers
of the routing step dominated tree time (~250 ms + ~130 ms per growth pass
at 1M rows), while dense matmuls run at full MXU rate. These kernels remove
every per-row memory op from the growth pass:

- `build_histograms_mxu`: hist[s, f, b, c] = slotOH^T @ (binOH * data_c) —
  both one-hot matrices are built in VMEM per row-block (never hitting HBM)
  and contracted on the MXU with bf16 inputs / f32 accumulation. Gradients
  and hessians are split hi/lo into two bf16 matmuls (double-bf16), giving
  ~2e-6 relative error vs exact f32 scatter — well inside the reference's
  own f32-histogram option (hist_t, USE_SINGLE_PRECISION).
  This is the TPU answer to the CUDA shared-memory scatter kernels
  (cuda_histogram_constructor.cu:18-307): on a systolic-array machine the
  histogram is reformulated as matrix multiplication instead of scatter.

- `route_rows_mxu`: one pass over the binned matrix that advances every
  row through the splits applied this pass (cuda_data_partition.cu:288's
  GenDataToLeftBitVector equivalent). All per-node lookups (split feature,
  threshold bin, children, categorical bitsets, next-pass slot) go through
  ONE [rows, nodes] one-hot f32 matmul against a packed node table —
  no gathers. Categorical bitset words are carried as two 16-bit halves so
  every table value stays exactly representable in f32.

HBM traffic per pass: one read of the binned matrix + small blocks;
flops: nchan * S * N * F * B MACs (bf16; nchan = 5 with double-precision
sums, 4 with single-bf16 hessians) for the histogram, negligible for
routing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["build_histograms_mxu", "route_rows_mxu", "pack_route_tables",
           "node_values_mxu"]

# v5e has 128 MB VMEM; the default 16 MB scoped limit starves the
# accumulate-in-VMEM histogram output on small row counts
_COMPILER_PARAMS = pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)


def _round_up(x: int, k: int) -> int:
    return ((x + k - 1) // k) * k


# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------

def _hist_kernel(nb: int, fc: int, b: int, s: int, flane: int,
                 mm_dtype=jnp.bfloat16, nchan: int = 5):
    fcb = fc * b

    def kernel(block_any_ref, slot_ref, bins_ref, data_ref, out_ref):
        ci = pl.program_id(0)
        ri = pl.program_id(1)

        @pl.when(ri == 0)
        def _():
            out_ref[0] = jnp.zeros_like(out_ref[0])

        # late growth passes have most rows parked in finished leaves
        # (slot -1); blocks with no active row skip all compute
        @pl.when(block_any_ref[ri] != 0)
        def _():
            slot = slot_ref[:, 0]                            # [nb] i32
            iota_s = jax.lax.broadcasted_iota(jnp.int32, (nb, s), 1)
            slot_oh = (slot[:, None] == iota_s)              # [nb, S] bool

            # chunk-extract without lane slicing: a [flane, fc*B] 0/1
            # selector copies feature ci*fc+j//B into one-hot column space
            # via the MXU (bin values <= 255 are exact in bf16)
            bins_f = bins_ref[:].astype(jnp.int32) \
                .astype(jnp.bfloat16)                        # [nb, flane]
            frow = jax.lax.broadcasted_iota(jnp.int32, (flane, fcb), 0)
            jcol = jax.lax.broadcasted_iota(jnp.int32, (flane, fcb), 1)
            sel = (frow == ci * fc + jcol // b).astype(jnp.bfloat16)
            ext = jax.lax.dot_general(
                bins_f, sel, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # [nb, fc*B]
            binidx = jax.lax.broadcasted_iota(jnp.int32, (nb, fcb), 1) % b
            bin_oh = (ext == binidx.astype(jnp.float32)) \
                .astype(mm_dtype)                            # [nb, fc*B]

            data = data_ref[:]                               # [nb, 8] f32
            for c in range(nchan):  # hi/lo pairs + cnt, or g/h/cnt
                lhs = jnp.where(slot_oh, data[:, c:c + 1],
                                jnp.float32(0.0)).astype(mm_dtype)
                part = jax.lax.dot_general(
                    lhs, bin_oh,
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)      # [S, fc*B]
                out_ref[0, c * s:(c + 1) * s, :] += part

    return kernel


@functools.partial(
    jax.jit, static_argnames=("num_slots", "bmax", "row_block", "fchunk",
                              "interpret", "use_f32", "double_prec"))
def build_histograms_mxu(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                         cnt: jax.Array, row_slot: jax.Array, *,
                         num_slots: int, bmax: int, row_block: int = 1024,
                         fchunk: int = 4, use_f32: bool = False,
                         double_prec: bool = True,
                         interpret: bool = False) -> jax.Array:
    """Per-slot histograms without sorting or gathering.

    Args mirror build_histograms; row_slot < 0 routes to no slot.
    Returns [num_slots, F, bmax, 3] f32 (grad, hess, count).

    double_prec=True splits gradients AND hessians into hi/lo bf16 pairs
    (~f32-accurate sums, 5 matmul channels). False keeps gradient sums
    hi/lo-exact but sums hessians as single bf16 (~2^-9 relative error;
    4 channels, ~1.3x faster) — the TPU analog of the reference GPU
    backend's gpu_use_dp switch.
    """
    n, f = bins.shape
    nb = row_block
    s = num_slots
    b = ((bmax + 127) // 128) * 128          # lane-aligned bin axis
    fc = fchunk
    nchunks = (f + fc - 1) // fc
    fpad = nchunks * fc
    flane = ((max(fpad, f) + 127) // 128) * 128

    npad = (-n) % nb
    if npad:
        bins = jnp.pad(bins, ((0, npad), (0, 0)))
    if flane != f:
        # padded feature columns always bin to 255 (a bin id real features
        # can also hit, but their chunks are sliced away below)
        bins = jnp.pad(bins, ((0, 0), (0, flane - f)),
                       constant_values=255)
    slot = jnp.where((row_slot < 0) | (row_slot >= s), -1, row_slot) \
        .astype(jnp.int32)
    if npad:
        slot = jnp.pad(slot, (0, npad), constant_values=-1)

    g = grad.astype(jnp.float32)
    h = hess.astype(jnp.float32)
    # reduce_precision (not a bf16 round-trip, which XLA elides under
    # --xla_allow_excess_precision) keeps the hi/lo split honest
    g_hi = jax.lax.reduce_precision(g, exponent_bits=8, mantissa_bits=7)
    if double_prec:
        h_hi = jax.lax.reduce_precision(h, exponent_bits=8, mantissa_bits=7)
        chans = [g_hi, g - g_hi, h_hi, h - h_hi, cnt.astype(jnp.float32)]
    else:
        # mixed precision: gradient sums (the squared gain numerator) stay
        # hi/lo-exact, hessian sums ride single bf16 — the denominator is
        # smoothed by lambda_l2/min_hessian and tolerates ~2^-9 error
        chans = [g_hi, g - g_hi, h, cnt.astype(jnp.float32)]
    nchan = len(chans)
    data = jnp.stack(chans + [jnp.zeros_like(g)] * (8 - nchan),
                     axis=1)                                 # [N, 8]
    if npad:
        data = jnp.pad(data, ((0, npad), (0, 0)))

    nblocks = (n + npad) // nb
    block_any = jnp.max(
        (slot >= 0).astype(jnp.int32).reshape(nblocks, nb), axis=1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nchunks, nblocks),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda ci, ri, ba: (ri, 0)),
            pl.BlockSpec((nb, flane), lambda ci, ri, ba: (ri, 0)),
            pl.BlockSpec((nb, 8), lambda ci, ri, ba: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((1, nchan * s, fc * b),
                               lambda ci, ri, ba: (ci, 0, 0)))
    out = pl.pallas_call(
        _hist_kernel(nb, fc, b, s, flane,
                     jnp.float32 if use_f32 else jnp.bfloat16,
                     nchan=nchan),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nchunks, nchan * s, fc * b),
                                       jnp.float32),
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(block_any, slot[:, None], bins, data)

    # [nchunks, C*S, fc*B] -> [S, F, B, 3]
    out = out.reshape(nchunks, nchan, s, fc, b)
    out = jnp.transpose(out, (2, 1, 0, 3, 4)).reshape(s, nchan, fpad, b)
    out = out[:, :, :f, :bmax]
    if double_prec:
        hist = jnp.stack([out[:, 0] + out[:, 1], out[:, 2] + out[:, 3],
                          out[:, 4]], axis=-1)               # [S, F, B, 3]
    else:
        hist = jnp.stack([out[:, 0] + out[:, 1], out[:, 2], out[:, 3]],
                         axis=-1)
    return hist


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

# packed node-table column layout. The MXU truncates f32 operands to
# bf16, whose integers are exact only up to 256 — node/child ids can reach
# 2*num_leaves, so they are stored as (quotient, remainder) base-256 pairs
# and reassembled after the contraction. Every other column is <= 256.
_COL_SPLIT = 0     # 1.0 if the node was split this pass
_COL_FEAT_R = 1    # split feature % 256 (used-feature idx)
_COL_THR = 2       # threshold bin (mxu path requires max_bin <= 256)
_COL_DEFLEFT = 3   # NaN-direction default_left
_COL_ISCAT = 4     # categorical decision
_COL_LEFT_Q = 5    # left child id // 256
_COL_LEFT_R = 6    # left child id % 256
_COL_RIGHT_Q = 7   # right child id // 256
_COL_RIGHT_R = 8   # right child id % 256
_COL_SLOT_Q = 9    # next-pass slot // 256 (-1 encodes as (-1, 255))
_COL_SLOT_R = 10   # next-pass slot % 256
_COL_FEAT_Q = 11   # split feature // 256 (wide datasets)
_N_COLS = 12


def pack_route_tables(split_mask, feat, thr, default_left, is_cat,
                      child_l, child_r, slot_of_node, cat_bitset,
                      m_pad: int, bmax: int):
    """Node tables for route_rows_mxu: ([m_pad, 8] f32 scalars,
    [m_pad, Bpad] 0/1 categorical left-set membership per bin)."""
    m1 = split_mask.shape[0]
    w = cat_bitset.shape[1]
    bpad = ((bmax + 127) // 128) * 128
    bits = jnp.arange(bpad, dtype=jnp.uint32)
    words = cat_bitset if w * 32 >= bpad else jnp.pad(
        cat_bitset, ((0, 0), (0, (bpad + 31) // 32 - w)))
    member = ((words[:, bits // 32] >> (bits % 32)[None, :]) &
              jnp.uint32(1)).astype(jnp.float32)      # [m1, Bpad]
    def qr(v):
        v = v.astype(jnp.int32)
        return ((v // 256).astype(jnp.float32)[:, None],
                (v % 256).astype(jnp.float32)[:, None])

    cl_q, cl_r = qr(child_l)
    cr_q, cr_r = qr(child_r)
    sl_q, sl_r = qr(slot_of_node)
    f_q, f_r = qr(feat)
    tbl = jnp.concatenate([
        split_mask.astype(jnp.float32)[:, None],
        f_r,
        thr.astype(jnp.float32)[:, None],
        default_left.astype(jnp.float32)[:, None],
        is_cat.astype(jnp.float32)[:, None],
        cl_q, cl_r, cr_q, cr_r,
        sl_q, sl_r,
        f_q], axis=1)
    if m_pad > m1:
        tbl = jnp.pad(tbl, ((0, m_pad - m1), (0, 0)))
        member = jnp.pad(member, ((0, m_pad - m1), (0, 0)))
    return tbl, member


def _route_kernel(nb: int, f: int, m: int, bpad: int):
    # every per-row quantity is kept [nb, 1] (2-D) — Mosaic lowers 2-D
    # masks/selects cleanly where 1-D bool vectors hit unsupported i1 casts
    def kernel(node_ref, bins_ref, tbl_ref, member_ref, feat_tbl_ref,
               out_ref):
        node = node_ref[:]                                   # [nb, 1] i32
        iota_m = jax.lax.broadcasted_iota(jnp.int32, (nb, m), 1)
        node_oh = (node == iota_m).astype(jnp.float32)       # [nb, M]
        gath = jax.lax.dot_general(
            node_oh, tbl_ref[:], dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [nb, K]

        def col(c):
            return gath[:, c:c + 1]                          # [nb, 1] f32

        def slot_of(node_f):
            oh = (node_f.astype(jnp.int32) == iota_m).astype(jnp.float32)
            qr = jax.lax.dot_general(
                oh, tbl_ref[:, _COL_SLOT_Q:_COL_SLOT_R + 1],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # [nb, 2]
            return qr[:, 0:1] * 256.0 + qr[:, 1:2]

        split = col(_COL_SPLIT)
        # blocks whose rows all sit in unsplit nodes (the common case in
        # late, narrow growth passes) skip the decision math entirely
        block_has_split = jnp.sum(split) > 0.5

        @pl.when(~block_has_split)
        def _():
            node_f = node.astype(jnp.float32)
            out_ref[:] = jnp.concatenate(
                [node_f, slot_of(node_f)], axis=1).astype(jnp.int32)

        @pl.when(block_has_split)
        def _():
            pf = col(_COL_FEAT_Q) * 256.0 + col(_COL_FEAT_R)
            thr = col(_COL_THR)
            defl = col(_COL_DEFLEFT) > 0.5
            iscat = col(_COL_ISCAT) > 0.5
            child_l = col(_COL_LEFT_Q) * 256.0 + col(_COL_LEFT_R)
            child_r = col(_COL_RIGHT_Q) * 256.0 + col(_COL_RIGHT_R)

            # column select: binv[r] = bins[r, pf[r]] via one-hot mask-sum
            bins_blk = bins_ref[:].astype(jnp.int32) \
                .astype(jnp.float32)                         # [nb, F]
            iota_f = jax.lax.broadcasted_iota(jnp.int32, (nb, f), 1) \
                .astype(jnp.float32)
            feat_oh = (pf == iota_f)                         # [nb, F] bool
            binv = jnp.sum(jnp.where(feat_oh, bins_blk, 0.0), axis=1,
                           keepdims=True)                    # [nb, 1] f32

            # per-feature flags (num_bins, missing_is_nan), same mask
            ftbl = feat_tbl_ref[:]                           # [F, 2] f32
            nbins = jnp.sum(jnp.where(feat_oh, ftbl[:, 0][None, :], 0.0),
                            axis=1, keepdims=True)
            mnan = jnp.sum(jnp.where(feat_oh, ftbl[:, 1][None, :], 0.0),
                           axis=1, keepdims=True) > 0.5
            is_nan_bin = mnan & (binv == nbins - 1.0)

            # categorical: membership of bin binv in the node's left set,
            # via the [M, B] 0/1 member table (matmul + column select)
            memb = jax.lax.dot_general(
                node_oh, member_ref[:],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # [nb, Bpad]
            iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, bpad), 1) \
                .astype(jnp.float32)
            in_set_f = jnp.sum(jnp.where(binv == iota_b, memb, 0.0),
                               axis=1, keepdims=True)        # 0/1 f32

            # predicates as 0/1 f32 (Mosaic lacks i1-valued selects)
            one = jnp.float32(1.0)
            zero = jnp.float32(0.0)
            iscat_f = jnp.where(iscat, one, zero)
            nan_f = jnp.where(is_nan_bin, one, zero)
            defl_f = jnp.where(defl, one, zero)
            le_f = jnp.where(binv <= thr, one, zero)
            num_gl = nan_f * defl_f + (one - nan_f) * le_f
            gl_f = iscat_f * in_set_f + (one - iscat_f) * num_gl
            child_f = gl_f * child_l + (one - gl_f) * child_r
            new_node_f = split * child_f + \
                (one - split) * node.astype(jnp.float32)     # [nb, 1]
            out_ref[:] = jnp.concatenate(
                [new_node_f, slot_of(new_node_f)],
                axis=1).astype(jnp.int32)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("row_block", "interpret"))
def route_rows_mxu(bins: jax.Array, row_node: jax.Array, tbl: jax.Array,
                   member: jax.Array, feat_tbl: jax.Array, *,
                   row_block: int = 1024, interpret: bool = False):
    """Advance rows one level and emit (new row_node, new row_slot).

    tbl/member: from pack_route_tables (M_pad lane-friendly).
    feat_tbl: [F, 2] f32: (num_bins, missing_is_nan).
    """
    n, f = bins.shape
    nb = row_block
    m, kcols = tbl.shape
    bpad = member.shape[1]
    npad = (-n) % nb
    if npad:
        bins = jnp.pad(bins, ((0, npad), (0, 0)))
        row_node = jnp.pad(row_node, (0, npad))
    nblocks = (n + npad) // nb
    out = pl.pallas_call(
        _route_kernel(nb, f, m, bpad),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda ri: (ri, 0)),
            pl.BlockSpec((nb, f), lambda ri: (ri, 0)),
            pl.BlockSpec((m, kcols), lambda ri: (0, 0)),
            pl.BlockSpec((m, bpad), lambda ri: (0, 0)),
            pl.BlockSpec((f, 2), lambda ri: (0, 0)),
        ],
        out_specs=pl.BlockSpec((nb, 2), lambda ri: (ri, 0)),
        out_shape=jax.ShapeDtypeStruct((n + npad, 2), jnp.int32),
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(row_node.astype(jnp.int32)[:, None], bins, tbl, member, feat_tbl)
    return out[:n, 0], out[:n, 1]


# ---------------------------------------------------------------------------
# per-row node-value lookup (score updates)
# ---------------------------------------------------------------------------

def _values_kernel(nb: int, m: int):
    def kernel(node_ref, tbl_ref, out_ref):
        node = node_ref[:]                                   # [nb, 1] i32
        iota_m = jax.lax.broadcasted_iota(jnp.int32, (nb, m), 1)
        node_oh = (node == iota_m).astype(jnp.float32)
        # the MXU truncates f32 operands to bf16, so the table carries a
        # (hi, lo) split; summing the two product columns restores ~f32
        # accuracy (boosting scores drift and stall trees otherwise)
        got = jax.lax.dot_general(
            node_oh, tbl_ref[:], dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [nb, 2]
        out_ref[:] = got[:, 0:1] + got[:, 1:2]

    return kernel


@functools.partial(jax.jit, static_argnames=("row_block", "interpret"))
def node_values_mxu(row_node: jax.Array, values: jax.Array, *,
                    row_block: int = 2048,
                    interpret: bool = False) -> jax.Array:
    """values[row_node] without a gather: [N] <- [M] table via one-hot
    matmul (score updates, reference score_updater.hpp:21-110)."""
    n = row_node.shape[0]
    m1 = values.shape[0]
    m = _round_up(m1, 128)
    # unlike a gather, the one-hot contraction touches EVERY table entry
    # (0 * NaN = NaN would poison all rows); never-referenced rows such as
    # the grower's scratch node can hold NaN, so sanitize first
    v = values.astype(jnp.float32)
    v = jnp.where(jnp.isfinite(v), v, 0.0)
    v_hi = jax.lax.reduce_precision(v, exponent_bits=8, mantissa_bits=7)
    tbl = jnp.stack([v_hi, v - v_hi], axis=1)                # [m1, 2]
    if m > m1:
        tbl = jnp.pad(tbl, ((0, m - m1), (0, 0)))
    nb = row_block
    npad = (-n) % nb
    node = row_node.astype(jnp.int32)
    if npad:
        node = jnp.pad(node, (0, npad))
    out = pl.pallas_call(
        _values_kernel(nb, m),
        grid=((n + npad) // nb,),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda ri: (ri, 0)),
            pl.BlockSpec((m, 2), lambda ri: (0, 0)),
        ],
        out_specs=pl.BlockSpec((nb, 1), lambda ri: (ri, 0)),
        out_shape=jax.ShapeDtypeStruct((n + npad, 1), jnp.float32),
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(node[:, None], tbl)
    return out[:n, 0]

