"""Pallas TPU histogram kernel: slot-grouped scatter-accumulate in VMEM.

The TPU answer to the reference's CUDA shared-memory histogram kernels
(cuda_histogram_constructor.cu:18-307): per-row scatter-adds serialize
on the TPU vector units, and the one-hot x MXU kernels in
histogram_mxu.py pay a per-row cost proportional to the frontier width
S — their slot-masked channel operand is [row_block, nchan*S], so every
row is multiplied against every live slot. This kernel removes the S
factor:

1. rows are partitioned by frontier slot ON DEVICE (partition_rows:
   a blocked-prefix-sum stable rank of the row->slot vector — or the
   retained argsort oracle, partition_impl= — padded so every
   `row_block` consecutive positions belong to ONE slot; the per-slot
   counts can come straight from route_rows_mxu(emit_counts=True),
   making routing + counting + partition one sweep with no O(N log N)
   sort);
2. each grid step builds the block's (feature, bin) one-hots in VMEM
   and computes `data8 @ onehot` on the MXU — [8, row_block] x
   [row_block, G*B] per feature group, all channels in one dot. Cost is
   8 x F x B MACs per row REGARDLESS of S, vs nchan x S x F x B for the
   one-hot kernels; the scatter path wins once the frontier outgrows
   ~8/nchan slots, a crossover hist_backend=auto (boosting/gbdt.py)
   measures on device rather than models;
3. consecutive same-slot blocks accumulate into the same output block,
   which Pallas keeps resident in VMEM (flash-attention-style
   revisiting) — a slot's [8, F*B] accumulator touches HBM once, after
   its last block, and the f32 final reduce to [S, F, bmax, 3] happens
   outside the kernel.

Accumulation precision: operands ride bf16 like the MXU kernels — in
quantized mode (use_quantized_grad) the integer gradient channels are
bf16-exact and the f32 accumulation of integer sums is EXACT while
every partial stays below 2^24, so histograms (and therefore models)
are bit-identical across hist_backend settings in the quantized
posture; exact mode rides the same hi/lo bf16 channel pairs as
histogram_mxu (~f32-accurate, equal to the MXU path up to last-ulp
summation-order noise). Bin ids stream as uint8 — or 4-bit packed
pairs (pack_bins_4bit), unpacked nibble-wise in VMEM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram_mxu import (_COMPILER_PARAMS, _FGROUP, _combine_hist,
                            _hist_channels, _packed_cols)

__all__ = ["build_histograms_pallas", "build_histograms_scatter",
           "partition_rows"]


#: rows per step of the scan partition's blocked cumsum (static; the
#: per-step one-hot working set is _SCAN_CB x (num_slots+1) i32)
_SCAN_CB = 4096


def _stable_order_scan(slot_full: jax.Array, sort_start: jax.Array,
                       num_slots: int) -> jax.Array:
    """The stable argsort permutation WITHOUT sorting: O(N*S) blocked
    prefix sums instead of the O(N log N) sort network.

    A stable sort by slot places row i at
        position[i] = sort_start[slot[i]] + rank[i]
    where rank[i] = #{j < i : slot[j] == slot[i]} — the running
    occurrence count of its slot. The rank comes from a blocked
    exclusive cumsum: rows stream in _SCAN_CB-row blocks; each step
    one-hots its block against the slot axis, takes the within-block
    exclusive cumsum, and adds the carried per-slot totals of all
    earlier blocks. Scattering arange(N) through `position` (a
    permutation of [0, N), so the scatter is collision-free) inverts
    it back into the order vector argsort would have produced —
    bit-identical, which is what keeps the scan and argsort partitions
    byte-equal downstream.
    """
    n = slot_full.shape[0]
    s1 = num_slots + 1
    cb = min(_SCAN_CB, max(n, 1))
    npad = (-n) % cb
    if npad:
        # padded rows ride the trash slot AFTER every real row, so no
        # real row's rank can count them
        slot_full = jnp.pad(slot_full, (0, npad),
                            constant_values=num_slots)
    blocks = slot_full.reshape(-1, cb)
    iota_s = jnp.arange(s1, dtype=jnp.int32)[None, :]

    def step(base, slot_blk):
        oh = (slot_blk[:, None] == iota_s).astype(jnp.int32)  # [cb, S+1]
        excl = jnp.cumsum(oh, axis=0) - oh
        rank_blk = base[slot_blk] + \
            jnp.take_along_axis(excl, slot_blk[:, None], axis=1)[:, 0]
        return base + jnp.sum(oh, axis=0), rank_blk

    _, ranks = jax.lax.scan(step, jnp.zeros(s1, jnp.int32), blocks)
    position = sort_start[slot_full] + ranks.reshape(-1)
    return jnp.zeros(n, jnp.int32).at[position[:n]].set(
        jnp.arange(n, dtype=jnp.int32))


def partition_rows(row_slot: jax.Array, *, num_slots: int, row_block: int,
                   counts: jax.Array = None, impl: str = "auto"):
    """Device-side padded partition of rows by frontier slot.

    Every `row_block` consecutive positions of the returned layout hold
    rows of ONE slot; the trash slot `num_slots` collects parked rows
    (slot < 0 / out of range) and layout padding.

    counts: optional per-slot row counts ([num_slots] or longer, e.g.
    the route_rows_mxu(emit_counts=True) output) — skips the
    segment_sum here, so routing + partition metadata is a single
    sweep over the rows.

    impl selects how the slot-stable row permutation is produced:
    "scan" (the "auto" resolution) computes the stable rank by blocked
    prefix sums (_stable_order_scan — no O(N log N) sort), "argsort"
    keeps the original stable sort as the bit-parity oracle. Both
    yield the identical permutation, hence identical block layouts.

    Returns (block_slot [TB] i32, src [TB*row_block] i32): src indexes
    the original rows (n = dummy/padding position) and TB is the static
    block-count bound ceil(n/row_block) + num_slots + 1.
    """
    if impl not in ("auto", "argsort", "scan"):
        raise ValueError(f"unknown partition impl {impl!r}")
    n = row_slot.shape[0]
    s = num_slots
    nb = row_block
    slot_full = jnp.where((row_slot < 0) | (row_slot >= s), s,
                          row_slot).astype(jnp.int32)
    if counts is None:
        counts = jax.ops.segment_sum(jnp.ones(n, jnp.int32), slot_full,
                                     num_segments=s + 1)  # [S+1]
    else:
        live = counts[:s].astype(jnp.int32)
        counts = jnp.concatenate(
            [live, (jnp.int32(n) - jnp.sum(live))[None]])
    sort_start = jnp.concatenate(
        [jnp.zeros(1, jnp.int32),
         jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    if impl == "argsort":
        # the retained O(N log N) bit-parity oracle — the ONLY
        # sanctioned sort on the partition path (PERF001)
        order = jnp.argsort(slot_full)  # tpulint: disable=PERF001
    else:
        order = _stable_order_scan(slot_full, sort_start, s)

    # padded block layout: ceil(count/nb) blocks per slot, min 1
    caps = jnp.maximum(1, -(-counts // nb))
    tb_max = (n + nb - 1) // nb + s + 1                   # static bound
    blk_start = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(caps).astype(jnp.int32)])
    # block j belongs to slot searchsorted(blk_start, j, 'right')-1;
    # tail blocks beyond blk_start[-1] go to the trash slot
    j = jnp.arange(tb_max, dtype=jnp.int32)
    block_slot = jnp.clip(
        jnp.searchsorted(blk_start, j, side="right") - 1, 0, s) \
        .astype(jnp.int32)
    block_slot = jnp.where(j >= blk_start[-1], s, block_slot)

    # padded source row per position (n -> dummy row)
    p = jnp.arange(tb_max * nb, dtype=jnp.int32)
    pslot = block_slot[p // nb]
    r = p - blk_start[pslot] * nb                         # offset in slot
    take = (r >= 0) & (r < counts[pslot])
    src_sorted = jnp.clip(sort_start[pslot] + r, 0, n - 1)
    src = jnp.where(take, order[src_sorted], n)
    return block_slot, src


def _scatter_kernel(nb: int, f: int, b: int, fh: int = 0,
                    mm_dtype=jnp.bfloat16):
    def kernel(slot_ref, bins_ref, data_ref, out_ref):
        i = pl.program_id(0)
        slot = slot_ref[i]
        prev = slot_ref[jnp.maximum(i - 1, 0)]
        first = (i == 0) | (slot != prev)

        @pl.when(first)
        def _():
            out_ref[0] = jnp.zeros_like(out_ref[0])

        bins_i = bins_ref[:].astype(jnp.int32)           # [Nb, Fcols]
        data = data_ref[:].astype(mm_dtype)              # [8, Nb]
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, b), 1)
        for gj in range(0, f, _FGROUP):
            js = range(gj, min(gj + _FGROUP, f))
            cols = _packed_cols(bins_i, js, fh) if fh else \
                [bins_i[:, j:j + 1] for j in js]
            oh = jnp.concatenate(
                [(c == iota_b) for c in cols],
                axis=1).astype(mm_dtype)                 # [Nb, G*B]
            part = jax.lax.dot_general(
                data, oh, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)      # [8, G*B]
            out_ref[0, :, gj * b:(gj + len(js)) * b] += part

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("num_slots", "bmax", "row_block", "num_features",
                     "double_prec", "quantized", "const_hess",
                     "partition_impl", "interpret"))
def build_histograms_scatter(bins: jax.Array, grad: jax.Array,
                             hess: jax.Array, cnt: jax.Array,
                             row_slot: jax.Array, *, num_slots: int,
                             bmax: int, row_block: int = 1024,
                             num_features: int = 0,
                             double_prec: bool = True,
                             quantized: bool = False,
                             const_hess: float = 0.0,
                             slot_counts: jax.Array = None,
                             partition_impl: str = "auto",
                             interpret: bool = False) -> jax.Array:
    """Per-slot histograms via the slot-grouped scatter kernel.

    Args mirror build_histograms_mxu_v2; row_slot < 0 routes to no
    slot. num_features > 0 marks `bins` as 4-bit packed
    (pack_bins_4bit) with that many logical features. slot_counts:
    optional per-slot row counts (route_rows_mxu emit_counts) so the
    partition skips its own counting pass. partition_impl selects the
    row-permutation scheme (partition_rows: auto|argsort|scan).

    Returns [num_slots, F, bmax, 3] f32 (grad, hess, count).
    """
    n, fcols = bins.shape
    f = num_features if num_features else fcols
    fh = fcols if num_features else 0
    nb = row_block
    s = num_slots
    b = ((bmax + 127) // 128) * 128      # lane-aligned bin axis
    fb = f * b

    block_slot, src = partition_rows(row_slot, num_slots=s,
                                     row_block=nb, counts=slot_counts,
                                     impl=partition_impl)
    tb_max = block_slot.shape[0]

    bins_ext = jnp.concatenate(
        [bins, jnp.zeros((1, fcols), bins.dtype)], axis=0)
    bins_pad = bins_ext[src]                              # [TB*Nb, Fc]
    data, nchan = _hist_channels(grad, hess, cnt, double_prec,
                                 quantized, const_hess)   # [N, 8]
    data8 = jnp.concatenate(
        [data, jnp.zeros((1, 8), jnp.float32)], axis=0)[src].T

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tb_max,),
        in_specs=[pl.BlockSpec((nb, fcols), lambda i, sl: (i, 0)),
                  pl.BlockSpec((8, nb), lambda i, sl: (0, i))],
        out_specs=pl.BlockSpec((1, 8, fb), lambda i, sl: (sl[i], 0, 0)))
    out = pl.pallas_call(
        _scatter_kernel(nb, f, b, fh=fh),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s + 1, 8, fb), jnp.float32),
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(block_slot, bins_pad, data8)

    # [S+1, 8, F*B] -> the shared postlude layout [1, C*S, F*B]
    out = jnp.transpose(out[:s, :nchan], (1, 0, 2)).reshape(
        1, nchan * s, fb)
    return _combine_hist(out, nchan=nchan, s=s, f=f, b=b, bmax=bmax,
                         double_prec=double_prec, const_hess=const_hess)


def build_histograms_pallas(bins: jax.Array, grad: jax.Array,
                            hess: jax.Array, cnt: jax.Array,
                            row_slot: jax.Array, *, num_slots: int,
                            bmax: int, row_block: int = 1024,
                            fchunk: int = 0,
                            partition_impl: str = "auto",
                            interpret: bool = False) -> jax.Array:
    """Compat contract of the original one-hot kernel for the portable
    grower (grower.py hist_impl="pallas"): exact full-precision
    channels on the scatter kernel. fchunk is accepted and ignored (the
    scatter kernel groups features by _FGROUP)."""
    del fchunk
    return build_histograms_scatter(
        bins, grad, hess, cnt, row_slot, num_slots=num_slots, bmax=bmax,
        row_block=row_block, partition_impl=partition_impl,
        interpret=interpret)
