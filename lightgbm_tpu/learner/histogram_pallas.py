"""Pallas TPU histogram kernel: one-hot x MXU matmul over node-blocked rows.

This is the TPU answer to the reference's CUDA shared-memory histogram
kernels (cuda_histogram_constructor.cu:18-307) and the per-thread-buffer
row-wise path (train_share_states.h:37-80). Scatter-adds serialize on TPU
(~2 s/pass for 1M x 28 x 256 measured), so the kernel reformulates the
histogram as matrix multiplication on the MXU:

1. rows are grouped by frontier slot (argsort of the row->slot vector) and
   padded so every `row_block` consecutive rows belong to ONE slot;
2. each grid step builds the block's one-hot matrix [row_block, F*B] in VMEM
   (never touching HBM — this is what a pure-XLA one-hot matmul cannot do)
   and computes `data8 @ onehot` on the MXU: [8, row_block] x
   [row_block, F*B] -> [8, F*B] — grad/hess/count channels in one pass;
3. consecutive same-slot blocks accumulate into the same output block, which
   Pallas keeps resident in VMEM (flash-attention-style revisiting).

Measured on v5e-1: 27 ms/pass for 1M rows x 28 features x 256 bins x 256
slots vs 2.04 s for the XLA scatter path (75x).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["build_histograms_pallas"]


def _hist_kernel(f: int, b: int, nb: int, fchunk: int):
    # Mosaic collapses [nb, fc, b] -> [nb, fc*b] only when b is a lane
    # multiple; b is padded to 128k by the caller.
    fb = f * b
    nchunks = (f + fchunk - 1) // fchunk

    def kernel(slot_ref, bins_ref, data_ref, out_ref):
        i = pl.program_id(0)
        slot = slot_ref[i]
        prev = slot_ref[jnp.maximum(i - 1, 0)]
        first = (i == 0) | (slot != prev)

        bins_all = bins_ref[:].astype(jnp.int32)            # [Nb, F]
        data = data_ref[:]                                   # [8, Nb] f32
        parts = []
        for ci in range(nchunks):
            lo = ci * fchunk
            hi = min(lo + fchunk, f)
            fc = hi - lo
            iota_b = jax.lax.broadcasted_iota(jnp.int32, (nb, fc, b), 2)
            oh = (bins_all[:, lo:hi][:, :, None] == iota_b) \
                .astype(jnp.float32).reshape(nb, fc * b)
            parts.append(jax.lax.dot_general(
                data, oh, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))         # [8, fc*B]
        contrib = jnp.concatenate(parts, axis=1) \
            if len(parts) > 1 else parts[0]

        @pl.when(first)
        def _():
            out_ref[0] = contrib

        @pl.when(~first)
        def _():
            out_ref[0] += contrib

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("num_slots", "bmax", "row_block", "fchunk"))
def build_histograms_pallas(bins: jax.Array, grad: jax.Array,
                            hess: jax.Array, cnt: jax.Array,
                            row_slot: jax.Array, *, num_slots: int,
                            bmax: int, row_block: int = 512,
                            fchunk: int = 7) -> jax.Array:
    """Histogram for every slot via the Pallas MXU kernel.

    Args match learner.histogram.build_histograms; returns
    hist [num_slots, F, bmax, 3] float32 (grad, hess, count).
    """
    n, f = bins.shape
    nb = row_block
    s = num_slots
    b_k = ((bmax + 127) // 128) * 128   # lane-aligned bin axis for Mosaic
    fb = f * b_k

    # ---- 1. group rows by slot (trash slot s for row_slot < 0) ----
    slot_full = jnp.where((row_slot < 0) | (row_slot >= s), s,
                          row_slot).astype(jnp.int32)
    order = jnp.argsort(slot_full)                        # [N]
    counts = jax.ops.segment_sum(jnp.ones(n, jnp.int32), slot_full,
                                 num_segments=s + 1)      # [S+1]
    sort_start = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)])

    # ---- 2. padded block layout: every block holds rows of one slot ----
    caps = jnp.maximum(1, -(-counts // nb))               # ceil, min 1 block
    tb_max = (n + nb - 1) // nb + s + 1                   # static bound
    blk_start = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(caps).astype(jnp.int32)])
    # block j belongs to slot searchsorted(blk_start, j, 'right')-1; tail
    # blocks beyond blk_start[-1] go to the trash slot
    j = jnp.arange(tb_max, dtype=jnp.int32)
    block_slot = jnp.clip(
        jnp.searchsorted(blk_start, j, side="right") - 1, 0, s) \
        .astype(jnp.int32)
    block_slot = jnp.where(j >= blk_start[-1], s, block_slot)

    # ---- 3. padded source row per position ----
    p = jnp.arange(tb_max * nb, dtype=jnp.int32)
    pslot = block_slot[p // nb]
    r = p - blk_start[pslot] * nb                         # offset in slot
    take = (r >= 0) & (r < counts[pslot])
    src_sorted = jnp.clip(sort_start[pslot] + r, 0, n - 1)
    src = jnp.where(take, order[src_sorted], n)           # n -> dummy row

    bins_ext = jnp.concatenate(
        [bins, jnp.zeros((1, f), bins.dtype)], axis=0)
    bins_pad = bins_ext[src]                              # [TB*Nb, F]
    zero1 = jnp.zeros(1, jnp.float32)
    ge = jnp.concatenate([grad.astype(jnp.float32), zero1])
    he = jnp.concatenate([hess.astype(jnp.float32), zero1])
    ce = jnp.concatenate([cnt.astype(jnp.float32), zero1])
    pad5 = jnp.zeros((5, tb_max * nb), jnp.float32)
    data8 = jnp.concatenate(
        [ge[src][None], he[src][None], ce[src][None], pad5], axis=0)

    # ---- 4. kernel ----
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tb_max,),
        in_specs=[pl.BlockSpec((nb, f), lambda i, sl: (i, 0)),
                  pl.BlockSpec((8, nb), lambda i, sl: (0, i))],
        out_specs=pl.BlockSpec((1, 8, fb), lambda i, sl: (sl[i], 0, 0)))
    out = pl.pallas_call(
        _hist_kernel(f, b_k, nb, fchunk),
        out_shape=jax.ShapeDtypeStruct((s + 1, 8, fb), jnp.float32),
        grid_spec=grid_spec,
    )(block_slot, bins_pad, data8)

    # [S+1, 8, F*Bk] -> [S, F, B, 3]
    hist = out[:s, :3].reshape(s, 3, f, b_k)[..., :bmax]
    return jnp.transpose(hist, (0, 2, 3, 1))
