"""Linear models in tree leaves (linear_tree=true).

TPU-native redesign of the reference LinearTreeLearner
(src/treelearner/linear_tree_learner.cpp:150-380, "CalculateLinear"):
after the tree structure is grown, every leaf gets a ridge-regularized
linear model over the numerical features on its root path, fit against the
same (grad, hess) Newton objective as the constant leaf values:

    minimize  sum_i [ g_i f(x_i) + 0.5 h_i f(x_i)^2 ]  + 0.5 lambda |beta|^2
    f(x) = beta . x_path + c     =>    [beta; c] = -(X'HX + lambda I)^-1 X'g

The reference accumulates per-leaf upper-triangular X'HX with OMP threads
and solves with Eigen fullPivLu per leaf. Here the whole accumulation is a
`lax.scan` over row chunks of batched outer products (MXU work), and all
leaves are solved at once with one batched `jnp.linalg.solve`.

Parity details kept from the reference:
- rows with NaN in any of their leaf's features are excluded from the fit
  (linear_tree_learner.cpp:260-278) and fall back to the constant
  `leaf_value` at prediction time (src/io/tree.cpp:133-150);
- leaves with fewer usable rows than features+1 keep the constant output
  (linear_tree_learner.cpp:325-333);
- `linear_lambda` is added to the coefficient diagonal only, not the
  intercept (linear_tree_learner.cpp:341-345);
- categorical features never enter leaf models
  (linear_tree_learner.cpp:209-216).

Deviation: the number of distinct path features per leaf model is capped at
a static `dmax` (feature count, max_depth and 31, whichever is smallest) to
keep shapes fixed under jit; paths deeper than that drop the
highest-indexed extra features.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .grower import TreeArrays

__all__ = ["LinearLeaves", "fit_linear_leaves", "linear_leaf_values"]


class LinearLeaves(NamedTuple):
    """Per-node linear leaf models, arrays sized like TreeArrays [M+1]."""
    const: jax.Array   # [M+1] f32 intercept (leaves; fallback = leaf_value)
    coeff: jax.Array   # [M+1, D] f32 coefficients (0 where unused)
    feat: jax.Array    # [M+1, D] i32 used-feature idx, -1 = pad
    nfeat: jax.Array   # [M+1] i32 number of model features


def _path_feature_masks(tree: TreeArrays, f: int, m1: int,
                        is_cat: jax.Array) -> jax.Array:
    """[M+1, F] bool: numerical features split on the root path of each
    node (the reference's tree->branch_features,
    linear_tree_learner.cpp:200-216)."""
    nodes = jnp.arange(m1)

    def cond(c):
        cur, _ = c
        return jnp.any(cur >= 0)

    def body(c):
        cur, mask = c
        valid = cur >= 0
        cc = jnp.clip(cur, 0, m1 - 1)
        feat = tree.split_feature[cc]
        fc = jnp.clip(feat, 0, f - 1)
        upd = valid & (feat >= 0) & ~is_cat[fc]
        mask = mask.at[nodes, fc].max(upd)
        # scratch row m parents itself (grower scatter side effect) —
        # a non-decreasing pointer means "stop", guarding the loop
        nxt = tree.parent[cc]
        return jnp.where(valid & (nxt != cur), nxt, -1), mask

    start = tree.parent[nodes]
    start = jnp.where(start == nodes, -1, start)
    _, mask = jax.lax.while_loop(
        cond, body, (start, jnp.zeros((m1, f), bool)))
    return mask


@functools.partial(jax.jit, static_argnames=("dmax", "chunk"))
def fit_linear_leaves(tree: TreeArrays, row_node: jax.Array,
                      raw: jax.Array, grad: jax.Array, hess: jax.Array,
                      cnt_weight: jax.Array, is_cat_feat: jax.Array,
                      linear_lambda: jax.Array, *, dmax: int,
                      chunk: int = 8192) -> LinearLeaves:
    """Fit all leaf models of one tree.

    Args:
      raw: [N, F] float32 raw (un-binned) feature values, NaN allowed.
      row_node: [N] leaf node id per row (grower output).
      grad/hess: per-row gradients/hessians with bagging folded in.
      cnt_weight: 1.0 for in-bag rows (out-of-bag rows are excluded from
        the fit, like the reference's leaf_map_[i] < 0 skip).
    """
    n, f = raw.shape
    m1 = tree.split_feature.shape[0]
    d1 = dmax + 1

    mask = _path_feature_masks(tree, f, m1, is_cat_feat)
    # first `dmax` set features in ascending index order (top_k tie-break)
    v, idx = jax.lax.top_k(mask.astype(jnp.float32), min(dmax, f))
    feat = jnp.where(v > 0, idx, -1).astype(jnp.int32)            # [M+1, <=D]
    if feat.shape[1] < dmax:
        feat = jnp.pad(feat, ((0, 0), (0, dmax - feat.shape[1])),
                       constant_values=-1)
    nfeat = jnp.sum(feat >= 0, axis=1).astype(jnp.int32)          # [M+1]

    # ---- chunked accumulation of X'HX, X'g, usable-row counts ----
    pad = (-n) % chunk
    nc = (n + pad) // chunk
    rawp = jnp.pad(raw, ((0, pad), (0, 0)))
    leafp = jnp.pad(row_node, (0, pad), constant_values=m1 - 1)
    gp = jnp.pad(grad, (0, pad))
    hp = jnp.pad(hess, (0, pad))
    cp = jnp.pad(cnt_weight, (0, pad))

    def step(carry, inp):
        xthx, xtg, nz = carry
        rawc, leafc, gc, hc, cc = inp
        lf = feat[leafc]                                          # [C, D]
        fm = lf >= 0
        xg = jnp.take_along_axis(rawc, jnp.clip(lf, 0, f - 1), axis=1)
        nanr = jnp.any(jnp.isnan(xg) & fm, axis=1)
        x = jnp.where(fm & ~jnp.isnan(xg), xg, 0.0)
        xt = jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], 1)
        vrow = (~nanr) & (cc > 0)
        wh = jnp.where(vrow, hc, 0.0)
        wg = jnp.where(vrow, gc, 0.0)
        outer = xt[:, :, None] * xt[:, None, :] * wh[:, None, None]
        xthx = xthx.at[leafc].add(outer)
        xtg = xtg.at[leafc].add(xt * wg[:, None])
        nz = nz.at[leafc].add(vrow.astype(jnp.int32))
        return (xthx, xtg, nz), None

    init = (jnp.zeros((m1, d1, d1), jnp.float32),
            jnp.zeros((m1, d1), jnp.float32),
            jnp.zeros(m1, jnp.int32))
    (xthx, xtg, nz), _ = jax.lax.scan(
        step, init,
        (rawp.reshape(nc, chunk, f), leafp.reshape(nc, chunk),
         gp.reshape(nc, chunk), hp.reshape(nc, chunk),
         cp.reshape(nc, chunk)))

    # ---- batched ridge solve ----
    lam_diag = jnp.concatenate(
        [jnp.full(dmax, 1.0, jnp.float32), jnp.zeros(1, jnp.float32)])
    a = xthx + (linear_lambda * jnp.diag(lam_diag))[None]
    # inactive feature slots: identity row/col + zero rhs => coeff 0
    active = jnp.concatenate([feat >= 0, jnp.ones((m1, 1), bool)], axis=1)
    pair = active[:, :, None] & active[:, None, :]
    a = jnp.where(pair, a, jnp.eye(d1, dtype=jnp.float32)[None])
    rhs = jnp.where(active, xtg, 0.0)
    sol = -jnp.linalg.solve(a, rhs[..., None])[..., 0]            # [M+1, D+1]

    ok = (tree.is_leaf & (nfeat > 0) & (nz >= nfeat + 1) &
          jnp.all(jnp.isfinite(sol), axis=1))
    const = jnp.where(ok, sol[:, dmax], tree.leaf_value)
    coeff = jnp.where(ok[:, None], sol[:, :dmax], 0.0)
    coeff = jnp.where(feat >= 0, coeff, 0.0)
    nfeat = jnp.where(ok, nfeat, 0)
    return LinearLeaves(const=const, coeff=coeff,
                        feat=jnp.where(nfeat[:, None] > 0, feat, -1),
                        nfeat=nfeat)


@jax.jit
def linear_leaf_values(tree: TreeArrays, lin: LinearLeaves,
                       leaf: jax.Array, raw: jax.Array) -> jax.Array:
    """[N] leaf-model outputs for rows routed to `leaf`; NaN in any model
    feature falls back to the constant leaf_value (tree.cpp:133-150)."""
    f = raw.shape[1]
    lf = lin.feat[leaf]                                           # [N, D]
    fm = lf >= 0
    xg = jnp.take_along_axis(raw, jnp.clip(lf, 0, f - 1), axis=1)
    nanr = jnp.any(jnp.isnan(xg) & fm, axis=1)
    x = jnp.where(fm & ~jnp.isnan(xg), xg, 0.0)
    val = lin.const[leaf] + jnp.sum(lin.coeff[leaf] * x, axis=1)
    return jnp.where(nanr, tree.leaf_value[leaf], val)
