"""Monotone-constraint bound recomputation (intermediate / advanced).

The reference implements three constraint methods
(src/treelearner/monotone_constraints.hpp:327 LeafConstraintsBase::Create):

- ``basic`` (:463): at each monotone split, cap/floor both children at the
  midpoint of their outputs. Incremental, order-independent — implemented
  inline in the growers.
- ``intermediate`` (:514): seed children bounds with the *actual* sibling
  outputs and, whenever outputs change, walk the tree to refresh the
  bounds of opposite-subtree leaves and re-find their best splits
  (GoUpToFindLeavesToUpdate :622, leaves_to_update).
- ``advanced`` (:856): additionally make bounds threshold-dependent so
  only the *contiguous* part of the opposite subtree constrains a leaf.

The reference's sequential pointer-chasing refresh is hostile to XLA, so
the TPU design recomputes EVERY leaf's bounds from the whole tree each
leaf-wise iteration — O(nodes^2) dense boolean/matmul work on arrays
<= ~1k wide, microseconds on an MXU and equivalent to the incremental
refresh at its fixed point:

- ``intermediate`` here: a leaf in the left subtree of an increasing
  monotone split is bounded above by the MINIMUM current leaf value of
  the right subtree (and symmetrically). Slightly more conservative than
  the reference's contiguity-refined refresh, strictly looser than
  ``basic``'s midpoints.
- ``advanced`` here: exact region adjacency — each leaf is a bin-space
  box (derived from its ancestor thresholds); only leaves whose boxes
  ADJOIN it along a monotone feature (touching in that feature,
  overlapping in all others) bound it. This is the precise pairwise
  condition for a monotone piecewise-constant tree, i.e. the limit the
  reference's advanced method approximates.

Both require leaf-wise (one split per iteration) growth: simultaneous
batched splits of adjacent leaves could legally move past each other
within bounds computed at pass start. The growers enforce that.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["recompute_bounds"]


def recompute_bounds(tree, monotone: jax.Array, num_bins: jax.Array, *,
                     method: str, missing_is_nan=None):
    """Per-node monotone output bounds from the current tree.

    Args:
      tree: TreeArrays ([M+1] arrays incl. the scratch row).
      monotone: [F] int8/int32 constraint direction per feature.
      num_bins: [F] per-feature bin counts (advanced box bounds).
      method: "intermediate" | "advanced".
      missing_is_nan: [F] bool — features whose LAST bin is the NaN bin.
        The NaN bin sits outside the numeric order, so advanced box
        extents exclude it: adjacency is evaluated in threshold space
        only (a leaf collecting NaN rows is not "above" the numeric top).

    Returns:
      (cons_min, cons_max): [M+1] f32 bounds (±inf where unconstrained).
    """
    m1 = tree.parent.shape[0]
    f = monotone.shape[0]
    ids = jnp.arange(m1, dtype=jnp.int32)
    par = jnp.clip(tree.parent, 0, m1 - 1)
    nonroot = tree.parent >= 0

    # parent one-hot and left/right child masks                  [m1, m1]
    P = (par[:, None] == ids[None, :]) & nonroot[:, None]
    is_leftc = (tree.left[par] == ids) & nonroot
    L0 = P & is_leftc[:, None]
    R0 = P & (~is_leftc)[:, None]

    # ancestor-or-self closure by log2 matrix squaring (parent chains
    # compose exactly because each row has a single parent)
    A = (P | (ids[:, None] == ids[None, :])).astype(jnp.float32)
    for _ in range(max(1, (m1 - 1).bit_length())):
        A = jnp.minimum(A @ A, 1.0)
    left_of = (A @ L0.astype(jnp.float32)) > 0.5             # [m1, m1]
    right_of = (A @ R0.astype(jnp.float32)) > 0.5

    leaf = tree.is_leaf
    val = tree.leaf_value.astype(jnp.float32)
    inf = jnp.float32(jnp.inf)

    feat_j = jnp.clip(tree.split_feature, 0, f - 1)
    is_num_split = (tree.left >= 0) & ~tree.is_cat
    mono_j = jnp.where(is_num_split, monotone[feat_j], 0)    # [m1]

    if method == "intermediate":
        def subtree_ext(mask, sign):
            v = jnp.where(mask & leaf[:, None], sign * val[:, None], inf)
            return sign * jnp.min(v, axis=0)                 # [m1] (of j)

        min_l = subtree_ext(left_of, 1.0)
        max_l = subtree_ext(left_of, -1.0)
        min_r = subtree_ext(right_of, 1.0)
        max_r = subtree_ext(right_of, -1.0)

        up = (mono_j > 0)[None, :]
        dn = (mono_j < 0)[None, :]
        cap = jnp.minimum(
            jnp.where(left_of & up, min_r[None, :], inf),
            jnp.where(right_of & dn, min_l[None, :], inf))
        flo = jnp.maximum(
            jnp.where(right_of & up, max_l[None, :], -inf),
            jnp.where(left_of & dn, max_r[None, :], -inf))
        return jnp.max(flo, axis=1), jnp.min(cap, axis=1)

    if method != "advanced":
        raise ValueError(f"unknown monotone method {method!r}")

    # ---- advanced: bin-space boxes + exact adjacency ----
    thr = tree.threshold_bin.astype(jnp.int32)
    cons_min = jnp.full(m1, -inf)
    cons_max = jnp.full(m1, inf)
    if missing_is_nan is None:
        top_bin = num_bins.astype(jnp.int32) - 1
    else:
        top_bin = num_bins.astype(jnp.int32) - 1 - \
            missing_is_nan.astype(jnp.int32)
    lo = jnp.zeros((m1, f), jnp.int32)
    hi = jnp.broadcast_to(top_bin[None, :], (m1, f))
    # box per node: ancestors' thresholds refine the interval on their
    # split feature (right child: f > thr; left child: f <= thr)
    for g in range(f):
        mask_j = is_num_split & (feat_j == g)
        lo_g = jnp.max(jnp.where(right_of & mask_j[None, :],
                                 (thr + 1)[None, :], 0), axis=1)
        hi_g = jnp.min(jnp.where(left_of & mask_j[None, :], thr[None, :],
                                 top_bin[g]), axis=1)
        lo = lo.at[:, g].set(lo_g)
        hi = hi.at[:, g].set(hi_g)

    # pairwise overlap count over features (for all-but-one tests)
    ov_cnt = jnp.zeros((m1, m1), jnp.int32)
    ovs = []
    for g in range(f):
        ov_g = (lo[:, None, g] <= hi[None, :, g]) & \
               (lo[None, :, g] <= hi[:, None, g])            # [m1, m1]
        ovs.append(ov_g)
        ov_cnt = ov_cnt + ov_g.astype(jnp.int32)

    kleaf = leaf[None, :]
    for g in range(f):
        ov_exc = (ov_cnt == f) | ((ov_cnt == f - 1) & ~ovs[g])
        adj_above = kleaf & ov_exc & \
            (hi[:, None, g] + 1 == lo[None, :, g])           # [i, k]
        adj_below = kleaf & ov_exc & \
            (lo[:, None, g] == hi[None, :, g] + 1)
        min_above = jnp.min(jnp.where(adj_above, val[None, :], inf),
                            axis=1)
        max_above = jnp.max(jnp.where(adj_above, val[None, :], -inf),
                            axis=1)
        min_below = jnp.min(jnp.where(adj_below, val[None, :], inf),
                            axis=1)
        max_below = jnp.max(jnp.where(adj_below, val[None, :], -inf),
                            axis=1)
        up = monotone[g] > 0
        dn = monotone[g] < 0
        # increasing: value(i) <= values above along g, >= values below
        cons_max = jnp.where(up, jnp.minimum(cons_max, min_above),
                             cons_max)
        cons_min = jnp.where(up, jnp.maximum(cons_min, max_below),
                             cons_min)
        # decreasing: value(i) <= values below, >= values above
        cons_max = jnp.where(dn, jnp.minimum(cons_max, min_below),
                             cons_max)
        cons_min = jnp.where(dn, jnp.maximum(cons_min, max_above),
                             cons_min)
    return cons_min, cons_max
