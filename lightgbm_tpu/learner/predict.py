"""Device tree traversal for scoring binned rows.

Reference prediction path: Tree::Predict with NumericalDecision /
CategoricalDecision per row (include/LightGBM/tree.h:335-412), OMP over rows
(predictor.hpp:30). TPU-native version: all rows advance one level per step
of a `lax.while_loop` — a vectorized pointer-chase over the tree arrays; the
loop exits when every row sits on a leaf. Inputs are BINNED values (new data
is quantized with the training BinMappers first), which makes device
decisions exact integer compares instead of float threshold compares.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .grower import TreeArrays

__all__ = ["predict_binned_tree", "predict_binned_forest",
           "leaf_index_tree", "leaf_node_tree"]


def _traverse(tree: TreeArrays, bins: jax.Array, num_bins: jax.Array,
              missing_is_nan: jax.Array, efb=None) -> jax.Array:
    """Return [N] leaf node id for each row. With `efb`, bins is the
    bundled [N, Fb] matrix and decisions translate through the bundle
    tables (efb.py route_bins) — node semantics stay in original
    feature space."""
    n = bins.shape[0]
    f = num_bins.shape[0]

    def cond(node):
        return jnp.any(tree.split_feature[node] >= 0)

    def body(node):
        feat = tree.split_feature[node]
        internal = feat >= 0
        fc = jnp.clip(feat, 0, f - 1)
        if efb is not None:
            from ..efb import route_bins
            binv = route_bins(bins, fc, efb)
        else:
            binv = jnp.take_along_axis(bins, fc[:, None], axis=1)[:, 0] \
                .astype(jnp.int32)
        thr = tree.threshold_bin[node]
        isc = tree.is_cat[node]
        is_nan_bin = missing_is_nan[fc] & (binv == num_bins[fc] - 1)
        bitw = tree.cat_bitset[node, binv // 32]
        in_set = ((bitw >> (binv % 32).astype(jnp.uint32)) &
                  jnp.uint32(1)) == 1
        go_left = jnp.where(
            isc, in_set,
            jnp.where(is_nan_bin, tree.default_left[node], binv <= thr))
        nxt = jnp.where(go_left, tree.left[node], tree.right[node])
        return jnp.where(internal, nxt, node)

    node0 = jnp.zeros(n, jnp.int32)
    return jax.lax.while_loop(cond, body, node0)


@jax.jit
def predict_binned_tree(tree: TreeArrays, bins: jax.Array,
                        num_bins: jax.Array,
                        missing_is_nan: jax.Array,
                        efb=None, row_valid=None) -> jax.Array:
    """[N] leaf values of one tree.

    `row_valid` ([N] bool, optional) marks pad rows inert: their output is
    exactly 0.0. Real rows are untouched — every traversal op is
    elementwise per row (the while_loop predicate only controls trip
    count, and settled rows are fixed points of the body), so a
    bucket-padded batch returns bit-identical values on its real rows.
    """
    leaf = _traverse(tree, bins, num_bins, missing_is_nan, efb)
    vals = tree.leaf_value[leaf]
    if row_valid is not None:
        vals = jnp.where(row_valid, vals, jnp.float32(0.0))
    return vals


@jax.jit
def leaf_node_tree(tree: TreeArrays, bins: jax.Array, num_bins: jax.Array,
                   missing_is_nan: jax.Array, efb=None) -> jax.Array:
    """[N] leaf NODE id per row (for linear-leaf model lookup)."""
    return _traverse(tree, bins, num_bins, missing_is_nan, efb)


@jax.jit
def leaf_index_tree(tree: TreeArrays, bins: jax.Array, num_bins: jax.Array,
                    missing_is_nan: jax.Array) -> jax.Array:
    """[N] leaf *index* (0..num_leaves-1 in node-id order) for predict_leaf_index.

    Leaf numbering: leaves ordered by node id, matching the order leaves are
    materialized in the serialized model (tree.py assigns the same order)."""
    leaf_node = _traverse(tree, bins, num_bins, missing_is_nan)
    is_leaf_node = tree.split_feature < 0
    leaf_rank = jnp.cumsum(is_leaf_node.astype(jnp.int32)) - 1
    return leaf_rank[leaf_node]


@functools.partial(jax.jit, static_argnames=("num_outputs",))
def predict_binned_forest(stacked: TreeArrays, tree_class: jax.Array,
                          bins: jax.Array, num_bins: jax.Array,
                          missing_is_nan: jax.Array,
                          num_outputs: int = 1,
                          row_valid=None) -> jax.Array:
    """Sum leaf values over a stacked forest.

    stacked: TreeArrays whose fields have a leading tree axis [T, ...].
    tree_class: [T] output column each tree adds to (multiclass).
    row_valid: [N] bool, optional. Pad rows (False) accumulate exactly
    0.0 in every output column while real rows stay bit-identical to an
    unpadded batch (per-row elementwise traversal; see
    predict_binned_tree). This is what lets the serving engine pad
    batches up to shape buckets without perturbing scores.
    Returns [N, num_outputs] raw scores.
    """
    n = bins.shape[0]
    t = stacked.leaf_value.shape[0]

    def body(i, acc):
        tree = jax.tree_util.tree_map(lambda a: a[i], stacked)
        vals = predict_binned_tree(tree, bins, num_bins, missing_is_nan,
                                   row_valid=row_valid)
        return acc.at[:, tree_class[i]].add(vals)

    out = jnp.zeros((n, num_outputs), jnp.float32)
    return jax.lax.fori_loop(0, t, body, out)
