"""Leaf-output renewal: per-leaf percentile re-fit for L1-type objectives.

Reference: RegressionL1loss::RenewTreeOutput and friends
(regression_objective.hpp; called from serial_tree_learner.cpp:721-758,
synced across ranks by GlobalSum there). The reference nth_element's each
leaf's residuals on host threads; here it is a device-wide double argsort
(residual, then stable by leaf) + segmented weighted-quantile lookup — one
fused op for all leaves, no per-leaf gathers.

Quantile convention: smallest element whose cumulative weight reaches
`pct * total_weight` of the leaf (the reference's weighted PercentileFun;
for unweighted data the reference linearly interpolates — the lower-bound
convention here differs by at most one residual step).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .grower import TreeArrays

__all__ = ["renew_tree_output"]


@functools.partial(jax.jit, static_argnames=("pct", "num_leaves"))
def renew_tree_output(tree: TreeArrays, row_node: jax.Array,
                      score: jax.Array, label: jax.Array,
                      weight: jax.Array, pct: float,
                      num_leaves: int) -> TreeArrays:
    """Replace leaf values with the pct-percentile of in-leaf residuals.

    weight: per-row weight (bagging cnt x data weight); 0 excludes a row.
    """
    m1 = tree.leaf_value.shape[0]
    n = row_node.shape[0]
    residual = label - score
    node = jnp.where(weight > 0, row_node, m1 - 1)  # out-of-bag -> scratch

    # group rows by node with residuals ascending inside each group
    o1 = jnp.argsort(residual, stable=True)
    node_o1 = node[o1]
    o2 = jnp.argsort(node_o1, stable=True)
    perm = o1[o2]
    s_node = node[perm]
    s_resid = residual[perm]
    s_w = weight[perm]

    total_w = jax.ops.segment_sum(weight, node, num_segments=m1)
    cum_w = jnp.cumsum(s_w)
    seg_start = jnp.searchsorted(s_node, jnp.arange(m1), side="left")
    cum_before = jnp.where(seg_start > 0, cum_w[jnp.maximum(seg_start - 1, 0)],
                           0.0)
    # rows whose in-segment cumweight reaches the target
    target = pct * total_w
    reach = (cum_w - cum_before[s_node]) >= target[s_node] - 1e-12
    pos = jnp.where(reach, jnp.arange(n), n)
    first_pos = jax.ops.segment_min(pos, s_node, num_segments=m1)
    first_pos = jnp.clip(first_pos, 0, n - 1)
    leaf_pct = s_resid[first_pos]

    ok = (tree.split_feature < 0) & (total_w > 0)
    new_vals = jnp.where(ok, leaf_pct, tree.leaf_value)
    return tree._replace(leaf_value=new_vals)
