"""Vectorized best-split search over histograms.

Replaces the reference's per-feature sequential gain scans
(FeatureHistogram::FindBestThresholdSequentially, feature_histogram.hpp:85-270
— a compile-time-specialized template over {L1, max_delta_step, smoothing,
missing-type, NA-direction}) with ONE batched computation over
[slots, features, bins]: cumulative sums along the bin axis, the closed-form
gain at every threshold, NA-left/NA-right evaluated as two masked variants,
and a flat argmax. Categorical splits (feature_histogram.hpp:278-485) use
the one-hot scan for low-cardinality features and the sorted-by-ratio
two-direction scan otherwise, emitting the left set as a bin bitset.

All math follows feature_histogram.hpp:737-860:
  ThresholdL1(s, l1) = sign(s) * max(|s| - l1, 0)
  output  = -ThresholdL1(g, l1) / (h + l2)            (clipped by max_delta_step,
                                                       smoothed toward parent)
  gain(output) = -(2 * ThresholdL1(g, l1) * output + (h + l2) * output^2)
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["SplitHyperParams", "BestSplits", "find_best_splits",
           "leaf_output", "leaf_gain"]


@dataclasses.dataclass(frozen=True)
class SplitHyperParams:
    """Static split-search hyperparameters (subset of Config)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    max_delta_step: float = 0.0
    path_smooth: float = 0.0
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    has_monotone: bool = False     # enables the constrained-output gain path
    monotone_penalty: float = 0.0
    extra_trees: bool = False      # one random threshold per (slot, feature)
    has_categorical: bool = False  # enables the categorical scan paths


class BestSplits(NamedTuple):
    """Per-slot best split (reference SplitInfo, split_info.hpp:22)."""
    gain: jax.Array          # [S] split gain (already minus gain_shift)
    feature: jax.Array       # [S] used-feature index, -1 if none
    threshold_bin: jax.Array  # [S] bin t: numerical left iff bin <= t
    default_left: jax.Array  # [S] bool, NaN direction
    left_grad: jax.Array     # [S]
    left_hess: jax.Array
    left_count: jax.Array
    left_output: jax.Array   # [S]
    right_output: jax.Array  # [S]
    per_feature_gain: jax.Array  # [S, F] best gain per feature (for voting)
    cat_bitset: jax.Array    # [S, W] uint32; categorical: bin in set -> left


def _threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(g, h, l1, l2, max_delta_step=0.0, path_smooth=0.0,
                count=None, parent_output=None):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:743-764)."""
    ret = -_threshold_l1(g, l1) / (h + l2)
    if max_delta_step > 0:
        ret = jnp.clip(ret, -max_delta_step, max_delta_step)
    if path_smooth > 0 and count is not None and parent_output is not None:
        n_over = count / path_smooth
        ret = ret * n_over / (n_over + 1.0) + parent_output / (n_over + 1.0)
    return ret


def _gain_given_output(g, h, l1, l2, output):
    """GetLeafGainGivenOutput (feature_histogram.hpp:851-860)."""
    sg = _threshold_l1(g, l1)
    return -(2.0 * sg * output + (h + l2) * output * output)


def leaf_gain(g, h, l1, l2, max_delta_step=0.0, path_smooth=0.0,
              count=None, parent_output=None):
    """GetLeafGain (feature_histogram.hpp:826-842)."""
    if max_delta_step <= 0 and path_smooth <= 0:
        sg = _threshold_l1(g, l1)
        return (sg * sg) / (h + l2)
    out = leaf_output(g, h, l1, l2, max_delta_step, path_smooth, count,
                      parent_output)
    return _gain_given_output(g, h, l1, l2, out)


def _split_gain(lg, lh, lc, rg, rh, rc, l1, l2, hp: SplitHyperParams,
                parent_output):
    """GetSplitGains without monotone (feature_histogram.hpp:785-806)."""
    return (leaf_gain(lg, lh, l1, l2, hp.max_delta_step, hp.path_smooth,
                      lc, parent_output) +
            leaf_gain(rg, rh, l1, l2, hp.max_delta_step, hp.path_smooth,
                      rc, parent_output))


def _monotone_penalty_factor(depth: jax.Array, p: float) -> jax.Array:
    """ComputeMonotoneSplitGainPenalty (monotone_constraints.hpp:355-364)."""
    eps = 1e-10
    d = depth.astype(jnp.float32)
    small = 1.0 - p / jnp.exp2(d) + eps
    large = 1.0 - jnp.exp2(p - 1.0 - d) + eps
    out = jnp.where(p <= 1.0, small, large)
    return jnp.where(p >= d + 1.0, eps, out)


@functools.partial(jax.jit, static_argnames=("hp",))
def find_best_splits(hist: jax.Array, parent_grad: jax.Array,
                     parent_hess: jax.Array, parent_count: jax.Array,
                     parent_output: jax.Array, num_bins: jax.Array,
                     missing_is_nan: jax.Array, is_cat: jax.Array,
                     feature_mask: jax.Array,
                     hp: SplitHyperParams,
                     monotone: jax.Array = None,
                     cons_min: jax.Array = None,
                     cons_max: jax.Array = None,
                     depth: jax.Array = None,
                     rand_bins: jax.Array = None,
                     gain_penalty: jax.Array = None) -> BestSplits:
    """Find the best split per slot.

    Args:
      hist: [S, F, B, 3] (grad, hess, count) histograms.
      parent_*: [S] node aggregates; parent_output: [S] node output value.
      num_bins: [F] per-feature bin counts (incl. NaN bin when present).
      missing_is_nan: [F] bool, feature has a trailing NaN bin.
      is_cat: [F] bool.
      feature_mask: [F] or [S, F] float/bool — 0 disables a feature
        (feature_fraction / feature-parallel shard / voting selection).
      gain_penalty: optional [S, F] gain subtracted per (slot, feature)
        after threshold selection — the CEGB DeltaGain hook (reference
        SerialTreeLearner::FindBestSplitsFromHistograms subtracting
        CostEfficientGradientBoosting::DetlaGain,
        cost_effective_gradient_boosting.hpp:46-70).
    """
    s, f, b, _ = hist.shape
    l1, l2 = hp.lambda_l1, hp.lambda_l2
    bins_r = jnp.arange(b, dtype=jnp.int32)

    # prefix sums along bins as a triangular-matrix contraction: XLA's
    # cumsum lowering is a serial/log-shift chain that measured ~2 orders
    # of magnitude slower than the MXU on this backend (it dominated tree
    # time); Precision.HIGHEST (bf16x6) keeps f32-equivalent accuracy
    tri = (bins_r[:, None] <= bins_r[None, :]).astype(jnp.float32)

    def cumsum_bins(x):                                        # [S,F,B,C]
        return jnp.einsum("sfbc,bt->sftc", x, tri,
                          precision=jax.lax.Precision.HIGHEST)
    # normalize feature_mask to [S, F]
    fmask = jnp.broadcast_to(
        feature_mask.astype(jnp.float32).reshape(
            (1, f) if feature_mask.ndim == 1 else (s, f)), (s, f))

    tot = jnp.stack([parent_grad, parent_hess, parent_count], -1)  # [S, 3]
    tot = tot[:, None, None, :]                                    # [S,1,1,3]

    # gain_shift: unsmoothed closed-form gain of the unsplit node
    # (feature_histogram.hpp:295-301 passes USE_SMOOTHING=false here)
    gain_shift = leaf_gain(parent_grad, parent_hess, l1, l2,
                           hp.max_delta_step)                      # [S]
    min_gain_shift = gain_shift + hp.min_gain_to_split

    # ---------- numerical features ----------
    prefix = cumsum_bins(hist)                                     # [S,F,B,3]
    nan_idx = jnp.maximum(num_bins - 1, 0)
    nan_sums = jnp.take_along_axis(
        hist, nan_idx[None, :, None, None].astype(jnp.int32),
        axis=2)                                                    # [S,F,1,3]
    nan_sums = jnp.where(missing_is_nan[None, :, None, None], nan_sums, 0.0)

    # threshold t valid iff t <= num_bins-2 (-1 more when NaN bin present)
    t_limit = num_bins - 2 - missing_is_nan.astype(jnp.int32)      # [F]
    valid_t = bins_r[None, None, :] <= t_limit[None, :, None]      # [1,F,B]
    valid_t = valid_t & (~is_cat[None, :, None]) & \
        (fmask[:, :, None] > 0)                                    # [S,F,B]
    if hp.extra_trees and rand_bins is not None:
        # extra-trees: evaluate ONE random threshold per (slot, feature)
        # (reference USE_RAND specialization, feature_histogram.hpp:85)
        valid_t = valid_t & (bins_r[None, None, :] ==
                             (rand_bins % jnp.maximum(t_limit + 1, 1)
                              [None, :])[:, :, None])

    def eval_option(left):                                         # [S,F,B,3]
        right = tot - left
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = right[..., 0], right[..., 1], right[..., 2]
        ok = ((lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf) &
              (lh >= hp.min_sum_hessian_in_leaf) &
              (rh >= hp.min_sum_hessian_in_leaf))
        if hp.has_monotone:
            # constrained-output gain path (GetSplitGains USE_MC branch,
            # feature_histogram.hpp:806-824): clamp child outputs to the
            # node's [min, max] constraint, kill order-violating splits
            po = parent_output[:, None, None]
            lout = leaf_output(lg, lh, l1, l2, hp.max_delta_step,
                               hp.path_smooth, lc, po)
            rout = leaf_output(rg, rh, l1, l2, hp.max_delta_step,
                               hp.path_smooth, rc, po)
            cmin = cons_min[:, None, None]
            cmax = cons_max[:, None, None]
            lout = jnp.clip(lout, cmin, cmax)
            rout = jnp.clip(rout, cmin, cmax)
            mc = monotone[None, :, None]
            violate = ((mc > 0) & (lout > rout)) | \
                      ((mc < 0) & (lout < rout))
            g = _gain_given_output(lg, lh, l1, l2, lout) + \
                _gain_given_output(rg, rh, l1, l2, rout)
            if hp.monotone_penalty > 0:
                pen = _monotone_penalty_factor(depth, hp.monotone_penalty)
                g = jnp.where(mc != 0, g * pen[:, None, None], g)
            g = jnp.where(violate, -jnp.inf, g)
        else:
            g = _split_gain(lg, lh, lc, rg, rh, rc, l1, l2, hp,
                            parent_output[:, None, None])
        return jnp.where(ok & valid_t, g, -jnp.inf)

    gain_na_right = eval_option(prefix)                       # NaN stays right
    gain_na_left = jnp.where(
        missing_is_nan[None, :, None],
        eval_option(prefix + nan_sums), -jnp.inf)             # NaN joins left

    # ---------- categorical ----------
    # One-hot branch for low-cardinality features, sorted-by-ratio two-way
    # scan otherwise, mirroring FindBestThresholdCategoricalInner
    # (feature_histogram.hpp:278-485): one-hot gains use the ORIGINAL l2,
    # sorted gains use l2 + cat_l2, gain_shift uses the original l2 in both;
    # sorted scan keeps bins with count >= cat_smooth, sorts ascending by
    # g/(h + cat_smooth), scans from both ends up to
    # min(max_cat_threshold, (used+1)/2) categories. Bin 0 (unseen/NaN)
    # always stays right. For a threshold at sorted position p the left set
    # is the first p+1 bins in scan direction, emitted as a bin bitset.
    # Deviation from the reference: the min_data_per_group group-batching
    # (which merges tiny categories between gain evaluations) is applied
    # only as a right-side floor, not as evaluation batching.
    cl2 = l2 + hp.cat_l2
    use_onehot_f = num_bins <= hp.max_cat_to_onehot                # [F]
    cat_basic_valid = (bins_r[None, None, :] >= 1) & \
        (bins_r[None, None, :] < num_bins[None, :, None])
    if hp.has_categorical:
        po3 = parent_output[:, None, None]
        # -- one-hot (original l2, feature_histogram.hpp:318-372) --
        lg, lh, lc = hist[..., 0], hist[..., 1], hist[..., 2]
        rg = tot[..., 0] - lg
        rh = tot[..., 1] - lh
        rc = tot[..., 2] - lc
        oh_ok = ((lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf) &
                 (lh >= hp.min_sum_hessian_in_leaf) &
                 (rh >= hp.min_sum_hessian_in_leaf))
        onehot_gain = (leaf_gain(lg, lh, l1, l2, hp.max_delta_step,
                                 hp.path_smooth, lc, po3) +
                       leaf_gain(rg, rh, l1, l2, hp.max_delta_step,
                                 hp.path_smooth, rc, po3))
        onehot_gain = jnp.where(oh_ok & cat_basic_valid, onehot_gain,
                                -jnp.inf)
        # -- sorted two-direction scan (l2 + cat_l2) --
        cnt3 = hist[..., 2]
        sort_ok = cat_basic_valid & (cnt3 >= hp.cat_smooth)
        ratio = jnp.where(sort_ok,
                          hist[..., 0] / (hist[..., 1] + hp.cat_smooth),
                          jnp.inf)
        used_bin = jnp.sum(sort_ok, axis=2)                        # [S,F]
        max_num_cat = jnp.minimum(hp.max_cat_threshold,
                                  (used_bin + 1) // 2)             # [S,F]
        pos_limit = jnp.minimum(used_bin, max_num_cat)[:, :, None]
        min_rc = max(hp.min_data_in_leaf, hp.min_data_per_group)

        def scan_dir(order):
            sh = jnp.take_along_axis(hist, order[..., None], axis=2)
            sp = cumsum_bins(sh)                                   # [S,F,B,3]
            slg, slh, slc = sp[..., 0], sp[..., 1], sp[..., 2]
            srg = tot[..., 0] - slg
            srh = tot[..., 1] - slh
            src = tot[..., 2] - slc
            ok = ((bins_r[None, None, :] < pos_limit) &
                  (slc >= hp.min_data_in_leaf) &
                  (slh >= hp.min_sum_hessian_in_leaf) &
                  (src >= min_rc) & (srh >= hp.min_sum_hessian_in_leaf))
            g = (leaf_gain(slg, slh, l1, cl2, hp.max_delta_step,
                           hp.path_smooth, slc, po3) +
                 leaf_gain(srg, srh, l1, cl2, hp.max_delta_step,
                           hp.path_smooth, src, po3))
            return jnp.where(ok, g, -jnp.inf), sp

        order_a = jnp.argsort(ratio, axis=2)
        order_d = jnp.argsort(jnp.where(sort_ok, -ratio, jnp.inf), axis=2)
        gain_a, sp_a = scan_dir(order_a)
        gain_d, sp_d = scan_dir(order_d)
        sorted_gain = jnp.maximum(gain_a, gain_d)
        cat_dir_bwd = gain_d > gain_a                              # [S,F,B]
        cat_gain = jnp.where(use_onehot_f[None, :, None], onehot_gain,
                             sorted_gain)
        cat_gain = jnp.where(
            is_cat[None, :, None] & (fmask[:, :, None] > 0) &
            (cat_gain > min_gain_shift[:, None, None]), cat_gain, -jnp.inf)
    else:
        cat_gain = jnp.full((s, f, b), -jnp.inf)
        cat_dir_bwd = jnp.zeros((s, f, b), bool)
        sp_a = sp_d = None
        order_a = order_d = None

    # ---------- combine & argmax ----------
    num_gain = jnp.maximum(gain_na_right, gain_na_left)
    num_gain = jnp.where(num_gain > min_gain_shift[:, None, None],
                         num_gain, -jnp.inf)
    all_gain = jnp.where(is_cat[None, :, None], cat_gain, num_gain)  # [S,F,B]
    if gain_penalty is not None:
        # constant across thresholds of one feature, so the per-feature
        # argmax is unchanged; only cross-feature competition and the
        # stored/selection gain see the penalty (as in the reference)
        all_gain = all_gain - gain_penalty[:, :, None]

    flat = all_gain.reshape(s, f * b)
    best_idx = jnp.argmax(flat, axis=1)                            # [S]
    best_gain = jnp.take_along_axis(flat, best_idx[:, None], 1)[:, 0]
    best_f = (best_idx // b).astype(jnp.int32)
    best_t = (best_idx % b).astype(jnp.int32)
    has_split = jnp.isfinite(best_gain)

    sel = (jnp.arange(s), best_f, best_t)
    chose_na_left = gain_na_left[sel] >= gain_na_right[sel]
    best_is_cat = is_cat[best_f]
    num_left = jnp.where(chose_na_left[:, None], (prefix + nan_sums)[sel],
                         prefix[sel])                              # [S, 3]
    w = (b + 31) // 32
    if hp.has_categorical:
        use_oh = use_onehot_f[best_f]                              # [S]
        dir_bwd = cat_dir_bwd[sel]                                 # [S]
        sorted_left = jnp.where(dir_bwd[:, None], sp_d[sel], sp_a[sel])
        cat_left = jnp.where(use_oh[:, None], hist[sel], sorted_left)
        left = jnp.where(best_is_cat[:, None], cat_left, num_left)
        # best one-hot split uses original l2; sorted uses l2 + cat_l2
        # (feature_histogram.hpp:384,476-489)
        eff_l2 = jnp.where(best_is_cat & ~use_oh, cl2, l2)
        # bin bitset of the left set: one-hot -> {best_t}; sorted -> the
        # first best_t+1 bins in the winning scan direction. Only the best
        # feature's row per slot is needed, so gather the [S, B] permutation
        # first and invert that (not the full [S, F, B] orders).
        order_sel = jnp.where(
            dir_bwd[:, None],
            order_d[jnp.arange(s), best_f], order_a[jnp.arange(s), best_f])
        rank_sel = jnp.zeros((s, b), jnp.int32).at[
            jnp.arange(s)[:, None], order_sel].set(
            jnp.broadcast_to(bins_r[None, :], (s, b)))  # bin -> sorted pos
        member_sorted = rank_sel <= best_t[:, None]                # [S, B]
        member_oh = bins_r[None, :] == best_t[:, None]
        member = best_is_cat[:, None] & jnp.where(
            use_oh[:, None], member_oh, member_sorted)
        pad = w * 32 - b
        member_p = jnp.pad(member, ((0, 0), (0, pad))) if pad else member
        weights = jnp.left_shift(
            jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32))
        cat_bitset = jnp.sum(
            member_p.reshape(s, w, 32).astype(jnp.uint32) *
            weights[None, None, :], axis=2, dtype=jnp.uint32)      # [S, W]
    else:
        left = num_left
        eff_l2 = l2
        cat_bitset = jnp.zeros((s, w), jnp.uint32)
    lgs, lhs, lcs = left[..., 0], left[..., 1], left[..., 2]
    rgs = parent_grad - lgs
    rhs = parent_hess - lhs
    rcs = parent_count - lcs
    lout = leaf_output(lgs, lhs, l1, eff_l2, hp.max_delta_step,
                       hp.path_smooth, lcs, parent_output)
    rout = leaf_output(rgs, rhs, l1, eff_l2, hp.max_delta_step,
                       hp.path_smooth, rcs, parent_output)
    if hp.has_monotone:
        lout = jnp.clip(lout, cons_min, cons_max)
        rout = jnp.clip(rout, cons_min, cons_max)

    # per-feature best gain (minus the gain shift) for voting
    per_feature_gain = jnp.max(all_gain, axis=2) - gain_shift[:, None]

    return BestSplits(
        gain=jnp.where(has_split, best_gain - gain_shift, -jnp.inf),
        feature=jnp.where(has_split, best_f, -1),
        threshold_bin=best_t,
        default_left=jnp.where(best_is_cat, False, chose_na_left),
        left_grad=lgs, left_hess=lhs, left_count=lcs,
        left_output=lout, right_output=rout,
        per_feature_gain=per_feature_gain,
        cat_bitset=cat_bitset)
