"""Vectorized best-split search over histograms.

Replaces the reference's per-feature sequential gain scans
(FeatureHistogram::FindBestThresholdSequentially, feature_histogram.hpp:85-270
— a compile-time-specialized template over {L1, max_delta_step, smoothing,
missing-type, NA-direction}) with ONE batched computation over
[slots, features, bins]: cumulative sums along the bin axis, the closed-form
gain at every threshold, NA-left/NA-right evaluated as two masked variants,
and a flat argmax. Categorical one-vs-rest scan included
(feature_histogram.hpp:278-485; sorted top-k scan lives in
categorical_sorted_scan below).

All math follows feature_histogram.hpp:737-860:
  ThresholdL1(s, l1) = sign(s) * max(|s| - l1, 0)
  output  = -ThresholdL1(g, l1) / (h + l2)            (clipped by max_delta_step,
                                                       smoothed toward parent)
  gain(output) = -(2 * ThresholdL1(g, l1) * output + (h + l2) * output^2)
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["SplitHyperParams", "BestSplits", "find_best_splits",
           "leaf_output", "leaf_gain"]


@dataclasses.dataclass(frozen=True)
class SplitHyperParams:
    """Static split-search hyperparameters (subset of Config)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    max_delta_step: float = 0.0
    path_smooth: float = 0.0
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    has_monotone: bool = False     # enables the constrained-output gain path
    monotone_penalty: float = 0.0
    extra_trees: bool = False      # one random threshold per (slot, feature)
    has_categorical: bool = False  # enables the categorical scan paths


class BestSplits(NamedTuple):
    """Per-slot best split (reference SplitInfo, split_info.hpp:22)."""
    gain: jax.Array          # [S] split gain (already minus gain_shift)
    feature: jax.Array       # [S] used-feature index, -1 if none
    threshold_bin: jax.Array  # [S] bin t: numerical left iff bin <= t
    default_left: jax.Array  # [S] bool, NaN direction
    left_grad: jax.Array     # [S]
    left_hess: jax.Array
    left_count: jax.Array
    left_output: jax.Array   # [S]
    right_output: jax.Array  # [S]
    per_feature_gain: jax.Array  # [S, F] best gain per feature (for voting)
    cat_bitset: jax.Array    # [S, W] uint32; categorical: bin in set -> left


def _threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(g, h, l1, l2, max_delta_step=0.0, path_smooth=0.0,
                count=None, parent_output=None):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:743-764)."""
    ret = -_threshold_l1(g, l1) / (h + l2)
    if max_delta_step > 0:
        ret = jnp.clip(ret, -max_delta_step, max_delta_step)
    if path_smooth > 0 and count is not None and parent_output is not None:
        n_over = count / path_smooth
        ret = ret * n_over / (n_over + 1.0) + parent_output / (n_over + 1.0)
    return ret


def _gain_given_output(g, h, l1, l2, output):
    """GetLeafGainGivenOutput (feature_histogram.hpp:851-860)."""
    sg = _threshold_l1(g, l1)
    return -(2.0 * sg * output + (h + l2) * output * output)


def leaf_gain(g, h, l1, l2, max_delta_step=0.0, path_smooth=0.0,
              count=None, parent_output=None):
    """GetLeafGain (feature_histogram.hpp:826-842)."""
    if max_delta_step <= 0 and path_smooth <= 0:
        sg = _threshold_l1(g, l1)
        return (sg * sg) / (h + l2)
    out = leaf_output(g, h, l1, l2, max_delta_step, path_smooth, count,
                      parent_output)
    return _gain_given_output(g, h, l1, l2, out)


def _split_gain(lg, lh, lc, rg, rh, rc, l1, l2, hp: SplitHyperParams,
                parent_output):
    """GetSplitGains without monotone (feature_histogram.hpp:785-806)."""
    return (leaf_gain(lg, lh, l1, l2, hp.max_delta_step, hp.path_smooth,
                      lc, parent_output) +
            leaf_gain(rg, rh, l1, l2, hp.max_delta_step, hp.path_smooth,
                      rc, parent_output))


def _monotone_penalty_factor(depth: jax.Array, p: float) -> jax.Array:
    """ComputeMonotoneSplitGainPenalty (monotone_constraints.hpp:355-364)."""
    eps = 1e-10
    d = depth.astype(jnp.float32)
    small = 1.0 - p / jnp.exp2(d) + eps
    large = 1.0 - jnp.exp2(p - 1.0 - d) + eps
    out = jnp.where(p <= 1.0, small, large)
    return jnp.where(p >= d + 1.0, eps, out)


@functools.partial(jax.jit, static_argnames=("hp",))
def find_best_splits(hist: jax.Array, parent_grad: jax.Array,
                     parent_hess: jax.Array, parent_count: jax.Array,
                     parent_output: jax.Array, num_bins: jax.Array,
                     missing_is_nan: jax.Array, is_cat: jax.Array,
                     feature_mask: jax.Array,
                     hp: SplitHyperParams,
                     monotone: jax.Array = None,
                     cons_min: jax.Array = None,
                     cons_max: jax.Array = None,
                     depth: jax.Array = None,
                     rand_bins: jax.Array = None) -> BestSplits:
    """Find the best split per slot.

    Args:
      hist: [S, F, B, 3] (grad, hess, count) histograms.
      parent_*: [S] node aggregates; parent_output: [S] node output value.
      num_bins: [F] per-feature bin counts (incl. NaN bin when present).
      missing_is_nan: [F] bool, feature has a trailing NaN bin.
      is_cat: [F] bool.
      feature_mask: [F] or [S, F] float/bool — 0 disables a feature
        (feature_fraction / feature-parallel shard / voting selection).
    """
    s, f, b, _ = hist.shape
    l1, l2 = hp.lambda_l1, hp.lambda_l2
    bins_r = jnp.arange(b, dtype=jnp.int32)
    # normalize feature_mask to [S, F]
    fmask = jnp.broadcast_to(
        feature_mask.astype(jnp.float32).reshape(
            (1, f) if feature_mask.ndim == 1 else (s, f)), (s, f))

    tot = jnp.stack([parent_grad, parent_hess, parent_count], -1)  # [S, 3]
    tot = tot[:, None, None, :]                                    # [S,1,1,3]

    # gain_shift: unsmoothed closed-form gain of the unsplit node
    # (feature_histogram.hpp:295-301 passes USE_SMOOTHING=false here)
    gain_shift = leaf_gain(parent_grad, parent_hess, l1, l2,
                           hp.max_delta_step)                      # [S]
    min_gain_shift = gain_shift + hp.min_gain_to_split

    # ---------- numerical features ----------
    prefix = jnp.cumsum(hist, axis=2)                              # [S,F,B,3]
    nan_idx = jnp.maximum(num_bins - 1, 0)
    nan_sums = jnp.take_along_axis(
        hist, nan_idx[None, :, None, None].astype(jnp.int32),
        axis=2)                                                    # [S,F,1,3]
    nan_sums = jnp.where(missing_is_nan[None, :, None, None], nan_sums, 0.0)

    # threshold t valid iff t <= num_bins-2 (-1 more when NaN bin present)
    t_limit = num_bins - 2 - missing_is_nan.astype(jnp.int32)      # [F]
    valid_t = bins_r[None, None, :] <= t_limit[None, :, None]      # [1,F,B]
    valid_t = valid_t & (~is_cat[None, :, None]) & \
        (fmask[:, :, None] > 0)                                    # [S,F,B]
    if hp.extra_trees and rand_bins is not None:
        # extra-trees: evaluate ONE random threshold per (slot, feature)
        # (reference USE_RAND specialization, feature_histogram.hpp:85)
        valid_t = valid_t & (bins_r[None, None, :] ==
                             (rand_bins % jnp.maximum(t_limit + 1, 1)
                              [None, :])[:, :, None])

    def eval_option(left):                                         # [S,F,B,3]
        right = tot - left
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = right[..., 0], right[..., 1], right[..., 2]
        ok = ((lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf) &
              (lh >= hp.min_sum_hessian_in_leaf) &
              (rh >= hp.min_sum_hessian_in_leaf))
        if hp.has_monotone:
            # constrained-output gain path (GetSplitGains USE_MC branch,
            # feature_histogram.hpp:806-824): clamp child outputs to the
            # node's [min, max] constraint, kill order-violating splits
            po = parent_output[:, None, None]
            lout = leaf_output(lg, lh, l1, l2, hp.max_delta_step,
                               hp.path_smooth, lc, po)
            rout = leaf_output(rg, rh, l1, l2, hp.max_delta_step,
                               hp.path_smooth, rc, po)
            cmin = cons_min[:, None, None]
            cmax = cons_max[:, None, None]
            lout = jnp.clip(lout, cmin, cmax)
            rout = jnp.clip(rout, cmin, cmax)
            mc = monotone[None, :, None]
            violate = ((mc > 0) & (lout > rout)) | \
                      ((mc < 0) & (lout < rout))
            g = _gain_given_output(lg, lh, l1, l2, lout) + \
                _gain_given_output(rg, rh, l1, l2, rout)
            if hp.monotone_penalty > 0:
                pen = _monotone_penalty_factor(depth, hp.monotone_penalty)
                g = jnp.where(mc != 0, g * pen[:, None, None], g)
            g = jnp.where(violate, -jnp.inf, g)
        else:
            g = _split_gain(lg, lh, lc, rg, rh, rc, l1, l2, hp,
                            parent_output[:, None, None])
        return jnp.where(ok & valid_t, g, -jnp.inf)

    gain_na_right = eval_option(prefix)                       # NaN stays right
    gain_na_left = jnp.where(
        missing_is_nan[None, :, None],
        eval_option(prefix + nan_sums), -jnp.inf)             # NaN joins left

    # ---------- categorical one-vs-rest ----------
    # left = single category bin ("bin == t" decision); NaN/unseen (bin 0)
    # always right. cat_l2/cat_smooth regularization per
    # feature_histogram.hpp:508-560 (one-hot branch).
    cat_valid = is_cat[None, :, None] & (fmask[:, :, None] > 0) & \
        (bins_r[None, None, :] >= 1) & \
        (bins_r[None, None, :] <= (num_bins[None, :, None] - 1))
    cl2 = l2 + hp.cat_l2
    lg, lh, lc = hist[..., 0], hist[..., 1], hist[..., 2]
    rg = tot[..., 0] - lg
    rh = tot[..., 1] - lh
    rc = tot[..., 2] - lc
    cat_ok = ((lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf) &
              (lh >= hp.min_sum_hessian_in_leaf) &
              (rh >= hp.min_sum_hessian_in_leaf))
    cat_gain_shift = leaf_gain(parent_grad, parent_hess, l1, cl2,
                               hp.max_delta_step)
    cat_gain = (leaf_gain(lg, lh, l1, cl2, hp.max_delta_step, hp.path_smooth,
                          lc, parent_output[:, None, None]) +
                leaf_gain(rg, rh, l1, cl2, hp.max_delta_step, hp.path_smooth,
                          rc, parent_output[:, None, None]))
    cat_min_shift = (cat_gain_shift + hp.min_gain_to_split)[:, None, None]
    cat_gain = jnp.where(cat_ok & cat_valid &
                         (cat_gain > cat_min_shift), cat_gain, -jnp.inf)

    # ---------- combine & argmax ----------
    num_gain = jnp.maximum(gain_na_right, gain_na_left)
    num_gain = jnp.where(num_gain > min_gain_shift[:, None, None],
                         num_gain, -jnp.inf)
    all_gain = jnp.where(is_cat[None, :, None], cat_gain, num_gain)  # [S,F,B]

    flat = all_gain.reshape(s, f * b)
    best_idx = jnp.argmax(flat, axis=1)                            # [S]
    best_gain = jnp.take_along_axis(flat, best_idx[:, None], 1)[:, 0]
    best_f = (best_idx // b).astype(jnp.int32)
    best_t = (best_idx % b).astype(jnp.int32)
    has_split = jnp.isfinite(best_gain)

    sel = (jnp.arange(s), best_f, best_t)
    chose_na_left = gain_na_left[sel] >= gain_na_right[sel]
    best_is_cat = is_cat[best_f]
    left = jnp.where(
        best_is_cat[:, None], hist[sel],
        jnp.where(chose_na_left[:, None], (prefix + nan_sums)[sel],
                  prefix[sel]))                                    # [S, 3]
    lgs, lhs, lcs = left[..., 0], left[..., 1], left[..., 2]
    rgs = parent_grad - lgs
    rhs = parent_hess - lhs
    rcs = parent_count - lcs
    eff_l2 = jnp.where(best_is_cat, cl2, l2)
    lout = leaf_output(lgs, lhs, l1, eff_l2, hp.max_delta_step,
                       hp.path_smooth, lcs, parent_output)
    rout = leaf_output(rgs, rhs, l1, eff_l2, hp.max_delta_step,
                       hp.path_smooth, rcs, parent_output)
    if hp.has_monotone:
        lout = jnp.clip(lout, cons_min, cons_max)
        rout = jnp.clip(rout, cons_min, cons_max)
    shift = jnp.where(best_is_cat, cat_gain_shift, gain_shift)

    # per-feature best gain (minus the feature's gain shift) for voting
    pf_shift = jnp.where(is_cat[None, :], cat_gain_shift[:, None],
                         gain_shift[:, None])                      # [S, F]
    per_feature_gain = jnp.max(all_gain, axis=2) - pf_shift        # [S, F]

    return BestSplits(
        gain=jnp.where(has_split, best_gain - shift, -jnp.inf),
        feature=jnp.where(has_split, best_f, -1),
        threshold_bin=best_t,
        default_left=jnp.where(best_is_cat, False, chose_na_left),
        left_grad=lgs, left_hess=lhs, left_count=lcs,
        left_output=lout, right_output=rout,
        per_feature_gain=per_feature_gain)
