"""Segmented bundle-space best-split search (EFB fast path).

The expansion design (efb.expand_histograms + split.find_best_splits)
materializes an [S, F, Bmax, 3] tensor per growth pass — at wide F that
tensor dominates the pass (measured 0.09 vs 0.16 trees/s against the
portable grower at 200k x 1000, docs/PerfNotes.md round 3). The
reference never expands: FeatureHistogram scans each sub-feature's
offset range of the bundled histogram directly (feature_histogram.hpp
offset scans over feature_group.h:25 ranges; bundling at
dataset.cpp:239-355 FastFeatureBundling).

This is that scan, TPU-first: every bundle position (g, p) hosts at most
one numeric threshold candidate (the EfbScan bijection, efb.py), so one
[S, Fb, Bb] batched computation — a csum along bundle bins, two static
gathers for the segment prefix, and the reconstructed default mass —
evaluates every threshold of every feature with NO expanded tensor.
Categorical features (never multi-bundled; identity columns) run through
the standard scan on a gathered [S, Fc, Bmax] slice.

Gain forms, NaN direction handling, monotone constraints, and min-data
gating mirror split.find_best_splits exactly. Two intended differences
from the expansion baseline:
- summation order (segment csum + default mass vs expanded csum),
  f32-equivalent via Precision.HIGHEST;
- EXACT-tie argmax order: candidates rank by bundle position here vs
  feature-major (f, t) order there — and a multi-bundled feature's
  default-bin threshold is hosted at its segment's LAST position, so
  a gain tie between the default threshold and a later empty-bin
  threshold resolves to the later bin. Ties need exactly equal f32
  gains (same partition), so the chosen SPLIT PARTITION is identical
  either way; only the recorded threshold/feature label can differ.
  The parity tests (test_efb_mxu.py) pass bit-exact on real data.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .split import (BestSplits, SplitHyperParams, leaf_output, leaf_gain,
                    _gain_given_output, _split_gain,
                    _monotone_penalty_factor, find_best_splits)

__all__ = ["find_best_splits_bundled"]


@functools.partial(jax.jit, static_argnames=("hp",))
def find_best_splits_bundled(hist_b: jax.Array, parent_grad: jax.Array,
                             parent_hess: jax.Array,
                             parent_count: jax.Array,
                             parent_output: jax.Array,
                             num_bins: jax.Array,
                             missing_is_nan: jax.Array, is_cat: jax.Array,
                             feature_mask: jax.Array,
                             hp: SplitHyperParams, efb,
                             monotone: jax.Array = None,
                             cons_min: jax.Array = None,
                             cons_max: jax.Array = None,
                             depth: jax.Array = None,
                             rand_bins: jax.Array = None,
                             gain_penalty: jax.Array = None) -> BestSplits:
    """find_best_splits over BUNDLED histograms [S, Fb, Bb, 3].

    Same contract as split.find_best_splits (per-ORIGINAL-feature
    num_bins/missing/is_cat/feature_mask, BestSplits in original feature
    ids) with `efb` an EfbDev whose .scan tables are present.
    """
    t = efb.scan
    s, fb, bb, _ = hist_b.shape
    f = int(num_bins.shape[0])
    bmax = efb.flat_pos.shape[1]
    l1, l2 = hp.lambda_l1, hp.lambda_l2
    P = fb * bb

    bins_r = jnp.arange(bb, dtype=jnp.int32)
    tri = (bins_r[:, None] <= bins_r[None, :]).astype(jnp.float32)
    csum = jnp.einsum("sfbc,bt->sftc", hist_b, tri,
                      precision=jax.lax.Precision.HIGHEST)
    flat_c = csum.reshape(s, P, 3)
    flat_h = hist_b.reshape(s, P, 3)
    # any single column's bin total is the node total (every row lands in
    # exactly one bin of every column) — expand_histograms' convention
    total = jnp.sum(hist_b[:, 0], axis=1)                       # [S, 3]

    fid = t.fid.reshape(P)
    fid_c = jnp.clip(fid, 0, f - 1)
    cand_t = t.cand_t.reshape(P)

    def c_at(idx):                                              # [P] csum
        safe = jnp.clip(idx, 0, P - 1)
        return jnp.where((idx >= 0)[None, :, None], flat_c[:, safe], 0.0)

    seg_sum = c_at(t.seg_hi_flat.reshape(P)) - \
        c_at(t.seg_lo_m1_flat.reshape(P))                       # [S, P, 3]
    dmass = jnp.where(t.is_multi_pos.reshape(P)[None, :, None],
                      total[:, None] - seg_sum, 0.0)
    pre_raw = c_at(t.prefix_flat.reshape(P))
    pre = jnp.where((t.prefix_flat.reshape(P) >= 0)[None, :, None],
                    pre_raw - c_at(t.seg_lo_m1_flat.reshape(P)), 0.0)
    left_nr = pre + jnp.where(t.incl_def.reshape(P)[None, :, None],
                              dmass, 0.0)                       # NaN right
    nan_pos = t.nan_flat.reshape(P)
    nan_stat = jnp.where(
        t.has_nan_pos.reshape(P)[None, :, None],
        jnp.where((nan_pos >= 0)[None, :, None],
                  flat_h[:, jnp.clip(nan_pos, 0, P - 1)], dmass), 0.0)
    left_nl = left_nr + nan_stat                                # NaN left

    # normalize feature_mask to [S, F] then gather per position
    fmask = jnp.broadcast_to(
        feature_mask.astype(jnp.float32).reshape(
            (1, f) if feature_mask.ndim == 1 else (s, f)), (s, f))
    fm_pos = fmask[:, fid_c] * (fid >= 0)                       # [S, P]

    valid = (cand_t >= 0)[None, :] & (fm_pos > 0)               # [S, P]
    if hp.extra_trees and rand_bins is not None:
        t_lim = (num_bins - 2 - missing_is_nan.astype(jnp.int32))[fid_c]
        rsel = rand_bins[:, fid_c] % jnp.maximum(t_lim + 1, 1)[None, :]
        valid = valid & (cand_t[None, :] == rsel)

    tot = jnp.stack([parent_grad, parent_hess, parent_count], -1)
    gain_shift = leaf_gain(parent_grad, parent_hess, l1, l2,
                           hp.max_delta_step)                   # [S]
    min_gain_shift = gain_shift + hp.min_gain_to_split

    mono_pos = monotone[fid_c] if monotone is not None else None

    def eval_option(left):                                      # [S, P, 3]
        right = tot[:, None] - left
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = right[..., 0], right[..., 1], right[..., 2]
        ok = ((lc >= hp.min_data_in_leaf) & (rc >= hp.min_data_in_leaf) &
              (lh >= hp.min_sum_hessian_in_leaf) &
              (rh >= hp.min_sum_hessian_in_leaf))
        if hp.has_monotone:
            po = parent_output[:, None]
            lout = leaf_output(lg, lh, l1, l2, hp.max_delta_step,
                               hp.path_smooth, lc, po)
            rout = leaf_output(rg, rh, l1, l2, hp.max_delta_step,
                               hp.path_smooth, rc, po)
            lout = jnp.clip(lout, cons_min[:, None], cons_max[:, None])
            rout = jnp.clip(rout, cons_min[:, None], cons_max[:, None])
            mc = mono_pos[None, :]
            violate = ((mc > 0) & (lout > rout)) | \
                      ((mc < 0) & (lout < rout))
            g = _gain_given_output(lg, lh, l1, l2, lout) + \
                _gain_given_output(rg, rh, l1, l2, rout)
            if hp.monotone_penalty > 0:
                pen = _monotone_penalty_factor(depth, hp.monotone_penalty)
                g = jnp.where(mc != 0, g * pen[:, None], g)
            g = jnp.where(violate, -jnp.inf, g)
        else:
            g = _split_gain(lg, lh, lc, rg, rh, rc, l1, l2, hp,
                            parent_output[:, None])
        return jnp.where(ok & valid, g, -jnp.inf)

    gain_nr = eval_option(left_nr)                              # [S, P]
    has_nan_p = t.has_nan_pos.reshape(P)
    gain_nl = jnp.where(has_nan_p[None, :], eval_option(left_nl),
                        -jnp.inf)
    num_gain = jnp.maximum(gain_nr, gain_nl)
    num_gain = jnp.where(num_gain > min_gain_shift[:, None], num_gain,
                         -jnp.inf)
    if gain_penalty is not None:
        num_gain = num_gain - gain_penalty[:, fid_c] * (fid >= 0)

    best_p = jnp.argmax(num_gain, axis=1)                       # [S]
    sel = (jnp.arange(s), best_p)
    num_best_gain = num_gain[sel]
    num_f = fid[best_p]
    num_t = cand_t[best_p]
    chose_na_left = gain_nl[sel] >= gain_nr[sel]
    num_left = jnp.where(chose_na_left[:, None], left_nl[sel],
                         left_nr[sel])                          # [S, 3]

    # per-feature best gain (voting-parallel): scatter-max positions->F
    pf_base = jnp.full((s, f), -jnp.inf)
    per_feature_gain = pf_base.at[:, fid_c].max(
        jnp.where(fid[None, :] >= 0, num_gain, -jnp.inf))
    per_feature_gain = per_feature_gain - gain_shift[:, None]

    # ---------- categorical sub-scan (identity columns; exact) ----------
    fc = int(t.cat_feats.shape[0])
    if hp.has_categorical and fc > 0:
        cf = t.cat_feats
        fp = efb.flat_pos[cf]                                   # [Fc, bmax]
        hist_cat = jnp.where(
            efb.is_valid_pos[cf][None, :, :, None],
            flat_h[:, fp.reshape(-1)].reshape(s, fc, bmax, 3), 0.0)
        bs_cat = find_best_splits(
            hist_cat, parent_grad, parent_hess, parent_count,
            parent_output, num_bins[cf], missing_is_nan[cf],
            jnp.ones(fc, bool), fmask[:, cf], hp,
            monotone=monotone[cf] if monotone is not None else None,
            cons_min=cons_min, cons_max=cons_max, depth=depth,
            rand_bins=rand_bins[:, cf] if rand_bins is not None else None,
            gain_penalty=gain_penalty[:, cf]
            if gain_penalty is not None else None)
        cat_gain = bs_cat.gain + gain_shift                     # undo shift
        cat_better = cat_gain > jnp.where(jnp.isfinite(num_best_gain),
                                          num_best_gain, -jnp.inf)
        cat_better = cat_better & (bs_cat.feature >= 0)
        per_feature_gain = per_feature_gain.at[:, cf].max(
            bs_cat.per_feature_gain)
        best_gain = jnp.where(cat_better, cat_gain, num_best_gain)
        best_f = jnp.where(cat_better, cf[jnp.clip(bs_cat.feature, 0)],
                           num_f)
        best_t = jnp.where(cat_better, bs_cat.threshold_bin, num_t)
        left = jnp.where(
            cat_better[:, None],
            jnp.stack([bs_cat.left_grad, bs_cat.left_hess,
                       bs_cat.left_count], -1), num_left)
        chose_na_left = jnp.where(cat_better, False, chose_na_left)
        cat_bitset = jnp.where(cat_better[:, None], bs_cat.cat_bitset, 0)
        best_is_cat = cat_better
        cat_lout, cat_rout = bs_cat.left_output, bs_cat.right_output
    else:
        best_gain, best_f, best_t = num_best_gain, num_f, num_t
        left = num_left
        w = (bmax + 31) // 32
        cat_bitset = jnp.zeros((s, w), jnp.uint32)
        best_is_cat = jnp.zeros(s, bool)
        cat_lout = cat_rout = jnp.zeros(s, jnp.float32)

    has_split = jnp.isfinite(best_gain)
    lgs, lhs, lcs = left[..., 0], left[..., 1], left[..., 2]
    rgs = parent_grad - lgs
    rhs = parent_hess - lhs
    rcs = parent_count - lcs
    lout = leaf_output(lgs, lhs, l1, l2, hp.max_delta_step,
                       hp.path_smooth, lcs, parent_output)
    rout = leaf_output(rgs, rhs, l1, l2, hp.max_delta_step,
                       hp.path_smooth, rcs, parent_output)
    if hp.has_monotone:
        lout = jnp.clip(lout, cons_min, cons_max)
        rout = jnp.clip(rout, cons_min, cons_max)
    # categorical outputs come from the sub-scan (cat_l2 semantics)
    lout = jnp.where(best_is_cat, cat_lout, lout)
    rout = jnp.where(best_is_cat, cat_rout, rout)

    return BestSplits(
        gain=jnp.where(has_split, best_gain - gain_shift, -jnp.inf),
        feature=jnp.where(has_split, best_f, -1),
        threshold_bin=jnp.maximum(best_t, 0),
        default_left=jnp.where(best_is_cat, False, chose_na_left),
        left_grad=lgs, left_hess=lhs, left_count=lcs,
        left_output=lout, right_output=rout,
        per_feature_gain=per_feature_gain,
        cat_bitset=cat_bitset)
