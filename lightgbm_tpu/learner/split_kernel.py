"""Fused best-split scan as a single Pallas kernel.

The XLA formulation in split.py (find_best_splits) is ~50 small
elementwise/reduce ops over [S, F, B] tensors; on this backend each op is
a separate kernel launch and the launch overhead dominates tree time
(measured ~275 ms/tree of the 498 ms total at the Higgs bench config —
vs ~15 ms of actual compute+bandwidth). This kernel is the TPU analog of
the reference's CUDABestSplitFinder (cuda_best_split_finder.cu:603
FindBestSplitsForLeafKernel): one launch scans a block of slots end to
end in VMEM — prefix sums along bins via a triangular MXU contraction,
the exact gain forms of split.py (shared helpers), NaN-direction
two-option scan, basic monotone clipping, and the per-slot argmax.

Scope (the grower falls back to find_best_splits outside it):
numerical features only (no categorical sorted scan), no extra_trees
random thresholds, no CEGB gain penalty, no per-feature voting gains.
Bit-parity with find_best_splits is regression-tested: same gain math,
same flat (feature*B + bin) argmax tie-breaking, same
NaN-direction choice (na_left wins ties).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .split import (BestSplits, SplitHyperParams, _gain_given_output,
                    _monotone_penalty_factor, _split_gain, leaf_gain,
                    leaf_output)

__all__ = ["find_best_splits_kernel", "kernel_supports"]

# jax < 0.5 names the params class TPUCompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
_COMPILER_PARAMS = _CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)

# per-slot output columns (selection only; gains/outputs recomputed in
# XLA from the picked sums — see kernel tail comment)
_O_HAS = 0      # has_split (0/1)
_O_FEAT = 1     # best feature idx (f32; -1 if none)
_O_BIN = 2      # best threshold bin (f32)
_O_NAL = 3      # chose NaN-left direction (0/1)
_O_LGR = 4      # left grad sum, NaN-right option
_O_LHR = 5
_O_LCR = 6
_O_LGL = 7      # left sums, NaN-left option
_O_LHL = 8
_O_LCL = 9
_N_OUT = 16     # padded


def kernel_supports(hp: SplitHyperParams) -> bool:
    """Whether the fused scan kernel covers this hyperparameter set."""
    return not hp.has_categorical and not hp.extra_trees


def _scan_kernel(sb: int, f: int, b: int, hp: SplitHyperParams,
                 has_monotone: bool):
    l1, l2 = hp.lambda_l1, hp.lambda_l2

    def kernel(hist_ref, parent_ref, fmask_ref, feat_tbl_ref, mono_ref,
               out_ref):
        # hist block [sb, 3, F, B] (channel-major for clean lane layout)
        hist = hist_ref[0].reshape(sb, 3, f, b)
        parent = parent_ref[:]                   # [sb, 8]: g h c out mn mx
        def pcol(c):
            # slice + expand_dims (the fused `[:, c:c+1, None]` indexing
            # lowers to an unsupported Mosaic gather)
            return jnp.expand_dims(parent[:, c:c + 1], 2)    # [sb, 1, 1]

        pg = pcol(0)
        ph = pcol(1)
        pc = pcol(2)
        po = pcol(3)

        # prefix sums along bins: [sb*3*F, B] @ tri[B, B] on the MXU with
        # the f32 bf16x6 decomposition (exact enough for f64-free parity
        # with jnp.cumsum; same contraction split.py uses)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (b, b), 0)
        iota_bt = jax.lax.broadcasted_iota(jnp.int32, (b, b), 1)
        # where() instead of bool-cast, f32 iotas instead of i32->f32
        # casts: Mosaic rejects sitofp on these layouts
        tri = jnp.where(iota_b <= iota_bt, jnp.float32(1.0),
                        jnp.float32(0.0))
        flat = hist.reshape(sb * 3 * f, b)
        prefix = jax.lax.dot_general(
            flat, tri, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32).reshape(sb, 3, f, b)

        feat_tbl = feat_tbl_ref[:]               # [F, 8]
        num_bins = jnp.expand_dims(feat_tbl[:, 0:1], 0)      # [1, F, 1]
        m_nan = jnp.expand_dims(feat_tbl[:, 1:2], 0) > 0.5
        fmask = fmask_ref[:].reshape(sb, f)[:, :, None] > 0

        # 2-D iota + cast (route-kernel-proven pattern), then expand:
        # Mosaic supports neither 3-D f32 iota nor some 3-D sitofp layouts
        bins_r = jnp.expand_dims(
            jax.lax.broadcasted_iota(jnp.int32, (f, b), 1)
            .astype(jnp.float32), 0)                          # [1, F, B]
        # NaN bin sums (last numeric bin when missing_is_nan)
        nan_pos = jnp.maximum(num_bins - 1.0, 0.0)
        is_nan_bin = (bins_r == nan_pos) & m_nan
        h_g, h_h, h_c = hist[:, 0], hist[:, 1], hist[:, 2]    # [sb, F, B]
        nan_g = jnp.sum(jnp.where(is_nan_bin, h_g, 0.0), axis=2,
                        keepdims=True)
        nan_h = jnp.sum(jnp.where(is_nan_bin, h_h, 0.0), axis=2,
                        keepdims=True)
        nan_c = jnp.sum(jnp.where(is_nan_bin, h_c, 0.0), axis=2,
                        keepdims=True)

        t_limit = num_bins - 2.0 - jnp.where(m_nan, 1.0, 0.0)
        valid_t = (bins_r <= t_limit) & fmask    # [sb, F, B]

        gain_shift3 = leaf_gain(pg, ph, l1, l2,
                                hp.max_delta_step)            # [sb, 1, 1]
        min_shift = gain_shift3 + hp.min_gain_to_split

        if has_monotone:
            mono = jnp.expand_dims(mono_ref[:][:, 0:1], 0)  # [1, F, 1]
            cmin = pcol(4)
            cmax = pcol(5)

        def eval_opt(lg, lh, lc):
            rg = pg - lg
            rh = ph - lh
            rc = pc - lc
            ok = ((lc >= hp.min_data_in_leaf) &
                  (rc >= hp.min_data_in_leaf) &
                  (lh >= hp.min_sum_hessian_in_leaf) &
                  (rh >= hp.min_sum_hessian_in_leaf))
            if has_monotone:
                lout = leaf_output(lg, lh, l1, l2, hp.max_delta_step,
                                   hp.path_smooth, lc, po)
                rout = leaf_output(rg, rh, l1, l2, hp.max_delta_step,
                                   hp.path_smooth, rc, po)
                lout = jnp.clip(lout, cmin, cmax)
                rout = jnp.clip(rout, cmin, cmax)
                violate = ((mono > 0) & (lout > rout)) | \
                          ((mono < 0) & (lout < rout))
                g = _gain_given_output(lg, lh, l1, l2, lout) + \
                    _gain_given_output(rg, rh, l1, l2, rout)
                if hp.monotone_penalty > 0:
                    depth = pcol(6)
                    pen = _monotone_penalty_factor(depth,
                                                   hp.monotone_penalty)
                    g = jnp.where(mono != 0, g * pen, g)
                g = jnp.where(violate, -jnp.inf, g)
            else:
                g = _split_gain(lg, lh, lc, rg, rh, rc, l1, l2, hp, po)
            return jnp.where(ok & valid_t, g, -jnp.inf)

        g_right = eval_opt(prefix[:, 0], prefix[:, 1], prefix[:, 2])
        g_left = jnp.where(
            m_nan, eval_opt(prefix[:, 0] + nan_g, prefix[:, 1] + nan_h,
                            prefix[:, 2] + nan_c), -jnp.inf)
        combined = jnp.maximum(g_right, g_left)
        combined = jnp.where(combined > min_shift, combined, -jnp.inf)

        # hierarchical argmax (Mosaic cannot reshape the lane dim into
        # [F, B]): feature winner by per-feature max, then bin winner
        # within it, both as min-index-achieving-max selects (Mosaic's
        # argmax/isfinite lowerings emit unsupported casts). First-max-
        # wins at each stage reproduces split.py's flat (f*B + b) argmax
        # tie order exactly.
        neg_inf = jnp.float32(-jnp.inf)
        big_idx = jnp.float32(1e9)
        iota_f2 = jax.lax.broadcasted_iota(jnp.int32, (sb, f), 1)
        iota_ff = iota_f2.astype(jnp.float32)                 # [sb, F]
        per_f = jnp.max(combined, axis=2)                     # [sb, F]
        fmax = jnp.max(per_f, axis=1, keepdims=True)          # [sb, 1]
        bf = jnp.min(jnp.where(per_f == fmax, iota_ff, big_idx),
                     axis=1, keepdims=True)                   # [sb, 1] f32
        sel_f2 = jnp.where(iota_ff == bf, jnp.float32(1.0),
                           jnp.float32(0.0))                  # [sb, F]
        sel_f = jnp.expand_dims(sel_f2, 2) > 0.5              # [sb, F, 1]

        # everything per-slot from here stays 2-D [sb, 1]: Mosaic 1-D
        # vector casts/selects are unsupported (same as the route kernel)
        def frow_max(x):                                      # -> [sb, B]
            return jnp.max(jnp.where(sel_f, x, neg_inf), axis=1)

        def frow_sum(x):                                      # -> [sb, B]
            return jnp.sum(jnp.where(sel_f, x, 0.0), axis=1)

        rowg = frow_max(combined)
        iota_b2 = jax.lax.broadcasted_iota(jnp.int32, (sb, b), 1)
        iota_bf = iota_b2.astype(jnp.float32)
        bmax_v = jnp.max(rowg, axis=1, keepdims=True)
        bt = jnp.min(jnp.where(rowg == bmax_v, iota_bf, big_idx),
                     axis=1, keepdims=True)                   # [sb, 1] f32
        sel_b = iota_bf == bt                                 # [sb, B]

        def pick(x):                                          # -> [sb, 1]
            return jnp.sum(jnp.where(sel_b, frow_sum(x), 0.0), axis=1,
                           keepdims=True)

        def pick_gain(x):                                     # -> [sb, 1]
            return jnp.max(jnp.where(sel_b, frow_max(x), neg_inf),
                           axis=1, keepdims=True)

        best_gain = pick_gain(combined)
        # isfinite lowers through unsupported casts; gains are either
        # finite or -inf by construction
        has_split = best_gain > jnp.float32(-3e38)

        na_left = pick_gain(g_left) >= pick_gain(g_right)     # [sb, 1]
        lg_r = pick(prefix[:, 0])
        lh_r = pick(prefix[:, 1])
        lc_r = pick(prefix[:, 2])
        nan_gb = jnp.broadcast_to(nan_g, (sb, f, b))
        nan_hb = jnp.broadcast_to(nan_h, (sb, f, b))
        nan_cb = jnp.broadcast_to(nan_c, (sb, f, b))
        lg_l = lg_r + pick(nan_gb)
        lh_l = lh_r + pick(nan_hb)
        lc_l = lc_r + pick(nan_cb)

        # emit ONLY the selection (indices, direction, picked sums) —
        # all exact integers / exact prefix values. Gains and outputs are
        # recomputed in XLA by the wrapper from these sums, so in-kernel
        # division/dot approximations never reach the returned numbers
        # (they can only perturb near-tie selections, ~1e-4 relative).
        one = jnp.float32(1.0)
        zero = jnp.float32(0.0)
        cols = [
            jnp.where(has_split, one, zero),
            jnp.where(has_split, bf, -1.0),
            bt,
            # ungated: split.py emits chose_na_left even for no-split
            # slots (downstream only reads committed splits)
            jnp.where(na_left, one, zero),
            lg_r, lh_r, lc_r, lg_l, lh_l, lc_l,
        ]
        out = jnp.concatenate(
            cols + [jnp.zeros((sb, _N_OUT - len(cols)), jnp.float32)],
            axis=1)                                           # [sb, 16]
        out_ref[:] = out

    return kernel


@functools.partial(
    jax.jit, static_argnames=("hp", "slot_block", "interpret"))
def find_best_splits_kernel(hist: jax.Array, parent_grad: jax.Array,
                            parent_hess: jax.Array, parent_count: jax.Array,
                            parent_output: jax.Array, num_bins: jax.Array,
                            missing_is_nan: jax.Array, is_cat: jax.Array,
                            feature_mask: jax.Array, hp: SplitHyperParams,
                            monotone=None, cons_min=None, cons_max=None,
                            depth=None, *, slot_block: int = 8,
                            interpret: bool = False) -> BestSplits:
    """find_best_splits (numerical subset) in one Pallas launch.

    Same contract as split.find_best_splits for the shapes it supports
    (kernel_supports(hp)); cat_bitset/per_feature_gain are zeros.
    """
    s, f, b, _ = hist.shape
    sb = slot_block
    spad = (-s) % sb
    bpad = ((b + 127) // 128) * 128 - b

    h = jnp.transpose(hist, (0, 3, 1, 2))                     # [S, 3, F, B]
    if spad or bpad:
        h = jnp.pad(h, ((0, spad), (0, 0), (0, 0), (0, bpad)))
    b_k = b + bpad

    has_mono = hp.has_monotone and monotone is not None
    parent_cols = [parent_grad, parent_hess, parent_count, parent_output]
    if has_mono:
        parent_cols += [cons_min, cons_max,
                        (depth if depth is not None
                         else jnp.zeros(s)).astype(jnp.float32)]
    parent = jnp.stack(
        parent_cols + [jnp.zeros(s, jnp.float32)] *
        (8 - len(parent_cols)), axis=1).astype(jnp.float32)   # [S, 8]
    if spad:
        parent = jnp.pad(parent, ((0, spad), (0, 0)))

    fmask = jnp.broadcast_to(
        feature_mask.astype(jnp.float32).reshape(
            (1, f) if feature_mask.ndim == 1 else (s, f)), (s, f))
    # numerical-only kernel: categorical features are masked off
    fmask = fmask * (~is_cat).astype(jnp.float32)[None, :]
    if spad:
        fmask = jnp.pad(fmask, ((0, spad), (0, 0)))

    feat_tbl = jnp.stack(
        [num_bins.astype(jnp.float32),
         missing_is_nan.astype(jnp.float32)] +
        [jnp.zeros(f, jnp.float32)] * 6, axis=1)              # [F, 8]
    mono_in = jnp.zeros((f, 8), jnp.float32)
    if has_mono:
        mono_in = mono_in.at[:, 0].set(monotone.astype(jnp.float32))

    nblk = (s + spad) // sb
    out = pl.pallas_call(
        _scan_kernel(sb, f, b_k, hp, has_mono),
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((1, sb * 3, f, b_k),
                         lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((sb, 8), lambda i: (i, 0)),
            pl.BlockSpec((sb, f), lambda i: (i, 0)),
            pl.BlockSpec((f, 8), lambda i: (0, 0)),
            pl.BlockSpec((f, 8), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((sb, _N_OUT), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((s + spad, _N_OUT), jnp.float32),
        interpret=interpret,
        **({} if interpret else {"compiler_params": _COMPILER_PARAMS}),
    )(h.reshape(nblk, sb * 3, f, b_k), parent, fmask, feat_tbl, mono_in)

    out = out[:s]
    w = (b + 31) // 32
    has_split = out[:, _O_HAS] > 0.5
    na_left = out[:, _O_NAL] > 0.5
    lg = jnp.where(na_left, out[:, _O_LGL], out[:, _O_LGR])
    lh = jnp.where(na_left, out[:, _O_LHL], out[:, _O_LHR])
    lc = jnp.where(na_left, out[:, _O_LCL], out[:, _O_LCR])
    rg = parent_grad - lg
    rh = parent_hess - lh
    rc = parent_count - lc
    # gains/outputs recomputed exactly here ([S]-sized XLA ops) from the
    # kernel's picked prefix sums — in-kernel approximations affect only
    # the selection of near-tie candidates, never the returned numbers
    l1, l2 = hp.lambda_l1, hp.lambda_l2
    gain_shift = leaf_gain(parent_grad, parent_hess, l1, l2,
                           hp.max_delta_step)
    if hp.has_monotone and monotone is not None:
        bfc = jnp.clip(out[:, _O_FEAT].astype(jnp.int32), 0, f - 1)
        lout = leaf_output(lg, lh, l1, l2, hp.max_delta_step,
                           hp.path_smooth, lc, parent_output)
        rout = leaf_output(rg, rh, l1, l2, hp.max_delta_step,
                           hp.path_smooth, rc, parent_output)
        lout = jnp.clip(lout, cons_min, cons_max)
        rout = jnp.clip(rout, cons_min, cons_max)
        g = _gain_given_output(lg, lh, l1, l2, lout) + \
            _gain_given_output(rg, rh, l1, l2, rout)
        if hp.monotone_penalty > 0:
            pen = _monotone_penalty_factor(
                depth if depth is not None else jnp.zeros(s),
                hp.monotone_penalty)
            g = jnp.where(monotone[bfc] != 0, g * pen, g)
    else:
        g = _split_gain(lg, lh, lc, rg, rh, rc, l1, l2, hp, parent_output)
        lout = leaf_output(lg, lh, l1, l2, hp.max_delta_step,
                           hp.path_smooth, lc, parent_output)
        rout = leaf_output(rg, rh, l1, l2, hp.max_delta_step,
                           hp.path_smooth, rc, parent_output)
    gain = jnp.where(has_split, g - gain_shift, -jnp.inf)
    return BestSplits(
        gain=gain,
        feature=jnp.where(has_split, out[:, _O_FEAT].astype(jnp.int32),
                          -1),
        threshold_bin=out[:, _O_BIN].astype(jnp.int32),
        default_left=na_left,  # ungated, matching split.py's junk slots
        left_grad=lg, left_hess=lh, left_count=lc,
        left_output=lout, right_output=rout,
        per_feature_gain=jnp.zeros((1, 1), jnp.float32),
        cat_bitset=jnp.zeros((s, w), jnp.uint32))
