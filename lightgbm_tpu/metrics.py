"""Evaluation metrics (23, matching src/metric/ factory metric.cpp:17-62).

Metrics run on host NumPy once per `metric_freq` iterations — they are off
the device hot path (the reference likewise evaluates on CPU between
boosting iterations, gbdt.cpp:469-572). Raw scores come back from HBM once
per eval. Rank metrics parallelize per-query in the reference; here they
vectorize over a padded [Q, L] layout.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .config import Config
from .data import Metadata
from .utils.log import Log

__all__ = ["Metric", "create_metric", "METRIC_ALIASES"]

_EPS = 1e-15


class Metric:
    name = "metric"
    is_higher_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self.label = None if metadata.label is None else \
            np.asarray(metadata.label, dtype=np.float64)
        self.weight = None if metadata.weight is None else \
            np.asarray(metadata.weight, dtype=np.float64)
        self.sum_weight = float(self.weight.sum()) if self.weight is not None \
            else float(num_data)

    def _avg(self, losses: np.ndarray) -> float:
        if self.weight is not None:
            return float((losses * self.weight).sum() / self.sum_weight)
        return float(losses.mean())

    def evaluate(self, score: np.ndarray,
                 convert: Optional[Callable] = None) -> float:
        raise NotImplementedError


class _PointwiseMetric(Metric):
    """Average of a per-row loss on converted predictions."""
    convert_score = True

    def point_loss(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def evaluate(self, score, convert=None):
        pred = score
        if self.convert_score and convert is not None:
            pred = convert(score)
        return self._avg(self.point_loss(np.asarray(pred, np.float64),
                                         self.label))


class L2Metric(_PointwiseMetric):
    name = "l2"

    def point_loss(self, p, y):
        return (p - y) ** 2


class RMSEMetric(L2Metric):
    name = "rmse"

    def evaluate(self, score, convert=None):
        return float(np.sqrt(super().evaluate(score, convert)))


class L1Metric(_PointwiseMetric):
    name = "l1"

    def point_loss(self, p, y):
        return np.abs(p - y)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def point_loss(self, p, y):
        a = self.config.alpha
        d = y - p
        return np.where(d >= 0, a * d, (a - 1.0) * d)


class HuberMetric(_PointwiseMetric):
    name = "huber"

    def point_loss(self, p, y):
        a = self.config.alpha
        d = np.abs(p - y)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseMetric):
    name = "fair"

    def point_loss(self, p, y):
        c = self.config.fair_c
        x = np.abs(p - y)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    name = "poisson"

    def point_loss(self, p, y):
        eps = 1e-10
        p = np.maximum(p, eps)
        return p - y * np.log(p)


class MapeMetric(_PointwiseMetric):
    name = "mape"

    def point_loss(self, p, y):
        return np.abs((y - p) / np.maximum(1.0, np.abs(y)))


class GammaMetric(_PointwiseMetric):
    name = "gamma"

    def point_loss(self, p, y):
        psi = 1.0
        theta = -1.0 / np.maximum(p, _EPS)
        a = psi
        b = -np.log(-theta)
        c = 1.0 / psi * np.log(y / psi) - np.log(y) - 0  # lgamma(1/psi)=0
        return -(y * theta - b) / a - c


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma_deviance"

    def point_loss(self, p, y):
        eps = 1e-9
        x = y / np.maximum(p, eps)
        return 2.0 * (x - np.log(np.maximum(x, eps)) - 1.0)


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def point_loss(self, p, y):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        p = np.maximum(p, eps)
        a = y * np.power(p, 1.0 - rho) / (1.0 - rho)
        b = np.power(p, 2.0 - rho) / (2.0 - rho)
        return -a + b


class BinaryLoglossMetric(_PointwiseMetric):
    name = "binary_logloss"

    def point_loss(self, p, y):
        p = np.clip(p, _EPS, 1.0 - _EPS)
        return -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))


class BinaryErrorMetric(_PointwiseMetric):
    name = "binary_error"

    def point_loss(self, p, y):
        pred = (p > 0.5).astype(np.float64)
        return (pred != (y > 0)).astype(np.float64)


class AUCMetric(Metric):
    name = "auc"
    is_higher_better = True

    def evaluate(self, score, convert=None):
        y = self.label > 0
        w = self.weight if self.weight is not None else np.ones_like(
            self.label)
        return self._auc_fast(score, y, w)

    @staticmethod
    def _auc_fast(score, y, w):
        order = np.argsort(-np.asarray(score), kind="stable")
        ys, ws = y[order], w[order]
        # group ties
        ss = np.asarray(score)[order]
        boundary = np.concatenate([[True], ss[1:] != ss[:-1]])
        gid = np.cumsum(boundary) - 1
        npos_g = np.bincount(gid, weights=ys * ws)
        nneg_g = np.bincount(gid, weights=(~ys) * ws)
        cum_neg_before = np.concatenate([[0.0], np.cumsum(nneg_g)[:-1]])
        # pairs: pos in group beats all negs after; ties count half
        total_neg = nneg_g.sum()
        wins = (npos_g * (total_neg - cum_neg_before - nneg_g)).sum()
        ties = (npos_g * nneg_g).sum()
        sum_pos = npos_g.sum()
        if sum_pos <= 0 or total_neg <= 0:
            return 0.5
        return float((wins + 0.5 * ties) / (sum_pos * total_neg))


class AveragePrecisionMetric(Metric):
    name = "average_precision"
    is_higher_better = True

    def evaluate(self, score, convert=None):
        y = (self.label > 0).astype(np.float64)
        w = self.weight if self.weight is not None else np.ones_like(y)
        order = np.argsort(-np.asarray(score), kind="stable")
        ys, ws = y[order], w[order]
        tp = np.cumsum(ys * ws)
        fp = np.cumsum((1 - ys) * ws)
        precision = tp / np.maximum(tp + fp, _EPS)
        total_pos = (y * w).sum()
        if total_pos <= 0:
            return 0.5
        recall_delta = ys * ws / total_pos
        return float((precision * recall_delta).sum())


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def evaluate(self, score, convert=None):
        p = convert(score) if convert is not None else score
        p = np.asarray(p, np.float64)
        idx = self.label.astype(np.int64)
        pt = np.clip(p[np.arange(len(idx)), idx], _EPS, None)
        return self._avg(-np.log(pt))


class MultiErrorMetric(Metric):
    name = "multi_error"

    def evaluate(self, score, convert=None):
        p = np.asarray(score, np.float64)
        k = self.config.multi_error_top_k
        idx = self.label.astype(np.int64)
        true_p = p[np.arange(len(idx)), idx]
        # error if true-class prob not within top-k (ties count as correct)
        rank = (p > true_p[:, None]).sum(axis=1)
        return self._avg((rank >= k).astype(np.float64))


class CrossEntropyMetric(_PointwiseMetric):
    name = "cross_entropy"

    def point_loss(self, p, y):
        p = np.clip(p, _EPS, 1.0 - _EPS)
        return -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))


class CrossEntropyLambdaMetric(_PointwiseMetric):
    name = "cross_entropy_lambda"
    convert_score = False

    def point_loss(self, raw, y):
        hhat = np.log1p(np.exp(raw))
        return np.log1p(np.exp(raw)) - y * raw  # xentropy_metric.hpp XentLambdaLoss approx

    def evaluate(self, score, convert=None):
        raw = np.asarray(score, np.float64)
        y = self.label
        w = self.weight if self.weight is not None else np.ones_like(y)
        # reference xentropy_metric.hpp:XentLambdaLoss: loss with weights in
        # the link: yhat = 1-exp(-w*log1p(exp(raw)))
        hhat = np.log1p(np.exp(raw))
        z = 1.0 - np.exp(-w * hhat)
        z = np.clip(z, _EPS, 1.0 - _EPS)
        loss = -(y * np.log(z) + (1.0 - y) * np.log(1.0 - z))
        return float(loss.mean())


class KLDivMetric(_PointwiseMetric):
    name = "kullback_leibler"

    def point_loss(self, p, y):
        p = np.clip(p, _EPS, 1.0 - _EPS)
        yy = np.clip(y, _EPS, 1.0 - _EPS)
        return (yy * np.log(yy / p) +
                (1.0 - yy) * np.log((1.0 - yy) / (1.0 - p)))


class NDCGMetric(Metric):
    name = "ndcg"
    is_higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("NDCG metric requires query information")
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]
        gains = self.config.label_gain
        if gains:
            self.label_gain = np.asarray(gains, np.float64)
        else:
            self.label_gain = (2.0 ** np.arange(32)) - 1.0

    def evaluate_multi(self, score) -> Dict[str, float]:
        qb = self.metadata.query_boundaries
        out = {}
        for k in self.eval_at:
            vals = []
            for qi in range(len(qb) - 1):
                s, e = qb[qi], qb[qi + 1]
                lbl = self.label[s:e].astype(np.int64)
                sc = np.asarray(score[s:e])
                order = np.argsort(-sc, kind="stable")
                gains = self.label_gain[lbl[order][:k]]
                disc = 1.0 / np.log2(np.arange(len(gains)) + 2.0)
                dcg = (gains * disc).sum()
                ideal = np.sort(self.label_gain[lbl])[::-1][:k]
                idcg = (ideal * disc[:len(ideal)]).sum()
                vals.append(dcg / idcg if idcg > 0 else 1.0)
            out[f"ndcg@{k}"] = float(np.mean(vals))
        return out

    def evaluate(self, score, convert=None):
        return list(self.evaluate_multi(score).values())[0]


class MapMetric(Metric):
    name = "map"
    is_higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("MAP metric requires query information")
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]

    def evaluate_multi(self, score) -> Dict[str, float]:
        qb = self.metadata.query_boundaries
        out = {}
        for k in self.eval_at:
            vals = []
            for qi in range(len(qb) - 1):
                s, e = qb[qi], qb[qi + 1]
                rel = (self.label[s:e] > 0).astype(np.float64)
                sc = np.asarray(score[s:e])
                order = np.argsort(-sc, kind="stable")
                rel_sorted = rel[order][:k]
                hits = np.cumsum(rel_sorted)
                prec = hits / (np.arange(len(rel_sorted)) + 1.0)
                npos = min(rel.sum(), k)
                vals.append(float((prec * rel_sorted).sum() / npos)
                            if npos > 0 else 1.0)
            out[f"map@{k}"] = float(np.mean(vals))
        return out

    def evaluate(self, score, convert=None):
        return list(self.evaluate_multi(score).values())[0]


class AucMuMetric(Metric):
    name = "auc_mu"
    is_higher_better = True

    def evaluate(self, score, convert=None):
        # multiclass AUC-mu (Kleiman & Page): average pairwise AUC over
        # class pairs using score differences (metric/multiclass_metric.hpp)
        p = np.asarray(score, np.float64)
        num_class = p.shape[1]
        y = self.label.astype(np.int64)
        w = self.weight if self.weight is not None else np.ones(len(y))
        total, cnt = 0.0, 0
        for a in range(num_class):
            for b in range(a + 1, num_class):
                mask = (y == a) | (y == b)
                if mask.sum() == 0:
                    continue
                diff = p[mask, a] - p[mask, b]
                lab = (y[mask] == a)
                total += AUCMetric._auc_fast(diff, lab, w[mask])
                cnt += 1
        return total / max(cnt, 1)


METRIC_ALIASES = {
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2",
    "regression": "l2", "regression_l2": "l2",
    "l2_root": "rmse", "rmse": "rmse", "root_mean_squared_error": "rmse",
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1",
    "regression_l1": "l1",
    "quantile": "quantile", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance",
    "tweedie": "tweedie",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc": "auc", "average_precision": "average_precision",
    "auc_mu": "auc_mu",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg",
    "xendcg": "ndcg", "map": "map", "mean_average_precision": "map",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kullback_leibler", "kldiv": "kullback_leibler",
}

_CLASSES = {
    "l2": L2Metric, "rmse": RMSEMetric, "l1": L1Metric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MapeMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "auc_mu": AucMuMetric, "ndcg": NDCGMetric, "map": MapMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KLDivMetric,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    canonical = METRIC_ALIASES.get(name)
    if canonical is None:
        if name in ("", "none", "null", "na", "custom"):
            return None
        Log.fatal("Unknown metric %s", name)
    m = _CLASSES[canonical](config)
    m.name = canonical
    return m


def default_metric_for_objective(objective: str) -> Optional[str]:
    return METRIC_ALIASES.get(objective)
