"""Objective functions: per-row gradient/hessian computation in pure JAX.

Redesign of the reference objective layer (src/objective/*.hpp, factory at
objective_function.cpp:17-89). Each objective exposes:

- `get_gradients(score) -> (grad, hess)`: traceable pure function (captured
  label/weight live on device), called inside the jitted boosting step — the
  per-iteration H2D gradient copy of the CUDA learner
  (cuda_single_gpu_tree_learner.cpp:79-80) disappears entirely.
- `boost_from_score()`: init score (BoostFromAverage, gbdt.cpp:335-344).
- `convert_output(raw)`: raw score -> prediction-space transform.
- `renew_tree_output`: optional leaf re-fit for percentile-based objectives
  (regression_objective.hpp RenewTreeOutput; implemented in
  learner/renew.py via segment quantiles).

Formulas follow the reference exactly:
  binary (binary_objective.hpp:105-135): y in {-1,+1},
    response = -y*sigma / (1 + exp(y*sigma*score)); hess=|r|*(sigma-|r|)
  multiclass softmax (multiclass_objective.hpp): p - onehot, h = 2p(1-p)
  poisson/gamma/tweedie: log-link forms (regression_objective.hpp:505-763)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .data import Metadata
from .utils.log import Log

__all__ = ["ObjectiveFunction", "create_objective", "OBJECTIVE_ALIASES"]

_EPS = 1e-15


class ObjectiveFunction:
    """Base class (reference include/LightGBM/objective_function.h)."""

    name = "custom"
    num_model_per_iteration = 1
    is_constant_hessian = False
    # the per-row hessian constant promised when is_constant_hessian:
    # get_gradients must return hess == constant_hessian_value * 1 for
    # every row (pre-weighting). Kernels reconstruct hessian sums as
    # constant x count, so subclasses with non-unit constant hessians
    # MUST override this alongside is_constant_hessian.
    constant_hessian_value = 1.0
    need_renew_tree_output = False
    # multiplier LightGBM applies to averaged init score (av. leaf output)
    boost_from_average_multiplier = 1.0

    def __init__(self, config: Config):
        self.config = config
        self.label: Optional[jax.Array] = None
        self.weight: Optional[jax.Array] = None

    def init(self, metadata: Metadata, num_data: int) -> None:
        if metadata.label is None:
            Log.fatal("Label is required for objective %s", self.name)
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label)
        self.weight = None if metadata.weight is None else \
            jnp.asarray(metadata.weight)
        self.check_label()

    def check_label(self) -> None:
        pass

    def _weighted(self, grad, hess) -> Tuple[jax.Array, jax.Array]:
        if self.weight is not None:
            return grad * self.weight, hess * self.weight
        return grad, hess

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def convert_output(self, raw: jax.Array) -> jax.Array:
        return raw

    def _avg_label(self) -> float:
        lbl = np.asarray(self.label, dtype=np.float64)
        if self.weight is not None:
            w = np.asarray(self.weight, dtype=np.float64)
            return float((lbl * w).sum() / max(w.sum(), _EPS))
        return float(lbl.mean())


# ---------------------------------------------------------------------------
# Regression family (regression_objective.hpp, 763 LoC)
# ---------------------------------------------------------------------------

class RegressionL2(ObjectiveFunction):
    name = "regression"
    is_constant_hessian = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            lbl = self.label
            self.trans_label = jnp.sign(lbl) * jnp.sqrt(jnp.abs(lbl))
        else:
            self.trans_label = self.label

    def get_gradients(self, score):
        grad = score - self.trans_label
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        lbl = np.asarray(self.trans_label, dtype=np.float64)
        if self.weight is not None:
            w = np.asarray(self.weight, dtype=np.float64)
            return float((lbl * w).sum() / max(w.sum(), _EPS))
        return float(lbl.mean())

    def convert_output(self, raw):
        if self.sqrt:
            return jnp.sign(raw) * raw * raw
        return raw


class RegressionL1(RegressionL2):
    name = "regression_l1"
    is_constant_hessian = True
    need_renew_tree_output = True
    renew_percentile = 0.5

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        lbl = np.asarray(self.label, dtype=np.float64)
        if self.weight is not None:
            # weighted median (regression_objective.hpp PercentileFun)
            w = np.asarray(self.weight, dtype=np.float64)
            order = np.argsort(lbl)
            cw = np.cumsum(w[order])
            return float(lbl[order][np.searchsorted(cw, 0.5 * cw[-1])])
        return float(np.percentile(lbl, 50))


class Huber(RegressionL2):
    name = "huber"
    is_constant_hessian = False
    need_renew_tree_output = True
    renew_percentile = 0.5

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                         jnp.sign(diff) * self.alpha)
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)


class Fair(RegressionL2):
    name = "fair"
    is_constant_hessian = False
    need_renew_tree_output = True
    renew_percentile = 0.5

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        diff = score - self.label
        c = self.c
        grad = c * diff / (jnp.abs(diff) + c)
        hess = c * c / ((jnp.abs(diff) + c) ** 2)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0


class Poisson(RegressionL2):
    name = "poisson"
    is_constant_hessian = False

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.max_delta_step = float(config.poisson_max_delta_step)

    def check_label(self):
        if float(np.asarray(self.label).min()) < 0:
            Log.fatal("[%s]: at least one target label is negative", self.name)

    def get_gradients(self, score):
        exp_s = jnp.exp(score)
        grad = exp_s - self.label
        hess = jnp.exp(score + self.max_delta_step)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        return float(np.log(max(self._avg_label(), _EPS)))

    def convert_output(self, raw):
        return jnp.exp(raw)


class Quantile(RegressionL2):
    name = "quantile"
    is_constant_hessian = True
    need_renew_tree_output = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)
        self.renew_percentile = self.alpha

    def get_gradients(self, score):
        delta = score - self.label
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        return float(np.percentile(np.asarray(self.label), self.alpha * 100))


class Mape(RegressionL2):
    name = "mape"
    is_constant_hessian = True
    need_renew_tree_output = True
    renew_percentile = 0.5

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        # label_weight = 1/max(1,|label|), folded into sample weight
        # (regression_objective.hpp RegressionMAPELOSS)
        lw = 1.0 / jnp.maximum(1.0, jnp.abs(self.label))
        self.weight = lw if self.weight is None else self.weight * lw

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        lbl = np.asarray(self.label, dtype=np.float64)
        w = np.asarray(self.weight, dtype=np.float64)
        order = np.argsort(lbl)
        cw = np.cumsum(w[order])
        return float(lbl[order][np.searchsorted(cw, 0.5 * cw[-1])])


class Gamma(Poisson):
    name = "gamma"

    def get_gradients(self, score):
        exp_s = jnp.exp(-score)
        grad = 1.0 - self.label * exp_s
        hess = self.label * exp_s
        return self._weighted(grad, hess)


class Tweedie(Poisson):
    name = "tweedie"

    def __init__(self, config: Config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score):
        rho = self.rho
        exp_1 = jnp.exp((1.0 - rho) * score)
        exp_2 = jnp.exp((2.0 - rho) * score)
        grad = -self.label * exp_1 + exp_2
        hess = (-self.label * (1.0 - rho) * exp_1 +
                (2.0 - rho) * exp_2)
        return self._weighted(grad, hess)


# ---------------------------------------------------------------------------
# Binary (binary_objective.hpp:216)
# ---------------------------------------------------------------------------

class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config: Config, is_pos=None):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        self._is_pos = is_pos or (lambda y: y > 0)
        if self.is_unbalance and self.scale_pos_weight != 1.0:
            Log.fatal("Cannot set is_unbalance and scale_pos_weight "
                      "at the same time")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        pos = self._is_pos(np.asarray(self.label))
        cnt_pos, cnt_neg = int(pos.sum()), int((~pos).sum())
        self.need_train = cnt_pos > 0 and cnt_neg > 0
        if not self.need_train:
            Log.warning("Contains only one class")
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self.y_signed = jnp.where(jnp.asarray(pos), 1.0, -1.0)
        self.label_weight = jnp.where(jnp.asarray(pos), w_pos, w_neg)
        self._pavg = float(pos.mean()) if num_data else 0.5
        if self.weight is not None:
            wsum = float(np.asarray(self.weight).sum())
            self._pavg = float(
                (pos * np.asarray(self.weight)).sum() / max(wsum, _EPS))

    def get_gradients(self, score):
        y = self.y_signed
        sig = self.sigmoid
        response = -y * sig / (1.0 + jnp.exp(y * sig * score))
        abs_r = jnp.abs(response)
        grad = response * self.label_weight
        hess = abs_r * (sig - abs_r) * self.label_weight
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        pavg = float(np.clip(self._pavg, 1e-15, 1.0 - 1e-15))
        init = float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)
        Log.info("[%s:BoostFromScore]: pavg=%f -> initscore=%f",
                 self.name, pavg, init)
        return init

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))


# ---------------------------------------------------------------------------
# Multiclass (multiclass_objective.hpp:279)
# ---------------------------------------------------------------------------

class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        if self.num_class < 2:
            raise ValueError(
                "multiclass objective needs num_class >= 2 "
                f"(got {self.num_class})")
        self.num_model_per_iteration = self.num_class

    def check_label(self):
        lbl = np.asarray(self.label)
        if lbl.min() < 0 or lbl.max() >= self.num_class:
            Log.fatal("Label must be in [0, %d) for multiclass objective",
                      self.num_class)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.onehot = jax.nn.one_hot(
            self.label.astype(jnp.int32), self.num_class, dtype=jnp.float32)

    def get_gradients(self, score):
        """score: [N, num_class] -> grad/hess [N, num_class]."""
        p = jax.nn.softmax(score, axis=-1)
        grad = p - self.onehot
        # hessian factor k/(k-1) (multiclass_objective.hpp:31,105) —
        # 2.0 at k=2, 1.25 at k=5; a hardcoded 2 over-damps leaf values
        # for k > 2 (round-5 task-matrix bench caught the gap)
        factor = self.num_class / (self.num_class - 1.0)
        hess = factor * p * (1.0 - p)
        if self.weight is not None:
            return grad * self.weight[:, None], hess * self.weight[:, None]
        return grad, hess

    def boost_from_score(self, class_id: int = 0) -> float:
        # log class prior (multiclass_objective.hpp:155: log of the
        # weighted class frequency, clamped at kEpsilon)
        lbl = np.asarray(self.label).astype(np.int64)
        w = np.asarray(self.weight, np.float64) \
            if self.weight is not None else np.ones(len(lbl))
        p = float(w[lbl == class_id].sum() / max(w.sum(), _EPS))
        return float(np.log(max(1e-15, p)))

    def convert_output(self, raw):
        return jax.nn.softmax(raw, axis=-1)


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.num_model_per_iteration = self.num_class
        self.sigmoid = float(config.sigmoid)
        self.binary_objs = []

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label)
        self.onehot_signed = []
        for k in range(self.num_class):
            obj = BinaryLogloss(self.config,
                                is_pos=lambda y, kk=k: y == kk)
            obj.init(metadata, num_data)
            self.binary_objs.append(obj)

    def get_gradients(self, score):
        grads, hesss = [], []
        for k in range(self.num_class):
            g, h = self.binary_objs[k].get_gradients(score[:, k])
            grads.append(g)
            hesss.append(h)
        return jnp.stack(grads, -1), jnp.stack(hesss, -1)

    def boost_from_score(self, class_id: int = 0) -> float:
        return self.binary_objs[class_id].boost_from_score()

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))


# ---------------------------------------------------------------------------
# Cross-entropy (xentropy_objective.hpp:283)
# ---------------------------------------------------------------------------

class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def check_label(self):
        lbl = np.asarray(self.label)
        if lbl.min() < 0 or lbl.max() > 1:
            Log.fatal("[%s]: label must be in [0, 1] interval", self.name)

    def get_gradients(self, score):
        # label in [0,1]; logistic link
        z = 1.0 / (1.0 + jnp.exp(-score))
        grad = z - self.label
        hess = z * (1.0 - z)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        p = float(np.clip(self._avg_label(), 1e-15, 1 - 1e-15))
        return float(np.log(p / (1.0 - p)))

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-raw))


class CrossEntropyLambda(CrossEntropy):
    name = "cross_entropy_lambda"

    def get_gradients(self, score):
        # (xentropy_objective.hpp:190-220): second parametrization
        w = self.weight if self.weight is not None else 1.0
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = jnp.exp(-score)
        grad = (1.0 - self.label / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (z * d)
        b = (d / w - 1.0) * c + 1.0
        hess = a * (1.0 + self.label * c * (a * b - 1.0)) / d * w
        # reference folds weight into the link not the loss; no extra mult
        return grad, hess

    def boost_from_score(self, class_id: int = 0) -> float:
        avg = self._avg_label()
        return float(np.log(np.expm1(np.clip(avg, 1e-15, None)) + 1e-15)) \
            if avg > 0 else -20.0

    def convert_output(self, raw):
        return jnp.log1p(jnp.exp(raw))


# ---------------------------------------------------------------------------
# Factory (objective_function.cpp:17-89)
# ---------------------------------------------------------------------------

OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression",
    "l2": "regression", "mean_squared_error": "regression",
    "mse": "regression", "l2_root": "regression", "rmse": "regression",
    "root_mean_squared_error": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank", "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg", "xendcg_mart": "rank_xendcg",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}


def create_objective(name: str, config: Config):
    from .objectives_rank import LambdarankNDCG, RankXENDCG
    canonical = OBJECTIVE_ALIASES.get(name)
    if canonical is None:
        # reg_sqrt shorthand objectives like "regression" handled above
        Log.fatal("Unknown objective %s", name)
    classes = {
        "regression": RegressionL2, "regression_l1": RegressionL1,
        "huber": Huber, "fair": Fair, "poisson": Poisson,
        "quantile": Quantile, "mape": Mape, "gamma": Gamma,
        "tweedie": Tweedie, "binary": BinaryLogloss,
        "multiclass": MulticlassSoftmax, "multiclassova": MulticlassOVA,
        "cross_entropy": CrossEntropy,
        "cross_entropy_lambda": CrossEntropyLambda,
        "lambdarank": LambdarankNDCG, "rank_xendcg": RankXENDCG,
    }
    if canonical == "none":
        return None
    if canonical in ("regression",) and name in ("l2_root", "rmse",
                                                 "root_mean_squared_error"):
        config.reg_sqrt = True
    return classes[canonical](config)
