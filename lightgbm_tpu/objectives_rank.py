"""Ranking objectives: LambdaRank-NDCG and XE-NDCG as batched pairwise ops.

Redesign of the reference rank objectives (src/objective/rank_objective.hpp:
LambdarankNDCG :95-281, RankXENDCG :283-365). The reference parallelizes an
OMP loop over queries, each doing an O(cnt^2) pairwise scan with a cached
sigmoid table. Here queries are padded into a dense [num_queries, max_len]
layout; gradients come from full pairwise [L, L] tensors, vmapped over a
query batch and `lax.scan`ned over batches to bound the O(Qb * L^2) memory.
The sigmoid lookup table (:229-256) is pointless on TPU — `jnp.exp` is
vectorized; clamping to [-50/sigma, 50/sigma] matches the table's domain.

DCG pieces follow src/metric/dcg_calculator.cpp: label_gain[i] = 2^i - 1,
discount[rank] = 1/log2(rank + 2), CalMaxDCGAtK over labels sorted desc.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .objectives import ObjectiveFunction
from .utils.log import Log

__all__ = ["LambdarankNDCG", "RankXENDCG", "pad_queries"]

_K_MIN_SCORE = -1e30


def default_label_gain(max_label: int = 31) -> np.ndarray:
    return (2.0 ** np.arange(max_label + 1)) - 1.0


def pad_queries(query_boundaries: np.ndarray,
                max_len: int = 0) -> Tuple[np.ndarray, np.ndarray, int]:
    """Dense doc-index layout [Q, L] (pad = num_data) + valid mask."""
    sizes = np.diff(query_boundaries)
    n = int(query_boundaries[-1])
    lmax = int(sizes.max()) if max_len <= 0 else max_len
    q = len(sizes)
    idx = np.full((q, lmax), n, dtype=np.int32)
    for qi in range(q):
        s, e = query_boundaries[qi], query_boundaries[qi + 1]
        idx[qi, :e - s] = np.arange(s, e, dtype=np.int32)
    valid = idx < n
    return idx, valid, lmax


class _RankingBase(ObjectiveFunction):
    """Query-padded ranking base (RankingObjective, rank_objective.hpp:25)."""

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("Ranking tasks require query information")
        self.query_boundaries = metadata.query_boundaries
        self.doc_idx, self.doc_valid, self.max_len = pad_queries(
            self.query_boundaries)
        self.num_queries = len(self.query_boundaries) - 1
        # pick a batch so Qb * L^2 * 4B stays ~128 MB
        l2 = max(self.max_len * self.max_len, 1)
        self.query_batch = max(1, min(self.num_queries,
                                      (32 * 1024 * 1024) // l2))
        self.doc_idx_d = jnp.asarray(self.doc_idx)
        self.doc_valid_d = jnp.asarray(self.doc_valid)
        self.label_pad = jnp.concatenate(
            [self.label, jnp.zeros(1, self.label.dtype)])

    def _per_query_grads(self, labels, scores, valid, qkey):
        raise NotImplementedError

    def get_gradients(self, score):
        n = self.num_data
        score_pad = jnp.concatenate([score, jnp.zeros(1, score.dtype)])
        qb = self.query_batch
        nq = self.num_queries
        num_batches = (nq + qb - 1) // qb
        pad_q = num_batches * qb
        didx = jnp.pad(self.doc_idx_d, ((0, pad_q - nq), (0, 0)),
                       constant_values=n)
        dval = jnp.pad(self.doc_valid_d, ((0, pad_q - nq), (0, 0)))
        didx_b = didx.reshape(num_batches, qb, self.max_len)
        dval_b = dval.reshape(num_batches, qb, self.max_len)
        extras = self._batch_extras(num_batches, qb)

        def step(carry, inp):
            g_acc, h_acc = carry
            bidx, bval, extra = inp
            lbl = self.label_pad[bidx]
            sc = score_pad[bidx]
            g, h = jax.vmap(self._per_query_grads)(lbl, sc, bval, extra)
            flat_idx = bidx.reshape(-1)
            g_acc = g_acc.at[flat_idx].add(
                jnp.where(bval.reshape(-1), g.reshape(-1), 0.0))
            h_acc = h_acc.at[flat_idx].add(
                jnp.where(bval.reshape(-1), h.reshape(-1), 0.0))
            return (g_acc, h_acc), None

        init = (jnp.zeros(n + 1, jnp.float32), jnp.zeros(n + 1, jnp.float32))
        (g, h), _ = jax.lax.scan(step, init, (didx_b, dval_b, extras))
        g, h = g[:n], h[:n]
        if self.weight is not None:
            g, h = g * self.weight, h * self.weight
        return g, h

    def _batch_extras(self, num_batches, qb):
        return jnp.zeros((num_batches, qb), jnp.float32)

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0


class LambdarankNDCG(_RankingBase):
    name = "lambdarank"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.norm = bool(config.lambdarank_norm)
        self.truncation_level = int(config.lambdarank_truncation_level)
        if config.label_gain:
            self.label_gain_np = np.asarray(config.label_gain, np.float64)
        else:
            self.label_gain_np = default_label_gain()

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label)
        if lbl.min() < 0 or not np.allclose(lbl, np.round(lbl)):
            Log.fatal("Label should be int >= 0 in lambdarank")
        if int(lbl.max()) >= len(self.label_gain_np):
            Log.fatal("Label %d exceeds label_gain size %d",
                      int(lbl.max()), len(self.label_gain_np))
        self.label_gain_d = jnp.asarray(self.label_gain_np, jnp.float32)
        # inverse max DCG at truncation level per query
        # (rank_objective.hpp:124-135)
        inv = np.zeros(self.num_queries, np.float64)
        disc = 1.0 / np.log2(np.arange(self.truncation_level) + 2.0)
        for qi in range(self.num_queries):
            s, e = self.query_boundaries[qi], self.query_boundaries[qi + 1]
            gains = np.sort(self.label_gain_np[
                lbl[s:e].astype(np.int64)])[::-1][:self.truncation_level]
            mdcg = float((gains * disc[:len(gains)]).sum())
            inv[qi] = 1.0 / mdcg if mdcg > 0 else 0.0
        self.inverse_max_dcgs = np.asarray(inv, np.float32)

    def _batch_extras(self, num_batches, qb):
        pad_q = num_batches * qb
        inv = np.zeros(pad_q, np.float32)
        inv[:self.num_queries] = self.inverse_max_dcgs
        return jnp.asarray(inv).reshape(num_batches, qb)

    def _per_query_grads(self, labels, scores, valid, inv_max_dcg):
        """Pairwise lambdas for one padded query (rank_objective.hpp:140-226).
        labels/scores/valid: [L]."""
        l = labels.shape[0]
        sig = self.sigmoid
        sc = jnp.where(valid, scores, _K_MIN_SCORE)
        order = jnp.argsort(-sc, stable=True)            # sorted positions
        s_lbl = labels[order].astype(jnp.int32)
        s_sc = sc[order]
        s_valid = valid[order]
        n_valid = jnp.sum(s_valid.astype(jnp.int32))
        gains = self.label_gain_d[jnp.clip(s_lbl, 0,
                                           len(self.label_gain_np) - 1)]
        ranks = jnp.arange(l)
        discount = 1.0 / jnp.log2(ranks + 2.0)

        best = s_sc[0]
        worst = s_sc[jnp.maximum(n_valid - 1, 0)]

        # pairwise [L, L] over sorted positions (i = row, j = col, i < j)
        pair_ok = (ranks[:, None] < ranks[None, :]) & \
                  s_valid[:, None] & s_valid[None, :] & \
                  (ranks[:, None] < self.truncation_level) & \
                  (s_lbl[:, None] != s_lbl[None, :])
        hi_is_i = s_lbl[:, None] > s_lbl[None, :]
        hi_sc = jnp.where(hi_is_i, s_sc[:, None], s_sc[None, :])
        lo_sc = jnp.where(hi_is_i, s_sc[None, :], s_sc[:, None])
        delta_score = hi_sc - lo_sc
        dcg_gap = jnp.abs(gains[:, None] - gains[None, :])
        paired_disc = jnp.abs(discount[:, None] - discount[None, :])
        delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
        if self.norm:
            delta_ndcg = jnp.where(
                best != worst,
                delta_ndcg / (0.01 + jnp.abs(delta_score)), delta_ndcg)
        ds = jnp.clip(delta_score * sig, -100.0, 100.0)
        p = 1.0 / (1.0 + jnp.exp(ds))                     # GetSigmoid
        p_lambda = -sig * delta_ndcg * p
        p_hess = p * (1.0 - p) * sig * sig * delta_ndcg
        p_lambda = jnp.where(pair_ok, p_lambda, 0.0)
        p_hess = jnp.where(pair_ok, p_hess, 0.0)

        # accumulate at sorted positions: high += p_lambda, low -= p_lambda
        lam_i = jnp.sum(jnp.where(hi_is_i, p_lambda, -p_lambda), axis=1)
        lam_j = jnp.sum(jnp.where(hi_is_i, -p_lambda, p_lambda), axis=0)
        lam_sorted = lam_i + lam_j
        hes_sorted = jnp.sum(p_hess, axis=1) + jnp.sum(p_hess, axis=0)
        sum_lambdas = -2.0 * jnp.sum(p_lambda)
        if self.norm:
            factor = jnp.where(sum_lambdas > 0,
                               jnp.log2(1.0 + sum_lambdas) /
                               jnp.maximum(sum_lambdas, 1e-30), 1.0)
            lam_sorted = lam_sorted * factor
            hes_sorted = hes_sorted * factor
        # scatter back from sorted positions to original doc positions
        lam = jnp.zeros(l, jnp.float32).at[order].set(lam_sorted)
        hes = jnp.zeros(l, jnp.float32).at[order].set(hes_sorted)
        return lam, hes


class RankXENDCG(_RankingBase):
    name = "rank_xendcg"

    def __init__(self, config: Config):
        super().__init__(config)
        self.seed = int(config.seed)
        self._iter = 0

    def _batch_extras(self, num_batches, qb):
        # fresh Gumbel draw per call (reference uses a per-query PRNG stream,
        # rank_objective.hpp:296-299; here one key folded per iteration)
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._iter)
        self._iter += 1
        return jax.random.uniform(
            key, (num_batches, qb, self.max_len), jnp.float32, 1e-7, 1.0)

    def _per_query_grads(self, labels, scores, valid, uniform):
        """XE-NDCG (rank_objective.hpp:301-355): three-term approximation."""
        sc = jnp.where(valid, scores, -jnp.inf)
        rho = jax.nn.softmax(sc)
        rho = jnp.where(valid, rho, 0.0)
        phi = jnp.where(valid, 2.0 ** labels - uniform, 0.0)
        inv_denom = 1.0 / jnp.maximum(jnp.sum(phi), 1e-15)
        term1 = -phi * inv_denom + rho
        params = jnp.where(valid, term1 / (1.0 - rho + 1e-15), 0.0)
        sum_l1 = jnp.sum(params)
        term2 = rho * (sum_l1 - params)
        params2 = jnp.where(valid, term2 / (1.0 - rho + 1e-15), 0.0)
        sum_l2 = jnp.sum(params2)
        lam = term1 + term2 + rho * (sum_l2 - params2)
        hes = rho * (1.0 - rho)
        cnt = jnp.sum(valid.astype(jnp.int32))
        lam = jnp.where((cnt <= 1) | ~valid, 0.0, lam)
        hes = jnp.where((cnt <= 1) | ~valid, 0.0, hes)
        return lam, hes
