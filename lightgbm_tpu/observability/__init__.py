"""Unified observability: spans, telemetry, MFU, compiles, exporters.

One process-global registry (`registry`) subsumes the fragments that
grew separately — `utils.timer.global_timer` (phase totals),
`reliability.counters` (degradation counters), `serving.metrics`
(per-model request metrics) — and adds what they cannot express:

- structured spans (`registry.trace.span("grow_tree", iter=i)`) with
  thread-safe nesting, an in-memory ring, and JSONL / Chrome-Perfetto
  `trace_event` export (`registry.dump_trace(path)`);
- per-iteration training telemetry (iteration wall time, phase split,
  grad/hess norms, leaves grown, bagging fraction, reliability-counter
  deltas) hooked into `boosting/gbdt.py`;
- device-utilization accounting: achieved MACs from the MXU histogram
  kernel dimensions (nchan * S * N * F * B, learner/histogram_mxu.py)
  turned into achieved-TFLOP/s and model-flops-utilization (MFU);
- compile accounting (compile count/seconds per jitted entry,
  shape-bucket hits — the serving bucket-cache semantics);
- exporters: `registry.snapshot()` JSON dict, Prometheus text format
  (served from `serving/server.py` at /metrics), `dump_trace(path)`;
- a crash flight recorder (`recorder`, flightrec.py): bounded ring of
  recent spans / collective brackets / fault hits / guard trips,
  flushed as an atomic ``postmortem_<rank>.json`` on fatal paths;
- budgeted device-profiler capture (`profiler`, profile.py) bracketing
  jax.profiler traces around spans matching ``profile_spans``;
- cross-rank trace merge (merge.py, ``python -m
  lightgbm_tpu.observability merge <dir>``) aligning per-rank clocks
  from samples piggybacked on guarded collectives;
- the bench regression sentinel (regress.py, ``bench.py --compare``)
  checking the BENCH_r*/MULTICHIP_r* trajectory for perf drops.

The registry is disabled by default; every instrumentation site is a
single `if registry.enabled:` branch, so the off path costs one
attribute read (<2% of any phase). Enable with the `observe` parameter
(config.py), `registry.enable()`, or per-surface flags.

Reference analog: Common::Timer / FunctionTimer RAII accumulators
printed under USE_TIMETAG (include/LightGBM/utils/common.h:973) — here
the accumulators are structured, exportable, and device-aware.
"""

from __future__ import annotations

from . import mfu
from .compiles import CompileAccounting
from .export import MetricsHTTPServer, prometheus_lines
from .flightrec import FlightRecorder, recorder
from .merge import merge_traces
from .profile import SpanProfiler, profiler
from .registry import ObservabilityRegistry, registry
from .telemetry import TrainingTelemetry
from .trace import Span, Trace

__all__ = [
    "registry", "ObservabilityRegistry", "Trace", "Span",
    "TrainingTelemetry", "CompileAccounting", "MetricsHTTPServer",
    "prometheus_lines", "mfu", "span", "snapshot", "dump_trace",
    "prometheus_text", "enable", "disable",
    "FlightRecorder", "recorder", "SpanProfiler", "profiler",
    "merge_traces",
]

# module-level conveniences bound to the process-global registry
span = registry.trace.span
snapshot = registry.snapshot
dump_trace = registry.dump_trace
prometheus_text = registry.prometheus_text
enable = registry.enable
disable = registry.disable
