"""CLI for the observability toolbox.

Subcommands:

``merge <dir> [-o OUT]``
    Merge every rank-tagged Perfetto trace under <dir> (the chrome
    dumps each rank writes via ``observe_trace_file``) into one
    clock-aligned trace with ``pid = rank`` and per-collective skew
    instants — see observability/merge.py and docs/Observability.md
    ("Cross-rank tracing").
"""

from __future__ import annotations

import sys
from typing import List, Optional

from .merge import merge_directory, merge_summary

USAGE = ("usage: python -m lightgbm_tpu.observability "
         "merge <trace_dir> [-o OUT]")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(USAGE)
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd != "merge":
        print(f"unknown command {cmd!r}\n{USAGE}", file=sys.stderr)
        return 2
    out = None
    if "-o" in rest:
        i = rest.index("-o")
        if i + 1 >= len(rest):
            print(f"-o needs a path\n{USAGE}", file=sys.stderr)
            return 2
        out = rest[i + 1]
        del rest[i:i + 2]
    if len(rest) != 1:
        print(USAGE, file=sys.stderr)
        return 2
    try:
        path, merged = merge_directory(rest[0], out=out)
    except (ValueError, OSError) as exc:
        print(f"merge failed: {exc}", file=sys.stderr)
        return 1
    print(f"wrote {path}")
    print(merge_summary(merged))
    return 0


if __name__ == "__main__":
    sys.exit(main())
