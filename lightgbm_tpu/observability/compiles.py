"""Compile accounting: count/seconds per jitted entry + cache hits.

XLA does not expose per-entry compile walls through a stable API, so
the accounting brackets the FIRST dispatch of an entry (trace + compile
+ first run) and counts later dispatches as hits — the same semantics
the serving bucket cache already uses (serving/engine.py: a
(model, bucket) miss IS a compilation of the serving predictor, a hit
is a cached dispatch). `compile_seconds` therefore includes the first
execution; for the large jitted entries here (fused multi-tree scan,
bucketed predictor) compilation dominates that first wall by an order
of magnitude, and the bound is honest: real compile time never exceeds
the recorded figure.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict

__all__ = ["CompileAccounting"]


class CompileAccounting:
    """Thread-safe per-entry {compiles, hits, compile_seconds}."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict] = {}

    def record(self, entry: str, seconds: float = 0.0,
               compiled: bool = True) -> None:
        with self._lock:
            rec = self._entries.setdefault(
                entry, {"compiles": 0, "hits": 0, "compile_seconds": 0.0})
            if compiled:
                rec["compiles"] += 1
                rec["compile_seconds"] += float(seconds)
            else:
                rec["hits"] += 1

    @contextlib.contextmanager
    def track(self, entry: str, compiled: bool = True):
        """Bracket a dispatch; the wall is attributed as compile
        seconds when `compiled` (first sighting), else counted a hit."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(entry, time.perf_counter() - t0, compiled)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def totals(self) -> Dict:
        snap = self.snapshot()
        return {
            "compile_count": sum(v["compiles"] for v in snap.values()),
            "hit_count": sum(v["hits"] for v in snap.values()),
            "compile_seconds": round(
                sum(v["compile_seconds"] for v in snap.values()), 6),
        }

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
