"""Exporters: Prometheus text rendering + the /metrics HTTP endpoint.

`prometheus_lines` flattens a nested snapshot dict into Prometheus
text-exposition (version 0.0.4) lines: numeric leaves become samples
named `<prefix>_<path>` (bools as 0/1, None and strings skipped),
optional labels render as `{k="v"}`. Each metric family gets a
`# TYPE ... gauge` header — counters here are monotonic in-process but
reset on restart, so gauge is the honest declaration.

`MetricsHTTPServer` is a stdlib-only (http.server) daemon-thread
endpoint: GET /metrics -> text format, /healthz -> ok, /snapshot ->
JSON. Bind port 0 for an ephemeral port (tests); `.port` carries the
bound port. No third-party client library is required or used.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

__all__ = ["prometheus_lines", "render_prometheus", "MetricsHTTPServer"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(*parts: str) -> str:
    name = "_".join(p for p in parts if p)
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _label_str(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    items = ",".join(
        '%s="%s"' % (_LABEL_RE.sub("_", str(k)),
                     str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in sorted(labels.items()))
    return "{" + items + "}"


def prometheus_lines(mapping: Dict, prefix: str,
                     labels: Optional[Dict[str, str]] = None,
                     _seen_types: Optional[set] = None) -> List[str]:
    """Flatten `mapping` (nested dicts / numeric leaves) into text-
    format lines. Non-numeric leaves (strings, None) are skipped."""
    out: List[str] = []
    seen = set() if _seen_types is None else _seen_types
    label_s = _label_str(labels)
    for key in sorted(mapping):
        value = mapping[key]
        name = _metric_name(prefix, str(key))
        if isinstance(value, dict):
            out.extend(prometheus_lines(value, name, labels, seen))
            continue
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)) or value != value:
            continue          # strings, None, NaN
        if name not in seen:
            seen.add(name)
            out.append(f"# TYPE {name} gauge")
        out.append(f"{name}{label_s} {value}")
    return out


def render_prometheus(sections) -> str:
    """Join (mapping, prefix, labels) sections into one scrape body."""
    lines: List[str] = []
    seen: set = set()
    for mapping, prefix, labels in sections:
        lines.extend(prometheus_lines(mapping, prefix, labels, seen))
    return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """Daemon-thread HTTP endpoint serving live metrics callbacks."""

    def __init__(self, render_text: Callable[[], str],
                 render_json: Optional[Callable[[], Dict]] = None,
                 host: str = "127.0.0.1", port: int = 0):
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (stdlib contract)
                path = self.path.split("?", 1)[0]
                try:
                    if path in ("/metrics", "/"):
                        body = outer._render_text().encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    elif path == "/healthz":
                        body, ctype = b"ok\n", "text/plain"
                    elif path == "/snapshot" and outer._render_json:
                        body = (json.dumps(outer._render_json())
                                + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as exc:  # render fault -> 500, not crash
                    self.send_error(500, str(exc)[:200])
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes stay out of stderr
                pass

        self._render_text = render_text
        self._render_json = render_json
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="lgbmtpu-metrics",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsHTTPServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
