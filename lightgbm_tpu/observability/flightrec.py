"""Crash flight recorder: the last N events, flushed at the moment of death.

The collective watchdog (reliability/watchdog.py) can say "rank 1 last
seen 12s ago" — but not what this rank was *doing* when it aborted, and
a rank killed by ``rank_death`` leaves nothing but an exit code. This
module keeps a bounded, lock-guarded ring of recent high-signal events:

- span closes (tapped from ``observability/trace.py``);
- collective brackets — site, deadline, peer heartbeat ages — from
  `CollectiveGuard.enter`/`exit_`;
- fault-site hits (reliability/faults.py) and non-finite guard trips
  (reliability/guards.py);
- clock-offset samples piggybacked on guarded collectives
  (parallel/comm.py).

On a fatal path — watchdog abort (before ``os._exit(113)``), injected
``rank_death`` (before ``os._exit(86)``), a non-finite guard trip, or
an unhandled exception in `engine.train`/`cli.main` — the ring is
flushed as one atomic ``postmortem_<rank>.json`` bundle (tmp +
``os.replace``), so every chaos-harness failure leaves a timeline
instead of an exit code.

Like the registry's collective hooks, recording stays on even when the
observability registry is disabled: these are rare, high-value incident
forensics, and the last thing a dying rank writes must not depend on an
enable flag. The recorder itself never raises — forensics must not take
down the exit path it documents.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

__all__ = ["FlightRecorder", "recorder", "POSTMORTEM_PREFIX",
           "current_rank"]

POSTMORTEM_PREFIX = "postmortem_"

#: flush reasons on which the process is about to die for real — these
#: fall back to the working directory when no bundle dir is configured
#: (a bundle *somewhere* beats no bundle); non-fatal reasons only flush
#: when a directory was configured (flightrec_dir / checkpoint_dir)
FATAL_REASONS = ("watchdog_abort", "rank_death")


def current_rank() -> int:
    """This process's rank: jax.process_index() when JAX is already
    loaded (authoritative in a multihost run), else the launcher env
    var, else 0. Never imports JAX — the recorder must stay usable on
    every exit path, including before/without JAX init."""
    if "jax" in sys.modules:
        try:
            return int(sys.modules["jax"].process_index())
        except Exception:
            pass
    try:
        return int(os.environ.get("LIGHTGBM_TPU_MACHINE_RANK", "0"))
    except ValueError:
        return 0


class FlightRecorder:
    """Bounded ring of recent events + atomic post-mortem flush."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=max(int(capacity), 16))
        self.enabled = True
        self.out_dir = ""
        self.dropped = 0
        self._flushes = 0
        self.last_flush_path = ""

    # -- configuration --------------------------------------------------
    def configure(self, *, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None,
                  out_dir: Optional[str] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if capacity is not None and \
                    self._ring.maxlen != max(int(capacity), 16):
                self._ring = collections.deque(
                    self._ring, maxlen=max(int(capacity), 16))
            if out_dir is not None:
                self.out_dir = str(out_dir)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0
            self._flushes = 0
            self.last_flush_path = ""

    # -- recording ------------------------------------------------------
    def record(self, kind: str, name: str, **fields) -> None:
        """Append one event. `kind` groups the event family ("span",
        "collective", "fault", "guard", "clock", "io", "abort",
        "exception"); `name` is the span name / site / what."""
        if not self.enabled:
            return
        rec: Dict = {"kind": kind, "name": name,
                     "t_wall": time.time(), "t_mono": time.monotonic()}
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(rec)

    def record_span(self, name: str, start: float, duration: float,
                    depth: int, parent: Optional[str]) -> None:
        self.record("span", name, dur_ms=round(duration * 1e3, 3),
                    depth=depth, parent=parent)

    def record_collective(self, site: str, phase: str,
                          deadline_s: Optional[float] = None,
                          heartbeat_ages: Optional[Dict] = None,
                          wall_s: Optional[float] = None) -> None:
        """One side of a collective bracket: phase "enter" carries the
        armed deadline and the peer heartbeat ages read at entry; phase
        "exit" carries the bracket's wall time."""
        ages = None
        if heartbeat_ages:
            ages = {str(r): round(float(a), 3)
                    for r, a in heartbeat_ages.items()}
        self.record("collective", site, phase=phase,
                    deadline_s=deadline_s, heartbeat_ages=ages,
                    wall_s=None if wall_s is None else round(wall_s, 6))

    def record_fault(self, site: str, mode: str) -> None:
        self.record("fault", site, mode=mode)

    def record_guard_trip(self, what: str, policy: str,
                          iteration: int) -> None:
        self.record("guard", what, policy=policy, iteration=int(iteration))

    def record_clock_sample(self, site: str, walls: List[float]) -> None:
        w = [float(v) for v in walls]
        skew = (max(w) - min(w)) if len(w) > 1 else 0.0
        self.record("clock", site, skew_s=round(skew, 6))

    def record_checkpoint(self, what: str, iteration: int,
                          path: str = "") -> None:
        self.record("io", what, iteration=int(iteration), path=path)

    def record_exception(self, where: str, exc: BaseException) -> None:
        self.record("exception", where, exc_type=type(exc).__name__,
                    exc=str(exc)[:500])

    # -- observation ----------------------------------------------------
    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> Dict:
        with self._lock:
            return {"enabled": int(self.enabled),
                    "events": len(self._ring),
                    "dropped": self.dropped,
                    "flushes": self._flushes}

    # -- the point of it all --------------------------------------------
    def flush(self, reason: str, out_dir: Optional[str] = None,
              extra: Optional[Dict] = None) -> Optional[str]:
        """Write the ring as ``postmortem_<rank>.json``, atomically.

        Destination: `out_dir` arg, else the configured `out_dir`, else
        — only for FATAL_REASONS, where the process is about to die —
        the working directory. Returns the bundle path, or None when
        disabled / no destination / the write itself failed (the flush
        never raises: it runs on paths that must reach os._exit)."""
        if not self.enabled:
            return None
        try:
            dest = out_dir or self.out_dir
            if not dest:
                if reason not in FATAL_REASONS:
                    return None
                dest = os.getcwd()
            os.makedirs(dest, exist_ok=True)
            rank = current_rank()
            path = os.path.join(dest, f"{POSTMORTEM_PREFIX}{rank}.json")
            bundle: Dict = {
                "reason": reason,
                "rank": rank,
                "pid": os.getpid(),
                "wall_time": time.time(),
                "dropped": self.dropped,
                "events": self.events(),
            }
            try:        # best-effort context; never block the flush
                from .registry import registry
                bundle["collective"] = registry.collective_snapshot()
                bundle["clock_skew"] = registry.clock_skew_snapshot()
                bundle["counters"] = registry.counters.snapshot()
            except Exception:
                pass
            if extra:
                bundle.update(extra)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(bundle, fh, indent=1)
                fh.write("\n")
            os.replace(tmp, path)
            with self._lock:
                self._flushes += 1
                self.last_flush_path = path
            print(f"lightgbm_tpu: flight recorder flushed "
                  f"{len(bundle['events'])} events to {path} "
                  f"(reason: {reason})", file=sys.stderr, flush=True)
            return path
        except Exception:
            return None


#: process-wide singleton; every instrumented site records through it
recorder = FlightRecorder()
