"""Cross-rank trace merge: N per-rank Perfetto files -> one timeline.

Each rank's Chrome/Perfetto trace (observability/trace.py `dump` with
the chrome format) carries a ``lightgbm_tpu_meta`` block: the rank, the
wall-clock instant of the trace epoch (``epoch_wall``), and the
clock-offset samples piggybacked on every guarded collective
(parallel/comm.py: each rank contributes its pre-collective ``wall``
stamp to the same ``process_allgather`` that moves the payload, so
every rank sees every rank's clock at every bracket — zero extra
collectives).

`merge_traces` aligns the per-rank clocks against the lowest rank
present (median pairwise offset over all samples: robust to the
arrival skew any single collective carries), rebases every event onto
that common timeline with ``pid = rank``, and injects one instant
event per collective sample whose args carry the per-rank corrected
arrival times and the residual skew — so rank skew at each collective
is directly visible in ui.perfetto.dev.

CLI: ``python -m lightgbm_tpu.observability merge <dir>``.
"""

from __future__ import annotations

import json
import os
import statistics
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["load_rank_trace", "find_rank_traces", "merge_traces",
           "MERGED_DEFAULT"]

MERGED_DEFAULT = "merged_trace.json"
META_KEY = "lightgbm_tpu_meta"


def load_rank_trace(path: str) -> Optional[Dict]:
    """Parse `path` as a rank-tagged chrome trace; None when it is not
    one (wrong JSON shape / no meta block — merge directories hold
    other JSON artifacts like postmortem bundles)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return None
    meta = doc.get(META_KEY)
    if not isinstance(meta, dict) or "rank" not in meta:
        return None
    return doc


def find_rank_traces(trace_dir: str) -> List[str]:
    """Every rank-tagged trace file directly under `trace_dir`."""
    paths = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".json") or name == MERGED_DEFAULT:
            continue
        path = os.path.join(trace_dir, name)
        if load_rank_trace(path) is not None:
            paths.append(path)
    return paths


def _clock_offsets(docs: Sequence[Dict], base_rank: int
                   ) -> Dict[int, float]:
    """rank -> estimated clock offset relative to `base_rank` (seconds
    to SUBTRACT from that rank's wall clock to land on the base rank's
    timeline). Median over every collective sample from every file; a
    rank with no samples gets offset 0 (best effort)."""
    deltas: Dict[int, List[float]] = {}
    for doc in docs:
        for sample in doc[META_KEY].get("clock_samples", ()) or ():
            walls = sample.get("walls") or []
            if len(walls) <= base_rank:
                continue
            base = float(walls[base_rank])
            for r, w in enumerate(walls):
                deltas.setdefault(r, []).append(float(w) - base)
    return {r: statistics.median(ds) for r, ds in deltas.items() if ds}


def merge_traces(paths: Sequence[str]) -> Dict:
    """Merge rank-tagged trace files into one clock-aligned Perfetto
    document. Raises ValueError when no usable trace is given."""
    docs: List[Dict] = []
    for p in paths:
        doc = load_rank_trace(p)
        if doc is not None:
            docs.append(doc)
    if not docs:
        raise ValueError("no rank-tagged trace files to merge "
                         "(need chrome-format dumps with a "
                         f"'{META_KEY}' block)")
    docs.sort(key=lambda d: int(d[META_KEY]["rank"]))
    ranks = [int(d[META_KEY]["rank"]) for d in docs]
    base_rank = ranks[0]
    offsets = _clock_offsets(docs, base_rank)

    # common timeline origin: the earliest corrected epoch
    corrected_epochs = {}
    for doc in docs:
        m = doc[META_KEY]
        r = int(m["rank"])
        corrected_epochs[r] = float(m.get("epoch_wall", 0.0)) - \
            offsets.get(r, 0.0)
    t0 = min(corrected_epochs.values())

    events: List[Dict] = []
    for doc in docs:
        r = int(doc[META_KEY]["rank"])
        events.append({"name": "process_name", "ph": "M", "pid": r,
                       "tid": 0, "args": {"name": f"lightgbm_tpu rank {r}"}})
        shift_us = (corrected_epochs[r] - t0) * 1e6
        for ev in doc.get("traceEvents", ()):
            if ev.get("ph") == "M":
                continue            # per-rank metadata is re-emitted above
            out = dict(ev)
            out["pid"] = r
            out["ts"] = round(float(ev.get("ts", 0.0)) + shift_us, 3)
            events.append(out)

    # one instant event per collective sample: corrected arrivals + skew
    collectives: List[Dict] = []
    seen_keys = set()
    for doc in docs:
        for i, sample in enumerate(
                doc[META_KEY].get("clock_samples", ()) or ()):
            site = str(sample.get("site", "collective"))
            walls = [float(w) for w in (sample.get("walls") or ())]
            if not walls:
                continue
            arrivals = {r: w - offsets.get(r, 0.0)
                        for r, w in enumerate(walls)}
            key = (site, i, round(min(arrivals.values()), 4))
            if key in seen_keys:    # every rank carries the same sample
                continue
            seen_keys.add(key)
            skew_s = max(arrivals.values()) - min(arrivals.values())
            last_rank = max(arrivals, key=arrivals.get)
            rec = {"site": site,
                   "skew_ms": round(skew_s * 1e3, 3),
                   "last_rank": last_rank,
                   "arrivals_ms": {str(r): round((a - t0) * 1e3, 3)
                                   for r, a in arrivals.items()}}
            collectives.append(rec)
            events.append({
                "name": f"skew:{site}", "ph": "i", "s": "g",
                "pid": last_rank, "tid": 0,
                "ts": round((arrivals[last_rank] - t0) * 1e6, 3),
                "cat": "lightgbm_tpu_clock",
                "args": rec})

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "lightgbm_tpu_merge": {
            "ranks": ranks,
            "base_rank": base_rank,
            "clock_offsets_s": {str(r): round(o, 6)
                                for r, o in offsets.items()},
            "collectives": collectives,
        },
    }


def merge_summary(merged: Dict) -> str:
    """Human tail for the CLI: per-site worst skew + offsets."""
    info = merged.get("lightgbm_tpu_merge", {})
    lines = [f"ranks merged: {info.get('ranks')}",
             f"clock offsets vs rank {info.get('base_rank', 0)} (s): "
             f"{info.get('clock_offsets_s')}"]
    worst: Dict[str, float] = {}
    for c in info.get("collectives", ()):
        worst[c["site"]] = max(worst.get(c["site"], 0.0), c["skew_ms"])
    for site, ms in sorted(worst.items()):
        lines.append(f"collective {site!r}: max rank skew {ms:.3f} ms")
    if not worst:
        lines.append("no collective clock samples found")
    return "\n".join(lines)


def merge_directory(trace_dir: str, out: Optional[str] = None
                    ) -> Tuple[str, Dict]:
    """Merge every rank trace under `trace_dir`; returns (path, doc)."""
    paths = find_rank_traces(trace_dir)
    merged = merge_traces(paths)
    out = out or os.path.join(trace_dir, MERGED_DEFAULT)
    tmp = f"{out}.tmp-{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(merged, fh)
        fh.write("\n")
    os.replace(tmp, out)
    return out, merged
