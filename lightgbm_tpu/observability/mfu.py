"""Device-utilization accounting: MACs -> achieved TFLOP/s -> MFU.

The MXU histogram kernel's arithmetic is known in closed form
(learner/histogram_mxu.py module docstring): one build pass over N rows
at frontier capacity S costs

    MACs = nchan * S * N * F * B_pad

bf16 multiply-accumulates, where nchan is the channel count (5 with
double-bf16 sums, 4 single-bf16, 3 quantized, 2 quantized +
constant-hessian — the same rules as `fits_v2`), F the feature count
and B_pad the bin axis padded to the 128-lane boundary. The batched
grower (grower_mxu.py) runs a deterministic doubling schedule
S = 2, 4, ..., s_max plus one full-capacity bridge pass, with sibling
subtraction halving the slots actually built per pass — so the MAC
count of a whole tree is a static function of the config, summed here
by `tree_macs`. Data-dependent fixup passes (measured ~0 at the bench
posture, docs/PerfNotes.md round 4) are excluded: the estimate is a
slight LOWER bound on device work, so the derived TFLOP/s and MFU
never overstate utilization. Routing matmul flops are negligible next
to the histogram (module docstring) and are likewise excluded.

MFU = achieved TFLOP/s / peak TFLOP/s of the device (bf16 peak per
chip; `LGBM_TPU_PEAK_TFLOPS` overrides the table). This is the
roofline-style accounting the GPU tree-boosting literature uses to
localize histogram kernels relative to hardware peak (PAPERS.md:
arxiv 1706.08359, 2011.02022).
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, Optional

__all__ = ["hist_channels", "histogram_macs", "tree_macs",
           "achieved_tflops", "mfu_fraction", "device_peak_tflops",
           "DeviceUtilization"]

# bf16 peak TFLOP/s per chip, by jax device_kind substring (most
# specific first). Sources: published TPU system specs per generation.
_PEAK_TFLOPS_BF16 = (
    ("v6e", 918.0), ("v6", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5litepod", 197.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def device_peak_tflops(device=None) -> float:
    """bf16 peak of the (first) visible device; 0.0 when unknown (CPU,
    interpret mode) so downstream MFU reads as unavailable rather than
    wrong. LGBM_TPU_PEAK_TFLOPS env overrides."""
    env = os.environ.get("LGBM_TPU_PEAK_TFLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        kind = str(getattr(device, "device_kind", "")).lower()
    except Exception:
        return 0.0
    if "tpu" not in kind and not kind.startswith("v"):
        return 0.0
    for pat, tf in _PEAK_TFLOPS_BF16:
        if pat in kind:
            return tf
    return 0.0


def hist_channels(*, double_prec: bool = True, quantized: bool = False,
                  const_hess: bool = False) -> int:
    """Histogram dot channels — must mirror fits_v2's nchan logic
    (histogram_mxu.py): [g_hi, g_lo, h_hi, h_lo, cnt] double-bf16,
    [g, h, cnt] single/quantized, minus the hessian channel(s) under
    the constant-hessian fast path."""
    if const_hess:
        return 2 if quantized else 3
    return 3 if quantized else (5 if double_prec else 4)


def _lane_pad(x: int) -> int:
    return ((int(x) + 127) // 128) * 128


def histogram_macs(*, num_slots: int, num_rows: int, num_features: int,
                   bmax: int, nchan: int,
                   row_block: int = 4096) -> int:
    """MACs of ONE histogram build pass: nchan * S * N_pad * F * B_pad
    (N padded to the row block the kernel grids over)."""
    n_pad = ((int(num_rows) + row_block - 1) // row_block) * row_block
    return int(nchan) * int(num_slots) * n_pad * int(num_features) * \
        _lane_pad(bmax)


def tree_macs(*, num_leaves: int, num_rows: int, num_features: int,
              bmax: int, double_prec: bool = True,
              quantized: bool = False, const_hess: bool = False,
              hist_subtraction: bool = True, overshoot: float = 2.0,
              bridge_gate: float = 0.0, row_block: int = 4096) -> int:
    """Estimated histogram MACs to grow one tree on the MXU path.

    Sums the grower's deterministic doubling schedule (grower_mxu.py:
    S = min(2*s, s_max) for s = 1, 2, 4, ... while s < s_max) plus the
    full-capacity bridge pass; sibling subtraction builds only the
    smaller child per pair, halving the slots per pass. A nonzero
    bridge_gate skips the bridge for on-schedule trees — the estimate
    keeps it (data-dependent skip), so treat the result as the
    no-skip schedule cost. Fixup passes (data-dependent, ~0 at the
    bench posture) are excluded."""
    over = overshoot if overshoot and overshoot >= 1.0 else 0.0
    L_g = int(math.ceil(num_leaves * over)) if over else int(num_leaves)
    s_max = L_g + 1
    nchan = hist_channels(double_prec=double_prec, quantized=quantized,
                          const_hess=const_hess)
    slots = 0
    s = 1
    passes = 0
    while s < s_max and passes < 32:
        s_p = min(max(2 * s, 2), s_max)
        slots += (s_p + 1) // 2 if hist_subtraction else s_p
        s *= 2
        passes += 1
    if over:
        # bridge pass at full capacity (skipped per-tree when
        # bridge_gate is already satisfied; counted here — see above)
        slots += (s_max + 1) // 2 if hist_subtraction else s_max
    return histogram_macs(num_slots=slots, num_rows=num_rows,
                          num_features=num_features, bmax=bmax,
                          nchan=nchan, row_block=row_block)


def achieved_tflops(macs_per_second: float) -> float:
    """1 MAC = 2 FLOPs; returns TFLOP/s."""
    return 2.0 * float(macs_per_second) / 1e12


def mfu_fraction(tflops: float, peak_tflops: Optional[float] = None
                 ) -> Optional[float]:
    """Model-flops-utilization in [0, 1]; None when the peak is
    unknown (never report a made-up denominator)."""
    peak = device_peak_tflops() if peak_tflops is None else peak_tflops
    if not peak or peak <= 0:
        return None
    return float(tflops) / float(peak)


class DeviceUtilization:
    """Accumulates estimated MACs + wall seconds; thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self.macs = 0
        self.trees = 0
        self.seconds = 0.0

    def add(self, macs: int, seconds: float, trees: int = 1) -> None:
        with self._lock:
            self.macs += int(macs)
            self.seconds += float(seconds)
            self.trees += int(trees)

    def reset(self) -> None:
        with self._lock:
            self.macs = 0
            self.trees = 0
            self.seconds = 0.0

    def snapshot(self) -> Dict:
        with self._lock:
            macs, secs, trees = self.macs, self.seconds, self.trees
        tf = achieved_tflops(macs / secs) if secs > 0 else 0.0
        peak = device_peak_tflops()
        frac = mfu_fraction(tf, peak) if macs else None
        return {
            "estimated_macs": macs,
            "trees": trees,
            "train_seconds": round(secs, 6),
            "achieved_tflops": round(tf, 6),
            "device_peak_tflops": peak,
            "mfu": round(frac, 8) if frac is not None else None,
        }
