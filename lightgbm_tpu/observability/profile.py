"""Device profiler capture: real XLA timelines for named spans.

Host-side spans (observability/trace.py) time the *dispatch*; the
device work behind it — the MXU histogram matmuls vs the scatter
kernels that the BENCH_r06 two-point protocol wants to attribute
(docs/Performance.md) — only shows up in a ``jax.profiler`` trace.
This module brackets ``jax.profiler.start_trace``/``stop_trace``
around spans whose name matches the ``profile_spans`` glob(s), with a
hard capture budget (``profile_max_captures``) so a long run collects
a handful of representative windows instead of gigabytes.

Config surface (config.py): ``profile_spans`` (comma-separated
fnmatch globs, e.g. ``pipeline_block,sharded_grow``), ``profile_dir``
(one subdirectory per capture), ``profile_max_captures``.

Degrades to a logged no-op wherever the profiler is unavailable
(missing tensorboard plugin, unsupported backend, a second profiler
already attached): the first failure disarms the profiler for the
rest of the process and training continues untouched.
"""

from __future__ import annotations

import fnmatch
import os
import re
import threading
from contextlib import contextmanager
from typing import Tuple

from ..utils.log import Log

__all__ = ["SpanProfiler", "profiler"]


def _start_trace(log_dir: str) -> None:
    """Indirection over jax.profiler.start_trace (tests stub this)."""
    import jax.profiler
    jax.profiler.start_trace(log_dir)


def _stop_trace() -> None:
    import jax.profiler
    jax.profiler.stop_trace()


class SpanProfiler:
    """Budgeted jax.profiler bracketing for matching span names."""

    def __init__(self):
        self._lock = threading.Lock()
        self.armed = False          # fast-path flag: one attr read
        self.patterns: Tuple[str, ...] = ()
        self.out_dir = ""
        self.max_captures = 0
        self.captures = 0
        self._active = False        # jax.profiler allows ONE live trace
        self._failed = False

    def configure(self, spans: str = "", out_dir: str = "",
                  max_captures: int = 4) -> None:
        with self._lock:
            self.patterns = tuple(
                p.strip() for p in str(spans or "").split(",") if p.strip())
            self.out_dir = str(out_dir or "")
            self.max_captures = max(0, int(max_captures))
            self.armed = bool(self.patterns and not self._failed and
                              self.max_captures > self.captures)

    def reset(self) -> None:
        with self._lock:
            self.armed = False
            self.patterns = ()
            self.out_dir = ""
            self.max_captures = 0
            self.captures = 0
            self._active = False
            self._failed = False

    # ------------------------------------------------------------------
    def matches(self, name: str) -> bool:
        return any(fnmatch.fnmatchcase(name, p) for p in self.patterns)

    def begin(self, name: str) -> bool:
        """Start a device trace for `name` if it matches, budget
        remains, and no capture is live. True iff a trace started —
        the caller owes a matching `end()`."""
        if not self.armed or not self.matches(name):
            return False
        with self._lock:
            if (self._active or self._failed or
                    self.captures >= self.max_captures):
                return False
            self._active = True
            self.captures += 1
            n = self.captures
            if self.captures >= self.max_captures:
                self.armed = False      # budget spent
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", name)
        log_dir = os.path.join(self.out_dir or "jax_profile",
                               f"{safe}_{n:03d}")
        try:
            os.makedirs(log_dir, exist_ok=True)
            _start_trace(log_dir)
        except Exception as exc:
            with self._lock:
                self._active = False
                self._failed = True
                self.armed = False
            Log.warning("span profiler unavailable (%s: %s); device "
                        "capture disabled for this process",
                        type(exc).__name__, exc)
            return False
        Log.info("span profiler: capturing %r -> %s (%d/%d)",
                 name, log_dir, n, self.max_captures)
        return True

    def end(self) -> None:
        try:
            _stop_trace()
        except Exception as exc:
            with self._lock:
                self._failed = True
                self.armed = False
            Log.warning("span profiler: stop_trace failed (%s: %s); "
                        "device capture disabled", type(exc).__name__, exc)
        finally:
            with self._lock:
                self._active = False

    @contextmanager
    def capture(self, name: str):
        """Bracket a region; yields True iff a device trace is live
        (callers use it to add a block_until_ready so the capture
        window covers the async device work, at zero cost when no
        capture is running)."""
        started = self.begin(name)
        try:
            yield started
        finally:
            if started:
                self.end()

    def snapshot(self) -> dict:
        with self._lock:
            return {"armed": int(self.armed),
                    "captures": self.captures,
                    "max_captures": self.max_captures,
                    "failed": int(self._failed)}


#: process-wide singleton, configured from Config by the Booster
profiler = SpanProfiler()
