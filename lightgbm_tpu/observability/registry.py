"""Process-global observability registry: one surface over the parts.

The registry COMPOSES the pre-existing fragments instead of replacing
them: `registry.timer` IS utils.timer.global_timer and
`registry.counters` IS reliability.counters.counters (same objects, so
every existing call site keeps working and feeds the unified snapshot),
plus the new components owned here — the span trace, the per-iteration
training telemetry, compile accounting and device-utilization (MFU)
accounting.

Everything is off by default. `enable()` flips one flag; instrumented
hot paths check `registry.enabled` (a single attribute read + branch)
and do nothing else when off, keeping the disabled-path overhead in
the noise (<2% of an iteration — tests/test_observability.py smokes
this).

The `record_train_iteration` / `record_fused_block` helpers keep the
gbdt.py hook sites to a couple of lines: they derive trees-per-
iteration, the analytic MAC estimate for MFU (MXU path only — other
kernels have no closed-form MAC model, so MFU reads as unavailable
rather than invented), fold in reliability-counter deltas, and mirror
the iteration into the span trace.
"""

from __future__ import annotations

import collections as _collections
import threading
from typing import Dict, List, Optional

from ..reliability.counters import counters as _rel_counters
from ..utils.timer import global_timer as _global_timer
from .compiles import CompileAccounting
from .export import render_prometheus
from .flightrec import current_rank, recorder as _flightrec
from .mfu import DeviceUtilization, tree_macs
from .profile import profiler as _profiler
from .telemetry import PHASE_KEYS, TrainingTelemetry
from .trace import Trace

__all__ = ["ObservabilityRegistry", "registry"]


class ObservabilityRegistry:
    """One process-global surface over tracing/telemetry/MFU/compiles
    plus the shared timer and reliability counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self.record_norms = False   # host-sync stats (norms, leaves)
        self.trace = Trace()
        self.training = TrainingTelemetry()
        self.compiles = CompileAccounting()
        self.mfu = DeviceUtilization()
        # pipelined-executor aggregates (pipeline/executor.py): how much
        # of the block walls the overlapped host work covered
        self._pipeline = {"blocks": 0, "iterations": 0,
                          "host_seconds": 0.0, "wall_seconds": 0.0}
        # level-pipelined grower aggregates (learner/grower_pipeline.py):
        # staged per-level dispatch counts, the speculative fixup
        # dispatches that turned out to be no-ops, and early stops from
        # the lagged done poll
        self._level_pipeline = {"trees": 0, "stage_dispatches": 0,
                                "fixup_dispatched": 0,
                                "fixup_speculative": 0, "early_stops": 0,
                                "wall_seconds": 0.0}
        # streamed-ingestion aggregates (streaming/loader.py): chunk and
        # byte volume per pass plus the frozen sketch sample size
        self._streaming = {"chunks": 0, "rows": 0, "bytes": 0,
                           "wall_seconds": 0.0, "sample_rows": 0,
                           "exact": 0}
        # histogram-backend resolution (boosting/gbdt.py
        # _resolved_hist_backend): the pinned choice + autotune timings
        self._hist_backend = {"choice": "", "autotuned": False,
                              "timings_ms": {}}
        # collective-watchdog aggregates (reliability/watchdog.py):
        # guarded brackets, deadline overruns, aborts and the worst
        # peer heartbeat age observed while diagnosing
        self._collective = {"guarded": 0, "wall_seconds": 0.0,
                            "timeouts": 0, "aborts": 0,
                            "heartbeat_age_max_s": 0.0, "world": 0}
        # cross-rank clock-offset samples piggybacked on guarded
        # collectives (parallel/comm.py): aggregates for /metrics plus
        # a bounded sample ring the trace dump embeds for the merge CLI
        self._clock_skew = {"samples": 0, "last_skew_s": 0.0,
                            "max_skew_s": 0.0}
        self._clock_samples: "collections.deque" = \
            _collections.deque(maxlen=512)
        # distributed-training aggregates (distributed/): crossbar mesh
        # setup (world size, reduce-scatter feature shard width) and the
        # binning sketch volume merged through mapper_sync
        self._distributed = {"world": 0, "feature_shard_width": 0,
                             "setup_wall_seconds": 0.0,
                             "sketch_rows": 0, "sketch_merges": 0}
        # continuous-loop freshness watchdog (continuous/trainer.py):
        # data-to-serving latency of the live generation plus the loop's
        # incident counters — torn publishes discarded on recovery and
        # poison windows quarantined after crash-looping
        self._freshness = {"generation": 0, "publishes": 0,
                           "data_to_serve_s": 0.0,
                           "max_data_to_serve_s": 0.0,
                           "staleness_slo_s": 0.0, "slo_alarm": 0,
                           "slo_breaches": 0, "torn_publishes": 0,
                           "quarantined_windows": 0}
        # elastic membership (distributed/elastic.py): the epoch/world
        # this rank currently believes, shrink/join commits observed,
        # and the wall spent rebuilding shards after a resize
        self._membership = {"epoch": 0, "world": 0, "resizes": 0,
                            "shrinks": 0, "joins": 0,
                            "reshard_wall_s": 0.0, "resharded_loads": 0}
        # shared singletons, NOT copies — existing call sites in
        # serving/, reliability/ and the phase timeits keep writing to
        # the same objects this registry reads.
        self.timer = _global_timer
        self.counters = _rel_counters

    # -- lifecycle ------------------------------------------------------
    def enable(self, ring: Optional[int] = None,
               norms: Optional[bool] = None) -> None:
        with self._lock:
            self.enabled = True
            self.trace.enabled = True
            if ring:
                self.trace.set_capacity(ring)
                self.training.set_capacity(ring)
            if norms is not None:
                self.record_norms = bool(norms)

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            self.trace.enabled = False

    def configure_from_config(self, cfg) -> None:
        """Wire the whole observability surface from a resolved Config
        (Booster.__init__): registry enable flag, flight-recorder ring
        and bundle directory (falling back to the checkpoint directory
        so multihost post-mortems land on shared storage), and the
        device span profiler."""
        if cfg.observe:
            self.enable(ring=cfg.observe_ring,
                        norms=cfg.observe_norms)
        _flightrec.configure(
            enabled=bool(cfg.flightrec),
            capacity=int(cfg.flightrec_ring),
            out_dir=cfg.flightrec_dir or cfg.checkpoint_dir or "")
        if cfg.profile_spans:
            _profiler.configure(spans=cfg.profile_spans,
                                out_dir=cfg.profile_dir,
                                max_captures=cfg.profile_max_captures)

    def reset(self) -> None:
        """Clear observability-owned state. The shared timer and
        reliability counters are left alone — they predate this
        subsystem and other code depends on their accumulation."""
        self.trace.reset()
        self.training.reset()
        self.compiles.reset()
        self.mfu.reset()
        with self._lock:
            self._pipeline = {"blocks": 0, "iterations": 0,
                              "host_seconds": 0.0, "wall_seconds": 0.0}
            self._level_pipeline = {"trees": 0, "stage_dispatches": 0,
                                    "fixup_dispatched": 0,
                                    "fixup_speculative": 0,
                                    "early_stops": 0, "wall_seconds": 0.0}
            self._streaming = {"chunks": 0, "rows": 0, "bytes": 0,
                               "wall_seconds": 0.0, "sample_rows": 0,
                               "exact": 0}
            self._hist_backend = {"choice": "", "autotuned": False,
                                  "timings_ms": {}}
            self._collective = {"guarded": 0, "wall_seconds": 0.0,
                                "timeouts": 0, "aborts": 0,
                                "heartbeat_age_max_s": 0.0, "world": 0}
            self._clock_skew = {"samples": 0, "last_skew_s": 0.0,
                                "max_skew_s": 0.0}
            self._clock_samples = _collections.deque(maxlen=512)
            self._distributed = {"world": 0, "feature_shard_width": 0,
                                 "setup_wall_seconds": 0.0,
                                 "sketch_rows": 0, "sketch_merges": 0}
            self._freshness = {"generation": 0, "publishes": 0,
                               "data_to_serve_s": 0.0,
                               "max_data_to_serve_s": 0.0,
                               "staleness_slo_s": 0.0, "slo_alarm": 0,
                               "slo_breaches": 0, "torn_publishes": 0,
                               "quarantined_windows": 0}
            self._membership = {"epoch": 0, "world": 0, "resizes": 0,
                                "shrinks": 0, "joins": 0,
                                "reshard_wall_s": 0.0,
                                "resharded_loads": 0}

    # -- exporters ------------------------------------------------------
    def level_pipeline_snapshot(self) -> Dict:
        with self._lock:
            p = dict(self._level_pipeline)
        disp = p["fixup_dispatched"]
        frac = p["fixup_speculative"] / disp if disp > 0 else 0.0
        return {"trees": p["trees"],
                "stage_dispatches": p["stage_dispatches"],
                "fixup_dispatched": disp,
                "fixup_speculative": p["fixup_speculative"],
                "speculative_frac": round(frac, 4),
                "early_stops": p["early_stops"],
                "wall_seconds": round(p["wall_seconds"], 6)}

    def pipeline_snapshot(self) -> Dict:
        with self._lock:
            p = dict(self._pipeline)
        frac = min(1.0, p["host_seconds"] / p["wall_seconds"]) \
            if p["wall_seconds"] > 0 else 0.0
        return {"blocks": p["blocks"], "iterations": p["iterations"],
                "host_seconds": round(p["host_seconds"], 6),
                "wall_seconds": round(p["wall_seconds"], 6),
                "overlap_frac": round(frac, 4)}

    def streaming_snapshot(self) -> Dict:
        with self._lock:
            s = dict(self._streaming)
        rps = s["rows"] / s["wall_seconds"] if s["wall_seconds"] > 0 else 0.0
        return {"chunks": s["chunks"], "rows": s["rows"],
                "bytes": s["bytes"], "sample_rows": s["sample_rows"],
                "exact": s["exact"],
                "wall_seconds": round(s["wall_seconds"], 6),
                "rows_per_sec": round(rps, 1)}

    def hist_backend_snapshot(self) -> Dict:
        """The pinned histogram backend as a flat exportable mapping.
        The string `choice` rides the JSON snapshot/bench tail; the
        Prometheus exporter skips strings, so the choice is ALSO
        one-hot encoded (is_mxu/is_pallas/is_scatter) for scrapers."""
        with self._lock:
            hb = dict(self._hist_backend)
        out: Dict = {"choice": hb["choice"],
                     "autotuned": bool(hb["autotuned"])}
        for name in ("mxu", "pallas", "scatter"):
            out["is_" + name] = int(hb["choice"] == name)
        for name, ms in sorted((hb.get("timings_ms") or {}).items()):
            out[str(name) + "_ms"] = round(float(ms), 3)
        return out

    def collective_snapshot(self) -> Dict:
        with self._lock:
            c = dict(self._collective)
        c["wall_seconds"] = round(c["wall_seconds"], 6)
        c["heartbeat_age_max_s"] = round(c["heartbeat_age_max_s"], 3)
        return c

    def distributed_snapshot(self) -> Dict:
        with self._lock:
            d = dict(self._distributed)
        d["setup_wall_seconds"] = round(d["setup_wall_seconds"], 6)
        return d

    def freshness_snapshot(self) -> Dict:
        with self._lock:
            f = dict(self._freshness)
        f["data_to_serve_s"] = round(f["data_to_serve_s"], 6)
        f["max_data_to_serve_s"] = round(f["max_data_to_serve_s"], 6)
        return f

    def membership_snapshot(self) -> Dict:
        with self._lock:
            m = dict(self._membership)
        m["reshard_wall_s"] = round(m["reshard_wall_s"], 6)
        return m

    def clock_skew_snapshot(self) -> Dict:
        with self._lock:
            s = dict(self._clock_skew)
        s["last_skew_s"] = round(s["last_skew_s"], 6)
        s["max_skew_s"] = round(s["max_skew_s"], 6)
        return s

    def clock_samples(self) -> List[Dict]:
        """The bounded ring of piggybacked clock-offset samples
        ({"site", "walls"}) that the chrome trace dump embeds for
        ``python -m lightgbm_tpu.observability merge``."""
        with self._lock:
            return list(self._clock_samples)

    def snapshot(self) -> Dict:
        return {
            "enabled": self.enabled,
            "clock_skew": self.clock_skew_snapshot(),
            "collective": self.collective_snapshot(),
            "distributed": self.distributed_snapshot(),
            "freshness": self.freshness_snapshot(),
            "membership": self.membership_snapshot(),
            "flightrec": _flightrec.snapshot(),
            "profiler": _profiler.snapshot(),
            "hist_backend": self.hist_backend_snapshot(),
            "pipeline": self.pipeline_snapshot(),
            "level_pipeline": self.level_pipeline_snapshot(),
            "streaming": self.streaming_snapshot(),
            "training": self.training.snapshot(),
            "compiles": {"entries": self.compiles.snapshot(),
                         **self.compiles.totals()},
            "device_utilization": self.mfu.snapshot(),
            "counters": self.counters.snapshot(),
            "timers": {k: round(float(v), 6)
                       for k, v in self.timer.totals().items()},
            "trace": {"spans_buffered": len(self.trace),
                      "dropped": self.trace.dropped},
        }

    def prometheus_text(self) -> str:
        snap = self.snapshot()
        training = dict(snap["training"])
        training.pop("last", None)   # unbounded-cardinality record
        return render_prometheus([
            ({"enabled": snap["enabled"]}, "lightgbm_tpu_observability",
             None),
            (training, "lightgbm_tpu_training", None),
            (snap["compiles"], "lightgbm_tpu_compiles", None),
            (snap["device_utilization"], "lightgbm_tpu_device", None),
            (snap["counters"], "lightgbm_tpu_reliability", None),
            (snap["collective"], "lightgbm_tpu_collective", None),
            (snap["distributed"], "lightgbm_tpu_distributed", None),
            (snap["freshness"], "lightgbm_tpu_freshness", None),
            (snap["membership"], "lightgbm_tpu_membership", None),
            (snap["clock_skew"], "lightgbm_tpu_clock_skew", None),
            (snap["flightrec"], "lightgbm_tpu_flightrec", None),
            (snap["hist_backend"], "lightgbm_tpu_hist_backend", None),
            (snap["pipeline"], "lightgbm_tpu_pipeline", None),
            (snap["level_pipeline"], "lightgbm_tpu_level_pipeline", None),
            (snap["streaming"], "lightgbm_tpu_streaming", None),
            (snap["timers"], "lightgbm_tpu_timer_seconds", None),
            (snap["trace"], "lightgbm_tpu_trace", None),
        ])

    def dump_trace(self, path: str, fmt: Optional[str] = None) -> str:
        return self.trace.dump(path, fmt, rank=current_rank(),
                               clock_samples=self.clock_samples())

    # -- training hooks (called from boosting/gbdt.py) ------------------
    def record_hist_autotune(self, choice: str, timings_ms: Dict,
                             autotuned: bool) -> None:
        """Pin the resolved histogram backend (+ per-backend autotune
        timings, ms). Recorded even when disabled — this is one-shot
        startup configuration, not per-iteration telemetry, and the
        bench JSON tail reads it regardless of the enable flag."""
        with self._lock:
            self._hist_backend = {
                "choice": str(choice), "autotuned": bool(autotuned),
                "timings_ms": {str(k): float(v)
                               for k, v in (timings_ms or {}).items()}}

    # -- collective-watchdog hooks (reliability/watchdog.py) ------------
    # recorded even when disabled, like record_hist_autotune: watchdog
    # events are rare, high-value incident forensics — the last thing
    # the run prints before aborting must not depend on an enable flag
    def record_collective_guard(self, wall_seconds: float) -> None:
        with self._lock:
            self._collective["guarded"] += 1
            self._collective["wall_seconds"] += float(wall_seconds)

    def record_collective_timeout(self) -> None:
        with self._lock:
            self._collective["timeouts"] += 1

    def record_collective_abort(self) -> None:
        with self._lock:
            self._collective["aborts"] += 1

    def record_heartbeat_age(self, age_s: float) -> None:
        with self._lock:
            self._collective["heartbeat_age_max_s"] = max(
                self._collective["heartbeat_age_max_s"], float(age_s))

    def record_collective_world(self, world: int) -> None:
        with self._lock:
            self._collective["world"] = int(world)

    # -- elastic-membership hooks (distributed/elastic.py) --------------
    # recorded even when disabled, like the watchdog hooks: a resize is
    # an incident, and the metrics tail is the only record a
    # reincarnated process has of the world it came from
    def record_membership(self, epoch: int, world: int) -> None:
        """This rank's current membership belief (set at distributed
        init and again after every epoch adoption)."""
        with self._lock:
            self._membership["epoch"] = int(epoch)
            self._membership["world"] = int(world)

    def record_membership_resize(self, kind: str, epoch: int,
                                 world: int, joined: int = 0) -> None:
        """One committed membership change: `kind` is "shrink" or
        "join"; `world`/`epoch` are the NEW values the record names."""
        with self._lock:
            m = self._membership
            m["resizes"] += 1
            if kind == "shrink":
                m["shrinks"] += 1
            m["joins"] += int(joined)
            m["epoch"] = int(epoch)
            m["world"] = int(world)

    def record_membership_reshard(self, wall_s: float) -> None:
        """One topology-flexible checkpoint load (W-rank bundle read by
        a W'-rank world): the elasticity cost the bench sentinel
        watches."""
        with self._lock:
            self._membership["resharded_loads"] += 1
            self._membership["reshard_wall_s"] += float(wall_s)

    def record_clock_sample(self, site: str, walls) -> None:
        """One piggybacked clock-offset sample from a guarded collective
        (parallel/comm.py): every rank's pre-collective wall stamp, one
        float per rank, moved by the SAME allgather as the payload.
        Recorded even when disabled, like the other collective hooks —
        skew forensics must survive the enable flag."""
        w = [float(v) for v in walls]
        if not w:
            return
        skew = (max(w) - min(w)) if len(w) > 1 else 0.0
        with self._lock:
            self._clock_skew["samples"] += 1
            self._clock_skew["last_skew_s"] = skew
            self._clock_skew["max_skew_s"] = max(
                self._clock_skew["max_skew_s"], skew)
            self._clock_samples.append({"site": str(site), "walls": w})
        _flightrec.record_clock_sample(site, w)

    # -- continuous-loop hooks (continuous/trainer.py) ------------------
    # recorded even when disabled, like the watchdog hooks: the
    # freshness SLO alarm and the loop's incident counters (torn
    # publishes, quarantines) are the forensics the chaos protocol
    # reads from metrics alone — they must not depend on an enable flag
    def record_freshness_publish(self, generation: int,
                                 data_to_serve_s: float,
                                 slo_s: float = 0.0) -> None:
        """One published generation: `data_to_serve_s` is the wall from
        first row of the window entering ingest to the hot-swap landing
        (data-to-serving latency). `slo_s` > 0 arms the staleness
        alarm: the gauge latches 1 whenever the latest publish blew the
        budget and clears on the next in-budget one."""
        lat = float(data_to_serve_s)
        breach = int(slo_s > 0 and lat > float(slo_s))
        with self._lock:
            f = self._freshness
            f["generation"] = int(generation)
            f["publishes"] += 1
            f["data_to_serve_s"] = lat
            f["max_data_to_serve_s"] = max(f["max_data_to_serve_s"], lat)
            f["staleness_slo_s"] = float(slo_s)
            f["slo_alarm"] = breach
            f["slo_breaches"] += breach

    def record_freshness_recover(self, generation: int) -> None:
        """Loop recovery re-read the GENERATION marker: seed the live
        generation gauge so a restarted process that publishes nothing
        (exhausted stream, serve-only restart) still reports the
        generation it is actually serving, not 0. Publish counters are
        untouched — only publishes move them."""
        with self._lock:
            f = self._freshness
            f["generation"] = max(f["generation"], int(generation))

    def record_freshness_torn_publish(self, generation: int) -> None:
        """A half-built generation found ahead of the marker on
        recovery — the torn-publish twin of streaming's torn
        stream-state pairs — detected and discarded."""
        with self._lock:
            self._freshness["torn_publishes"] += 1

    def record_freshness_quarantine(self, window: int) -> None:
        """A poison window skipped after crash-looping the cycle past
        its retry budget."""
        with self._lock:
            self._freshness["quarantined_windows"] += 1

    def tree_macs_for(self, gbdt) -> int:
        """Analytic per-tree MAC estimate for this booster's config;
        cached on the booster. 0 off the MXU path (no MAC model) —
        including when hist_backend resolves to the scatter kernels,
        whose cost is partition- not matmul-shaped: MFU then reads as
        unavailable rather than invented (docs/Observability.md)."""
        cached = getattr(gbdt, "_obs_tree_macs", None)
        if cached is not None:
            return cached
        macs = 0
        if (getattr(gbdt, "_hist_impl", None) == "mxu" and
                getattr(gbdt, "_hist_backend", None) in (None, "mxu")):
            cfg = gbdt.config
            macs = tree_macs(
                num_leaves=cfg.num_leaves, num_rows=gbdt.num_data,
                num_features=int(gbdt.num_bins_d.shape[0]),
                bmax=gbdt.bmax, double_prec=cfg.gpu_use_dp,
                quantized=cfg.use_quantized_grad,
                const_hess=bool(gbdt._const_hessian()),
                hist_subtraction=cfg.hist_subtraction,
                overshoot=cfg.growth_overshoot,
                bridge_gate=cfg.growth_bridge_gate)
        gbdt._obs_tree_macs = macs
        return macs

    def phase_deltas(self, before: Dict[str, float]) -> Dict[str, float]:
        """Per-iteration phase walls from two global_timer snapshots."""
        now = self.timer.totals()
        return {k: now.get(k, 0.0) - before.get(k, 0.0)
                for k in PHASE_KEYS if now.get(k, 0.0) > before.get(k, 0.0)}

    def record_train_iteration(self, gbdt, iteration: int, t0: float,
                               wall_s: float,
                               phases: Optional[Dict[str, float]] = None,
                               gradients=None, hessians=None,
                               tree=None) -> None:
        if not self.enabled:
            return
        trees = int(getattr(gbdt, "num_tree_per_iteration", 1))
        macs = self.tree_macs_for(gbdt) * trees
        extra: Dict = {}
        if self.record_norms:
            import numpy as np
            if gradients is not None:
                extra["grad_norm"] = float(
                    np.linalg.norm(np.asarray(gradients)))
            if hessians is not None:
                extra["hess_norm"] = float(
                    np.linalg.norm(np.asarray(hessians)))
            if tree is not None:
                # host sync on the fresh tree — norms-gated for a reason
                # (see gbdt.py's lagged stall poll)
                extra["leaves"] = int(np.asarray(tree.num_leaves))
        self.training.record_iteration(
            iteration, wall_s, phases=phases, trees=trees,
            bagging_fraction=float(gbdt.config.bagging_fraction),
            macs=macs or None, counters=self.counters.snapshot(), **extra)
        if macs:
            self.mfu.add(macs, wall_s, trees)
        self.trace.add("train_iter", t0, wall_s, iteration=int(iteration))

    def record_fused_block(self, gbdt, iteration: int, k: int, t0: float,
                           wall_s: float, was_built: bool) -> None:
        """One record for a k-iteration fused scan dispatch (no host
        boundary inside the block). The first dispatch of a fused
        program is its compilation — counted under entry
        "fused_train" with the bracketing semantics of compiles.py."""
        if not self.enabled:
            return
        kcls = int(getattr(gbdt, "num_tree_per_iteration", 1))
        trees = int(k) * kcls
        macs = self.tree_macs_for(gbdt) * trees
        self.compiles.record("fused_train",
                             wall_s if was_built else 0.0,
                             compiled=was_built)
        self.training.record_iteration(
            iteration, wall_s, trees=trees, iterations=int(k), fused=True,
            bagging_fraction=float(gbdt.config.bagging_fraction),
            macs=macs or None, counters=self.counters.snapshot())
        if macs:
            self.mfu.add(macs, wall_s, trees)
        self.trace.add("fused_block", t0, wall_s, iterations=int(k),
                       compiled=bool(was_built))

    def record_pipeline_block(self, iteration: int, k: int, t0: float,
                              wall_s: float, host_s: float,
                              overlap_frac: float) -> None:
        """One pipelined-executor block: wall_s spans dispatch to metric
        sync, host_s is the overlapped host window inside it (previous
        block's tree unpacking + scheduling). Training compute itself is
        already recorded by record_fused_block — this layer only
        accounts the overlap."""
        if not self.enabled:
            return
        with self._lock:
            p = self._pipeline
            p["blocks"] += 1
            p["iterations"] += int(k)
            p["host_seconds"] += float(host_s)
            p["wall_seconds"] += float(wall_s)
        self.trace.add("pipeline_block", t0, wall_s, iteration=int(iteration),
                       iterations=int(k),
                       host_ms=round(float(host_s) * 1e3, 3),
                       overlap_frac=round(float(overlap_frac), 4))

    def record_level_pipeline(self, iteration: int, t0: float,
                              wall_s: float, stages: int,
                              fixup_dispatched: int,
                              fixup_speculative: int,
                              stopped_early: bool) -> None:
        """One level-pipelined tree (learner/grower_pipeline.py):
        `stages` staged programs dispatched, of the fixups
        `fixup_speculative` were in flight past the (lagged) done flag
        and executed as identity no-ops. Training compute is recorded
        elsewhere — this layer accounts the dispatch overlap so a
        merged trace shows where speculation paid or wasted."""
        if not self.enabled:
            return
        with self._lock:
            p = self._level_pipeline
            p["trees"] += 1
            p["stage_dispatches"] += int(stages)
            p["fixup_dispatched"] += int(fixup_dispatched)
            p["fixup_speculative"] += int(fixup_speculative)
            p["early_stops"] += int(bool(stopped_early))
            p["wall_seconds"] += float(wall_s)
        self.trace.add("level_pipeline", t0, wall_s,
                       iteration=int(iteration), stages=int(stages),
                       fixup=int(fixup_dispatched),
                       speculative=int(fixup_speculative))

    def record_streaming_chunk(self, phase: str, chunk_index: int,
                               t0: float, wall_s: float, rows: int,
                               nbytes: int) -> None:
        """One ingested chunk from streaming/loader.py: `phase` is
        "sketch" (pass 1) or "bin" (pass 2); wall_s covers the chunk's
        host work including any overlapped parse it absorbed."""
        if not self.enabled:
            return
        with self._lock:
            s = self._streaming
            s["chunks"] += 1
            if phase == "bin":   # pass 2 re-streams the same rows
                s["rows"] += int(rows)
            s["bytes"] += int(nbytes)
            s["wall_seconds"] += float(wall_s)
        self.trace.add("streaming_chunk", t0, wall_s, phase=str(phase),
                       chunk=int(chunk_index), rows=int(rows),
                       bytes=int(nbytes))

    def record_streaming_sketch(self, sample_rows: int,
                                exact: bool) -> None:
        """The frozen pass-1 reservoir: its row count and whether it
        held the whole stream (exact => bit-parity boundaries)."""
        if not self.enabled:
            return
        with self._lock:
            self._streaming["sample_rows"] = int(sample_rows)
            self._streaming["exact"] = int(bool(exact))

    def record_distributed_setup(self, world: int,
                                 feature_shard_width: int,
                                 wall_seconds: float) -> None:
        """Crossbar mesh resolution (boosting/gbdt.py _setup_parallel):
        device-mesh world size, the reduce-scatter feature shard width
        (0 = psum full-histogram aggregation), and the setup wall."""
        if not self.enabled:
            return
        with self._lock:
            d = self._distributed
            d["world"] = int(world)
            d["feature_shard_width"] = int(feature_shard_width)
            d["setup_wall_seconds"] += float(wall_seconds)

    def record_distributed_sketch(self, rows: int) -> None:
        """One per-rank sketch merged through the distributed-binning
        mapper_sync (distributed/binning.py)."""
        if not self.enabled:
            return
        with self._lock:
            d = self._distributed
            d["sketch_rows"] += int(rows)
            d["sketch_merges"] += 1


#: process-global singleton; `lightgbm_tpu.observability.registry`.
registry = ObservabilityRegistry()
