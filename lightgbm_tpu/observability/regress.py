"""Bench regression sentinel: the perf trajectory as a checked artifact.

Every round leaves a ``BENCH_r<N>.json`` (wrapped single-line bench
record: {"n", "cmd", "rc", "tail", "parsed": {metric record}}), a
``MULTICHIP_r<N>.json`` ({"n_devices", "rc", "ok", "skipped", "tail"};
real-training rounds add {"trees_per_sec", "vs_baseline",
"tree_learner"} — bench.py --multichip)
and — since the serving chaos PR — a ``SERVE_r<N>.json``
(bench-record shape, emitted by bench_serve.py: sustained QPS at
p99<10ms plus shed/fallback/failover side channels) in the repo root. Nothing ever read them back — a silent perf
regression would ride along unnoticed until someone eyeballed the
numbers. This module parses the whole trajectory, computes per-metric
best-so-far, and flags the latest round when it drops more than
``REGRESSION_THRESHOLD`` below the best earlier round.

The trajectory is imperfect by construction (rounds where the
accelerator was unavailable have ``rc != 0`` / ``parsed: null`` /
``value: 0``): such records are *unusable samples*, excluded from
best-so-far — but an unusable LATEST round after any usable one is
itself reported as a regression (the bench stopped working).

A round may instead DECLARE denial: a bench/serve record with
``"skipped": true`` and a ``"skip_reason"`` string (the multichip
series has carried the same flag since r01). Skipped rounds are not
samples and do not trip the unusable-latest rule — the distinction is
intent: an rc=0/value=0 record says "the bench ran and measured
nothing" (that IS a regression), a skipped record says "the operator
established the hardware was unreachable and recorded why" (r06:
wedged accelerator tunnel, probe timeout — the attribution evidence
for such rounds lives in the record's side channels and
docs/PerfNotes.md instead of the headline value).

Wired into ``bench.py --compare [--strict]`` (strict: exit nonzero on
regressions) and the ``make bench`` tail; tier-1 tests schema-validate
the real records (tests/test_regress.py).
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

__all__ = ["REGRESSION_THRESHOLD", "load_trajectory", "validate_record",
           "compare"]

#: fractional drop vs best-so-far that counts as a regression
REGRESSION_THRESHOLD = 0.10

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _load_series(root: str, pattern: str) -> List[Tuple[int, str, Dict]]:
    """[(round, filename, record)] sorted by round number."""
    out = []
    for path in glob.glob(os.path.join(root, pattern)):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        with open(path) as fh:
            out.append((int(m.group(1)), os.path.basename(path),
                        json.load(fh)))
    out.sort()
    return out


def load_trajectory(root: str) -> Dict[str, List[Tuple[int, str, Dict]]]:
    """{"bench": [...], "multichip": [...], "serve": [...]}
    round-ordered records."""
    return {"bench": _load_series(root, "BENCH_r*.json"),
            "multichip": _load_series(root, "MULTICHIP_r*.json"),
            "serve": _load_series(root, "SERVE_r*.json")}


def validate_record(kind: str, name: str, rec) -> List[str]:
    """Schema problems with one on-disk record ([] when clean)."""
    problems: List[str] = []

    def _need(key, types):
        if key not in rec:
            problems.append(f"{name}: missing key {key!r}")
        elif not isinstance(rec[key], types):
            problems.append(f"{name}: {key!r} has type "
                            f"{type(rec[key]).__name__}")

    if not isinstance(rec, dict):
        return [f"{name}: record is {type(rec).__name__}, not an object"]
    if kind in ("bench", "serve"):
        # SERVE_r*.json (bench_serve.py) uses the bench record shape,
        # so serving rides the same sentinel machinery as training
        _need("n", int)
        _need("rc", int)
        _need("cmd", str)
        if "skipped" in rec:
            _need("skipped", bool)
            if rec.get("skipped") is True and not isinstance(
                    rec.get("skip_reason"), str):
                problems.append(f"{name}: skipped record needs a "
                                f"'skip_reason' string")
        if "parsed" not in rec:
            problems.append(f"{name}: missing key 'parsed'")
        elif rec["parsed"] is not None:
            p = rec["parsed"]
            if not isinstance(p, dict):
                problems.append(f"{name}: 'parsed' is not an object")
            else:
                for key, types in (("metric", str), ("unit", str),
                                   ("value", (int, float))):
                    if key not in p:
                        problems.append(f"{name}: parsed missing {key!r}")
                    elif not isinstance(p[key], types):
                        problems.append(f"{name}: parsed[{key!r}] has "
                                        f"type {type(p[key]).__name__}")
    elif kind == "multichip":
        _need("n_devices", int)
        _need("rc", int)
        _need("ok", bool)
        _need("skipped", bool)
        # real-training fields (bench.py --multichip, r06+): optional —
        # dry-run rounds predate them — but typed when present
        for key, types in (("trees_per_sec", (int, float)),
                           ("vs_baseline", (int, float)),
                           ("tree_learner", str)):
            if key in rec and not isinstance(rec[key], types):
                problems.append(f"{name}: {key!r} has type "
                                f"{type(rec[key]).__name__}")
        # elasticity-cost block (rounds that exercised a mid-run
        # resize): optional, but when present it is a typed object so
        # the sentinel can trust its series
        if "chaos_resize" in rec:
            cr = rec["chaos_resize"]
            if not isinstance(cr, dict):
                problems.append(f"{name}: 'chaos_resize' is not an "
                                f"object")
            else:
                for key, types in (
                        ("resizes", int),
                        ("reshard_wall_s", (int, float)),
                        ("post_resize_trees_per_sec", (int, float))):
                    if key in cr and not isinstance(cr[key], types):
                        problems.append(
                            f"{name}: chaos_resize[{key!r}] has type "
                            f"{type(cr[key]).__name__}")
    else:
        problems.append(f"{name}: unknown record kind {kind!r}")
    return problems


def _bench_points(records) -> Dict[str, List[Tuple[int, float]]]:
    """metric name -> [(round, value)] usable samples only. The
    primary per-round value lands under the parsed 'metric' name;
    ratio side-channels (vs_baseline, ...) become '<metric>:<key>'."""
    series: Dict[str, List[Tuple[int, float]]] = {}
    for rnd, _, rec in records:
        parsed = rec.get("parsed")
        if rec.get("skipped", False) or rec.get("rc", 1) != 0 or \
                not isinstance(parsed, dict):
            continue
        metric = str(parsed.get("metric", "bench"))
        value = parsed.get("value")
        if isinstance(value, (int, float)) and value > 0:
            series.setdefault(metric, []).append((rnd, float(value)))
            # ratio/aux side-channels tracked with the same drop
            # detector: multichip ratios, and the serve bench's
            # packed-vs-unpacked multi-model columns (PR 15)
            for key in ("vs_baseline", "vs_single_core",
                        "mm_packed_qps", "mm_unpacked_qps",
                        "mm_packed_speedup"):
                v = parsed.get(key)
                if isinstance(v, (int, float)) and v > 0:
                    series.setdefault(f"{metric}:{key}", []) \
                        .append((rnd, float(v)))
    return series


def _multichip_points(records) -> Dict[str, List[Tuple[int, float]]]:
    """multichip metric series: rounds that measured real training
    (bench.py --multichip writes trees_per_sec; dry-run rounds don't)
    feed the same drop detector the bench series uses."""
    series: Dict[str, List[Tuple[int, float]]] = {}
    for rnd, _, rec in records:
        if rec.get("rc", 1) != 0 or rec.get("skipped", False):
            continue
        for key in ("trees_per_sec", "vs_baseline"):
            v = rec.get(key)
            if isinstance(v, (int, float)) and v > 0:
                series.setdefault(f"multichip_{key}", []) \
                    .append((rnd, float(v)))
        # elasticity cost (rounds that resized mid-run): post-resize
        # throughput rides the drop detector like the main series;
        # reshard wall is tracked inverted (1/wall) so a slower reshard
        # registers as the drop it is
        cr = rec.get("chaos_resize")
        if isinstance(cr, dict) and cr.get("resizes", 0):
            v = cr.get("post_resize_trees_per_sec")
            if isinstance(v, (int, float)) and v > 0:
                series.setdefault("multichip_post_resize_trees_per_sec",
                                  []).append((rnd, float(v)))
            w = cr.get("reshard_wall_s")
            if isinstance(w, (int, float)) and w > 0:
                series.setdefault("multichip_reshard_inv_wall", []) \
                    .append((rnd, 1.0 / float(w)))
    return series


def compare(root: Optional[str] = None,
            threshold: float = REGRESSION_THRESHOLD) -> Dict:
    """The ``bench_regressions`` section: per-metric latest vs
    best-so-far over the BENCH_r*/MULTICHIP_r* trajectory under
    `root` (default: repo root = this package's parent)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    traj = load_trajectory(root)
    metrics: Dict[str, Dict] = {}
    regressions: List[Dict] = []

    all_points = dict(_bench_points(traj["bench"]))
    for metric, pts in _bench_points(traj["serve"]).items():
        all_points[f"serve:{metric}"] = pts
    all_points.update(_multichip_points(traj["multichip"]))
    for metric, points in sorted(all_points.items()):
        latest_rnd, latest = points[-1]
        earlier = points[:-1]
        entry: Dict = {"latest": latest, "latest_round": latest_rnd,
                       "samples": len(points)}
        if earlier:
            best_rnd, best = max(earlier, key=lambda p: p[1])
            entry.update(best=best, best_round=best_rnd,
                         delta_frac=round((latest - best) / best, 4))
            if latest < best * (1.0 - threshold):
                regressions.append({
                    "metric": metric, "latest": latest,
                    "latest_round": latest_rnd, "best": best,
                    "best_round": best_rnd,
                    "drop_frac": round(1.0 - latest / best, 4)})
        metrics[metric] = entry

    # an unusable latest bench/serve round after any usable one: the
    # bench itself regressed, whatever the numbers used to say
    for series_name, series in (("bench_record", traj["bench"]),
                                ("serve_record", traj["serve"])):
        if series and _bench_points(series):
            last_rnd, last_name, last = series[-1]
            usable_rounds = {r for pts in _bench_points(series).values()
                             for r, _ in pts}
            if last.get("skipped", False):
                # declared denial: not a sample, not a bench failure
                continue
            if last_rnd not in usable_rounds:
                regressions.append({
                    "metric": series_name, "latest_round": last_rnd,
                    "record": last_name,
                    "drop_frac": 1.0,
                    "detail": f"rc={last.get('rc')!r} "
                              f"parsed={last.get('parsed')!r}"})

    mc = [(rnd, rec) for rnd, _, rec in traj["multichip"]
          if not rec.get("skipped", False)]
    if mc:
        oks = [(rnd, bool(rec.get("ok", False))) for rnd, rec in mc]
        latest_rnd, latest_ok = oks[-1]
        metrics["multichip_ok"] = {"latest": int(latest_ok),
                                   "latest_round": latest_rnd,
                                   "samples": len(oks)}
        if not latest_ok and any(ok for _, ok in oks[:-1]):
            regressions.append({
                "metric": "multichip_ok", "latest": 0,
                "latest_round": latest_rnd, "best": 1,
                "drop_frac": 1.0})

    return {"root": root, "threshold": threshold,
            "bench_records": len(traj["bench"]),
            "multichip_records": len(traj["multichip"]),
            "serve_records": len(traj["serve"]),
            "metrics": metrics, "regressions": regressions}


def render_compare(result: Dict) -> str:
    """Human tail for ``bench.py --compare`` (stderr)."""
    lines = [f"bench trajectory: {result['bench_records']} bench + "
             f"{result['multichip_records']} multichip + "
             f"{result.get('serve_records', 0)} serve records "
             f"(threshold {result['threshold']:.0%})"]
    for metric, e in sorted(result["metrics"].items()):
        if "best" in e:
            lines.append(
                f"  {metric}: latest {e['latest']:g} (r{e['latest_round']:02d})"
                f" vs best {e['best']:g} (r{e['best_round']:02d}), "
                f"delta {e['delta_frac']:+.1%}")
        else:
            lines.append(f"  {metric}: latest {e['latest']:g} "
                         f"(r{e['latest_round']:02d}), no earlier sample")
    if result["regressions"]:
        for r in result["regressions"]:
            lines.append(f"  REGRESSION {r['metric']}: "
                         f"-{r['drop_frac']:.1%} at "
                         f"r{r['latest_round']:02d}")
    else:
        lines.append("  no regressions")
    return "\n".join(lines)
