"""Per-iteration training telemetry: wall, phases, norms, counters.

One record per boosting iteration (or per fused block — the fused scan
has no host boundary between its inner iterations, so a block lands as
one record carrying its iteration span). Records ride a bounded ring;
aggregates (iteration count, phase totals, total wall) accumulate
separately so a long run's summary never depends on ring capacity.

Reliability counters (device retries, fallbacks, guard trips,
checkpoint writes — reliability/counters.py) are folded in as per-record
DELTAS: each record carries only the counters that moved since the
previous record, so a degraded iteration is visible exactly where it
happened instead of as an end-of-run total.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

__all__ = ["TrainingTelemetry", "PHASE_KEYS"]

#: phase-timer keys recorded per iteration (utils/timer.py names).
#: `tree_train` is ONE fused device dispatch covering histogram build,
#: split search and routing — the on-device phases are not separable
#: host-side without a device profiler; `update_score` is the apply
#: (score-update) phase.
PHASE_KEYS = ("boosting", "bagging", "tree_train", "update_score",
              "linear_fit")


class TrainingTelemetry:
    """Bounded ring of per-iteration records + running aggregates."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=max(int(capacity), 16))
        self._last_counters: Optional[Dict[str, int]] = None
        self.iterations = 0
        self.trees = 0
        self.total_wall_s = 0.0
        self.phase_totals: Dict[str, float] = {}

    # ------------------------------------------------------------------
    def record_iteration(self, iteration: int, wall_s: float, *,
                         phases: Optional[Dict[str, float]] = None,
                         trees: int = 1, iterations: int = 1,
                         fused: bool = False,
                         leaves: Optional[int] = None,
                         grad_norm: Optional[float] = None,
                         hess_norm: Optional[float] = None,
                         bagging_fraction: Optional[float] = None,
                         macs: Optional[int] = None,
                         counters: Optional[Dict[str, int]] = None
                         ) -> Dict:
        """Append one record. `iterations` > 1 marks a fused block
        covering [iteration, iteration + iterations). `counters` is an
        absolute snapshot (reliability.counters.snapshot()); the record
        stores the delta vs the previous record."""
        rec: Dict = {"iteration": int(iteration),
                     "wall_s": round(float(wall_s), 6)}
        if iterations != 1:
            rec["iterations"] = int(iterations)
        if fused:
            rec["fused"] = True
        if trees != 1:
            rec["trees"] = int(trees)
        if phases:
            rec["phases"] = {k: round(float(v), 6)
                             for k, v in phases.items() if v}
        if leaves is not None:
            rec["leaves"] = int(leaves)
        if grad_norm is not None:
            rec["grad_norm"] = float(grad_norm)
        if hess_norm is not None:
            rec["hess_norm"] = float(hess_norm)
        if bagging_fraction is not None and bagging_fraction != 1.0:
            rec["bagging_fraction"] = float(bagging_fraction)
        if macs:
            rec["estimated_macs"] = int(macs)
        with self._lock:
            if counters is not None:
                prev = self._last_counters or {}
                delta = {k: v - prev.get(k, 0) for k, v in counters.items()
                         if v - prev.get(k, 0)}
                if delta:
                    rec["counters"] = delta
                self._last_counters = dict(counters)
            self._ring.append(rec)
            self.iterations += int(iterations)
            self.trees += int(trees)
            self.total_wall_s += float(wall_s)
            for k, v in (phases or {}).items():
                if v:
                    self.phase_totals[k] = \
                        self.phase_totals.get(k, 0.0) + float(v)
        return rec

    # ------------------------------------------------------------------
    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def last(self) -> Optional[Dict]:
        with self._lock:
            return dict(self._ring[-1]) if self._ring else None

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._ring = collections.deque(self._ring,
                                           maxlen=max(int(capacity), 16))

    def snapshot(self) -> Dict:
        with self._lock:
            n = self.iterations
            out = {
                "iterations": n,
                "trees": self.trees,
                "total_wall_s": round(self.total_wall_s, 6),
                "mean_iter_s": round(self.total_wall_s / n, 6) if n else 0.0,
                "phase_totals": {k: round(v, 6)
                                 for k, v in self.phase_totals.items()},
                "records_buffered": len(self._ring),
            }
            if self._ring:
                out["last"] = dict(self._ring[-1])
        return out

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last_counters = None
            self.iterations = 0
            self.trees = 0
            self.total_wall_s = 0.0
            self.phase_totals = {}
