"""Structured spans: thread-safe nesting, ring buffer, trace export.

A span is one timed region with attributes. Nesting is tracked with a
per-thread stack (`threading.local`), so concurrent threads — the
serving micro-batcher workers, checkpoint writers — interleave freely
without corrupting each other's parent/depth bookkeeping. Completed
spans land in one lock-guarded ring (`collections.deque(maxlen=...)`),
oldest-evicted, so tracing a long training run is O(ring) memory.

Export formats:
- JSONL: one span dict per line (jq/pandas-friendly);
- Chrome/Perfetto `trace_event` JSON ("ph": "X" complete events with
  microsecond ts/dur), loadable in chrome://tracing or ui.perfetto.dev.

The disabled path returns a shared no-op context manager — no
allocation, no clock read, one attribute check.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .flightrec import recorder as _flightrec
from .profile import profiler as _profiler

__all__ = ["Span", "Trace"]


class _NullSpan:
    """Shared no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Span:
    """One live timed region; append itself to the ring on __exit__."""

    __slots__ = ("_trace", "name", "attrs", "start", "duration",
                 "depth", "parent")

    def __init__(self, trace: "Trace", name: str, attrs: Dict):
        self._trace = trace
        self.name = name
        self.attrs = attrs
        self.start = 0.0
        self.duration = 0.0
        self.depth = 0
        self.parent: Optional[str] = None

    def __enter__(self) -> "Span":
        stack = self._trace._stack()
        self.depth = len(stack)
        self.parent = stack[-1].name if stack else None
        stack.append(self)
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.duration = time.perf_counter() - self.start
        stack = self._trace._stack()
        # balanced exit is the overwhelmingly common case; an exception
        # unwinding several spans at once still pops each in turn
        if stack and stack[-1] is self:
            stack.pop()
        else:  # pragma: no cover - unbalanced enter/exit
            try:
                stack.remove(self)
            except ValueError:
                pass
        self._trace._append(self.name, self.start, self.duration,
                            self.depth, self.parent, self.attrs)
        return False


class Trace:
    """Span factory + completed-span ring. Thread-safe."""

    def __init__(self, capacity: int = 4096):
        self.enabled = False
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=max(int(capacity), 16))
        self._local = threading.local()
        # open-span stacks by thread id (the same list objects as the
        # threading.local stacks) so the watchdog's heartbeat thread can
        # name another thread's innermost open span
        self._open: Dict[int, List["Span"]] = {}
        self._epoch = time.perf_counter()
        self._epoch_wall = time.time()   # same instant, wall clock —
        self.dropped = 0          # spans evicted from the ring

    # ------------------------------------------------------------------
    def span(self, name: str, **attrs):
        """Context manager timing a region; no-op when disabled. When
        the device profiler is armed for this span name, the region is
        additionally bracketed in a jax.profiler capture."""
        if not self.enabled:
            return _NULL_SPAN
        sp = Span(self, name, attrs)
        if _profiler.armed and _profiler.matches(name):
            return _ProfiledSpan(sp, name)
        return sp

    def add(self, name: str, start: float, duration: float, **attrs):
        """Record an already-measured region (hot-path hooks measure
        with their own perf_counter reads and call this once, keeping
        the instrumented loop free of context-manager plumbing).
        `start` is a time.perf_counter() timestamp."""
        if not self.enabled:
            return
        self._append(name, start, duration, 0, None, attrs)

    # ------------------------------------------------------------------
    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
            with self._lock:
                self._open[threading.get_ident()] = stack
        return stack

    def innermost_open(self) -> Tuple[str, float]:
        """(name, age_s) of the most recently opened span still open on
        ANY thread; ("", 0.0) when nothing is open. Read cross-thread
        for the watchdog heartbeat payload: stacks are only appended/
        popped under the GIL, so a stale read costs at most one span of
        accuracy in a diagnostic."""
        with self._lock:
            stacks = list(self._open.values())
        best: Optional[Span] = None
        for stack in stacks:
            if stack:
                top = stack[-1]
                if best is None or top.start > best.start:
                    best = top
        if best is None:
            return "", 0.0
        return best.name, max(0.0, time.perf_counter() - best.start)

    def _append(self, name, start, duration, depth, parent, attrs):
        rec = {
            "name": name,
            "dur": duration,                 # seconds
            "tid": threading.get_ident(),
            "depth": depth,
        }
        if parent is not None:
            rec["parent"] = parent
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            # epoch read under the lock: reset() rebinds it concurrently
            rec["ts"] = start - self._epoch  # seconds since trace epoch
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(rec)
        # span-close tap for the crash flight recorder (bounded ring,
        # survives as the postmortem timeline — flightrec.py)
        _flightrec.record_span(name, start, duration, depth, parent)

    # ------------------------------------------------------------------
    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._ring = collections.deque(self._ring,
                                           maxlen=max(int(capacity), 16))

    def spans(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0
            self._epoch = time.perf_counter()
            self._epoch_wall = time.time()

    @property
    def epoch_wall(self) -> float:
        """Wall-clock instant of the trace epoch: the anchor the
        cross-rank merge (observability/merge.py) uses to place this
        rank's perf_counter-relative timestamps on a shared timeline."""
        with self._lock:
            return self._epoch_wall

    # ------------------------------------------------------------------
    # export
    def to_chrome_trace(self, rank: Optional[int] = None,
                        clock_samples: Optional[List[Dict]] = None
                        ) -> Dict:
        """Chrome/Perfetto `trace_event` format: "X" complete events,
        microsecond timestamps (chrome://tracing, ui.perfetto.dev).
        With `rank`, the document gains rank-tagged process_name
        metadata and a ``lightgbm_tpu_meta`` block (rank, wall-clock
        epoch, piggybacked clock-offset samples) that
        ``python -m lightgbm_tpu.observability merge`` consumes."""
        pid = os.getpid()
        events = []
        if rank is not None:
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0,
                           "args": {"name": f"lightgbm_tpu rank {rank}"}})
        for rec in self.spans():
            ev = {
                "name": rec["name"],
                "ph": "X",
                "ts": round(rec["ts"] * 1e6, 3),
                "dur": round(rec["dur"] * 1e6, 3),
                "pid": pid,
                "tid": rec["tid"],
                "cat": "lightgbm_tpu",
            }
            args = dict(rec.get("attrs", ()))
            if "parent" in rec:
                args["parent"] = rec["parent"]
            if args:
                ev["args"] = args
            events.append(ev)
        doc: Dict = {"traceEvents": events, "displayTimeUnit": "ms"}
        if rank is not None:
            doc["lightgbm_tpu_meta"] = {
                "rank": int(rank),
                "epoch_wall": self.epoch_wall,
                "clock_samples": list(clock_samples or ()),
            }
        return doc

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(rec) for rec in self.spans())

    def dump(self, path: str, fmt: Optional[str] = None,
             rank: Optional[int] = None,
             clock_samples: Optional[List[Dict]] = None) -> str:
        """Write the ring to `path`. fmt: "jsonl" | "chrome"; default
        by extension (.jsonl -> JSONL, anything else -> Chrome JSON).
        Returns the format written."""
        if fmt is None:
            fmt = "jsonl" if str(path).endswith(".jsonl") else "chrome"
        with open(path, "w") as fh:
            if fmt == "jsonl":
                fh.write(self.to_jsonl())
                fh.write("\n")
            else:
                json.dump(self.to_chrome_trace(
                    rank=rank, clock_samples=clock_samples), fh)
                fh.write("\n")
        return fmt


class _ProfiledSpan:
    """A Span whose region is additionally captured by the device
    profiler (observability/profile.py). Entering starts the
    jax.profiler trace first so it covers the whole span."""

    __slots__ = ("_span", "_name", "_started")

    def __init__(self, span: Span, name: str):
        self._span = span
        self._name = name
        self._started = False

    def __enter__(self) -> Span:
        self._started = _profiler.begin(self._name)
        return self._span.__enter__()

    def __exit__(self, *exc) -> bool:
        out = self._span.__exit__(*exc)
        if self._started:
            _profiler.end()
        return out
