from .comm import CommSpec
from .mesh import default_mesh, make_mesh, setup_multihost

__all__ = ["CommSpec", "make_mesh", "default_mesh", "setup_multihost"]
