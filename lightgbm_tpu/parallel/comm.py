"""Communication spec for distributed tree learning.

The reference's whole network layer (src/network/: Bruck allgather,
recursive-halving reduce-scatter, socket/MPI linkers — SURVEY.md §2.4)
collapses to THREE collective call sites expressed with jax.lax ops inside
`shard_map`; XLA picks the wire algorithms (ICI/DCN routing, ring vs
recursive) that src/network/network.cpp:68-301 hand-implements:

- data-parallel  (data_parallel_tree_learner.cpp): histogram merge
  = `psum` / `psum_scatter` over the row-sharded mesh axis.
- feature-parallel (feature_parallel_tree_learner.cpp): best-split sync
  = `all_gather` of per-device SplitInfo + argmax (the max-gain reducer of
  parallel_tree_learner.h:191-214).
- voting-parallel (voting_parallel_tree_learner.cpp, PV-Tree): local top-k
  votes -> `psum` of vote one-hots -> top-2k feature selection -> masked
  histogram `psum`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = ["CommSpec", "check_collective_fault"]


def check_collective_fault() -> None:
    """Host-side injection hook for the `collective_psum` fault site.

    The collectives themselves run inside shard_map-traced code where a
    Python raise would bake into the compiled program, so the GBDT
    growth dispatch calls this at the host boundary before every
    sharded-grower launch — the point where a real interconnect failure
    would surface as a dispatch error. Retried/fallback handling lives
    with the caller (reliability/retry.py)."""
    from ..reliability import faults
    faults.inject("collective_psum")


@dataclasses.dataclass(frozen=True)
class CommSpec:
    """Static distributed-training configuration (hashable for jit)."""
    axis: str = "data"            # mesh axis name
    mode: str = "data"            # "data" | "feature" | "voting"
    num_devices: int = 1
    top_k: int = 20               # voting-parallel top-k (config.top_k)

    def __post_init__(self):
        if self.mode not in ("data", "feature", "voting"):
            raise ValueError(f"unknown parallel mode {self.mode!r}")
