"""Communication spec for distributed tree learning.

The reference's whole network layer (src/network/: Bruck allgather,
recursive-halving reduce-scatter, socket/MPI linkers — SURVEY.md §2.4)
collapses to THREE collective call sites expressed with jax.lax ops inside
`shard_map`; XLA picks the wire algorithms (ICI/DCN routing, ring vs
recursive) that src/network/network.cpp:68-301 hand-implements:

- data-parallel  (data_parallel_tree_learner.cpp): histogram merge
  = `psum` / `psum_scatter` over the row-sharded mesh axis.
- feature-parallel (feature_parallel_tree_learner.cpp): best-split sync
  = `all_gather` of per-device SplitInfo + argmax (the max-gain reducer of
  parallel_tree_learner.h:191-214).
- voting-parallel (voting_parallel_tree_learner.cpp, PV-Tree): local top-k
  votes -> `psum` of vote one-hots -> top-2k feature selection -> masked
  histogram `psum`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

__all__ = ["CommSpec", "check_collective_fault", "guarded_allgather",
           "checkpoint_agree", "checkpoint_coordinator",
           "CheckpointCoordinator"]


def check_collective_fault() -> None:
    """Host-side injection hook for the `collective_psum` fault site.

    The collectives themselves run inside shard_map-traced code where a
    Python raise would bake into the compiled program, so the GBDT
    growth dispatch calls this at the host boundary before every
    sharded-grower launch — the point where a real interconnect failure
    would surface as a dispatch error. Retried/fallback handling lives
    with the caller (reliability/retry.py)."""
    from ..reliability import faults
    faults.inject("collective_psum")


def guarded_allgather(x, label: str = "allgather") -> np.ndarray:
    """THE host-boundary allgather: every cross-process gather in the
    library funnels through here so one choke point carries both the
    `collective_psum` fault site (rank_death chaos schedules included)
    and the collective-watchdog deadline bracket. A peer that died
    before this call leaves us blocked inside `process_allgather`; the
    watchdog deadline turns that into a named "rank k last seen Ns ago"
    abort instead of an eternal hang.

    Each call also piggybacks one wall-clock stamp per rank on the SAME
    pytree allgather (one extra float64 on the wire, zero extra
    collectives): the samples feed the cross-rank clock alignment of
    ``python -m lightgbm_tpu.observability merge`` and the
    lightgbm_tpu_clock_skew metrics. A membership epoch (one int64)
    rides along the same way: a rank resumed from a stale membership
    record would otherwise exchange rows sharded for the WRONG world —
    every gather cross-checks epochs and raises on divergence
    (distributed/elastic.py, stale-epoch rejection)."""
    import time
    from jax.experimental import multihost_utils
    from ..reliability.watchdog import collective_guard
    check_collective_fault()
    arr = np.asarray(x)
    if arr.ndim:        # ascontiguousarray would promote 0-d to 1-d,
        arr = np.ascontiguousarray(arr)   # changing the wire shape

    with collective_guard(label):
        gathered, walls, epochs = multihost_utils.process_allgather(
            (arr, np.float64(time.time()), np.int64(_local_epoch())))
    _record_clock_sample(label, walls)
    _check_epochs(label, epochs)
    return np.asarray(gathered)


def _local_epoch() -> int:
    """This rank's membership epoch, stamped onto every gather."""
    from ..distributed.elastic import current_epoch
    return current_epoch()


def _check_epochs(label: str, epochs) -> None:
    """Stale-epoch rejection: every rank sees every rank's epoch on the
    gather it just completed, so divergence raises on ALL ranks in the
    same bracket (rank-uniform data -> rank-uniform control flow; no
    COLL002 split-brain)."""
    from ..distributed.elastic import check_epoch_agreement
    check_epoch_agreement(np.asarray(epochs).reshape(-1), label)


def _record_clock_sample(label: str, walls) -> None:
    """Feed one piggybacked clock sample (every rank's pre-collective
    wall stamp) to the observability registry; never raises — clock
    forensics must not fail the collective that carried them."""
    try:
        from ..observability.registry import registry
        registry.record_clock_sample(label,
                                     np.asarray(walls).reshape(-1))
    except Exception:       # pragma: no cover - forensics only
        pass


def checkpoint_agree(value: int, label: str = "checkpoint_agree"
                     ) -> np.ndarray:
    """One-int agreement collective (the PR-8 agreement-flag idiom):
    every rank contributes `value`, every rank sees all of them, and
    all can decide identically — used by the coordinated checkpoint
    protocol to agree on the iteration to snapshot and on shard-write
    success before the commit marker is cut. Delegates to
    `guarded_allgather`, inheriting its fault site and watchdog
    bracket."""
    out = guarded_allgather(np.asarray([int(value)], dtype=np.int64),
                            label=label)
    return out.reshape(-1)


@dataclasses.dataclass(frozen=True)
class CheckpointCoordinator:
    """The handle `save_checkpoint` uses to run the multihost commit
    protocol. Exists only when >1 process participates — single-host
    saves keep the original (and cheaper) tmp+rename path."""
    rank: int
    world: int

    def agree(self, value: int, label: str = "checkpoint_agree"):
        return checkpoint_agree(value, label=label)


def checkpoint_coordinator() -> Optional[CheckpointCoordinator]:
    """A `CheckpointCoordinator` for this run, or None on one process
    (coordination degenerates to nothing — no collectives issued)."""
    import jax
    try:
        world = jax.process_count()
    except RuntimeError:
        world = 1
    if world <= 1:
        return None
    return CheckpointCoordinator(rank=jax.process_index(), world=world)


@dataclasses.dataclass(frozen=True)
class CommSpec:
    """Static distributed-training configuration (hashable for jit)."""
    axis: str = "data"            # mesh axis name
    mode: str = "data"            # "data" | "feature" | "voting"
    num_devices: int = 1
    top_k: int = 20               # voting-parallel top-k (config.top_k)
    # histogram merge algorithm for the row-sharded modes:
    # "psum" replicates the full [S, F, B, 3] histogram on every device
    # (the seed behavior); "reduce_scatter" gives each device a
    # contiguous feature shard of the global histogram and merges only
    # [S]-sized split candidates (distributed/hist_agg.py — the
    # reference's ReduceScatter of data_parallel_tree_learner.cpp:184).
    hist_agg: str = "psum"

    def __post_init__(self):
        if self.mode not in ("data", "feature", "voting"):
            raise ValueError(f"unknown parallel mode {self.mode!r}")
        if self.hist_agg not in ("psum", "reduce_scatter"):
            raise ValueError(
                f"unknown histogram aggregation {self.hist_agg!r} "
                f"(expected 'psum' or 'reduce_scatter')")
