"""Sharded tree learner: shard_map'ped growth over a device mesh.

The factory role of the reference's CreateTreeLearner crossbar
(tree_learner.cpp:16-64: device x {serial,feature,data,voting}) — here the
"device" dimension is always TPU/XLA and the parallelism dimension picks the
collective pattern (CommSpec). Parallel learners in the reference are
templates OVER the serial learner (parallel_tree_learner.h:26-107); here the
same single `grow_tree` body runs inside `shard_map`, with its collectives
activated by `comm`.

Sharding contract (1-D mesh, axis "data"):
- data/voting: bins/grad/hess/cnt row-sharded; tree replicated out.
- feature: bins replicated (the reference feature-parallel replicates data,
  docs/Features.rst:109); the per-device feature shard is derived from
  axis_index inside the grower.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # jax < 0.6: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map_exp(f, **kw)

from ..learner.grower import grow_tree
from .comm import CommSpec

__all__ = ["make_sharded_grower", "shard_rows", "replicate"]


def shard_rows(mesh: Mesh, *arrays):
    """Place arrays with rows sharded over the mesh axis."""
    axis = mesh.axis_names[0]
    out = []
    for a in arrays:
        spec = P(axis) if a.ndim >= 1 else P()
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return out if len(out) > 1 else out[0]


def replicate(mesh: Mesh, *arrays):
    out = [jax.device_put(a, NamedSharding(mesh, P())) for a in arrays]
    return out if len(out) > 1 else out[0]


def make_sharded_grower(mesh: Mesh, comm: CommSpec, *, num_leaves: int,
                        max_depth: int, hp, leafwise: bool, bmax: int,
                        feature_block: int = 8, use_mxu: bool = False,
                        mxu_kwargs: Optional[dict] = None,
                        interpret: bool = False, monotone=None,
                        monotone_method: str = "basic",
                        interaction_groups: Optional[tuple] = None,
                        feature_fraction_bynode: float = 1.0,
                        with_rng: bool = False, forced=None,
                        cegb_cfg=None, with_cegb_state: bool = False,
                        efb=None, with_bins_ft: bool = False):
    """Build a shard_map'ped grower with the given static config.

    use_mxu (data-parallel only) runs the MXU grower inside shard_map
    with per-pass histogram psum over the mesh axis — the TPU form of
    DataParallelTreeLearner's histogram Reduce-Scatter
    (data_parallel_tree_learner.cpp:184-186). Other modes (and the CPU
    fallback) keep the portable scatter grower, whose collectives live
    inside grow_tree itself.

    with_rng=True adds a replicated rng_key argument (the 9th) so
    per-node feature sampling / extra_trees / quantized rounding take a
    per-iteration key: every shard holds the identical key, samples the
    identical masks, and therefore takes identical split decisions — the
    reference syncs sampling seeds across machines the same way
    (application.cpp:170-175 GlobalSyncUpByMin of seeds).

    with_bins_ft=True adds a trailing feature-sharded argument: the
    [N_global, F/world] transpose from
    distributed/hist_agg.py::build_feature_shards, enabling the exact
    reduce-scatter histogram flavor inside grow_tree."""
    axis = comm.axis
    data_spec = P(axis) if comm.mode in ("data", "voting") else P()

    if use_mxu and comm.mode == "data":
        from ..learner.grower_mxu import grow_tree_mxu
        grower = functools.partial(
            grow_tree_mxu, num_leaves=num_leaves, max_depth=max_depth,
            hp=hp, bmax=bmax, psum_axis=axis, interpret=interpret,
            monotone=monotone, interaction_groups=interaction_groups,
            feature_fraction_bynode=feature_fraction_bynode,
            forced=forced, cegb_cfg=cegb_cfg, efb=efb,
            **(mxu_kwargs or {}))
    else:
        grower = functools.partial(
            grow_tree, num_leaves=num_leaves, max_depth=max_depth, hp=hp,
            leafwise=leafwise, bmax=bmax, feature_block=feature_block,
            comm=comm, monotone=monotone,
            monotone_method=monotone_method,
            interaction_groups=interaction_groups,
            feature_fraction_bynode=feature_fraction_bynode,
            forced=forced, cegb_cfg=cegb_cfg, efb=efb)

    # forced-split spec arrays are baked in as static closures (tree-wide
    # constants); CEGB state travels as a live argument because the
    # row_feat_used flags persist and grow across trees. Its per-row
    # component shards with the rows (reference is_feature_used_ is
    # per-datapoint, cost_effective_gradient_boosting.hpp:56).
    in_specs = (data_spec, data_spec, data_spec, data_spec,
                P(), P(), P(), P())
    if with_rng:
        in_specs += (P(),)
    if with_cegb_state:
        # the per-row flags only exist under the lazy penalty; the (1,1)
        # placeholder otherwise must stay replicated
        rfu_spec = data_spec if (cegb_cfg is not None and
                                 cegb_cfg.has_lazy) else P()
        in_specs += ((P(), P(), P(), rfu_spec),)
    if with_bins_ft:
        in_specs += (P(None, axis),)
    out_specs = (P(), data_spec)
    if with_cegb_state:
        out_specs = (P(), data_spec, (P(), rfu_spec))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False)
    def sharded(bins, grad, hess, cnt, feature_mask, num_bins,
                missing_is_nan, is_cat, *rest):
        rest = list(rest)
        kw = {}
        if with_rng:
            kw["rng_key"] = rest.pop(0)
        if with_cegb_state:
            kw["cegb_state"] = tuple(rest.pop(0))
        if with_bins_ft:
            kw["bins_ft"] = rest.pop(0)
        return grower(bins, grad, hess, cnt, feature_mask, num_bins,
                      missing_is_nan, is_cat, **kw)

    return jax.jit(sharded)
