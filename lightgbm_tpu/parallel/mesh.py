"""Device mesh helpers.

The reference initializes a process-global Network singleton from a machine
list (network.cpp:17-30, linkers_socket.cpp). The TPU equivalent is a
`jax.sharding.Mesh` over the visible devices; multi-host pods join via
`jax.distributed.initialize` (DCN) before constructing the mesh — the
moral analog of the reference's `Network::Init`.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "default_mesh", "init_distributed"]


def make_mesh(num_devices: int = 0, axis: str = "data") -> Mesh:
    devices = jax.devices()
    if num_devices <= 0:
        num_devices = len(devices)
    if num_devices > len(devices):
        raise ValueError(
            f"requested {num_devices} devices, only {len(devices)} visible")
    return Mesh(np.array(devices[:num_devices]), (axis,))


def default_mesh(axis: str = "data") -> Mesh:
    return make_mesh(0, axis)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host initialization (reference Network::Init + machine list;
    here jax.distributed handles rendezvous over DCN)."""
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
