"""Device mesh helpers.

The reference initializes a process-global Network singleton from a machine
list (network.cpp:17-30, linkers_socket.cpp). The TPU equivalent is a
`jax.sharding.Mesh` over the visible devices; multi-host pods join via
`jax.distributed.initialize` (DCN) before constructing the mesh — the
moral analog of the reference's `Network::Init`.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "default_mesh", "init_distributed",
           "provision_virtual_devices", "setup_multihost"]


def provision_virtual_devices(n_devices: int) -> None:
    """Force an n-device virtual CPU backend (the reference's no-cluster
    distributed testing, _test_distributed.py:54-135, is N localhost
    processes; ours is N virtual XLA host devices).

    Must run BEFORE the first backend touch: once any jax.devices() call
    initializes a backend, the CPU device count is latched for the process.
    jax may be pre-imported by the harness, so env vars alone are too
    late — the jax.config updates are what actually take effect. This
    permanently switches the process (and, via os.environ, subprocesses)
    to the CPU platform; it is a one-shot test/dryrun provision, not a
    runtime mode toggle.
    """
    try:
        from jax._src import xla_bridge as _xb
        already_up = _xb.backends_are_initialized()
    except Exception:
        # Private API moved: attempt the config mutations below —
        # jax_num_cpu_devices raises its own clear error post-init, and
        # succeeds pre-init, so provisioning still works either way.
        already_up = False
    if already_up:
        if len(jax.devices()) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices but the JAX backend was already "
                f"initialized with {len(jax.devices())}; call "
                f"provision_virtual_devices before any other JAX use")
        return
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={n_devices}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except (AttributeError, KeyError):
        pass  # older jax without this config: XLA_FLAGS alone works pre-init
    jax.config.update("jax_platforms", "cpu")
    # verify the provision actually took: when the initialized-backend
    # detection above is unavailable (private API moved) and some
    # harness touched JAX first, the config mutations silently miss the
    # already-latched backend — the resulting single-device mesh errors
    # would surface far away, in shard_map. Touching jax.devices() here
    # latches the backend we just configured, which the very next call
    # (make_mesh) does anyway.
    got = len(jax.devices())
    if got < n_devices:
        raise RuntimeError(
            f"provision_virtual_devices({n_devices}) had no effect: the "
            f"JAX backend is up with {got} device(s). The CPU device "
            f"count latches at first backend use — call "
            f"provision_virtual_devices before any other JAX use "
            f"(imports are fine; jax.devices()/jit/device_put are not).")


def make_mesh(num_devices: int = 0, axis: str = "data") -> Mesh:
    devices = jax.devices()
    if num_devices <= 0:
        num_devices = len(devices)
    if num_devices > len(devices):
        raise ValueError(
            f"requested {num_devices} devices, only {len(devices)} visible")
    return Mesh(np.array(devices[:num_devices]), (axis,))


def default_mesh(axis: str = "data") -> Mesh:
    return make_mesh(0, axis)


def _enable_cpu_collectives() -> None:
    """Cross-process computations on the CPU backend need a real
    collectives implementation — with the default ("none") every
    multi-process jit/allgather fails with "Multiprocess computations
    aren't implemented on the CPU backend". jaxlib ships gloo; select
    it before the backend initializes. Only applies when the process
    is pinned to CPU (multi-process CPU tests, the chaos harness);
    TPU runs keep the default ICI/DCN transport."""
    plat = os.environ.get("JAX_PLATFORMS") or ""
    try:
        plat = plat or (jax.config.jax_platforms or "")
    except AttributeError:
        pass
    if "cpu" not in plat:
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, KeyError, ValueError):
        pass    # older jax: no such config (and no CPU collectives)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host initialization (reference Network::Init + machine list;
    here jax.distributed handles rendezvous over DCN)."""
    if coordinator_address is not None:
        _enable_cpu_collectives()
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)


def _local_addresses() -> set:
    import socket
    addrs = {"127.0.0.1", "localhost", "0.0.0.0"}
    try:
        host = socket.gethostname()
        addrs.add(host)
        for ip in socket.gethostbyname_ex(host)[2]:
            addrs.add(ip)
    except OSError:
        pass
    return addrs


def setup_multihost(num_machines: int, machines: str = "",
                    machine_list_filename: str = "",
                    local_listen_port: int = 12400) -> None:
    """Join a multi-machine training group from the reference's network
    config surface (config.h: machines / machine_list_filename /
    local_listen_port / num_machines; Network::Init + linkers_socket.cpp
    machine-list parsing). The TPU equivalent is a jax.distributed
    rendezvous over DCN: machine 0's entry is the coordinator, each
    process finds its rank by matching its local addresses + listen port
    in the list (override with env LIGHTGBM_TPU_MACHINE_RANK). After
    this, jax.devices() is the GLOBAL device set and the mesh/shard_map
    collectives span all hosts."""
    import os

    # NOTE: jax.process_count() would itself initialize the backend;
    # consult the distributed client state directly instead
    try:
        from jax._src.distributed import global_state as _dstate
        if _dstate.client is not None:
            # rendezvous already done (e.g. by the launcher). A stale
            # rendezvous that doesn't match THIS machine list would make
            # collectives hang or span wrong ranks — verify, don't trust.
            want_rank = os.environ.get("LIGHTGBM_TPU_MACHINE_RANK")
            got_n = getattr(_dstate, "num_processes", None)
            got_rank = getattr(_dstate, "process_id", None)
            if got_n is not None and got_n != num_machines:
                raise RuntimeError(
                    f"a jax.distributed rendezvous already exists with "
                    f"{got_n} processes, but num_machines={num_machines} "
                    f"was requested. Re-fitting with a different machine "
                    f"set requires fresh worker processes (the JAX "
                    f"rendezvous is once-per-process, like the "
                    f"reference's Network::Init socket ring).")
            if (want_rank is not None and got_rank is not None
                    and int(want_rank) != got_rank):
                raise RuntimeError(
                    f"existing rendezvous has rank {got_rank} but "
                    f"LIGHTGBM_TPU_MACHINE_RANK={want_rank}; restart the "
                    f"worker processes to change machine ranks.")
            return
    except ImportError:
        pass
    try:
        from jax._src import xla_bridge as _xb
        if _xb.backends_are_initialized():
            raise RuntimeError(
                "multi-machine setup must run before any JAX backend use "
                "(the reference calls Network::Init before loading data, "
                "application.cpp:165). Call "
                "lightgbm_tpu.setup_multihost(...) at program start, "
                "before constructing Datasets or Boosters.")
    except ImportError:
        pass
    entries = []
    if machines:
        for item in machines.split(","):
            item = item.strip()
            if not item:
                continue
            host, _, port = item.rpartition(":")
            entries.append((host, int(port)))
    elif machine_list_filename:
        with open(machine_list_filename) as fh:
            for line in fh:
                parts = line.split()
                if len(parts) >= 2:
                    entries.append((parts[0], int(parts[1])))
    if not entries:
        raise ValueError(
            "num_machines > 1 requires `machines` (host:port,...) or "
            "machine_list_filename (reference config.h machine list)")
    if len(entries) != num_machines:
        raise ValueError(
            f"machine list has {len(entries)} entries but "
            f"num_machines={num_machines}")
    rank_env = os.environ.get("LIGHTGBM_TPU_MACHINE_RANK")
    if rank_env is not None:
        rank = int(rank_env)
    else:
        local = _local_addresses()
        matches = [i for i, (h, p) in enumerate(entries)
                   if h in local and p == local_listen_port]
        if len(matches) != 1:
            raise ValueError(
                "could not determine this machine's rank from the "
                "machine list (matched %d entries); set "
                "LIGHTGBM_TPU_MACHINE_RANK" % len(matches))
        rank = matches[0]
    coordinator = f"{entries[0][0]}:{entries[0][1]}"
    _enable_cpu_collectives()
    jax.distributed.initialize(coordinator, num_machines, rank)
    _seed_membership_epoch(num_machines)


def _seed_membership_epoch(world: int) -> None:
    """Adopt the membership epoch a reincarnating supervisor handed us
    (LIGHTGBM_TPU_EPOCH, written when an elastic shrink committed) so
    the very first guarded collective of the new world already carries
    the agreed epoch — a straggler resumed from the OLD membership
    record diverges on that gather and is rejected instead of silently
    exchanging rows sharded for the wrong world."""
    epoch_env = os.environ.get("LIGHTGBM_TPU_EPOCH")
    try:
        from ..distributed.elastic import set_epoch
        if epoch_env is not None:
            set_epoch(int(epoch_env))
        from ..observability.registry import registry
        registry.record_membership(
            int(epoch_env) if epoch_env is not None else 0, world)
    except Exception:   # pragma: no cover - forensics must not block init
        pass
