"""Device mesh helpers.

The reference initializes a process-global Network singleton from a machine
list (network.cpp:17-30, linkers_socket.cpp). The TPU equivalent is a
`jax.sharding.Mesh` over the visible devices; multi-host pods join via
`jax.distributed.initialize` (DCN) before constructing the mesh — the
moral analog of the reference's `Network::Init`.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "default_mesh", "init_distributed",
           "provision_virtual_devices"]


def provision_virtual_devices(n_devices: int) -> None:
    """Force an n-device virtual CPU backend (the reference's no-cluster
    distributed testing, _test_distributed.py:54-135, is N localhost
    processes; ours is N virtual XLA host devices).

    Must run BEFORE the first backend touch: once any jax.devices() call
    initializes a backend, the CPU device count is latched for the process.
    jax may be pre-imported by the harness, so env vars alone are too
    late — the jax.config updates are what actually take effect. This
    permanently switches the process (and, via os.environ, subprocesses)
    to the CPU platform; it is a one-shot test/dryrun provision, not a
    runtime mode toggle.
    """
    try:
        from jax._src import xla_bridge as _xb
        already_up = _xb.backends_are_initialized()
    except Exception:
        # Private API moved: attempt the config mutations below —
        # jax_num_cpu_devices raises its own clear error post-init, and
        # succeeds pre-init, so provisioning still works either way.
        already_up = False
    if already_up:
        if len(jax.devices()) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices but the JAX backend was already "
                f"initialized with {len(jax.devices())}; call "
                f"provision_virtual_devices before any other JAX use")
        return
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={n_devices}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except (AttributeError, KeyError):
        pass  # older jax without this config: XLA_FLAGS alone works pre-init
    jax.config.update("jax_platforms", "cpu")


def make_mesh(num_devices: int = 0, axis: str = "data") -> Mesh:
    devices = jax.devices()
    if num_devices <= 0:
        num_devices = len(devices)
    if num_devices > len(devices):
        raise ValueError(
            f"requested {num_devices} devices, only {len(devices)} visible")
    return Mesh(np.array(devices[:num_devices]), (axis,))


def default_mesh(axis: str = "data") -> Mesh:
    return make_mesh(0, axis)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host initialization (reference Network::Init + machine list;
    here jax.distributed handles rendezvous over DCN)."""
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
