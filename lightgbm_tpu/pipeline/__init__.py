"""Pipelined device-resident training executor.

Double-buffers the fused block dispatch (boosting/fused.py): while
block k runs on device, the host unpacks block k-1's stacked trees into
per-tree views, updates the adaptive block scheduler and observability,
and only then syncs block k's per-iteration metric arrays for the
callback/early-stop protocol. Models are bit-identical to the
non-pipelined block loop in engine.train (the parity oracle —
tests/test_pipeline.py); the win is that per-tree host work and device
compute overlap instead of alternating.

Engaged by engine.train when `pipeline=true` (default) and the run is
already block-dispatch eligible; `pipeline=false` reverts to the
non-pipelined loop unchanged.
"""

from .executor import PipelineStats, run_pipelined
from .scheduler import AdaptiveBlockScheduler

__all__ = ["AdaptiveBlockScheduler", "PipelineStats", "run_pipelined"]
