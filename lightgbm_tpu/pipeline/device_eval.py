"""In-graph valid-set metrics over a fused block's score trajectory.

The non-pipelined block loop pulls each valid set's FULL per-iteration
score matrix to the host ([block, N] or [block, N, C] f32) and runs
metrics.py on it — on a remoted accelerator that transfer dwarfs the
metric arithmetic. Here the metric reductions themselves ride the
device: one vmapped dispatch per valid set turns the trajectory into a
[block, n_metrics] f32 array, so the early-stop/callback protocol syncs
a few hundred bytes per block instead of the score matrices.

Fidelity contract: formulas mirror metrics.py term-for-term (weighted
mean = (loss * w).sum() / sum_weight, the same eps floors, the same
convert_output application), but arithmetic is f32 on device while
metrics.py computes in np.float64 — logged metric VALUES may differ in
the trailing digits. Trees, scores and split decisions never flow
through this module, so models are unaffected; only an exactly-tied
early-stop comparison could flip, which is why the parity suite pins
best_iteration across both eval paths. The one deliberate deviation:
upper clip bounds use 1e-7 where metrics.py uses 1e-15, because
1 - 1e-15 rounds to 1.0 in f32 and log(1 - p) would hit -inf.

Engagement is all-or-nothing per run: if ANY metric on ANY valid set
has no device kernel (the rank/AUC families need per-query sorts), the
executor falls back to host evaluation for everything — mixed cadences
would complicate the sync schedule for no measured win.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["DeviceEval", "build_device_eval"]

_EPS = 1e-15          # lower clip floor (f32-representable; metrics.py)
_EPS_HI = 1e-7        # upper clip margin: 1 - 1e-15 == 1.0 in f32

# metrics.py _PointwiseMetric family with a direct jnp transcription.
# cross_entropy_lambda is excluded (its weighted link function folds the
# weight INSIDE the loss, a different averaging contract), as are the
# sort-based families (auc, average_precision, auc_mu, ndcg, map).
_POINTWISE = frozenset((
    "l2", "rmse", "l1", "quantile", "huber", "fair", "poisson", "mape",
    "gamma", "gamma_deviance", "tweedie", "binary_logloss",
    "binary_error", "cross_entropy", "kullback_leibler"))
_MULTI = frozenset(("multi_logloss", "multi_error"))


def _point_loss(m, p, y):
    """jnp transcription of metrics.py point_loss for metric m."""
    n, cfg = m.name, m.config
    if n in ("l2", "rmse"):
        return (p - y) ** 2
    if n == "l1":
        return jnp.abs(p - y)
    if n == "quantile":
        a = float(cfg.alpha)
        d = y - p
        return jnp.where(d >= 0, a * d, (a - 1.0) * d)
    if n == "huber":
        a = float(cfg.alpha)
        d = jnp.abs(p - y)
        return jnp.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))
    if n == "fair":
        c = float(cfg.fair_c)
        x = jnp.abs(p - y)
        return c * x - c * c * jnp.log1p(x / c)
    if n == "poisson":
        pp = jnp.maximum(p, 1e-10)
        return pp - y * jnp.log(pp)
    if n == "mape":
        return jnp.abs((y - p) / jnp.maximum(1.0, jnp.abs(y)))
    if n == "gamma":
        theta = -1.0 / jnp.maximum(p, _EPS)
        b = -jnp.log(-theta)
        # psi=1 makes metrics.py's c term log(y) - log(y); keep it so
        # non-positive labels propagate the same NaNs
        return -(y * theta - b) - (jnp.log(y) - jnp.log(y))
    if n == "gamma_deviance":
        x = y / jnp.maximum(p, 1e-9)
        return 2.0 * (x - jnp.log(jnp.maximum(x, 1e-9)) - 1.0)
    if n == "tweedie":
        rho = float(cfg.tweedie_variance_power)
        pp = jnp.maximum(p, 1e-10)
        a = y * jnp.power(pp, 1.0 - rho) / (1.0 - rho)
        b = jnp.power(pp, 2.0 - rho) / (2.0 - rho)
        return -a + b
    if n in ("binary_logloss", "cross_entropy"):
        pp = jnp.clip(p, _EPS, 1.0 - _EPS_HI)
        return -(y * jnp.log(pp) + (1.0 - y) * jnp.log(1.0 - pp))
    if n == "binary_error":
        return ((p > 0.5) != (y > 0)).astype(jnp.float32)
    if n == "kullback_leibler":
        pp = jnp.clip(p, _EPS, 1.0 - _EPS_HI)
        yy = jnp.clip(y, _EPS, 1.0 - _EPS_HI)
        return (yy * jnp.log(yy / pp) +
                (1.0 - yy) * jnp.log((1.0 - yy) / (1.0 - pp)))
    raise KeyError(n)


def _supported(m, num_class: int) -> bool:
    n = getattr(m, "name", None)
    if num_class > 1:
        return n in _MULTI
    return n in _POINTWISE


class DeviceEval:
    """Per-valid-set compiled trajectory evaluators plus the metadata
    to rebuild the engine's evaluation_result_list protocol on host."""

    def __init__(self, fns, names, valid_names):
        self.fns = fns                # per valid set: fn(traj)->[b, nm]
        self.names = names            # per valid set: metric name list
        self.valid_names = valid_names

    def dispatch(self, trajs) -> List[Optional[jax.Array]]:
        """Launch the metric reductions for every valid set (async —
        returns device arrays without syncing)."""
        return [fn(trajs[vi]) if fn is not None else None
                for vi, fn in enumerate(self.fns)]

    def evlist_at(self, mhost: List[Optional[np.ndarray]], j: int) -> List:
        """(valid_name, metric_name, value, higher_better) tuples for
        inner iteration j, replicating GBDT._eval's dict collapse of
        duplicate metric names and Booster.eval_valid's tuple shape."""
        res = []
        for vi, vn in enumerate(self.valid_names):
            if mhost[vi] is None:
                continue
            vals = {}
            for mi, name in enumerate(self.names[vi]):
                vals[name] = float(mhost[vi][j, mi])
            for name, v in vals.items():
                higher = name.split("@")[0] in (
                    "auc", "ndcg", "map", "average_precision", "auc_mu")
                res.append((vn, name, v, higher))
        return res


def build_device_eval(booster) -> Optional[DeviceEval]:
    """DeviceEval over every valid set of `booster`, or None when any
    metric anywhere lacks a device kernel (host-eval fallback)."""
    gb = booster.gbdt
    valid_metrics = getattr(gb, "valid_metrics", None)
    if not valid_metrics:
        return None
    num_class = int(getattr(gb, "num_tree_per_iteration", 1))
    for ms in valid_metrics:
        for m in ms:
            if not _supported(m, num_class):
                return None
    obj = gb.objective
    fns, names = [], []
    for ms in valid_metrics:
        if not ms:
            fns.append(None)
            names.append([])
            continue
        fns.append(_make_set_fn(ms, obj, num_class))
        names.append([m.name for m in ms])
    return DeviceEval(fns, names, list(booster.name_valid_sets))


def _make_set_fn(ms, obj, num_class: int):
    """Compile fn(traj [b, N] | [b, N, C]) -> [b, len(ms)] f32 for one
    valid set's metric list."""
    label = jnp.asarray(ms[0].label, jnp.float32)
    weight = None if ms[0].weight is None \
        else jnp.asarray(ms[0].weight, jnp.float32)
    sum_weight = float(ms[0].sum_weight)
    idx = None
    if num_class > 1:
        idx = jnp.asarray(ms[0].label.astype(np.int64), jnp.int32)

    def avg(loss):
        if weight is None:
            return jnp.mean(loss)
        return jnp.sum(loss * weight) / sum_weight

    def one_point(s):
        conv = None   # convert_output(s), computed once, shared

        def converted():
            nonlocal conv
            if conv is None:
                conv = obj.convert_output(s) if obj is not None else s
            return conv

        vals = []
        for m in ms:
            if m.name == "multi_logloss":
                p = converted()
                pt = jnp.take_along_axis(p, idx[:, None], axis=1)[:, 0]
                vals.append(avg(-jnp.log(jnp.clip(pt, _EPS, None))))
            elif m.name == "multi_error":
                k = int(m.config.multi_error_top_k)
                tp = jnp.take_along_axis(s, idx[:, None], axis=1)
                rank = (s > tp).sum(axis=1)
                vals.append(avg((rank >= k).astype(jnp.float32)))
            else:
                p = converted() if getattr(m, "convert_score", True) else s
                v = avg(_point_loss(m, p, label))
                if m.name == "rmse":
                    v = jnp.sqrt(v)
                vals.append(v)
        return jnp.stack(vals)

    return jax.jit(jax.vmap(one_point))
