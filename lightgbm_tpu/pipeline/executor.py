"""Double-buffered training loop: overlap host work with device blocks.

The non-pipelined block loop in engine.train alternates strictly:
dispatch a fused block, sync, unpack its stacked trees, evaluate, run
callbacks, repeat — the device idles through all host work. This
executor reorders the same steps around JAX's async dispatch so the
expensive host step (unpacking K stacked TreeArrays into per-tree
views) always runs while the NEXT block is computing:

    dispatch block k (async)  ──────────────┐ device busy
    launch block k's metric reductions      │
    finalize block k-1's trees  <── overlap │ host busy
    scheduler / observability updates       │
    sync block k's metrics  ────────────────┘ explicit sync point
    callbacks j = 0..b-1 (early stop may raise)

Nothing is speculative: block k+1 is never dispatched before block k's
early-stop decisions, so the executor trains the byte-identical model
of the non-pipelined loop — which stays available via pipeline=false as
the parity oracle (tests/test_pipeline.py). Early stop mid-block
replicates the engine's protocol exactly: finalize this block's trees,
restore block-final valid scores, roll back the post-stop trees, pin
valid scores to the stopping iteration's trajectory point, re-raise.

Metric values come from device reductions when every metric supports it
(device_eval.py) — the sync then moves a [b, n_metrics] array instead
of full score matrices — else from the host metrics path, identically
to the engine loop.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

import numpy as np

from ..callback import EarlyStopException
from ..observability import registry as _obs
from ..observability.profile import profiler as _profiler
from .device_eval import build_device_eval
from .scheduler import AdaptiveBlockScheduler

__all__ = ["PipelineStats", "run_pipelined"]


class PipelineStats:
    """Per-run pipeline accounting, attached to the booster's GBDT as
    `_pipeline_stats` unconditionally (bench.py reads it with
    observability off; registry.record_pipeline_block mirrors it into
    the unified snapshot when observability is on)."""

    def __init__(self):
        self.blocks = 0
        self.iterations = 0
        self.block_sizes: List[int] = []
        self.host_ms: List[float] = []      # overlapped host work / block
        self.device_ms: List[float] = []    # dispatch->results wall / block

    def add(self, k: int, host_ms: float, device_ms: float) -> None:
        self.blocks += 1
        self.iterations += int(k)
        self.block_sizes.append(int(k))
        self.host_ms.append(float(host_ms))
        self.device_ms.append(float(device_ms))

    @property
    def overlap_frac(self) -> float:
        """Fraction of total block wall covered by overlapped host
        work — the pipelining win (0 = fully serial)."""
        wall = sum(self.device_ms)
        if wall <= 0:
            return 0.0
        return min(1.0, sum(self.host_ms) / wall)

    def as_dict(self) -> dict:
        return {
            "blocks": self.blocks,
            "iterations": self.iterations,
            "block_sizes": list(self.block_sizes),
            "host_ms": [round(v, 3) for v in self.host_ms],
            "device_ms": [round(v, 3) for v in self.device_ms],
            "overlap_frac": round(self.overlap_frac, 4),
        }


def run_pipelined(booster, *, start_iter: int, num_boost_round: int,
                  base_block: int, run_callbacks: Callable[[int, List], None],
                  has_valid: bool, stopping_rounds: int = 0) -> List:
    """Train [start_iter, num_boost_round) pipelined; returns the last
    evaluation_result_list. Raises EarlyStopException (and any callback
    exception) with the booster in the exact state the non-pipelined
    block loop would leave it in — engine.train's handlers run
    unchanged."""
    gb = booster.gbdt
    cfg = booster.config
    sched = AdaptiveBlockScheduler(
        base_block, adaptive=bool(cfg.pipeline_adaptive_blocks),
        target_ms=float(cfg.pipeline_target_block_ms),
        max_block=int(cfg.pipeline_max_block),
        stopping_rounds=int(stopping_rounds or 0))
    dev = build_device_eval(booster) \
        if has_valid and cfg.pipeline_device_eval else None
    stats = PipelineStats()
    gb._pipeline_stats = stats
    pending: Optional[dict] = None
    evlist: List = []
    i = start_iter
    try:
        while i < num_boost_round:
            b = sched.next_block(num_boost_round - i)
            was_built = getattr(gb, "_fused_run", None) is None
            t0 = time.perf_counter()
            with _profiler.capture("pipeline_block") as _capturing:
                handle = booster.update_batch_dispatch(b)
                traj = getattr(gb, "_fused_valid_traj", None)
                mx = dev.dispatch(traj) \
                    if dev is not None and traj is not None else None
                if _capturing:
                    # live device capture: force the async block to
                    # complete inside the trace window (costs the
                    # overlap for this one profiled block only)
                    import jax
                    jax.block_until_ready((handle, traj, mx))
            t1 = time.perf_counter()
            # ---- overlapped host window: the previous block's trees
            # unpack while this block runs on device
            if pending is not None:
                booster.finalize_block(pending)
                pending = None
            t2 = time.perf_counter()
            # ---- explicit sync: small metric arrays in device-eval
            # mode; in host mode the trajectory syncs lazily when the
            # metrics first touch it below
            mhost = [None if a is None else np.asarray(a) for a in mx] \
                if mx is not None else None
            t3 = time.perf_counter()
            host_ms = (t2 - t1) * 1e3
            block_ms = (t3 - t0) * 1e3
            stats.add(b, host_ms, block_ms)
            if _obs.enabled:
                _obs.record_pipeline_block(
                    i, b, t0, (t3 - t0), (t2 - t1),
                    min(1.0, host_ms / block_ms) if block_ms > 0 else 0.0)
            # ---- per-iteration metric/callback protocol (identical to
            # the engine block loop; early stop decisions gate the next
            # dispatch, so nothing downstream is speculative)
            finalized = False
            try:
                if traj is not None and has_valid:
                    try:
                        for j in range(b):
                            if mhost is not None:
                                evlist = dev.evlist_at(mhost, j)
                            else:
                                for vi in range(len(traj)):
                                    gb.valid_scores[vi] = traj[vi][j]
                                evlist = booster.eval_valid()
                            run_callbacks(i + j, evlist)
                    except EarlyStopException:
                        # this block's trees must exist before rollback
                        # pops them; then replicate the engine's restore
                        # protocol: block-final scores, roll the
                        # post-stop trees back, pin valid scores to the
                        # stopping iteration's trajectory point
                        booster.finalize_block(handle)
                        finalized = True
                        for vi in range(len(traj)):
                            gb.valid_scores[vi] = traj[vi][b - 1]
                        for _ in range(b - 1 - j):
                            booster.rollback_one_iter()
                        for vi in range(len(traj)):
                            gb.valid_scores[vi] = traj[vi][j]
                        raise
                elif has_valid:
                    # belt-and-braces (mirrors engine.train): a missing
                    # trajectory degrades to block-end eval cadence
                    evlist = booster.eval_valid()
                    run_callbacks(i + b - 1, evlist)
                else:
                    for j in range(b):
                        evlist = []
                        run_callbacks(i + j, evlist)
            except BaseException:
                # any other exit: leave the booster consistent — trees
                # hold the full block, so scores must too
                if not finalized:
                    booster.finalize_block(handle)
                    if traj is not None:
                        for vi in range(len(traj)):
                            gb.valid_scores[vi] = traj[vi][b - 1]
                raise
            # in host-eval mode the loop above left valid_scores at
            # traj[b-1], the block-final state; device mode never moved
            # them off it
            pending = handle
            i += b
            sched.observe(b, t3 - t0, compiled=was_built)
    finally:
        if pending is not None:
            booster.finalize_block(pending)
    return evlist
