"""Adaptive block sizing for the pipelined executor.

Block partitioning is a pure dispatch-cadence choice: the fused scan
advances scores iteration-exactly and the per-iteration callback
protocol runs for every inner iteration of whatever block it landed in,
so ANY partition of the remaining iterations trains the identical model
and stops at the identical iteration (tests/test_pipeline.py pins
this). That freedom is what makes measured-rate sizing safe.

The tradeoff being tuned: larger blocks amortize more host round-trips
(the whole point of fused dispatch) but coarsen the early-stop sync
cadence — iterations past the stopping point inside the final block are
trained and rolled back. The scheduler starts from the configured
fused_block_size, learns the steady-state iteration rate from completed
blocks (compile-bearing blocks are excluded — a jit build wall is not a
training rate), and grows the block toward pipeline_target_block_ms of
device time per dispatch, never crossing an early_stopping_rounds
boundary and never exceeding pipeline_max_block.
"""

from __future__ import annotations

__all__ = ["AdaptiveBlockScheduler"]


class AdaptiveBlockScheduler:
    def __init__(self, base_block: int, *, adaptive: bool = True,
                 target_ms: float = 250.0, max_block: int = 200,
                 stopping_rounds: int = 0):
        self.base = max(1, int(base_block))
        self.adaptive = bool(adaptive)
        self.target_s = float(target_ms) / 1e3
        self.max_block = max(1, int(max_block))
        self.stopping_rounds = max(0, int(stopping_rounds))
        self._rate = None  # iterations/sec EMA over post-compile blocks

    @property
    def rate(self):
        return self._rate

    def next_block(self, remaining: int) -> int:
        k = self.base
        if self.adaptive and self._rate is not None:
            # never shrink below the configured base: the user asked for
            # at least that much amortization per dispatch
            k = max(self.base, int(self._rate * self.target_s))
        if self.stopping_rounds:
            # align with the early-stop window: at most one stopping
            # span of overrun compute sits past the decision point
            k = min(k, self.stopping_rounds)
        return max(1, min(k, self.max_block, int(remaining)))

    def observe(self, k: int, wall_s: float, compiled: bool = False) -> None:
        if compiled or wall_s <= 0:
            return
        r = k / wall_s
        self._rate = r if self._rate is None else 0.5 * self._rate + 0.5 * r
