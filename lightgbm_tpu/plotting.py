"""Plotting utilities (reference python-package/lightgbm/plotting.py, 690 LoC):
plot_importance, plot_metric, plot_split_value_histogram, plot_tree /
create_tree_digraph. Matplotlib/graphviz are imported lazily and optional.

The public signatures and plot semantics match the reference package (the
API contract); the internals are organised differently — axis setup and
decoration are centralised in ``_axes``/``_finish`` instead of repeated
per function.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel
from .utils.log import Log

__all__ = ["plot_importance", "plot_metric", "plot_split_value_histogram",
           "plot_tree", "create_tree_digraph"]


def _pair(value, name: str):
    """Validate a 2-tuple argument (figsize/xlim/ylim) and return it."""
    if not isinstance(value, tuple) or len(value) != 2:
        raise TypeError(f"{name} must be a tuple of 2 elements.")
    return value


def _axes(ax, figsize, dpi):
    """Return the target axes, creating a figure when none was passed."""
    if ax is not None:
        return ax
    import matplotlib.pyplot as plt
    if figsize is not None:
        _pair(figsize, "figsize")
    fig, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    return ax


def _finish(ax, *, title=None, xlabel=None, ylabel=None, xlim=None,
            ylim=None, grid=True):
    """Apply the shared decoration set every plot_* function supports."""
    if xlim is not None:
        ax.set_xlim(_pair(xlim, "xlim"))
    if ylim is not None:
        ax.set_ylim(_pair(ylim, "ylim"))
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _to_booster(booster) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel.")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple] = None, ylim: Optional[Tuple] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    bst = _to_booster(booster)
    if importance_type == "auto":
        importance_type = "split"
    imp = np.asarray(bst.feature_importance(importance_type), dtype=float)
    if imp.size == 0:
        raise ValueError("Booster's feature_importance is empty.")
    names = np.asarray(bst.feature_name(), dtype=object)

    keep = imp > 0 if ignore_zero else np.ones(imp.shape, bool)
    order = np.argsort(imp[keep], kind="stable")  # ascending -> top bar last
    sel = np.flatnonzero(keep)[order]
    if max_num_features is not None and max_num_features > 0:
        sel = sel[-max_num_features:]
    if sel.size == 0:
        raise ValueError("There are no importances to plot.")

    ax = _axes(ax, figsize, dpi)
    ys = np.arange(sel.size)
    ax.barh(ys, imp[sel], align="center", height=height, **kwargs)
    is_gain = importance_type == "gain"
    for yi, fi in enumerate(sel):
        v = imp[fi]
        ax.text(v + 1, yi, f"{v:.{precision}f}" if is_gain else str(int(v)),
                va="center")
    ax.set_yticks(ys)
    ax.set_yticklabels(names[sel])
    return _finish(ax, title=title, xlabel=xlabel, ylabel=ylabel,
                   xlim=xlim, ylim=ylim, grid=grid)


def plot_metric(booster: Union[Dict, Booster], metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None, title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "@metric@",
                figsize=None, dpi=None, grid: bool = True):
    if isinstance(booster, dict):
        history = booster
    elif isinstance(booster, LGBMModel):
        history = dict(booster.evals_result_)
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not history:
        raise ValueError("eval results cannot be empty.")

    names = list(dataset_names) if dataset_names else list(history)
    if metric is None:
        # default: first metric recorded for the first dataset
        metric = next(iter(history[names[0]]))

    ax = _axes(ax, figsize, dpi)
    for name in names:
        curve = history.get(name, {}).get(metric)
        if curve is not None:
            ax.plot(np.arange(len(curve)), curve, label=name)
    ax.legend(loc="best")
    return _finish(ax, title=title, xlabel=xlabel,
                   ylabel=ylabel.replace("@metric@", metric),
                   xlim=xlim, ylim=ylim, grid=grid)


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with "
                                     "@index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid: bool = True):
    bst = _to_booster(booster)
    model = bst._host_model()
    if isinstance(feature, str):
        fidx = model.feature_names.index(feature)
    else:
        fidx = int(feature)
    values = []
    for t in model.trees:
        for i in range(t.num_leaves - 1):
            if int(t.split_feature[i]) == fidx and \
                    not (int(t.decision_type[i]) & 1):
                values.append(float(t.threshold[i]))
    if not values:
        raise ValueError(
            "Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")
    hist, bin_edges = np.histogram(values, bins=bins or "auto")
    ax = _axes(ax, figsize, dpi)
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2
    ax.bar(centers, hist, width=width_coef * (bin_edges[1] - bin_edges[0]))
    if title:
        title = title.replace("@feature@", str(feature)).replace(
            "@index/name@", "name" if isinstance(feature, str) else "index")
    return _finish(ax, title=title, xlabel=xlabel, ylabel=ylabel,
                   xlim=xlim, ylim=ylim, grid=grid)


def create_tree_digraph(booster, tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: int = 3, orientation: str = "horizontal",
                        **kwargs):
    import graphviz
    bst = _to_booster(booster)
    model = bst._host_model()
    if tree_index >= len(model.trees):
        raise IndexError("tree_index is out of range.")
    t = model.trees[tree_index]
    show_info = show_info or []
    graph = graphviz.Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr(rankdir=rankdir)

    def add(node, parent=None, decision=None):
        if node < 0:
            li = ~node
            name = f"leaf{li}"
            label = f"leaf {li}: {t.leaf_value[li]:.{precision}f}"
            if "leaf_count" in show_info:
                label += f"\ncount: {int(t.leaf_count[li])}"
            if "leaf_weight" in show_info:
                label += f"\nweight: {t.leaf_weight[li]:.{precision}f}"
            graph.node(name, label=label)
        else:
            name = f"split{node}"
            fname = model.feature_names[int(t.split_feature[node])] \
                if model.feature_names else f"f{int(t.split_feature[node])}"
            op = "==" if int(t.decision_type[node]) & 1 else "<="
            label = f"{fname} {op} {t.threshold[node]:.{precision}f}"
            if "split_gain" in show_info:
                label += f"\ngain: {t.split_gain[node]:.{precision}f}"
            if "internal_count" in show_info:
                label += f"\ncount: {int(t.internal_count[node])}"
            if "internal_value" in show_info:
                label += f"\nvalue: {t.internal_value[node]:.{precision}f}"
            graph.node(name, label=label)
            add(int(t.left_child[node]), name, "yes")
            add(int(t.right_child[node]), name, "no")
        if parent is not None:
            graph.edge(parent, name, decision)
        return name

    add(0 if t.num_leaves > 1 else -1)
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              show_info=None, precision: int = 3,
              orientation: str = "horizontal", **kwargs):
    import io
    import matplotlib.image as mpimg
    ax = _axes(ax, figsize, dpi)
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation, **kwargs)
    s = io.BytesIO(graph.pipe(format="png"))
    img = mpimg.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
