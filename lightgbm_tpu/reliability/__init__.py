"""Fault tolerance for training and serving.

Three pillars (see docs/Reliability.md):

- checkpoint/resume: atomic training-state bundles + `train(...,
  resume_from=)` so a killed run resumes to a model byte-identical to
  an uninterrupted one (`reliability.checkpoint`);
- unified fault injection: a registry of named sites with deterministic
  skip/fail schedules, the single lever robustness tests pull
  (`reliability.faults`);
- guard rails + retry: non-finite detection with configurable policy,
  and capped-exponential-backoff retries at device dispatch boundaries
  (`reliability.guards`, `reliability.retry`).

Every recovery is counted (`reliability.counters`) so degradation shows
up in the bench JSON record and the serving metrics snapshot.
"""

from .counters import ReliabilityCounters, counters
from .faults import FaultRegistry, InjectedFault, KNOWN_SITES, faults
from .guards import GUARD_POLICIES, GuardError
from .retry import retry_call
from .checkpoint import (CheckpointState, latest_checkpoint,
                         load_checkpoint, save_checkpoint)

__all__ = [
    "ReliabilityCounters", "counters",
    "FaultRegistry", "InjectedFault", "KNOWN_SITES", "faults",
    "GUARD_POLICIES", "GuardError",
    "retry_call",
    "CheckpointState", "latest_checkpoint", "load_checkpoint",
    "save_checkpoint",
]
