"""Fault tolerance for training and serving.

Four pillars (see docs/Reliability.md):

- checkpoint/resume: atomic training-state bundles + `train(...,
  resume_from=)` so a killed run resumes to a model byte-identical to
  an uninterrupted one; multihost runs commit bundles through a
  coordinated agree/shard/COMMIT protocol (`reliability.checkpoint`);
- unified fault injection: a registry of named sites with deterministic
  skip/fail schedules — including a ``rank_death`` mode that kills the
  whole process for chaos testing — the single lever robustness tests
  pull (`reliability.faults`);
- guard rails + retry: non-finite detection with configurable policy,
  and capped-exponential-backoff retries at device dispatch boundaries
  (`reliability.guards`, `reliability.retry`);
- collective watchdog: deadline + heartbeat bracketing of host-boundary
  collectives, so a dead rank is diagnosed ("rank k last seen Ns ago")
  and survivors abort cleanly instead of hanging forever
  (`reliability.watchdog`).

Every recovery is counted (`reliability.counters`) so degradation shows
up in the bench JSON record and the serving metrics snapshot.
"""

from .counters import ReliabilityCounters, counters
from .faults import (FaultRegistry, InjectedFault, KNOWN_SITES,
                     RANK_DEATH_EXIT_CODE, faults)
from .guards import GUARD_POLICIES, GuardError
from .retry import retry_call
from .checkpoint import (CheckpointState, latest_checkpoint,
                         load_checkpoint, pin_bundle, pinned_bundle,
                         save_checkpoint)
from .watchdog import (CollectiveGuard, WATCHDOG_EXIT_CODE, active_guard,
                       collective_guard, configure_watchdog,
                       maybe_start_watchdog, shutdown_watchdog)

__all__ = [
    "ReliabilityCounters", "counters",
    "FaultRegistry", "InjectedFault", "KNOWN_SITES",
    "RANK_DEATH_EXIT_CODE", "faults",
    "GUARD_POLICIES", "GuardError",
    "retry_call",
    "CheckpointState", "latest_checkpoint", "load_checkpoint",
    "pin_bundle", "pinned_bundle", "save_checkpoint",
    "CollectiveGuard", "WATCHDOG_EXIT_CODE", "active_guard",
    "collective_guard", "configure_watchdog", "maybe_start_watchdog",
    "shutdown_watchdog",
]
