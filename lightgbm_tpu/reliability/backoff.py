"""Capped-exponential backoff policy for cross-attempt crash loops.

`retry.retry_call` owns the in-call retry ladder (one function, one
attempt budget, sleeps inline). The continuous loop needs the same
curve but OUTSIDE a single call: a cycle that crash-loops is retried
across full recover/rebuild attempts, and the attempt counter lives in
the driver, not in a wrapper frame. This policy object is that curve —
deterministic (no jitter, same as retry.py, so chaos tests can assert
exact delays) and injectable (`sleep=` stub for tests).
"""

from __future__ import annotations

import time
from typing import Callable

__all__ = ["BackoffPolicy"]


class BackoffPolicy:
    """delay(attempt) = min(base_ms * 2**attempt, max_ms), attempt 0-based."""

    def __init__(self, base_ms: float = 50.0, max_ms: float = 2000.0,
                 sleep: Callable[[float], None] = time.sleep):
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self._sleep = sleep

    def delay_ms(self, attempt: int) -> float:
        if self.base_ms <= 0:
            return 0.0
        return min(self.base_ms * (2.0 ** max(0, int(attempt))),
                   self.max_ms)

    def wait(self, attempt: int) -> float:
        """Sleep the capped delay for `attempt`; returns the delay (ms)
        actually slept so callers can log/record it."""
        delay = self.delay_ms(attempt)
        if delay > 0:
            self._sleep(delay / 1e3)
        return delay
