"""Capped-exponential backoff policy for cross-attempt crash loops.

`retry.retry_call` owns the in-call retry ladder (one function, one
attempt budget, sleeps inline). The continuous loop needs the same
curve but OUTSIDE a single call: a cycle that crash-loops is retried
across full recover/rebuild attempts, and the attempt counter lives in
the driver, not in a wrapper frame. This policy object is that curve —
deterministic by default (no jitter, same as retry.py, so chaos tests
can assert exact delays) and injectable (`sleep=` stub for tests).

Multi-rank retry ladders want the opposite of determinism: after an
elastic resize every survivor retries against the SAME recovering peer
on the SAME curve, so deterministic delays fire synchronized retry
storms at exactly the moments the peer is busiest. ``jitter=
"decorrelated"`` switches to the decorrelated-jitter curve (Brooker,
AWS Architecture Blog 2015): each delay is drawn uniformly from
[base, 3 * previous_delay], capped — successive ranks decorrelate
after the first draw even if they crashed in lockstep. The RNG is a
private seeded ``random.Random`` so tests (and reproducibility-minded
supervisors) get a deterministic-yet-jittered sequence per seed.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional

__all__ = ["BackoffPolicy"]


class BackoffPolicy:
    """delay(attempt) = min(base_ms * 2**attempt, max_ms), attempt 0-based.

    With ``jitter="decorrelated"``:
    delay = min(max_ms, uniform(base_ms, 3 * previous_delay)) — stateful
    across calls (attempt number only floors the first draw), bounded by
    [base_ms, max_ms] at every step.
    """

    def __init__(self, base_ms: float = 50.0, max_ms: float = 2000.0,
                 sleep: Callable[[float], None] = time.sleep,
                 jitter: str = "none", seed: Optional[int] = None):
        if jitter not in ("none", "decorrelated"):
            raise ValueError(f"unknown jitter mode {jitter!r} "
                             f"(expected 'none' or 'decorrelated')")
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self.jitter = jitter
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._prev_ms = self.base_ms

    def reset(self) -> None:
        """Forget jitter state (a recovered run restarts the ladder)."""
        self._prev_ms = self.base_ms

    def delay_ms(self, attempt: int) -> float:
        if self.base_ms <= 0:
            return 0.0
        if self.jitter == "decorrelated":
            drawn = self._rng.uniform(self.base_ms, 3.0 * self._prev_ms)
            self._prev_ms = min(max(drawn, self.base_ms), self.max_ms)
            return self._prev_ms
        return min(self.base_ms * (2.0 ** max(0, int(attempt))),
                   self.max_ms)

    def wait(self, attempt: int) -> float:
        """Sleep the capped delay for `attempt`; returns the delay (ms)
        actually slept so callers can log/record it."""
        delay = self.delay_ms(attempt)
        if delay > 0:
            self._sleep(delay / 1e3)
        return delay
