"""Atomic checkpoint bundles for kill-and-resume training.

A bundle is a directory, not a file, because a resumable run needs more
than the model: the exact f32 score state, the RNG stream position and
the mid-period bagging mask all have to come back bit-for-bit for the
resumed run to reproduce an uninterrupted one. Layout::

    <dir>/ckpt_0000012/          # iteration 12 has been trained
        model.txt                # Booster.model_to_string()
        state.json               # iteration, world_size, eval history...
        arrays.npz               # train_score, rng_key, bag_mask, ...
    <dir>/LATEST                 # name of the newest complete bundle

Atomicity is tmp+rename at both levels: the bundle is assembled under a
dot-prefixed temp name and `os.rename`d into place (POSIX rename is
atomic within a filesystem), and LATEST is rewritten via `os.replace`.
A crash mid-write leaves only a `.tmp-*` turd that the next save
sweeps; readers never observe a partial bundle.

Multihost runs use a *coordinated* variant of the same layout (pass a
`parallel.comm.CheckpointCoordinator` to `save_checkpoint`): ranks
first agree on the iteration via a one-int allgather (the PR-8
agreement-flag idiom), then every rank writes its own
``shard_<rank>.npz`` into the shared bundle directory while rank 0
writes ``model.txt`` + ``state.json``, then a second one-int agreement
confirms every shard landed, and only then does rank 0 cut the
``COMMIT`` marker and advance LATEST. A rank dying anywhere in the
middle leaves a marker-less bundle that `latest_checkpoint` refuses to
return — the multihost extension of PR 7's torn-state detection.
Single-host bundles never carry a COMMIT file (completeness there is
the directory rename itself), so their layout is unchanged.

The reference's closest analog is continued training from a saved model
(`engine.py` init_model) — but that path re-seeds init scores through a
host predict and restarts the RNG, so it converges *near* the original
run, not *onto* it. Bundles restore the exact state instead.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..observability.flightrec import recorder
from ..utils.log import Log, LightGBMError
from .counters import counters
from .faults import faults

__all__ = ["CheckpointState", "save_checkpoint", "load_checkpoint",
           "load_checkpoint_resharded", "bundle_world",
           "latest_checkpoint", "FORMAT_VERSION", "COMMIT_MARKER",
           "PIN_FILE", "pin_bundle", "pinned_bundle"]

FORMAT_VERSION = 1

_BUNDLE_PREFIX = "ckpt_"
_LATEST = "LATEST"
#: presence of this file inside a bundle written by >1 rank is the
#: commit point of the coordinated save protocol; bundles that declare
#: world_size > 1 in state.json but lack it are partial and ignored
COMMIT_MARKER = "COMMIT"
#: top-level file naming the bundle the serving registry's live
#: generation was published from; `_prune` never deletes it, no matter
#: how far `keep_last` has advanced past it (pin-by-generation)
PIN_FILE = "PINNED"


@dataclass
class CheckpointState:
    """One loaded bundle, ready for `Booster._restore_training_state`."""
    iteration: int
    model_str: str
    state: Dict = field(default_factory=dict)
    arrays: Dict[str, np.ndarray] = field(default_factory=dict)
    path: str = ""


def _bundle_name(iteration: int) -> str:
    return f"{_BUNDLE_PREFIX}{iteration:07d}"


def _bundle_iter(name: str) -> Optional[int]:
    if not name.startswith(_BUNDLE_PREFIX):
        return None
    try:
        return int(name[len(_BUNDLE_PREFIX):])
    except ValueError:
        return None


def _listdir(path: str) -> List[str]:
    """os.listdir that treats a vanished directory as empty — another
    rank (or a killed process) may remove it mid-scan."""
    try:
        return os.listdir(path)
    except (FileNotFoundError, NotADirectoryError):
        return []


def _sweep_tmp(ckpt_dir: str) -> None:
    # coordinated ranks write through in-bundle tmp files, never
    # top-level `.tmp-*` dirs, so concurrent sweeps cannot eat a peer's
    # in-flight work; a racing unlink just means someone swept first
    for name in _listdir(ckpt_dir):
        if name.startswith(".tmp-"):
            shutil.rmtree(os.path.join(ckpt_dir, name),
                          ignore_errors=True)


def _read_state(bundle: str) -> Optional[Dict]:
    try:
        with open(os.path.join(bundle, "state.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _is_complete(bundle: str) -> bool:
    """True when `bundle` is safe to resume from. Single-writer bundles
    (world_size absent or <= 1) are complete by construction — they
    became visible via an atomic directory rename. Coordinated bundles
    additionally need the COMMIT marker: every shard confirmed."""
    state = _read_state(bundle)
    if state is None:
        return False
    if int(state.get("world_size", 1)) <= 1:
        return True
    return os.path.isfile(os.path.join(bundle, COMMIT_MARKER))


def _write_text_atomic(bundle: str, name: str, text: str) -> None:
    tmp = os.path.join(bundle, f"{name}.tmp-{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, os.path.join(bundle, name))


def _write_npz_atomic(bundle: str, name: str,
                      arrays: Dict[str, np.ndarray]) -> None:
    tmp = os.path.join(bundle, f"{name}.tmp-{os.getpid()}")
    # hand savez a file object, not the tmp path: given a path without
    # a .npz suffix it would append one and break the os.replace
    with open(tmp, "wb") as f:
        np.savez(f, **{k: np.asarray(v) for k, v in arrays.items()})
    os.replace(tmp, os.path.join(bundle, name))


def save_checkpoint(ckpt_dir: str, iteration: int, model_str: str,
                    state: Dict, arrays: Dict[str, np.ndarray],
                    keep_last: int = 0, coordinator=None) -> str:
    """Write one atomic bundle; returns its path.

    `keep_last` > 0 prunes older bundles after the new one is visible,
    so the retention floor never drops below the newest snapshot.
    Passing a `CheckpointCoordinator` switches to the multihost commit
    protocol (module docstring) — every rank must call with one."""
    if coordinator is not None and coordinator.world > 1:
        return _save_coordinated(ckpt_dir, iteration, model_str, state,
                                 arrays, keep_last, coordinator)
    os.makedirs(ckpt_dir, exist_ok=True)
    _sweep_tmp(ckpt_dir)
    name = _bundle_name(iteration)
    final = os.path.join(ckpt_dir, name)
    tmp = os.path.join(ckpt_dir, f".tmp-{name}-{os.getpid()}")

    faults.inject("checkpoint_io")

    os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, "model.txt"), "w") as f:
        f.write(model_str)
    full_state = {"format_version": FORMAT_VERSION,
                  "iteration": int(iteration), "world_size": 1}
    full_state.update(state)
    with open(os.path.join(tmp, "state.json"), "w") as f:
        json.dump(full_state, f, indent=1, sort_keys=True)
    np.savez(os.path.join(tmp, "arrays.npz"),
             **{k: np.asarray(v) for k, v in arrays.items()})

    if os.path.isdir(final):          # re-checkpoint of the same iter
        shutil.rmtree(final)
    os.rename(tmp, final)

    latest_tmp = os.path.join(ckpt_dir, _LATEST + ".tmp")
    with open(latest_tmp, "w") as f:
        f.write(name + "\n")
    os.replace(latest_tmp, os.path.join(ckpt_dir, _LATEST))

    if keep_last and keep_last > 0:
        _prune(ckpt_dir, keep_last)
    counters.inc("checkpoint_saves")
    recorder.record_checkpoint("checkpoint_save", iteration, final)
    Log.info(f"checkpoint: saved iteration {iteration} -> {final}")
    return final


def _save_coordinated(ckpt_dir: str, iteration: int, model_str: str,
                      state: Dict, arrays: Dict[str, np.ndarray],
                      keep_last: int, coord) -> str:
    """The multihost commit protocol. Collective layout (every rank
    runs the SAME sequence, or peers strand — tpulint COLL002):

        agree(iteration)  ->  write own shard  ->  agree(ok)
                                                        |
                       rank 0 only:  COMMIT + LATEST + prune

    Rank-local write failures are caught and voted into the second
    agreement instead of raised, so all ranks raise the same error
    together and the marker-less bundle is discarded on resume."""
    rank, world = int(coord.rank), int(coord.world)
    its = np.asarray(coord.agree(int(iteration),
                                 label="checkpoint_agree")).reshape(-1)
    agreed = int(its.min())
    if int(its.max()) != agreed:
        raise LightGBMError(
            f"coordinated checkpoint: ranks disagree on the iteration "
            f"to snapshot ({sorted(set(int(i) for i in its))}) — "
            f"callback periods must be identical on every rank")
    name = _bundle_name(agreed)
    final = os.path.join(ckpt_dir, name)
    ok = 1
    try:
        faults.inject("checkpoint_io")
        os.makedirs(final, exist_ok=True)
        _write_npz_atomic(final, f"shard_{rank:03d}.npz", arrays)
        if rank == 0:
            _write_text_atomic(final, "model.txt", model_str)
            full_state = {"format_version": FORMAT_VERSION,
                          "iteration": agreed, "world_size": world}
            full_state.update(state)
            _write_text_atomic(final, "state.json",
                               json.dumps(full_state, indent=1,
                                          sort_keys=True))
    except Exception as exc:
        Log.warning("coordinated checkpoint: rank %d failed to write "
                    "its shard for iteration %d (%s: %s)", rank, agreed,
                    type(exc).__name__, exc)
        ok = 0
    oks = np.asarray(coord.agree(ok,
                                 label="checkpoint_commit")).reshape(-1)
    if int(oks.min(initial=1)) == 0:
        bad = [r for r in range(oks.shape[0]) if int(oks[r]) == 0]
        raise LightGBMError(
            f"coordinated checkpoint at iteration {agreed} failed on "
            f"rank(s) {bad}; bundle left uncommitted (ignored on "
            f"resume)")
    if rank == 0:
        _write_text_atomic(final, COMMIT_MARKER,
                           f"iteration={agreed} world_size={world}\n")
        latest_tmp = os.path.join(ckpt_dir, _LATEST + ".tmp")
        with open(latest_tmp, "w") as f:
            f.write(name + "\n")
        os.replace(latest_tmp, os.path.join(ckpt_dir, _LATEST))
        if keep_last and keep_last > 0:
            _prune(ckpt_dir, keep_last)
    counters.inc("checkpoint_saves")
    recorder.record_checkpoint("checkpoint_commit", agreed, final)
    Log.info(f"checkpoint: rank {rank}/{world} committed iteration "
             f"{agreed} -> {final}")
    return final


def pin_bundle(ckpt_dir: str, bundle: Optional[str]) -> None:
    """Mark `bundle` (a path or bare bundle name) as the one the
    serving registry's live generation was published from. `_prune`
    skips it regardless of `keep_last`, so a slow consumer of an old
    generation can never find its bytes gone. Pass None to unpin.
    Written via os.replace (the LATEST idiom) so readers never see a
    torn pin."""
    os.makedirs(ckpt_dir, exist_ok=True)
    pin = os.path.join(ckpt_dir, PIN_FILE)
    if bundle is None:
        try:
            os.unlink(pin)
        except FileNotFoundError:
            pass
        return
    tmp = pin + f".tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(os.path.basename(bundle) + "\n")
    os.replace(tmp, pin)


def pinned_bundle(ckpt_dir: str) -> Optional[int]:
    """Iteration of the pinned bundle, or None. A vanished or garbled
    pin file reads as unpinned — same ENOENT discipline as `_listdir`:
    a killed publisher may have left nothing, and that must not wedge
    pruning."""
    try:
        with open(os.path.join(ckpt_dir, PIN_FILE)) as f:
            return _bundle_iter(f.read().strip())
    except OSError:
        return None


def _prune(ckpt_dir: str, keep_last: int) -> None:
    """Keep the newest `keep_last` COMPLETE bundles. Incomplete
    (uncommitted) bundles never count toward the quota — and any
    incomplete bundle older than the newest complete one is a stale
    torn write from a killed run, removed as garbage. The bundle named
    by the PIN_FILE (the serving registry's live generation) is never
    removed, even when it has aged out of the quota. Every removal
    tolerates a concurrent rank racing us to it."""
    pinned = pinned_bundle(ckpt_dir)
    complete: List[int] = []
    stale: List[int] = []
    for name in _listdir(ckpt_dir):
        it = _bundle_iter(name)
        if it is None:
            continue
        if _is_complete(os.path.join(ckpt_dir, name)):
            complete.append(it)
        else:
            stale.append(it)
    complete.sort()
    for it in complete[:-keep_last]:
        if it == pinned:
            continue
        shutil.rmtree(os.path.join(ckpt_dir, _bundle_name(it)),
                      ignore_errors=True)
    if complete:
        newest = complete[-1]
        for it in stale:
            if it < newest and it != pinned:
                shutil.rmtree(os.path.join(ckpt_dir, _bundle_name(it)),
                              ignore_errors=True)


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest COMPLETE bundle under `ckpt_dir`, or None.

    Trusts LATEST when it points at an existing complete bundle,
    otherwise scans (LATEST is advisory; the bundles are the durable
    record). Coordinated bundles without their COMMIT marker — a rank
    died between shard write and commit — are skipped."""
    if not os.path.isdir(ckpt_dir):
        return None
    latest = os.path.join(ckpt_dir, _LATEST)
    if os.path.isfile(latest):
        with open(latest) as f:
            name = f.read().strip()
        cand = os.path.join(ckpt_dir, name)
        if _is_complete(cand):
            return cand
    best: Optional[int] = None
    for name in _listdir(ckpt_dir):
        it = _bundle_iter(name)
        if it is None:
            continue
        if not _is_complete(os.path.join(ckpt_dir, name)):
            continue
        if best is None or it > best:
            best = it
    return os.path.join(ckpt_dir, _bundle_name(best)) if best is not None \
        else None


def _resolve_bundle(path: str) -> str:
    """`path` itself when it is a complete bundle, else the newest
    complete bundle under it; raises when none exists."""
    if _is_complete(path):
        return path
    found = latest_checkpoint(path)
    if found is None:
        raise LightGBMError(
            f"no complete checkpoint bundle found under {path!r}")
    return found


def bundle_world(path: str) -> Optional[int]:
    """world_size of the bundle that a resume from `path` would pick,
    or None when no complete bundle exists — the topology probe the
    elastic resume path uses to decide between the strict per-shard
    loader and `load_checkpoint_resharded`."""
    try:
        bundle = _resolve_bundle(path)
    except LightGBMError:
        return None
    state = _read_state(bundle)
    if state is None:
        return None
    return int(state.get("world_size", 1))


def load_checkpoint_resharded(path: str) -> CheckpointState:
    """Topology-flexible load (distributed/elastic.py): read ALL of a
    W-rank coordinated bundle's ``shard_<rank>.npz`` files and
    concatenate the row-partitioned arrays in rank order into the
    global arrays an uninterrupted single-partition run would hold.
    Every rank of the new W'-rank world calls this, then slices its own
    contiguous row block at restore time (`elastic.reshard_offsets` +
    `elastic.reshard_slice` inside `GBDT.restore_training_state`).

    The returned state carries ``resharded_from_world`` (the old W),
    ``reshard_total_rows`` (global training rows) and
    ``reshard_rows_per_rank`` — the restore path's slicing contract and
    the test oracle for W -> W' -> W byte-identity. ``rng_key`` is
    rank-replicated (every shard holds the same stream position), so
    shard 0's copy is taken verbatim."""
    import time as _time
    t0 = _time.monotonic()
    bundle = _resolve_bundle(path)
    state = _read_state(bundle)
    if state is None:
        raise LightGBMError(f"checkpoint {bundle!r} lost its state.json "
                            f"mid-load (concurrent prune?)")
    ver = state.get("format_version")
    if ver != FORMAT_VERSION:
        raise LightGBMError(
            f"checkpoint {bundle!r} has format_version={ver!r}; "
            f"this build reads version {FORMAT_VERSION}")
    ws = int(state.get("world_size", 1))
    with open(os.path.join(bundle, "model.txt")) as f:
        model_str = f.read()
    shards: List[Dict[str, np.ndarray]] = []
    if ws <= 1:
        npz_path = os.path.join(bundle, "arrays.npz")
        if os.path.isfile(npz_path):
            with np.load(npz_path) as npz:
                shards.append({k: npz[k] for k in npz.files})
    else:
        for r in range(ws):
            npz_path = os.path.join(bundle, f"shard_{r:03d}.npz")
            if not os.path.isfile(npz_path):
                raise LightGBMError(
                    f"resharded load: checkpoint {bundle!r} declares "
                    f"world_size={ws} but shard_{r:03d}.npz is missing")
            with np.load(npz_path) as npz:
                shards.append({k: npz[k] for k in npz.files})
    arrays: Dict[str, np.ndarray] = {}
    rows_per_rank: List[int] = []
    if shards:
        keys = set(shards[0])
        for r, shard in enumerate(shards):
            if set(shard) != keys:
                raise LightGBMError(
                    f"resharded load: shard {r} of {bundle!r} carries "
                    f"keys {sorted(shard)} but shard 0 has "
                    f"{sorted(keys)} — bundle is torn")
        rows_per_rank = [
            int(np.asarray(s["train_score"]).shape[0]) if "train_score"
            in s else 0 for s in shards]
        for key in keys:
            parts = [np.asarray(s[key]) for s in shards]
            if key != "rng_key" and parts[0].ndim:
                # row-partitioned state (train_score, bag_mask,
                # valid_score_i): rank-order concatenation rebuilds the
                # global row order the partitioner sliced
                arrays[key] = np.concatenate(parts, axis=0) \
                    if len(parts) > 1 else parts[0]
            else:
                # rank-replicated (rng_key) or scalar state
                arrays[key] = parts[0]
    out_state = dict(state)
    out_state["resharded_from_world"] = ws
    out_state["reshard_rows_per_rank"] = rows_per_rank
    out_state["reshard_total_rows"] = int(sum(rows_per_rank))
    counters.inc("checkpoint_resharded_loads")
    recorder.record_checkpoint("checkpoint_reshard",
                               int(state["iteration"]), bundle)
    from ..observability.registry import registry
    registry.record_membership_reshard(_time.monotonic() - t0)
    Log.info(f"checkpoint: resharded load of {bundle} "
             f"(world_size={ws}, rows={out_state['reshard_total_rows']})")
    return CheckpointState(iteration=int(state["iteration"]),
                           model_str=model_str, state=out_state,
                           arrays=arrays, path=bundle)


def load_checkpoint(path: str, rank: Optional[int] = None,
                    world: Optional[int] = None) -> CheckpointState:
    """Load a bundle. `path` may be a bundle directory or a checkpoint
    directory (the newest complete bundle is picked).

    Coordinated bundles require `rank` (to pick the shard arrays) and
    validate the topology: a bundle written by W ranks only resumes
    into a W-rank run — scores/bag masks are partition-local, and a
    different partitioning would silently corrupt them."""
    bundle = path
    if not _is_complete(bundle):
        found = latest_checkpoint(path)
        if found is None:
            raise LightGBMError(
                f"no complete checkpoint bundle found under {path!r}")
        bundle = found
    state = _read_state(bundle)
    if state is None:
        raise LightGBMError(f"checkpoint {bundle!r} lost its state.json "
                            f"mid-load (concurrent prune?)")
    ver = state.get("format_version")
    if ver != FORMAT_VERSION:
        raise LightGBMError(
            f"checkpoint {bundle!r} has format_version={ver!r}; "
            f"this build reads version {FORMAT_VERSION}")
    ws = int(state.get("world_size", 1))
    if ws > 1:
        if rank is None:
            raise LightGBMError(
                f"checkpoint {bundle!r} was written by {ws} coordinated "
                f"ranks; pass rank=/world= to pick this rank's shard")
        if world is not None and int(world) != ws:
            raise LightGBMError(
                f"checkpoint {bundle!r} was written by world_size={ws} "
                f"but this run has world_size={int(world)} — resume "
                f"needs the same topology (partition-local state)")
        if not 0 <= int(rank) < ws:
            raise LightGBMError(
                f"rank {rank} out of range for world_size={ws} "
                f"checkpoint {bundle!r}")
        npz_path = os.path.join(bundle, f"shard_{int(rank):03d}.npz")
    else:
        npz_path = os.path.join(bundle, "arrays.npz")
    with open(os.path.join(bundle, "model.txt")) as f:
        model_str = f.read()
    arrays: Dict[str, np.ndarray] = {}
    if os.path.isfile(npz_path):
        with np.load(npz_path) as npz:
            arrays = {k: npz[k] for k in npz.files}
    return CheckpointState(iteration=int(state["iteration"]),
                           model_str=model_str, state=state,
                           arrays=arrays, path=bundle)
