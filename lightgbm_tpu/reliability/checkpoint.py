"""Atomic checkpoint bundles for kill-and-resume training.

A bundle is a directory, not a file, because a resumable run needs more
than the model: the exact f32 score state, the RNG stream position and
the mid-period bagging mask all have to come back bit-for-bit for the
resumed run to reproduce an uninterrupted one. Layout::

    <dir>/ckpt_0000012/          # iteration 12 has been trained
        model.txt                # Booster.model_to_string()
        state.json               # iteration, flags, eval history, ...
        arrays.npz               # train_score, rng_key, bag_mask, ...
    <dir>/LATEST                 # name of the newest complete bundle

Atomicity is tmp+rename at both levels: the bundle is assembled under a
dot-prefixed temp name and `os.rename`d into place (POSIX rename is
atomic within a filesystem), and LATEST is rewritten via `os.replace`.
A crash mid-write leaves only a `.tmp-*` turd that the next save
sweeps; readers never observe a partial bundle.

The reference's closest analog is continued training from a saved model
(`engine.py` init_model) — but that path re-seeds init scores through a
host predict and restarts the RNG, so it converges *near* the original
run, not *onto* it. Bundles restore the exact state instead.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..utils.log import Log, LightGBMError
from .counters import counters
from .faults import faults

__all__ = ["CheckpointState", "save_checkpoint", "load_checkpoint",
           "latest_checkpoint", "FORMAT_VERSION"]

FORMAT_VERSION = 1

_BUNDLE_PREFIX = "ckpt_"
_LATEST = "LATEST"


@dataclass
class CheckpointState:
    """One loaded bundle, ready for `Booster._restore_training_state`."""
    iteration: int
    model_str: str
    state: Dict = field(default_factory=dict)
    arrays: Dict[str, np.ndarray] = field(default_factory=dict)
    path: str = ""


def _bundle_name(iteration: int) -> str:
    return f"{_BUNDLE_PREFIX}{iteration:07d}"


def _bundle_iter(name: str) -> Optional[int]:
    if not name.startswith(_BUNDLE_PREFIX):
        return None
    try:
        return int(name[len(_BUNDLE_PREFIX):])
    except ValueError:
        return None


def _sweep_tmp(ckpt_dir: str) -> None:
    for name in os.listdir(ckpt_dir):
        if name.startswith(".tmp-"):
            shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)


def save_checkpoint(ckpt_dir: str, iteration: int, model_str: str,
                    state: Dict, arrays: Dict[str, np.ndarray],
                    keep_last: int = 0) -> str:
    """Write one atomic bundle; returns its path.

    `keep_last` > 0 prunes older bundles after the new one is visible,
    so the retention floor never drops below the newest snapshot."""
    os.makedirs(ckpt_dir, exist_ok=True)
    _sweep_tmp(ckpt_dir)
    name = _bundle_name(iteration)
    final = os.path.join(ckpt_dir, name)
    tmp = os.path.join(ckpt_dir, f".tmp-{name}-{os.getpid()}")

    faults.inject("checkpoint_io")

    os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, "model.txt"), "w") as f:
        f.write(model_str)
    full_state = {"format_version": FORMAT_VERSION, "iteration": int(iteration)}
    full_state.update(state)
    with open(os.path.join(tmp, "state.json"), "w") as f:
        json.dump(full_state, f, indent=1, sort_keys=True)
    np.savez(os.path.join(tmp, "arrays.npz"),
             **{k: np.asarray(v) for k, v in arrays.items()})

    if os.path.isdir(final):          # re-checkpoint of the same iter
        shutil.rmtree(final)
    os.rename(tmp, final)

    latest_tmp = os.path.join(ckpt_dir, _LATEST + ".tmp")
    with open(latest_tmp, "w") as f:
        f.write(name + "\n")
    os.replace(latest_tmp, os.path.join(ckpt_dir, _LATEST))

    if keep_last and keep_last > 0:
        _prune(ckpt_dir, keep_last)
    counters.inc("checkpoint_saves")
    Log.info(f"checkpoint: saved iteration {iteration} -> {final}")
    return final


def _prune(ckpt_dir: str, keep_last: int) -> None:
    bundles: List[int] = []
    for name in os.listdir(ckpt_dir):
        it = _bundle_iter(name)
        if it is not None:
            bundles.append(it)
    for it in sorted(bundles)[:-keep_last]:
        shutil.rmtree(os.path.join(ckpt_dir, _bundle_name(it)),
                      ignore_errors=True)


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest complete bundle under `ckpt_dir`, or None.

    Trusts LATEST when it points at an existing bundle, otherwise scans
    (LATEST is advisory; the bundles are the durable record)."""
    if not os.path.isdir(ckpt_dir):
        return None
    latest = os.path.join(ckpt_dir, _LATEST)
    if os.path.isfile(latest):
        with open(latest) as f:
            name = f.read().strip()
        cand = os.path.join(ckpt_dir, name)
        if os.path.isfile(os.path.join(cand, "state.json")):
            return cand
    best: Optional[int] = None
    for name in os.listdir(ckpt_dir):
        it = _bundle_iter(name)
        if it is None:
            continue
        if not os.path.isfile(os.path.join(ckpt_dir, name, "state.json")):
            continue
        if best is None or it > best:
            best = it
    return os.path.join(ckpt_dir, _bundle_name(best)) if best is not None \
        else None


def load_checkpoint(path: str) -> CheckpointState:
    """Load a bundle. `path` may be a bundle directory or a checkpoint
    directory (the newest complete bundle is picked)."""
    bundle = path
    if not os.path.isfile(os.path.join(bundle, "state.json")):
        found = latest_checkpoint(path)
        if found is None:
            raise LightGBMError(f"no checkpoint bundle found under {path!r}")
        bundle = found
    with open(os.path.join(bundle, "state.json")) as f:
        state = json.load(f)
    ver = state.get("format_version")
    if ver != FORMAT_VERSION:
        raise LightGBMError(
            f"checkpoint {bundle!r} has format_version={ver!r}; "
            f"this build reads version {FORMAT_VERSION}")
    with open(os.path.join(bundle, "model.txt")) as f:
        model_str = f.read()
    arrays: Dict[str, np.ndarray] = {}
    npz_path = os.path.join(bundle, "arrays.npz")
    if os.path.isfile(npz_path):
        with np.load(npz_path) as npz:
            arrays = {k: npz[k] for k in npz.files}
    return CheckpointState(iteration=int(state["iteration"]),
                           model_str=model_str, state=state,
                           arrays=arrays, path=bundle)
