"""Process-wide reliability counters: degradation must be observable.

Every silent recovery path (device retry, fused->per-iteration fallback,
guard-rail trip, checkpoint write failure) increments a named counter
here so the bench JSON record and the serving metrics snapshot can
surface how degraded a run actually was. Mirrors the reference's
philosophy that a fallback without a log line is a bug — except these
are machine-readable.
"""

from __future__ import annotations

import threading
from typing import Dict

__all__ = ["ReliabilityCounters", "counters"]

_KEYS = (
    "device_retries",      # retry_call attempts that followed a failure
    "fallbacks",           # degraded dispatches (fused->per-iter, device->host)
    "guard_trips",         # non-finite guard activations
    "checkpoint_saves",    # successful checkpoint bundles written
    "checkpoint_failures", # checkpoint writes that failed (training continued)
)


class ReliabilityCounters:
    """Thread-safe named counters with a stable snapshot schema."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {k: 0 for k in _KEYS}

    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + int(n)

    def get(self, key: str) -> int:
        with self._lock:
            return self._counts.get(key, 0)

    def snapshot(self) -> Dict[str, int]:
        """All keys, always present — consumers index without guards."""
        with self._lock:
            out = {k: 0 for k in _KEYS}
            out.update(self._counts)
            return out

    def reset(self) -> None:
        with self._lock:
            self._counts = {k: 0 for k in _KEYS}


#: process-wide singleton
counters = ReliabilityCounters()
