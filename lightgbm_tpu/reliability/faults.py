"""Unified fault-injection registry: one lever for every robustness test.

The seed repo's only fault hook was an env-var counter wired to a single
dispatch site (`boosting/gbdt.py` `_FAULT_ENV`) that *mutated*
``os.environ`` as its state — process-global, leaking across tests and
racing under threads. This module replaces it with an in-process
registry of named sites and deterministic schedules, keeping the env
var purely as an initial-schedule *source*.

A schedule is "skip S dispatches, then fail the next N" — the same
"S:N" grammar the env hook used, so ``LGBM_TPU_INJECT_FUSED_FAULT=2:1``
still means "let two fused dispatches through, then kill one".

Sites registered by the library (tests may add their own):

==========================  ==================================================
site                        raised from
==========================  ==================================================
``fused_dispatch``          GBDT.train_many, before the fused multi-tree scan
``histogram_build``         GBDT tree growth dispatch (histogram + split path)
``collective_psum``         parallel dispatch boundary before sharded growth
``serving_device_predict``  serving BucketedPredictor.predict_raw
``serving_replica_predict`` serving ReplicaSet.dispatch, per-replica device
                            attempt (drives breaker open/failover)
``serving_hot_swap``        serving Server.hot_swap, before the registry swap
``serving_hot_swap_commit`` serving Server.hot_swap, after the atomic publish
                            but before the old batcher drains — the other
                            side of the swap's commit point
``checkpoint_io``           reliability.checkpoint bundle writes
``streaming_ingest``        streaming.loader per-chunk ingest step (both
                            passes), before sketch/bin work on the chunk
``distributed_hist_agg``    distributed.hist_agg.build_feature_shards,
                            before the feature-shard all_to_all transpose
``loop_publish``            continuous.ContinuousTrainer._publish, after the
                            serving swap but before the generation marker
                            advances (torn-publish window)
``elastic_resize``          distributed.elastic.propose_shrink, before the
                            shrink vote touches the heartbeat directory —
                            a failed vote falls back to the watchdog abort
==========================  ==================================================

All injection is host-side, at dispatch boundaries: raising inside
jit/shard_map-traced code would either bake into the compiled program or
never run, so the hooks sit where Python still owns control flow.

Schedules fire in one of two modes. ``mode="raise"`` (default) raises
`InjectedFault`, exercising the in-process recovery ladders.
``mode="rank_death"`` instead terminates the whole process with
``os._exit`` at the nth hit of the site — the chaos harness's model of
a rank dying mid-collective (testing/chaos.py): no exception handler
runs, no network goodbye is sent, peers are simply left waiting, which
is exactly what the collective watchdog (reliability/watchdog.py) has
to survive.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, Optional, Tuple

__all__ = [
    "InjectedFault", "FaultRegistry", "faults", "KNOWN_SITES",
    "RANK_DEATH_EXIT_CODE",
]

#: exit status of a rank killed by a ``rank_death`` schedule —
#: distinguishable from a watchdog abort (watchdog.WATCHDOG_EXIT_CODE)
#: and from ordinary python failures (1) in chaos-test assertions
RANK_DEATH_EXIT_CODE = 86

KNOWN_SITES = (
    "fused_dispatch",
    "histogram_build",
    "collective_psum",
    "serving_device_predict",
    "serving_replica_predict",
    "serving_pack_predict",
    "serving_hot_swap",
    "serving_hot_swap_commit",
    "checkpoint_io",
    "streaming_ingest",
    "distributed_hist_agg",
    "loop_publish",
    "elastic_resize",
)


class InjectedFault(RuntimeError):
    """Raised by `FaultRegistry.inject` when a schedule fires.

    Subclasses RuntimeError so every pre-existing recovery path
    (train_many's fused fallback, the serving degradation ladder,
    bench's block retry) treats an injected fault exactly like a real
    device error."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at site '{site}' (test hook)")
        self.site = site


class _Schedule:
    __slots__ = ("skip", "fail", "mode")

    def __init__(self, skip: int, fail: int, mode: str = "raise"):
        self.skip = int(skip)
        self.fail = int(fail)
        self.mode = mode


def parse_schedule(val: str) -> Tuple[int, int]:
    """Parse the "N" / "S:N" grammar into (skip, fail)."""
    skip, _, fail = str(val).partition(":")
    if not fail:
        skip, fail = "0", skip
    return int(skip), int(fail)


def _rank_death_exit(site: str) -> None:
    """Kill this rank, abruptly. ``os._exit`` (not ``sys.exit``) is the
    point: no exception propagation, no atexit hooks, no distributed
    shutdown handshake — peers blocked in a collective get NO signal,
    which is the failure the watchdog deadline exists to catch. Tests
    stub this function to observe the firing without dying."""
    print(f"lightgbm_tpu: injected rank_death at site '{site}' "
          f"(os._exit({RANK_DEATH_EXIT_CODE}))", file=sys.stderr,
          flush=True)
    # the killed rank's last act: leave a postmortem bundle so the
    # chaos harness sees a timeline, not just exit code 86
    from ..observability.flightrec import recorder
    recorder.flush("rank_death")
    os._exit(RANK_DEATH_EXIT_CODE)


class FaultRegistry:
    """Thread-safe registry of named injection sites.

    ``schedule(site, skip=S, fail=N)`` arms a site; every ``inject``
    call then consumes one step: the first S calls pass, the next N
    raise `InjectedFault`, later calls pass. ``trips(site)`` counts
    how many faults actually fired (visible to metrics/tests)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._schedules: Dict[str, _Schedule] = {}
        self._trips: Dict[str, int] = {}
        self._calls: Dict[str, int] = {}
        # last env value seeded per (env name, site), so an unchanged
        # env var does not re-arm a consumed schedule
        self._env_seen: Dict[Tuple[str, str], str] = {}

    # -- arming ---------------------------------------------------------
    def schedule(self, site: str, fail: int = 1, skip: int = 0,
                 mode: str = "raise") -> None:
        if mode not in ("raise", "rank_death"):
            raise ValueError(f"unknown fault mode {mode!r} "
                             f"(expected 'raise' or 'rank_death')")
        with self._lock:
            if fail <= 0 and skip <= 0:
                self._schedules.pop(site, None)
            else:
                self._schedules[site] = _Schedule(skip, fail, mode)

    def schedule_from_env(self, site: str, env: str) -> None:
        """Seed `site`'s schedule from environment variable `env`.

        The env var is read-only state: the countdown lives in the
        registry, and re-seeding only happens when the raw env value
        changes (so a consumed schedule stays consumed). A
        ``:rank_death`` suffix ("S:N:rank_death") selects the
        process-killing mode."""
        val = os.environ.get(env, "")
        with self._lock:
            key = (env, site)
            if self._env_seen.get(key) == val:
                return
            self._env_seen[key] = val
            if not val:
                self._schedules.pop(site, None)
                return
            mode = "raise"
            sched_val = val
            if val.endswith(":rank_death"):
                mode = "rank_death"
                sched_val = val[:-len(":rank_death")]
            skip, fail = parse_schedule(sched_val)
            self._schedules[site] = _Schedule(skip, fail, mode)

    def clear(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._schedules.clear()
                self._trips.clear()
                self._calls.clear()
                self._env_seen.clear()
            else:
                self._schedules.pop(site, None)
                self._trips.pop(site, None)
                self._calls.pop(site, None)
                for key in [k for k in self._env_seen if k[1] == site]:
                    del self._env_seen[key]

    # -- firing ---------------------------------------------------------
    def inject(self, site: str) -> None:
        """Consume one schedule step at `site`. When it fires, either
        raise `InjectedFault` (mode "raise") or terminate the process
        (mode "rank_death") — the chosen action runs OUTSIDE the lock."""
        mode = None
        with self._lock:
            self._calls[site] = self._calls.get(site, 0) + 1
            sched = self._schedules.get(site)
            if sched is None:
                return
            if sched.skip > 0:
                sched.skip -= 1
                return
            if sched.fail > 0:
                sched.fail -= 1
                if sched.fail == 0 and sched.skip == 0:
                    del self._schedules[site]
                self._trips[site] = self._trips.get(site, 0) + 1
                mode = sched.mode
            else:
                del self._schedules[site]
                return
        from ..observability.flightrec import recorder
        recorder.record_fault(site, mode or "raise")
        if mode == "rank_death":
            _rank_death_exit(site)
            return      # only reachable when _rank_death_exit is stubbed
        raise InjectedFault(site)

    # -- observation ----------------------------------------------------
    def remaining(self, site: str) -> Tuple[int, int]:
        """(skip, fail) still pending at `site`; (0, 0) when disarmed."""
        with self._lock:
            sched = self._schedules.get(site)
            return (sched.skip, sched.fail) if sched else (0, 0)

    def trips(self, site: Optional[str] = None) -> int:
        with self._lock:
            if site is not None:
                return self._trips.get(site, 0)
            return sum(self._trips.values())

    def calls(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._trips)

    # -- test convenience -----------------------------------------------
    def injected(self, site: str, fail: int = 1, skip: int = 0):
        """Context manager arming `site` on entry, disarming on exit."""
        registry = self

        class _Ctx:
            def __enter__(self):
                registry.schedule(site, fail=fail, skip=skip)
                return registry

            def __exit__(self, *exc):
                registry.schedule(site, fail=0, skip=0)
                return False

        return _Ctx()


#: process-wide singleton; everything in the library injects through it
faults = FaultRegistry()
