"""Numeric guard rails: catch non-finite training state before it
poisons the model.

A single NaN gradient (exploding custom objective, bad init score,
device memory fault) silently corrupts every later iteration — scores
are cumulative. With ``guard_nonfinite`` enabled the trainer checks
gradients/hessians before growth and split gains / scores after, and
applies a policy:

``warn``            log + sanitize non-finite values to 0 and continue
``skip_iteration``  drop the iteration's contribution, keep training
``rollback``        `rollback_one_iter` the offending iteration, keep
                    training (reference Boosting::RollbackOneIter)
``raise``           raise `GuardError` immediately

Each activation increments the ``guard_trips`` counter. The checks are
host syncs (one scalar readback per check point), which is why the
guard is opt-in and forces the per-iteration training path — the fused
multi-tree scan has no host control flow to interpose on.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..utils.log import Log
from .counters import counters

__all__ = ["GuardError", "GUARD_POLICIES", "all_finite", "trip"]

GUARD_POLICIES = ("off", "warn", "skip_iteration", "rollback", "raise")


class GuardError(RuntimeError):
    """Raised by the ``raise`` guard policy on non-finite state."""


def all_finite(*arrays) -> bool:
    """True when every element of every array is finite. One fused
    reduction per array, a single bool readback total."""
    ok = True
    for a in arrays:
        if a is None:
            continue
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a)))
    return bool(ok)


def trip(what: str, policy: str, iteration: int) -> None:
    """Record a guard activation and apply the terminal part of the
    policy (logging / raising); the caller implements skip/rollback."""
    counters.inc("guard_trips")
    from ..observability.flightrec import recorder
    recorder.record_guard_trip(what, policy, iteration)
    recorder.flush("guard_nonfinite")
    msg = (f"non-finite {what} detected at iteration {iteration} "
           f"(guard_nonfinite={policy})")
    if policy == "raise":
        raise GuardError(msg)
    Log.warning(msg)
