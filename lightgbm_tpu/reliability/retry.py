"""Capped-exponential-backoff retry for device dispatch boundaries.

Transient device faults (preempted TPU slice, XLA launch hiccup) are
worth a couple of retries before a dispatch degrades to its fallback
path (fused -> per-iteration, device predict -> host predict). The
policy is deliberately tiny: fixed attempt budget, exponential backoff
with a cap, no jitter — deterministic for tests, and the backoff only
exists to let a wedged runtime drain.
"""

from __future__ import annotations

import time
from typing import Callable, Tuple, Type

from ..utils.log import Log
from .counters import counters

__all__ = ["retry_call"]


def retry_call(fn: Callable, *args,
               attempts: int = 3,
               backoff_ms: float = 50.0,
               backoff_max_ms: float = 2000.0,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               site: str = "",
               on_retry: Callable[[], None] = None,
               sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call ``fn(*args, **kwargs)``; on failure retry up to `attempts`
    total calls with capped exponential backoff. Each retry increments
    the ``device_retries`` counter. The final failure propagates so the
    caller's degradation path still runs."""
    attempts = max(1, int(attempts))
    for attempt in range(attempts):
        try:
            return fn(*args, **kwargs)
        except retry_on as exc:
            if attempt == attempts - 1:
                raise
            counters.inc("device_retries")
            if on_retry is not None:
                on_retry()
            delay = min(backoff_ms * (2.0 ** attempt), backoff_max_ms) / 1e3
            Log.warning(
                f"retry {attempt + 1}/{attempts - 1}"
                f"{' at ' + site if site else ''} after {type(exc).__name__}:"
                f" {exc} (backoff {delay * 1e3:.0f}ms)")
            if delay > 0:
                sleep(delay)
