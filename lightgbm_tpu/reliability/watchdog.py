"""Collective watchdog: a dead rank must fail loudly, not hang forever.

XLA collectives assume every participant eventually arrives. When a
rank dies mid-run (OOM kill, preemption, the chaos harness's
``rank_death`` injection), its peers block inside the next collective
with no error, no timeout and no diagnostic — the failure mode the
reference's socket layer (network.h:89-275) could at least surface as
a recv() error. This module restores that property at the host
boundary:

- every host-side collective entry point (parallel/comm.py
  ``guarded_allgather``, the GBDT sharded-growth dispatch) brackets the
  blocking call in a `CollectiveGuard` deadline
  (``collective_timeout_s``);
- each rank writes a lightweight file heartbeat (``heartbeat_dir``,
  shared filesystem) every ``heartbeat_interval_s``;
- when a bracket overruns its deadline, a monitor thread reads the peer
  heartbeats, diagnoses "rank k last seen Ns ago", logs it, and aborts
  the local process with ``os._exit(WATCHDOG_EXIT_CODE)`` — hanging
  forever is strictly worse than dying with a named culprit.

The guard is OFF by default: it arms only when ``collective_timeout_s``
is set > 0 AND more than one process participates, so single-host runs
(and the entire tier-1 suite) never pay a thread or a branch. The first
bracket of each site label gets ``FIRST_DEADLINE_FACTOR`` x the
deadline, because the first dispatch of a sharded program includes its
XLA compilation.

Deadlines use the monotonic clock (process-local intervals); heartbeat
files carry wall-clock stamps (cross-process ages). Both clocks are
injectable for the fake-clock unit tests (tests/test_watchdog.py).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional, Tuple

from ..observability.flightrec import recorder as _flightrec
from ..utils.log import Log
from .faults import InjectedFault

__all__ = [
    "CollectiveGuard", "WATCHDOG_EXIT_CODE", "FIRST_DEADLINE_FACTOR",
    "active_guard", "collective_guard", "configure_watchdog",
    "maybe_start_watchdog", "shutdown_watchdog",
    "read_heartbeats", "read_heartbeat_info", "write_heartbeat",
]

#: exit status of a watchdog abort — distinct from RANK_DEATH_EXIT_CODE
#: (the injected death) and from ordinary failures (1), so chaos tests
#: can tell the killed rank from the survivor that diagnosed it
WATCHDOG_EXIT_CODE = 113

#: first bracket of each site label stretches the deadline by this
#: factor: the first sharded-growth dispatch includes XLA compilation,
#: which legitimately dwarfs any steady-state collective
FIRST_DEADLINE_FACTOR = 4.0

_HB_PREFIX = "hb_rank_"


# ----------------------------------------------------------------------
# heartbeat files: tmp+replace so readers never see a torn stamp.
# Line 1 is the wall-clock stamp (the original single-line format);
# line 2, when present, is "<span_age_s> <span_name>" — what this rank
# was doing when it last stamped, so a peer diagnosing a hang can say
# *where* the quiet rank was, not just when it was last seen.
def write_heartbeat(heartbeat_dir: str, rank: int, now: float,
                    span_name: str = "", span_age: float = 0.0) -> None:
    """Stamp `rank`'s liveness at wall-clock `now` (atomic replace),
    optionally tagged with the rank's innermost open span."""
    os.makedirs(heartbeat_dir, exist_ok=True)
    path = os.path.join(heartbeat_dir, f"{_HB_PREFIX}{rank:03d}")
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(repr(float(now)))
        if span_name:
            f.write(f"\n{span_age:.3f} {span_name}")
    os.replace(tmp, path)


def read_heartbeat_info(heartbeat_dir: str
                        ) -> Dict[int, Tuple[float, str, float]]:
    """{rank: (last stamp, span name, span age at stamp)} for every
    readable heartbeat file. Files in the pre-span single-line format
    parse as (stamp, "", 0.0). Tolerates concurrent writers and
    vanishing files (ENOENT races)."""
    info: Dict[int, Tuple[float, str, float]] = {}
    try:
        names = os.listdir(heartbeat_dir)
    except (FileNotFoundError, NotADirectoryError):
        return info
    for name in names:
        if not name.startswith(_HB_PREFIX) or name.endswith(".tmp"):
            continue
        try:
            rank = int(name[len(_HB_PREFIX):])
            with open(os.path.join(heartbeat_dir, name)) as f:
                lines = f.read().splitlines()
            stamp = float(lines[0].strip())
            span_name, span_age = "", 0.0
            if len(lines) > 1 and lines[1].strip():
                age_s, _, span_name = lines[1].strip().partition(" ")
                span_age = float(age_s)
            info[rank] = (stamp, span_name, span_age)
        except (ValueError, OSError, IndexError):
            continue        # torn tmp name / racing unlink: skip
    return info


def read_heartbeats(heartbeat_dir: str) -> Dict[int, float]:
    """{rank: last wall-clock stamp} for every readable heartbeat
    file (the stamp-only view of `read_heartbeat_info`)."""
    return {r: t[0] for r, t in read_heartbeat_info(heartbeat_dir).items()}


class CollectiveGuard:
    """Deadline + heartbeat bracket around blocking collectives.

    Pure state machine over injectable clocks: `enter`/`exit_` mark the
    active bracket, `poll` reports an overrun (as the diagnostic string)
    without side effects, and `start` wires the real-time threads that
    call them. Unit tests drive enter/poll with fake clocks and an
    `abort_fn` stub; production uses the monitor thread and os._exit."""

    def __init__(self, timeout_s: float, rank: int = 0, world: int = 1,
                 heartbeat_dir: str = "",
                 heartbeat_interval_s: float = 1.0,
                 first_deadline_factor: float = FIRST_DEADLINE_FACTOR,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 abort_fn: Optional[Callable[[str], None]] = None,
                 elastic: Optional[dict] = None):
        if timeout_s <= 0:
            raise ValueError("CollectiveGuard needs collective_timeout_s"
                             " > 0 (0 disables the watchdog)")
        self.timeout_s = float(timeout_s)
        self.rank = int(rank)
        self.world = int(world)
        self.heartbeat_dir = heartbeat_dir
        self.interval_s = max(1e-3, float(heartbeat_interval_s))
        self.first_factor = max(1.0, float(first_deadline_factor))
        self._clock = clock
        self._wall = wall
        self._abort_fn = abort_fn
        #: {"min_world": int, "epoch_timeout_s": float, "ckpt_dir": str}
        #: when elastic_resize is on — the abort path then proposes a
        #: shrink before giving up (distributed/elastic.py)
        self.elastic = dict(elastic) if elastic else None
        self._lock = threading.Lock()
        self._site: Optional[str] = None
        self._deadline: Optional[float] = None
        self._entered: Optional[float] = None
        self._seen_sites: set = set()
        self._stop = threading.Event()
        self._threads: list = []

    # -- bracket --------------------------------------------------------
    def enter(self, site: str) -> None:
        factor = 1.0
        with self._lock:
            if site not in self._seen_sites:
                self._seen_sites.add(site)
                factor = self.first_factor
            self._site = site
            self._entered = self._clock()
            self._deadline = self._entered + self.timeout_s * factor
        self.heartbeat_once()
        _flightrec.record_collective(
            site, "enter", deadline_s=self.timeout_s * factor,
            heartbeat_ages=self.heartbeat_ages() or None)

    def exit_(self) -> None:
        from ..observability.registry import registry
        with self._lock:
            entered, site = self._entered, self._site
            self._site = self._deadline = self._entered = None
        if entered is not None:
            wall_s = self._clock() - entered
            registry.record_collective_guard(wall_s)
            _flightrec.record_collective(site, "exit", wall_s=wall_s)

    @contextmanager
    def guard(self, site: str):
        """Bracket one blocking collective. An exception inside the
        bracket (a peer connection dropping often surfaces as a
        dispatch error rather than a hang) gets the same heartbeat
        diagnosis logged before it propagates; `InjectedFault` is the
        in-process test hook and passes through silently."""
        self.enter(site)
        try:
            yield
        except InjectedFault:
            raise
        except BaseException:
            diag = self.diagnose(site)
            Log.warning("collective watchdog: error inside collective "
                        "bracket — %s", diag)
            print(f"collective watchdog: {diag}", file=sys.stderr,
                  flush=True)
            raise
        finally:
            self.exit_()

    # -- liveness -------------------------------------------------------
    def _span_payload(self) -> Tuple[str, float]:
        """What this rank is doing right now, for the heartbeat tag:
        the active collective bracket when one is open (the interesting
        case for a hang diagnosis), else the innermost open trace span."""
        with self._lock:
            site, entered = self._site, self._entered
        if site is not None and entered is not None:
            return f"collective:{site}", max(0.0, self._clock() - entered)
        from ..observability.registry import registry
        return registry.trace.innermost_open()

    def heartbeat_once(self) -> None:
        if self.heartbeat_dir:
            name, age = self._span_payload()
            try:
                write_heartbeat(self.heartbeat_dir, self.rank,
                                self._wall(), span_name=name,
                                span_age=age)
            except OSError as exc:
                Log.warning("collective watchdog: heartbeat write "
                            "failed (%s: %s)", type(exc).__name__, exc)

    def heartbeat_ages(self) -> Dict[int, float]:
        """{rank: seconds since last stamp} for every rank with a
        heartbeat file (missing ranks simply have no entry)."""
        now = self._wall()
        return {r: max(0.0, now - ts) for r, ts in
                read_heartbeats(self.heartbeat_dir).items()} \
            if self.heartbeat_dir else {}

    def diagnose(self, site: str) -> str:
        """Human-readable account of who went quiet, built from the
        heartbeat files — 'rank k last seen Ns ago' names the culprit."""
        head = (f"collective '{site}' exceeded collective_timeout_s="
                f"{self.timeout_s:g} on rank {self.rank}")
        if not self.heartbeat_dir:
            return head + " (no heartbeat_dir configured; cannot name " \
                          "the stalled rank)"
        now = self._wall()
        info = read_heartbeat_info(self.heartbeat_dir)
        ages = {r: max(0.0, now - t[0]) for r, t in info.items()}
        from ..observability.registry import registry
        peers = {r: a for r, a in ages.items() if r != self.rank}
        if peers:
            registry.record_heartbeat_age(max(peers.values()))
        stale_after = 3.0 * self.interval_s
        missing = [r for r in range(self.world)
                   if r != self.rank and r not in ages]
        stale = sorted((a, r) for r, a in peers.items()
                       if a > stale_after)
        parts = []
        for age, r in reversed(stale):
            part = f"rank {r} last seen {age:.1f}s ago"
            span_name = info[r][1]
            if span_name:
                part += f" in span {span_name}"
            parts.append(part)
        for r in missing:
            parts.append(f"rank {r} never heartbeat")
        if not parts:
            return head + (" — all peer heartbeats fresh (wedged "
                           "interconnect, or this rank is the straggler)")
        return head + ": " + ", ".join(parts)

    # -- monitoring -----------------------------------------------------
    def poll(self) -> Optional[str]:
        """Diagnostic string if the active bracket overran its
        deadline, else None. Side-effect free; callable from tests."""
        with self._lock:
            expired = (self._deadline is not None and
                       self._clock() > self._deadline)
            site = self._site
        if not expired or site is None:
            return None
        from ..observability.registry import registry
        registry.record_collective_timeout()
        return self.diagnose(site)

    def _try_elastic_resize(self, diag: str) -> bool:
        """The elastic branch of the abort path: vote a shrink through
        the heartbeat directory instead of dying. True means the resize
        committed and this rank is gone (or, with a stubbed abort_fn,
        the stub was told); False falls through to the plain abort —
        a failed vote is never worse than today's behavior."""
        ela = self.elastic
        if ela is None or not self.heartbeat_dir:
            return False
        from ..distributed import elastic
        exit_code = elastic.ELASTIC_RESIZE_EXIT_CODE
        try:
            rec = elastic.propose_shrink(
                self.heartbeat_dir, rank=self.rank, world=self.world,
                epoch=elastic.current_epoch(),
                min_world=int(ela.get("min_world", 1)),
                timeout_s=float(ela.get("epoch_timeout_s", 30.0)),
                stale_after_s=3.0 * self.interval_s,
                reason=diag[:300],
                resume_bundle=self._elastic_resume_bundle(),
                wall=self._wall)
        except Exception as exc:
            # includes InjectedFault at the elastic_resize site: the
            # vote machinery must never mask the abort it replaces
            Log.warning("elastic resize failed (%s: %s); falling back "
                        "to watchdog abort", type(exc).__name__, exc)
            return False
        if rec is None:
            return False
        msg = (f"collective watchdog: {diag} — membership epoch "
               f"{rec.epoch} committed (world {self.world} -> "
               f"{rec.world}); exiting for reincarnation "
               f"(os._exit({exit_code}))")
        Log.warning(msg)
        print(msg, file=sys.stderr, flush=True)
        _flightrec.record("resize", "watchdog", diag=diag[:500],
                          epoch=rec.epoch, world=rec.world,
                          exit_code=exit_code)
        if self._abort_fn is not None:
            if _flightrec.out_dir:
                _flightrec.flush("elastic_resize")
            self._abort_fn(f"elastic_resize epoch={rec.epoch} "
                           f"world={rec.world}: {diag}")
            return True
        _flightrec.flush("elastic_resize")
        os._exit(exit_code)
        return True     # unreachable; keeps the stubbed-exit tests honest

    def _elastic_resume_bundle(self) -> str:
        """The bundle the reincarnated world should resume from — the
        newest committed checkpoint, named in the membership record so
        the supervisor can snapshot it before relaunching."""
        ckpt_dir = (self.elastic or {}).get("ckpt_dir", "")
        if not ckpt_dir:
            return ""
        try:
            from .checkpoint import latest_checkpoint
            return latest_checkpoint(ckpt_dir) or ""
        except Exception:       # forensics only; never block the vote
            return ""

    def _abort(self, diag: str) -> None:
        from ..observability.registry import registry
        if self._try_elastic_resize(diag):
            return
        registry.record_collective_abort()
        _flightrec.record("abort", "watchdog", diag=diag[:500],
                          exit_code=WATCHDOG_EXIT_CODE)
        msg = ("collective watchdog: " + diag +
               f" — aborting this rank (os._exit({WATCHDOG_EXIT_CODE})) "
               f"instead of hanging; resume from the last coordinated "
               f"checkpoint")
        Log.warning(msg)
        print(msg, file=sys.stderr, flush=True)
        if self._abort_fn is not None:
            # stubbed abort (tests): flush only to a configured bundle
            # directory, never the fatal-path cwd fallback
            if _flightrec.out_dir:
                _flightrec.flush("watchdog_abort")
            self._abort_fn(diag)
            return
        _flightrec.flush("watchdog_abort")
        os._exit(WATCHDOG_EXIT_CODE)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.heartbeat_once()

    def _monitor_loop(self) -> None:
        poll_s = max(0.05, min(0.5, self.interval_s, self.timeout_s / 8))
        while not self._stop.wait(poll_s):
            diag = self.poll()
            if diag is not None:
                self._abort(diag)
                return      # only reached with a stubbed abort_fn

    def start(self) -> "CollectiveGuard":
        self.heartbeat_once()
        threads = []
        for target, name in ((self._heartbeat_loop, "lgbm-heartbeat"),
                             (self._monitor_loop, "lgbm-watchdog")):
            th = threading.Thread(target=target, name=name, daemon=True)
            th.start()
            threads.append(th)
        with self._lock:
            self._threads = threads
        Log.info("collective watchdog armed: rank %d/%d, "
                 "collective_timeout_s=%g, heartbeat_dir=%r",
                 self.rank, self.world, self.timeout_s,
                 self.heartbeat_dir or "<none>")
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:               # joins happen lockless: the
            threads = self._threads    # monitor's poll() needs the lock
            self._threads = []
        for th in threads:
            th.join(timeout=2.0)


# ----------------------------------------------------------------------
# process-global guard: configured once per multihost run, consulted by
# every collective entry point through `collective_guard(...)`
_guard: Optional[CollectiveGuard] = None
_guard_lock = threading.Lock()


def active_guard() -> Optional[CollectiveGuard]:
    return _guard


@contextmanager
def collective_guard(site: str):
    """Bracket a blocking collective with the configured guard; a
    no-op (no branch beyond one global read) when the watchdog is
    disabled — the single-host/tier-1 fast path."""
    g = _guard
    if g is None:
        yield
        return
    with g.guard(site):
        yield


def configure_watchdog(timeout_s: float, rank: int = 0, world: int = 1,
                       heartbeat_dir: str = "",
                       interval_s: float = 1.0,
                       abort_fn: Optional[Callable[[str], None]] = None,
                       elastic: Optional[dict] = None
                       ) -> Optional[CollectiveGuard]:
    """Install (or tear down) the process-global guard. Disabled — and
    any previous guard stopped — when `timeout_s` <= 0 or `world` <= 1:
    the watchdog is strictly a multi-process affair. Idempotent for
    unchanged settings, so every collective entry point may call it.
    `elastic` ({"min_world", "epoch_timeout_s", "ckpt_dir"}) switches
    the abort path to propose-shrink (distributed/elastic.py)."""
    global _guard
    with _guard_lock:
        if timeout_s <= 0 or world <= 1:
            if _guard is not None:
                _guard.stop()
                _guard = None
            return None
        g = _guard
        if (g is not None and g.timeout_s == float(timeout_s) and
                g.rank == int(rank) and g.world == int(world) and
                g.heartbeat_dir == heartbeat_dir and
                g.interval_s == float(interval_s) and
                g.elastic == (dict(elastic) if elastic else None)):
            return g
        if g is not None:
            g.stop()
        if heartbeat_dir:
            # restart hygiene: a reincarnated (or plainly restarted)
            # world inherits the heartbeat dir of its predecessor —
            # sweep heartbeats of ranks beyond the new world and shrink
            # proposals consumed by committed epochs, so they cannot
            # mis-age into "rank k last seen" culprits or confuse the
            # next vote
            from ..distributed.elastic import (current_epoch,
                                               sweep_stale_epoch_files)
            sweep_stale_epoch_files(heartbeat_dir, current_epoch(),
                                    int(world))
        from ..observability.registry import registry
        registry.record_collective_world(int(world))
        _guard = CollectiveGuard(
            timeout_s, rank=rank, world=world,
            heartbeat_dir=heartbeat_dir,
            heartbeat_interval_s=interval_s, abort_fn=abort_fn,
            elastic=elastic).start()
        return _guard


def maybe_start_watchdog(cfg) -> Optional[CollectiveGuard]:
    """Arm the watchdog from a resolved `Config` if this really is a
    multi-process run. Called from the collective entry points
    themselves (distributed bin finding, `_setup_parallel`), so
    whichever runs first arms it; cheap and idempotent afterwards.
    With no explicit `heartbeat_dir` the heartbeats ride under
    `checkpoint_dir` — already required to be a shared filesystem for
    coordinated checkpoints."""
    timeout_s = float(getattr(cfg, "collective_timeout_s", 0.0) or 0.0)
    if timeout_s <= 0:
        return None
    import jax
    try:
        world = jax.process_count()
    except RuntimeError:
        world = 1
    if world <= 1:
        return None
    hb = cfg.heartbeat_dir
    if not hb and cfg.checkpoint_dir:
        hb = os.path.join(cfg.checkpoint_dir, "heartbeats")
    elastic = None
    if bool(getattr(cfg, "elastic_resize", False)):
        elastic = {"min_world": int(getattr(cfg, "elastic_min_world", 1)),
                   "epoch_timeout_s": float(
                       getattr(cfg, "elastic_epoch_timeout_s", 30.0)),
                   "ckpt_dir": cfg.checkpoint_dir or ""}
    return configure_watchdog(timeout_s, rank=jax.process_index(),
                              world=world, heartbeat_dir=hb,
                              interval_s=cfg.heartbeat_interval_s,
                              elastic=elastic)


def shutdown_watchdog() -> None:
    """Stop the global guard and its threads (tests; end of run)."""
    global _guard
    with _guard_lock:
        if _guard is not None:
            _guard.stop()
            _guard = None
