"""TPU-resident inference serving engine.

Loads a trained (or file-loaded) Booster once into stacked device
arrays and serves request streams through a shape-bucketed compiled
predictor with micro-batching, admission control, host fallback, and a
per-model metrics surface. See docs/Serving.md and `Server`.
"""

from .batcher import MicroBatcher, OverloadError
from .engine import BucketedPredictor, max_compilations, next_bucket
from .forest import DeviceForest, FeatureBinner, build_device_forest
from .metrics import ModelMetrics
from .registry import ModelEntry, ModelRegistry
from .server import Server

__all__ = [
    "Server", "ModelRegistry", "ModelEntry", "ModelMetrics",
    "MicroBatcher", "OverloadError", "BucketedPredictor",
    "DeviceForest", "FeatureBinner", "build_device_forest",
    "next_bucket", "max_compilations",
]
