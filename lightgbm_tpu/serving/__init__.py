"""TPU-resident inference serving engine.

Loads a trained (or file-loaded) Booster once into stacked device
arrays, replicates it across local devices, and serves request streams
through a shape-bucketed compiled predictor with micro-batching,
SLO-budgeted admission control, per-replica self-healing circuit
breakers, failover, zero-downtime hot-swap, host fallback, and a
per-model metrics surface. See docs/Serving.md and `Server`.
"""

from .batcher import (BatcherClosed, DeadlineExceeded, MicroBatcher,
                      OverloadError)
from .breaker import BREAKER_STATES, CircuitBreaker, breaker_state_code
from .engine import BucketedPredictor, max_compilations, next_bucket
from .forest import DeviceForest, FeatureBinner, build_device_forest
from .metrics import ModelMetrics
from .registry import ModelEntry, ModelRegistry
from .replicas import NoReplicaAvailable, Replica, ReplicaSet
from .server import Server

__all__ = [
    "Server", "ModelRegistry", "ModelEntry", "ModelMetrics",
    "MicroBatcher", "OverloadError", "BatcherClosed",
    "DeadlineExceeded", "CircuitBreaker", "BREAKER_STATES",
    "breaker_state_code", "Replica", "ReplicaSet",
    "NoReplicaAvailable", "BucketedPredictor",
    "DeviceForest", "FeatureBinner", "build_device_forest",
    "next_bucket", "max_compilations",
]
