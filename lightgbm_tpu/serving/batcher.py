"""Micro-batching request queue with admission control.

Concurrent small predict requests are coalesced into one device call:
a background worker drains the queue, packing requests until
`max_batch_size` rows are gathered or `max_wait_ms` has elapsed since
the oldest queued request. One device batch then serves them all and
each caller's Future gets its slice back — per-request launch overhead
amortizes across the coalesced batch (the same motivation as the
reference's row-parallel Predictor, but across *requests* instead of
rows).

Two scheduling policies pick WHICH queued requests form the batch:

- ``fifo``: the historical prefix packer — requests dispatch strictly
  in arrival order, and one large request at the head stalls every
  small one behind it until it fits.
- ``slo`` (continuous batching): requests are packed in
  remaining-SLO-budget order with skip-and-fill — a request too large
  for the remaining batch capacity is *skipped*, and later smaller
  requests fill the gap, so small tight-budget requests interleave
  with large ones instead of queueing behind them. Requests without a
  deadline sort as infinite budget (pure FIFO among themselves), and a
  starvation guard promotes anything waiting longer than
  ``_STARVE_FACTOR`` coalescing windows to the front so a large
  request can never be skipped forever. `interleave_count` counts
  requests that jumped a skipped earlier-scheduled one.

Admission control: once `max_queue` requests are waiting, new arrivals
are shed immediately with `OverloadError` instead of growing the queue
without bound — a bounded queue keeps tail latency bounded too.

SLO budgets (the top rung of the degradation ladder, docs/Serving.md):
a request may carry a *deadline*. At submit the batcher projects the
queue wait from an online linear model of batch service time,
``s(rows) = base + rows * slope`` (EMA moments, `_ServiceModel`) — if
the projection already overshoots the remaining budget the request is
shed NOW with `DeadlineExceeded`, while the caller can still answer it
cheaply (host predict), instead of letting it queue, expire, and waste
a device slot. The rows term matters on shared (multi-model pack)
queues: one member's huge batches must not inflate the projection for
another member's 8-row requests — a scalar batch-wall EMA did exactly
that and over-shed small requests. In ``slo`` mode the projection also
counts only queued rows whose budget is at least as tight as the
incoming request's, since looser work is scheduled behind it. Requests
that expire anyway (service time spiked after admission) are expired
at dispatch time, again with `DeadlineExceeded`, never silently
dropped.

`pause()`/`resume()` freeze the worker between batches; tests use this
to enqueue a deterministic set of requests and observe exactly one
coalesced device batch. `clock` is injectable for deterministic
scheduler/admission tests.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional

import numpy as np

from ..utils.log import Log

__all__ = ["MicroBatcher", "OverloadError", "BatcherClosed",
           "DeadlineExceeded", "SCHEDULERS"]


class OverloadError(RuntimeError):
    """Request shed by admission control (queue depth exceeded)."""


class DeadlineExceeded(RuntimeError):
    """Request's SLO budget cannot be met by the device queue.

    Raised at submit when the projected queue wait overshoots the
    remaining budget, or set on the future when a queued request
    expires before dispatch. The server's deadline policy decides what
    the caller sees: ``fallback`` answers via host predict, ``fail``
    propagates this error."""


class BatcherClosed(RuntimeError):
    """A queued request's batcher shut down before dispatching it.

    Distinct from a device failure: the request itself is fine, the
    queue is just going away. The server catches this and drains the
    request through the host-predict fallback instead of dropping it
    (and without degrading the model entry)."""


class _Request:
    __slots__ = ("bins", "future", "t_enqueue", "deadline", "slot")

    def __init__(self, bins: np.ndarray,
                 deadline: Optional[float] = None,
                 slot: Optional[int] = None,
                 now: Optional[float] = None):
        self.bins = bins
        self.future: Future = Future()
        self.t_enqueue = time.monotonic() if now is None else now
        self.deadline = deadline      # absolute monotonic, or None
        self.slot = slot              # pack slot (multi-model batchers)


class _ServiceModel:
    """Online linear model of device batch service time:
    ``s(rows) = base + rows * slope``, fit from EMA first/second
    moments of (rows, wall) observations.

    Replaces the scalar batch-wall EMA: on a queue shared by models of
    very different sizes (a `ForestPack`), one member's 1024-row
    batches would drive a scalar EMA to the large-batch wall and the
    admission projection would shed every small-model request sharing
    the device — even though an 8-row dispatch is far cheaper. The
    slope is clamped non-negative (more rows never *predicts* faster)
    and falls back to the plain EMA mean while the observed row sizes
    are degenerate (no variance to fit a slope from)."""

    def __init__(self, seed_s: float, alpha: float = 0.3):
        self._alpha = float(alpha)
        self._base = float(seed_s)
        self._slope = 0.0
        self._er: Optional[float] = None   # EMA rows
        self._edt = float(seed_s)          # EMA wall seconds
        self._erdt = 0.0                   # EMA rows*wall
        self._er2 = 0.0                    # EMA rows^2

    def update(self, rows: int, dt: float) -> None:
        a = self._alpha
        r = float(rows)
        if self._er is None:
            self._er, self._edt = r, float(dt)
            self._erdt, self._er2 = r * dt, r * r
        else:
            self._er += a * (r - self._er)
            self._edt += a * (dt - self._edt)
            self._erdt += a * (r * dt - self._erdt)
            self._er2 += a * (r * r - self._er2)
        var = self._er2 - self._er * self._er
        cov = self._erdt - self._er * self._edt
        if var > 1e-9 and cov > 0.0:
            self._slope = cov / var
            self._base = max(self._edt - self._slope * self._er, 0.0)
        else:
            self._slope = 0.0
            self._base = self._edt

    def projected(self, rows: int) -> float:
        return self._base + self._slope * float(rows)


#: schedulers accepted by MicroBatcher (docs/Serving.md "Continuous
#: batching"): prefix FIFO packing vs remaining-budget skip-and-fill
SCHEDULERS = ("fifo", "slo")


class MicroBatcher:
    """Coalescing queue in front of one model's device predictor.

    `run_batch([N, F] bins) -> [N, num_outputs]` is the only downstream
    dependency; the batcher never imports JAX itself.
    """

    #: slo-mode starvation guard: a request waiting longer than this
    #: many coalescing windows goes to the front regardless of budget
    _STARVE_FACTOR = 20.0

    def __init__(self, run_batch: Callable[[np.ndarray], np.ndarray],
                 max_batch_size: int = 1024, max_wait_ms: float = 2.0,
                 max_queue: int = 128, name: str = "model",
                 scheduler: str = "fifo",
                 clock: Callable[[], float] = time.monotonic):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if scheduler not in SCHEDULERS:
            raise ValueError(
                f"scheduler must be one of {SCHEDULERS}, got "
                f"'{scheduler}'")
        self._run_batch = run_batch
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.name = name
        self.scheduler = scheduler
        self._clock = clock
        self._queue: List[_Request] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._paused = False
        self._closed = False
        self.shed_count = 0
        self.deadline_shed_count = 0   # budget-projection sheds at submit
        self.deadline_expired_count = 0  # expired while queued
        self.batch_count = 0
        self.coalesced_requests = 0
        self.interleave_count = 0      # requests that jumped a skipped one
        # rows-aware service-time model, seeds the queue-wait
        # projection before the first batch completes
        self._svc = _ServiceModel(max(self.max_wait_ms, 1.0) / 1e3)
        self._worker = threading.Thread(
            target=self._loop, name=f"serve-batcher-{name}", daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    def submit(self, bins: np.ndarray,
               deadline: Optional[float] = None,
               slot: Optional[int] = None) -> Future:
        """Queue one request's binned rows; resolves to its raw scores.

        `deadline` is an absolute `time.monotonic()` instant. When the
        projected queue wait (`_projected_wait_locked`) would already
        blow the budget, the request is shed here with
        `DeadlineExceeded` so the caller can still answer it on time
        via the host path. `slot` tags the request for multi-model
        pack batchers (ignored by the plain dispatch)."""
        req = _Request(bins, deadline, slot, now=self._clock())
        with self._lock:
            if self._closed:
                raise BatcherClosed(
                    f"batcher '{self.name}' is closed")
            if len(self._queue) >= self.max_queue:
                self.shed_count += 1
                raise OverloadError(
                    f"serving queue for '{self.name}' is full "
                    f"({self.max_queue} requests waiting)")
            if deadline is not None:
                wait_s = self._projected_wait_locked(len(bins), deadline)
                if req.t_enqueue + wait_s > deadline:
                    self.deadline_shed_count += 1
                    raise DeadlineExceeded(
                        f"serving queue for '{self.name}': projected "
                        f"wait {wait_s * 1e3:.1f}ms exceeds remaining "
                        f"budget "
                        f"{(deadline - req.t_enqueue) * 1e3:.1f}ms")
            self._queue.append(req)
            self._wake.notify()
        return req.future

    def _projected_wait_locked(self, incoming_rows: int,
                               deadline: Optional[float] = None) -> float:
        """Estimated seconds before a request submitted now gets its
        result: device batches ahead of it × the rows-aware service
        model, plus the coalescing window it may itself sit out. In
        ``slo`` mode only queued requests whose budget is at least as
        tight count as "ahead" — looser and deadline-free work is
        scheduled behind the incoming request, so it cannot delay it.

        An EMPTY queue always projects just the coalescing window: the
        service estimate only refreshes when batches actually dispatch,
        so shedding idle-queue requests on a stale estimate (e.g. one
        poisoned by a cold-start compile) would starve the model of the
        very samples that correct it. Caller holds _lock."""
        if self.scheduler == "slo" and deadline is not None:
            ahead = sum(len(r.bins) for r in self._queue
                        if r.deadline is not None and
                        r.deadline <= deadline)
        else:
            ahead = sum(len(r.bins) for r in self._queue)
        if ahead == 0:
            return self.max_wait_ms / 1e3
        rows = ahead + int(incoming_rows)
        batches_ahead = max(
            (rows + self.max_batch_size - 1) // self.max_batch_size, 1)
        per_batch = min(rows, self.max_batch_size)
        return batches_ahead * self._svc.projected(per_batch) + \
            self.max_wait_ms / 1e3

    def pause(self) -> None:
        """Freeze the worker between batches (deterministic tests)."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._wake.notify()

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def close(self, timeout: float = 5.0,
              drain_queued: bool = True) -> int:
        """Shut the worker down; returns how many queued requests were
        resolved with `BatcherClosed` (the hot-swap `swap_drains`
        accounting).

        ``drain_queued=True`` (plain shutdown) lets the worker dispatch
        whatever is already queued before exiting. ``drain_queued=False``
        (hot-swap) pops the queue immediately so no queued request runs
        against the outgoing forest — each future gets `BatcherClosed`
        and the server re-answers it through the host path of the OLD
        entry (same binning, no torn model)."""
        if drain_queued:
            with self._lock:
                self._closed = True
                self._paused = False
                self._wake.notify()
        else:
            with self._lock:
                pulled, self._queue = self._queue, []
                self._closed = True
                self._paused = False
                self._wake.notify()
            for req in pulled:
                if not req.future.done():
                    req.future.set_exception(BatcherClosed(
                        f"batcher '{self.name}' closed before "
                        f"dispatching this request"))
        self._worker.join(timeout=timeout)
        # with drain_queued=True the worker drains the queue on close
        # (the take condition includes _closed), so leftovers only
        # exist when the join timed out — a wedged device dispatch.
        # Resolve them with BatcherClosed so upstream can re-route each
        # request through the host fallback instead of hanging or
        # dropping its caller.
        with self._lock:
            leftovers, self._queue = self._queue, []
        for req in leftovers:
            if not req.future.done():
                req.future.set_exception(BatcherClosed(
                    f"batcher '{self.name}' closed before dispatching "
                    f"this request"))
        drained = len(leftovers)
        if not drain_queued:
            drained += len(pulled)
        return drained

    # ------------------------------------------------------------------
    def _schedule_order_locked(self, now: float) -> List[_Request]:
        """Queue in dispatch-priority order. ``fifo``: arrival order.
        ``slo``: starved requests first, then tightest remaining
        budget (deadline-free = infinite budget), FIFO tie-break.
        Caller holds _lock."""
        if self.scheduler == "fifo":
            return list(self._queue)
        starve_s = self._STARVE_FACTOR * self.max_wait_ms / 1e3

        def key(r: _Request):
            starved = (now - r.t_enqueue) >= starve_s
            budget = (r.deadline - now) if r.deadline is not None \
                else float("inf")
            return (not starved, budget, r.t_enqueue)

        return sorted(self._queue, key=key)

    def _take_batch(self) -> Optional[List[_Request]]:
        """Block until a coalescible batch is ready (or closed)."""
        with self._lock:
            while True:
                if self._closed and not self._queue:
                    return None
                if self._queue and not self._paused:
                    now = self._clock()
                    oldest = min(r.t_enqueue for r in self._queue)
                    order = self._schedule_order_locked(now)
                    rows = 0
                    take: List[_Request] = []
                    skipped = False
                    interleaves = 0
                    for req in order:
                        if take and rows + len(req.bins) > \
                                self.max_batch_size:
                            if self.scheduler == "fifo":
                                break       # strict prefix packing
                            skipped = True  # skip-and-fill: later,
                            continue        # smaller requests may fit
                        if skipped:
                            interleaves += 1
                        rows += len(req.bins)
                        take.append(req)
                        if rows >= self.max_batch_size:
                            break
                    waited_ms = (now - oldest) * 1e3
                    if (rows >= self.max_batch_size or self._closed or
                            waited_ms >= self.max_wait_ms):
                        taken = {id(r) for r in take}
                        self._queue = [r for r in self._queue
                                       if id(r) not in taken]
                        self.interleave_count += interleaves
                        return take
                    # more coalescing headroom: sleep out the window
                    self._wake.wait(
                        timeout=(self.max_wait_ms - waited_ms) / 1e3)
                    continue
                self._wake.wait(timeout=0.1)

    def _expire_overdue(self, batch: List[_Request]) -> List[_Request]:
        """Resolve requests whose deadline already passed (admission's
        projection was optimistic) with `DeadlineExceeded`; the rest
        dispatch. Never silently drops a future."""
        now = self._clock()
        live: List[_Request] = []
        expired = 0
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                expired += 1
                if not req.future.done():
                    req.future.set_exception(DeadlineExceeded(
                        f"request expired in '{self.name}' queue "
                        f"({(now - req.t_enqueue) * 1e3:.1f}ms waited)"))
            else:
                live.append(req)
        if expired:
            with self._lock:
                self.deadline_expired_count += expired
        return live

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as exc:
            # worker death is a serving fatal: every queued caller
            # would hang. Post-mortem it, then resolve everything with
            # BatcherClosed so upstream host-drains each request.
            from ..observability.flightrec import recorder
            recorder.record_exception(
                f"serving_batcher_worker[{self.name}]", exc)
            recorder.flush("exception")
            Log.warning(f"serving batcher worker for '{self.name}' "
                        f"died: {exc}")
            with self._lock:
                self._closed = True
                leftovers, self._queue = self._queue, []
            for req in leftovers:
                if not req.future.done():
                    req.future.set_exception(BatcherClosed(
                        f"batcher '{self.name}' worker died before "
                        f"dispatching this request"))
            raise

    def _dispatch(self, batch: List[_Request]) -> None:
        """Run one coalesced batch and resolve its futures (worker
        thread). Subclasses override to change the dispatch shape —
        the pack batcher (serving/multimodel.py) groups requests by
        slot into one fused multi-model launch."""
        bins = batch[0].bins if len(batch) == 1 else \
            np.concatenate([r.bins for r in batch], axis=0)
        raw = self._run_batch(bins)
        lo = 0
        for req in batch:
            hi = lo + len(req.bins)
            req.future.set_result(raw[lo:hi])
            lo = hi

    def _loop_inner(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            batch = self._expire_overdue(batch)
            if not batch:
                continue
            self.batch_count += 1
            self.coalesced_requests += len(batch)
            rows = sum(len(r.bins) for r in batch)
            t0 = time.monotonic()
            try:
                self._dispatch(batch)
            except Exception as exc:  # surface to callers, keep serving
                Log.warning(f"serving batch for '{self.name}' failed: "
                            f"{exc}")
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(exc)
            except BaseException:
                # worker is dying (KeyboardInterrupt/SystemExit): this
                # batch was already popped from the queue, so resolve
                # its futures here before _loop's post-mortem handler
                # deals with the rest of the queue
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(BatcherClosed(
                            f"batcher '{self.name}' worker died while "
                            f"dispatching this request"))
                raise
            finally:
                dt = time.monotonic() - t0
                with self._lock:
                    self._svc.update(rows, dt)
