"""Micro-batching request queue with admission control.

Concurrent small predict requests are coalesced into one device call:
a background worker drains the queue, packing requests in FIFO order
until `max_batch_size` rows are gathered or `max_wait_ms` has elapsed
since the oldest queued request. One device batch then serves them all
and each caller's Future gets its slice back — per-request launch
overhead amortizes across the coalesced batch (the same motivation as
the reference's row-parallel Predictor, but across *requests* instead
of rows).

Admission control: once `max_queue` requests are waiting, new arrivals
are shed immediately with `OverloadError` instead of growing the queue
without bound — a bounded queue keeps tail latency bounded too.

`pause()`/`resume()` freeze the worker between batches; tests use this
to enqueue a deterministic set of requests and observe exactly one
coalesced device batch.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional

import numpy as np

from ..utils.log import Log

__all__ = ["MicroBatcher", "OverloadError", "BatcherClosed"]


class OverloadError(RuntimeError):
    """Request shed by admission control (queue depth exceeded)."""


class BatcherClosed(RuntimeError):
    """A queued request's batcher shut down before dispatching it.

    Distinct from a device failure: the request itself is fine, the
    queue is just going away. The server catches this and drains the
    request through the host-predict fallback instead of dropping it
    (and without degrading the model entry)."""


class _Request:
    __slots__ = ("bins", "future", "t_enqueue")

    def __init__(self, bins: np.ndarray):
        self.bins = bins
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()


class MicroBatcher:
    """FIFO coalescing queue in front of one model's device predictor.

    `run_batch([N, F] bins) -> [N, num_outputs]` is the only downstream
    dependency; the batcher never imports JAX itself.
    """

    def __init__(self, run_batch: Callable[[np.ndarray], np.ndarray],
                 max_batch_size: int = 1024, max_wait_ms: float = 2.0,
                 max_queue: int = 128, name: str = "model"):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._run_batch = run_batch
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.name = name
        self._queue: List[_Request] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._paused = False
        self._closed = False
        self.shed_count = 0
        self.batch_count = 0
        self.coalesced_requests = 0
        self._worker = threading.Thread(
            target=self._loop, name=f"serve-batcher-{name}", daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    def submit(self, bins: np.ndarray) -> Future:
        """Queue one request's binned rows; resolves to its raw scores."""
        req = _Request(bins)
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._queue) >= self.max_queue:
                self.shed_count += 1
                raise OverloadError(
                    f"serving queue for '{self.name}' is full "
                    f"({self.max_queue} requests waiting)")
            self._queue.append(req)
            self._wake.notify()
        return req.future

    def pause(self) -> None:
        """Freeze the worker between batches (deterministic tests)."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._wake.notify()

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._closed = True
            self._paused = False
            self._wake.notify()
        self._worker.join(timeout=timeout)
        # the worker drains the queue on close (the take condition
        # includes _closed), so leftovers only exist when the join
        # timed out — a wedged device dispatch. Resolve them with
        # BatcherClosed so upstream can re-route each request through
        # the host fallback instead of hanging or dropping its caller.
        with self._lock:
            leftovers, self._queue = self._queue, []
        for req in leftovers:
            if not req.future.done():
                req.future.set_exception(BatcherClosed(
                    f"batcher '{self.name}' closed before dispatching "
                    f"this request"))

    # ------------------------------------------------------------------
    def _take_batch(self) -> Optional[List[_Request]]:
        """Block until a coalescible batch is ready (or closed)."""
        with self._lock:
            while True:
                if self._closed and not self._queue:
                    return None
                if self._queue and not self._paused:
                    oldest = self._queue[0].t_enqueue
                    rows = 0
                    take = 0
                    for req in self._queue:
                        if take and rows + len(req.bins) > \
                                self.max_batch_size:
                            break
                        rows += len(req.bins)
                        take += 1
                        if rows >= self.max_batch_size:
                            break
                    waited_ms = (time.monotonic() - oldest) * 1e3
                    if (rows >= self.max_batch_size or self._closed or
                            waited_ms >= self.max_wait_ms):
                        batch = self._queue[:take]
                        del self._queue[:take]
                        return batch
                    # more coalescing headroom: sleep out the window
                    self._wake.wait(
                        timeout=(self.max_wait_ms - waited_ms) / 1e3)
                    continue
                self._wake.wait(timeout=0.1)

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            self.batch_count += 1
            self.coalesced_requests += len(batch)
            try:
                bins = batch[0].bins if len(batch) == 1 else \
                    np.concatenate([r.bins for r in batch], axis=0)
                raw = self._run_batch(bins)
                lo = 0
                for req in batch:
                    hi = lo + len(req.bins)
                    req.future.set_result(raw[lo:hi])
                    lo = hi
            except Exception as exc:  # surface to callers, keep serving
                Log.warning(f"serving batch for '{self.name}' failed: "
                            f"{exc}")
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(exc)
