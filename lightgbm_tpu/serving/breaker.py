"""Self-healing circuit breaker for the serving device path.

The PR-1 failure handling was a sticky ``degraded`` flag on the model
entry: one device failure parked the model on the host path until a
manual ``refresh_model``. That is the wrong shape for transient device
trouble (a preempted slice, a wedged runtime that drains) — the flag
never heals, so a single hiccup permanently forfeits the device
throughput the serving engine exists for.

This breaker replaces it with the classic three-state machine, one
instance per (model, replica):

    closed ──(threshold consecutive failures)──▶ open
    open ──(cooldown elapsed, one probe granted)──▶ half_open
    half_open ──probe succeeds──▶ closed        (self-heals)
    half_open ──probe fails────▶ open           (cooldown restarts)

``try_acquire()`` is the routing gate: closed grants every dispatch;
open grants nothing until ``cooldown_s`` has elapsed, then transitions
to half_open and grants exactly ONE probe dispatch (concurrent callers
are refused while the probe is in flight); the probe's
``record_success``/``record_failure`` closes or re-opens. Success in
the closed state resets the consecutive-failure count, so only an
unbroken run of failures opens the breaker — the property injected
faults drive in tests (`faults.injected("serving_replica_predict",
fail=threshold)` opens it, the next cooldown-elapsed dispatch probes,
and a clean device closes it again).

The clock is injectable so tests step through cooldowns without
sleeping. All transitions are visible in ``snapshot()`` (state string,
open/close/probe counters) — the chaos harness asserts the full
open → half_open → closed cycle from metrics alone.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict

__all__ = ["CircuitBreaker", "BREAKER_STATES", "breaker_state_code"]

#: state -> numeric code for the Prometheus gauge (closed sorts lowest
#: so dashboards can alert on max() per model)
BREAKER_STATES = ("closed", "half_open", "open")


def breaker_state_code(state: str) -> int:
    """closed=0, half_open=1, open=2 (the `breaker_state` gauge)."""
    return BREAKER_STATES.index(state)


class CircuitBreaker:
    """Per-replica three-state breaker; thread-safe, injectable clock."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self._lock = threading.Lock()
        self.threshold = int(threshold)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self._clock = clock
        self._state = "closed"
        self._failures = 0          # consecutive, reset by any success
        self._opened_at = 0.0
        self._probe_inflight = False
        self.opens = 0
        self.closes = 0             # heal transitions (half_open->closed)
        self.probes = 0

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def available(self) -> bool:
        """Non-consuming routing check: could a dispatch be granted now?
        (closed, or open with the cooldown elapsed, or half_open with a
        free probe slot.) Never transitions state or reserves the probe
        — use `try_acquire` for that."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                return self._clock() - self._opened_at >= self.cooldown_s
            return not self._probe_inflight

    def try_acquire(self) -> bool:
        """Routing gate for one dispatch. Closed always grants; open
        grants nothing until the cooldown elapses, then moves to
        half_open and grants the single probe; half_open refuses while
        the probe is in flight. A granted half_open acquire MUST be
        paired with record_success/record_failure."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                self._state = "half_open"
                self._probe_inflight = True
                self.probes += 1
                return True
            # half_open: only the single probe flies
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            self.probes += 1
            return True

    # ------------------------------------------------------------------
    def record_success(self) -> None:
        with self._lock:
            if self._state == "half_open":
                self._state = "closed"
                self.closes += 1
            # success while open is a stale in-flight result: the
            # breaker opened on newer evidence, keep it open
            if self._state == "closed":
                self._failures = 0
            self._probe_inflight = False

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == "half_open":
                self._state = "open"
                self._opened_at = self._clock()
                self.opens += 1
                return
            if self._state == "open":
                return              # already open; cooldown keeps running
            self._failures += 1
            if self._failures >= self.threshold:
                self._state = "open"
                self._opened_at = self._clock()
                self.opens += 1

    def force_open(self) -> None:
        """Ops/chaos hook: trip the breaker now (cooldown starts)."""
        with self._lock:
            if self._state != "open":
                self.opens += 1
            self._state = "open"
            self._opened_at = self._clock()
            self._probe_inflight = False

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "state": self._state,
                "state_code": breaker_state_code(self._state),
                "consecutive_failures": self._failures,
                "opens": self.opens,
                "closes": self.closes,
                "probes": self.probes,
            }
