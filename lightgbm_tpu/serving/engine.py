"""Shape-bucketed device predictor: bounded XLA compilations.

`predict_binned_forest` is jit-compiled per (batch-shape, forest-shape)
pair, so an unconstrained request stream — batch sizes 1, 2, 3, ... —
would recompile on every new size and the compile queue, not the MXU,
would set the latency floor (the launch/compile overhead both GPU
tree-inference papers in PAPERS.md identify as the real bottleneck).

The engine therefore pads every batch up to a power-of-two row bucket
in [min_bucket, max_bucket]: after warmup a model can be hit by at most
``ceil(log2(max_bucket)) + 1`` distinct shapes, whatever the traffic
looks like. Batches larger than max_bucket are chunked, so the biggest
compiled program is also bounded. Pad rows are zero-binned and masked
inert by `row_valid` (learner/predict.py), so bucket padding is
invisible in the scores — bit-identical to the unpadded call.

The bucket cache is also the compile COUNTER: a (model, bucket) miss is
exactly an XLA compilation of the serving predictor for that model, a
hit is a cached dispatch. Both counts surface in the metrics snapshot.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Tuple

import numpy as np

from ..observability import registry as _obs
from ..utils.timer import global_timer
from .forest import DeviceForest

__all__ = ["BucketedPredictor", "next_bucket", "max_compilations"]


def next_bucket(n: int, min_bucket: int, max_bucket: int) -> int:
    """Smallest power-of-two bucket >= n, clamped to [min_bucket,
    max_bucket]."""
    b = max(min_bucket, 1)
    while b < n and b < max_bucket:
        b <<= 1
    return min(b, max_bucket)


def max_compilations(max_bucket: int) -> int:
    """Upper bound on predictor compilations per model after warmup."""
    return int(np.ceil(np.log2(max(max_bucket, 2)))) + 1


class BucketedPredictor:
    """Device dispatch through the bucket cache. Thread-safe."""

    def __init__(self, min_bucket: int = 16, max_bucket: int = 1024):
        if min_bucket < 1 or max_bucket < min_bucket:
            raise ValueError("need 1 <= min_bucket <= max_bucket")
        self.min_bucket = int(min_bucket)
        self.max_bucket = int(max_bucket)
        self._seen: Dict[Tuple[int, int], int] = {}   # (forest id, bucket)
        self._lock = threading.Lock()
        self.compile_count = 0
        self.hit_count = 0
        self.device_batches = 0

    # ------------------------------------------------------------------
    def counters_for(self, forest: DeviceForest) -> Dict[str, int]:
        with self._lock:
            buckets = [b for (fid, b) in self._seen if fid == id(forest)]
        return {"buckets_compiled": len(buckets),
                "max_compilations": max_compilations(self.max_bucket)}

    def _record(self, forest: DeviceForest, bucket: int) -> bool:
        """Count the dispatch; True when the bucket was already warm."""
        with self._lock:
            key = (id(forest), bucket)
            hit = key in self._seen
            if hit:
                self._seen[key] += 1
                self.hit_count += 1
            else:
                self._seen[key] = 1
                self.compile_count += 1
            self.device_batches += 1
            return hit

    # ------------------------------------------------------------------
    def predict_raw(self, forest: DeviceForest, bins: np.ndarray,
                    metrics=None) -> np.ndarray:
        """[N, F] serving bins -> [N, num_outputs] raw f32 scores.

        `metrics` (serving.metrics.ModelMetrics, optional) receives a
        record_batch per device dispatch: hit = bucket already warm,
        compiled = first sighting of (model, bucket)."""
        import jax.numpy as jnp
        from ..learner.predict import predict_binned_forest
        from ..reliability import faults

        # registered fault site: the serving device-dispatch boundary
        # (retry + host-fallback handling live in serving/server.py)
        faults.inject("serving_device_predict")

        n = bins.shape[0]
        if n == 0:
            return np.zeros((0, forest.num_outputs), np.float32)
        outs = []
        lo = 0
        while lo < n:
            hi = min(lo + self.max_bucket, n)
            chunk = bins[lo:hi]
            rows = hi - lo
            bucket = next_bucket(rows, self.min_bucket, self.max_bucket)
            if rows < bucket:
                chunk = np.concatenate(
                    [chunk, np.zeros((bucket - rows, chunk.shape[1]),
                                     chunk.dtype)])
            valid = jnp.asarray(np.arange(bucket) < rows)
            hit = self._record(forest, bucket)
            if metrics is not None:
                metrics.record_batch(bucket_hit=hit, compiled=not hit)
            _t0 = time.perf_counter()
            with global_timer.timeit("serve_device_predict"):
                raw = predict_binned_forest(
                    forest.stacked, forest.tree_class, jnp.asarray(chunk),
                    forest.num_bins, forest.missing_is_nan,
                    num_outputs=forest.num_outputs, row_valid=valid)
                raw = np.asarray(raw)    # device -> host sync
            if _obs.enabled:
                # a bucket-cache miss IS a compilation of the serving
                # predictor for this shape (module docstring); fold it
                # into the unified compile accounting + span trace
                _dt = time.perf_counter() - _t0
                _obs.compiles.record(f"serving_predict_b{bucket}", _dt,
                                     compiled=not hit)
                _obs.trace.add("serve_device_predict", _t0, _dt,
                               bucket=bucket, rows=rows)
            outs.append(raw[:rows])
            lo = hi
        return np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]
