"""Device-resident serving forest: one-time Booster -> device-array load.

The training-side device predictor (learner/predict.py) works in BIN
space: rows are quantized with the training BinMappers and every node
decision is an exact integer compare. At serving time the training
mappers may be gone (model loaded from a text file), so the forest is
rebuilt from the model itself: the only feature values a tree ever
compares against are its own split thresholds, so binning new rows
against the sorted set of per-feature thresholds reproduces every
`value <= threshold` decision exactly (the same trick the reference's
CUDA predictor uses to avoid re-binning, and what makes the serving
path self-contained — no Dataset required).

Per-feature missing handling is folded into the reconstruction:

- missing_type NAN  -> a trailing NaN bin routed by each node's
  default_left (the `missing_is_nan` mechanism of `_traverse`).
- missing_type ZERO -> NaN maps to 0.0 first, then |v| <= kZeroThreshold
  maps to the trailing default-routed bin — exactly the reference's
  NumericalDecision ZERO branch (tree.h:335-412) expressed in bin space.
- missing_type NONE -> NaN maps to 0.0 and bins normally.

Categorical features bin raw category values through a rank LUT; bin 0
is the unseen/NaN dummy whose bit is never set in any node bitset, so
unseen categories fall right — matching HostTree.predict_rows.

A model whose numeric nodes disagree on missing_type within one feature
(impossible for models trained here, possible for foreign hand-edited
files) or that uses linear leaves is marked unsupported; the serving
engine then degrades to the host predict path instead of guessing.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..tree import HostModel, HostTree

__all__ = ["DeviceForest", "FeatureBinner", "build_device_forest"]

_CAT_BIT = 1
_DEFAULT_LEFT_BIT = 2
_MISSING_SHIFT = 2
_ZERO_THRESHOLD = 1e-35


@dataclasses.dataclass
class FeatureBinner:
    """Host-side quantizer for one original feature, rebuilt from the
    model's own split thresholds (numeric) or category bitsets (cat)."""
    is_categorical: bool = False
    # numeric: sorted unique split thresholds; v <= edges[k] <-> bin <= k
    edges: Optional[np.ndarray] = None
    missing_type: int = 0          # 0 None, 1 Zero, 2 NaN (tree.h masks)
    # categorical: raw category value -> bin (0 = unseen/NaN dummy)
    cat_to_bin: Optional[Dict[int, int]] = None
    num_bin: int = 1

    @property
    def has_default_bin(self) -> bool:
        """Trailing bin routed by default_left (NaN bin / zero bin)."""
        return not self.is_categorical and self.missing_type in (1, 2)

    def bin_values(self, col: np.ndarray) -> np.ndarray:
        """[N] raw float column -> [N] int32 serving bins."""
        if self.is_categorical:
            out = np.zeros(len(col), np.int32)
            ok = np.isfinite(col) & (col >= 0) & (col < 2147483647.0)
            lut = self.cat_to_bin or {}
            ints = col[ok].astype(np.int64)
            out[ok] = np.array([lut.get(int(v), 0) for v in ints],
                               np.int32) if len(ints) else 0
            return out
        if self.edges is None or len(self.edges) == 0:
            return np.zeros(len(col), np.int32)
        isnan = np.isnan(col)
        vals = np.where(isnan, 0.0, col)  # NONE/ZERO: NaN behaves as 0
        out = np.searchsorted(self.edges, vals, side="left").astype(np.int32)
        if self.missing_type == 2:          # NAN: dedicated trailing bin
            out = np.where(isnan, self.num_bin - 1, out)
        elif self.missing_type == 1:        # ZERO: |v|<=eps default-routed
            out = np.where(np.abs(vals) <= _ZERO_THRESHOLD,
                           self.num_bin - 1, out)
        return out.astype(np.int32)


class _StackedArrays:
    """Forest-shaped numpy staging buffers before the device push."""

    def __init__(self, t: int, m1: int, w: int):
        self.split_feature = np.full((t, m1), -1, np.int32)
        self.threshold_bin = np.zeros((t, m1), np.int32)
        self.default_left = np.zeros((t, m1), bool)
        self.is_cat = np.zeros((t, m1), bool)
        self.cat_bitset = np.zeros((t, m1, w), np.uint32)
        self.left = np.full((t, m1), -1, np.int32)
        self.right = np.full((t, m1), -1, np.int32)
        self.parent = np.full((t, m1), -1, np.int32)
        self.leaf_value = np.zeros((t, m1), np.float32)
        self.num_nodes = np.zeros(t, np.int32)
        self.num_leaves = np.zeros(t, np.int32)


@dataclasses.dataclass
class DeviceForest:
    """Stacked device arrays + host binners for one loaded model.

    Built once per model load (see `build_device_forest` /
    `Booster.device_forest`), then shared by every request: the serving
    hot path only bins rows and calls the jitted
    `predict_binned_forest` on the resident arrays.
    """
    stacked: object                 # TreeArrays with leading [T] axis
    tree_class: object              # jnp [T] i32
    num_bins: object                # jnp [F] i32
    missing_is_nan: object          # jnp [F] bool
    binners: List[FeatureBinner]
    num_outputs: int
    num_features: int
    num_trees: int
    objective: str
    average_output: bool
    num_iterations: int
    supported: bool = True
    unsupported_reason: str = ""
    _model: Optional[HostModel] = None

    def bin_rows(self, X: np.ndarray) -> np.ndarray:
        """[N, >=F] raw features -> [N, F] int32 serving bins."""
        X = np.asarray(X, np.float64)
        n = X.shape[0]
        out = np.zeros((n, self.num_features), np.int32)
        for f, binner in enumerate(self.binners):
            if f >= X.shape[1]:
                break
            out[:, f] = binner.bin_values(X[:, f])
        return out

    def convert_raw(self, raw: np.ndarray,
                    raw_score: bool = False) -> np.ndarray:
        """Raw device scores -> HostModel.predict output: averaged for
        RF models, objective-converted unless raw_score, [N] when the
        model has a single output column."""
        raw = np.asarray(raw, np.float64)
        if self.average_output:
            raw = raw / max(self.num_iterations, 1)
        if not raw_score and self._model is not None:
            raw = self._model._convert_output(raw)
        return raw[:, 0] if self.num_outputs == 1 else raw

    def place_on(self, device) -> "DeviceForest":
        """The same logical forest with its device arrays pinned to
        `device`; host-side binners and the fallback model are shared
        (arrays are immutable, so replicas share nothing mutable).
        `ForestPack` implements the same method — replica placement is
        polymorphic over single models and packs."""
        import jax
        return dataclasses.replace(
            self,
            stacked=jax.device_put(self.stacked, device),
            tree_class=jax.device_put(self.tree_class, device),
            num_bins=jax.device_put(self.num_bins, device),
            missing_is_nan=jax.device_put(self.missing_is_nan, device))

    def nbytes_device(self) -> int:
        import jax
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a in jax.tree_util.tree_leaves(self.stacked))


def _node_missing_type(dt: int) -> int:
    return (dt >> _MISSING_SHIFT) & 3


def _collect_binners(model: HostModel) -> (List[FeatureBinner], str):
    """Rebuild per-feature quantizers from the forest's own decisions."""
    nf = model.max_feature_idx + 1
    thresholds: List[set] = [set() for _ in range(nf)]
    cat_vals: List[set] = [set() for _ in range(nf)]
    mtypes: List[set] = [set() for _ in range(nf)]
    is_cat_f = np.zeros(nf, bool)
    for t in model.trees:
        for i in range(t.num_leaves - 1):
            f = int(t.split_feature[i])
            dt = int(t.decision_type[i])
            if dt & _CAT_BIT:
                is_cat_f[f] = True
                ci = int(t.threshold[i])
                lo = int(t.cat_boundaries[ci])
                hi = int(t.cat_boundaries[ci + 1])
                for w in range(lo, hi):
                    word = int(t.cat_threshold[w])
                    base = (w - lo) * 32
                    while word:
                        b = (word & -word).bit_length() - 1
                        cat_vals[f].add(base + b)
                        word &= word - 1
            else:
                thresholds[f].add(float(t.threshold[i]))
                mtypes[f].add(_node_missing_type(dt))
    binners: List[FeatureBinner] = []
    for f in range(nf):
        if is_cat_f[f] and thresholds[f]:
            return [], (f"feature {f} mixes categorical and numerical "
                        "splits")
        if len(mtypes[f]) > 1:
            return [], (f"feature {f} mixes missing_type values "
                        f"{sorted(mtypes[f])} across nodes")
        if is_cat_f[f]:
            cats = sorted(cat_vals[f])
            binners.append(FeatureBinner(
                is_categorical=True,
                cat_to_bin={c: i + 1 for i, c in enumerate(cats)},
                num_bin=len(cats) + 1))
        else:
            edges = np.asarray(sorted(thresholds[f]), np.float64)
            mt = next(iter(mtypes[f])) if mtypes[f] else 0
            # bins: len(edges)+1 value ranges, +1 default-routed bin for
            # NAN/ZERO missing handling
            nb = len(edges) + 1 + (1 if mt in (1, 2) else 0)
            binners.append(FeatureBinner(edges=edges, missing_type=mt,
                                         num_bin=nb))
    return binners, ""


def _fill_tree(buf: _StackedArrays, ti: int, t: HostTree,
               binners: List[FeatureBinner]) -> None:
    """One HostTree (reference numbering: internal 0..ni-1, leaf ~li)
    into node-id space (internal i -> i, leaf li -> ni + li)."""
    ni = max(t.num_leaves - 1, 0)
    nl = t.num_leaves

    def node_id(c: int) -> int:
        return c if c >= 0 else ni + (~c)

    for i in range(ni):
        f = int(t.split_feature[i])
        dt = int(t.decision_type[i])
        binner = binners[f]
        buf.split_feature[ti, i] = f
        buf.left[ti, i] = node_id(int(t.left_child[i]))
        buf.right[ti, i] = node_id(int(t.right_child[i]))
        if dt & _CAT_BIT:
            buf.is_cat[ti, i] = True
            ci = int(t.threshold[i])
            lo = int(t.cat_boundaries[ci])
            hi = int(t.cat_boundaries[ci + 1])
            lut = binner.cat_to_bin or {}
            for w in range(lo, hi):
                word = int(t.cat_threshold[w])
                base = (w - lo) * 32
                while word:
                    b = (word & -word).bit_length() - 1
                    sb = lut.get(base + b, 0)
                    if sb > 0:
                        buf.cat_bitset[ti, i, sb // 32] |= np.uint32(
                            1 << (sb % 32))
                    word &= word - 1
        else:
            thr = float(t.threshold[i])
            # exact: thr is a member of the edge set by construction
            buf.threshold_bin[ti, i] = int(
                np.searchsorted(binner.edges, thr, side="left"))
            mt = _node_missing_type(dt)
            if mt in (1, 2):
                buf.default_left[ti, i] = bool(dt & _DEFAULT_LEFT_BIT)
        children = (int(t.left_child[i]), int(t.right_child[i]))
        for c in children:
            buf.parent[ti, node_id(c)] = i
    for li in range(nl):
        buf.leaf_value[ti, ni + li] = np.float32(t.leaf_value[li])
    buf.num_nodes[ti] = ni + nl
    buf.num_leaves[ti] = nl


def build_device_forest(model: HostModel) -> DeviceForest:
    """Flatten + stack a HostModel into resident device arrays.

    Returns an unsupported (host-fallback) DeviceForest instead of
    raising when the model cannot be served from device exactly.
    """
    import jax.numpy as jnp
    from ..learner.grower import TreeArrays

    k = max(model.num_tree_per_iteration, 1)
    nf = model.max_feature_idx + 1

    def unsupported(reason: str) -> DeviceForest:
        return DeviceForest(
            stacked=None, tree_class=None, num_bins=None,
            missing_is_nan=None, binners=[], num_outputs=k,
            num_features=nf, num_trees=len(model.trees),
            objective=model.objective,
            average_output=model.average_output,
            num_iterations=model.num_iterations,
            supported=False, unsupported_reason=reason, _model=model)

    if not model.trees:
        return unsupported("model has no trees")
    if any(t.is_linear for t in model.trees):
        return unsupported("linear-leaf models need raw feature values; "
                           "served via the host predict path")
    binners, why = _collect_binners(model)
    if why:
        return unsupported(why)

    m1 = max(max(t.num_leaves - 1, 0) + t.num_leaves
             for t in model.trees) + 1          # + scratch row
    max_cat_bin = max((b.num_bin for b in binners if b.is_categorical),
                      default=1)
    w = max((max_cat_bin - 1) // 32 + 1, 1)
    buf = _StackedArrays(len(model.trees), m1, w)
    for ti, t in enumerate(model.trees):
        _fill_tree(buf, ti, t, binners)

    stacked = TreeArrays(
        split_feature=jnp.asarray(buf.split_feature),
        threshold_bin=jnp.asarray(buf.threshold_bin),
        default_left=jnp.asarray(buf.default_left),
        is_cat=jnp.asarray(buf.is_cat),
        cat_bitset=jnp.asarray(buf.cat_bitset),
        left=jnp.asarray(buf.left),
        right=jnp.asarray(buf.right),
        parent=jnp.asarray(buf.parent),
        leaf_value=jnp.asarray(buf.leaf_value),
        sum_grad=jnp.zeros((len(model.trees), m1), jnp.float32),
        sum_hess=jnp.zeros((len(model.trees), m1), jnp.float32),
        count=jnp.zeros((len(model.trees), m1), jnp.float32),
        gain=jnp.zeros((len(model.trees), m1), jnp.float32),
        depth=jnp.zeros((len(model.trees), m1), jnp.int32),
        is_leaf=jnp.asarray(buf.split_feature < 0),
        num_nodes=jnp.asarray(buf.num_nodes),
        num_leaves=jnp.asarray(buf.num_leaves))
    tree_class = jnp.asarray(
        [model.tree_class[i] if i < len(model.tree_class) else i % k
         for i in range(len(model.trees))], jnp.int32)
    num_bins = jnp.asarray([b.num_bin for b in binners], jnp.int32)
    # the trailing default-routed bin (NaN bin or ZERO bin) rides the
    # traversal's missing_is_nan mechanism either way
    missing = jnp.asarray([b.has_default_bin for b in binners])
    return DeviceForest(
        stacked=stacked, tree_class=tree_class, num_bins=num_bins,
        missing_is_nan=missing, binners=binners, num_outputs=k,
        num_features=nf, num_trees=len(model.trees),
        objective=model.objective, average_output=model.average_output,
        num_iterations=model.num_iterations, _model=model)
