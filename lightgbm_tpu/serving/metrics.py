"""Per-model serving metrics: QPS, latency percentiles, cache, sheds.

A lock-guarded ring buffer of request latencies plus monotonic
counters; `snapshot()` renders a JSON-able dict (the schema documented
in docs/Serving.md). Device/binning phase totals ride the process-wide
`utils.timer.global_timer` under ``serve_*`` keys, so `python -c`
profiling and the training phases share one report.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from ..utils.timer import global_timer

__all__ = ["ModelMetrics", "PackMetrics"]

_PERCENTILES = (50.0, 95.0, 99.0)


class ModelMetrics:
    """Counters + bounded latency reservoir for one registered model."""

    def __init__(self, window: int = 2048):
        self._lock = threading.Lock()
        self._window = max(int(window), 16)
        self._lat_ms = np.zeros(self._window, np.float64)
        self._lat_n = 0          # total recorded (ring writes)
        self.requests = 0
        self.rows = 0
        self.batches = 0         # coalesced device batches
        self.bucket_hits = 0
        self.compile_count = 0
        self.shed_count = 0
        self.fallback_count = 0  # requests served by the host path
        self.errors = 0
        self.device_retries = 0  # device dispatches that needed a retry
        self.guard_trips = 0     # non-finite device outputs caught
        self.deadline_misses = 0  # SLO budget sheds + queue expiries
        self.failovers = 0       # batches re-routed to another replica
        self.swap_drains = 0     # requests host-drained by a hot-swap
        self._started = time.monotonic()
        self._first_request: Optional[float] = None
        self._last_request: Optional[float] = None

    # ------------------------------------------------------------------
    def record_request(self, rows: int, latency_s: float,
                       fallback: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            self.requests += 1
            self.rows += int(rows)
            if fallback:
                self.fallback_count += 1
            self._lat_ms[self._lat_n % self._window] = latency_s * 1e3
            self._lat_n += 1
            if self._first_request is None:
                self._first_request = now
            self._last_request = now

    def record_batch(self, bucket_hit: bool, compiled: bool) -> None:
        with self._lock:
            self.batches += 1
            if bucket_hit:
                self.bucket_hits += 1
            if compiled:
                self.compile_count += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed_count += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_retry(self) -> None:
        with self._lock:
            self.device_retries += 1

    def record_guard_trip(self) -> None:
        with self._lock:
            self.guard_trips += 1

    def record_deadline_miss(self) -> None:
        with self._lock:
            self.deadline_misses += 1

    def record_failover(self) -> None:
        with self._lock:
            self.failovers += 1

    def record_swap_drain(self, n: int = 1) -> None:
        with self._lock:
            self.swap_drains += int(n)

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            n = min(self._lat_n, self._window)
            lats = np.sort(self._lat_ms[:n]) if n else np.zeros(0)
            span = None
            if self._first_request is not None and self.requests > 1:
                span = max(self._last_request - self._first_request, 1e-9)
            qps = (self.requests / span) if span else float(self.requests)
            rows_per_s = (self.rows / span) if span else float(self.rows)
            out = {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "qps": round(qps, 3),
                "rows_per_sec": round(rows_per_s, 3),
                "bucket_cache_hits": self.bucket_hits,
                "compile_count": self.compile_count,
                "shed_count": self.shed_count,
                "fallback_count": self.fallback_count,
                # degradation visibility (docs/Reliability.md):
                # "fallbacks" mirrors fallback_count under the unified
                # reliability-counter name
                "fallbacks": self.fallback_count,
                "device_retries": self.device_retries,
                "guard_trips": self.guard_trips,
                "deadline_misses": self.deadline_misses,
                "failovers": self.failovers,
                "swap_drains": self.swap_drains,
                "errors": self.errors,
                "uptime_sec": round(time.monotonic() - self._started, 3),
            }
            for p in _PERCENTILES:
                key = f"p{int(p)}_ms"
                out[key] = round(float(np.percentile(lats, p)), 3) \
                    if n else None
        return out


class PackMetrics:
    """Counters for one ForestPack's fused dispatch path (the
    ``lightgbm_tpu_multimodel`` Prometheus family, docs/
    Observability.md). Occupancy is packed rows over slot-grouped
    capacity — low occupancy means the resident members rarely have
    concurrent traffic and the pack is mostly padding."""

    def __init__(self):
        self._lock = threading.Lock()
        self.fused_dispatches = 0     # kernel launches (rounds)
        self.packed_rows = 0          # real rows scored through the pack
        self.capacity_rows = 0        # slots * row_block summed
        self.slots_active_total = 0   # slots with rows, summed per round
        self.compile_count = 0        # pack bucket-cache misses
        self.rebuilds = 0             # pack republished (evict/hot-swap)
        self.rebuild_drains = 0       # futures host-drained by a rebuild
        self.device_retries = 0
        self.guard_trips = 0
        self.failovers = 0

    def record_dispatch(self, rows: int, capacity: int, slots: int,
                        compiled: bool) -> None:
        with self._lock:
            self.fused_dispatches += 1
            self.packed_rows += int(rows)
            self.capacity_rows += int(capacity)
            self.slots_active_total += int(slots)
            if compiled:
                self.compile_count += 1

    def record_rebuild(self, drained: int = 0) -> None:
        with self._lock:
            self.rebuilds += 1
            self.rebuild_drains += int(drained)

    # the replica fleet's retry/failover bookkeeping (replicas.dispatch)
    # records against the pack when the whole pack fails over
    def record_retry(self) -> None:
        with self._lock:
            self.device_retries += 1

    def record_guard_trip(self) -> None:
        with self._lock:
            self.guard_trips += 1

    def record_failover(self) -> None:
        with self._lock:
            self.failovers += 1

    def snapshot(self) -> Dict:
        with self._lock:
            occupancy = (self.packed_rows / self.capacity_rows) \
                if self.capacity_rows else 0.0
            avg_slots = (self.slots_active_total / self.fused_dispatches) \
                if self.fused_dispatches else 0.0
            return {
                "fused_dispatches": self.fused_dispatches,
                "packed_rows": self.packed_rows,
                "capacity_rows": self.capacity_rows,
                "occupancy": round(occupancy, 4),
                "avg_slots_active": round(avg_slots, 3),
                "compile_count": self.compile_count,
                "rebuilds": self.rebuilds,
                "rebuild_drains": self.rebuild_drains,
                "device_retries": self.device_retries,
                "guard_trips": self.guard_trips,
                "failovers": self.failovers,
            }


def timer_totals() -> Dict[str, float]:
    """serve_* phase totals from the process-global timer."""
    return {k: round(v, 6) for k, v in global_timer.totals().items()
            if k.startswith("serve_")}
