"""Multi-model forest packing: many models, one fused device dispatch.

A registry full of per-tenant models serializes on the predict path
when every `DeviceForest` dispatches alone — N small models cost N
kernel launches per coalescing window even though each launch moves a
few thousand rows. The Booster accelerator (arXiv:2011.02022) shows
forest traversal is throughput-bound on node-fetch parallelism, and
the GPU tree-boosting line (arXiv:1706.08359) takes its inference wins
from batching many trees into one dense kernel; `ForestPack` applies
both on TPU by padding heterogeneous member forests into ONE
slot-grouped device layout and answering a mixed batch of
(model, rows) pairs in one `predict_packed_forest` launch.

Layout (the PR-6 one-slot-per-block idiom, rotated to serving):

- every member's tree arrays are padded to common pow-2 node/bitset/
  feature extents and concatenated on the tree axis, member trees
  CONTIGUOUS in slot order — so the f32 accumulation order per member
  is identical to its solo `predict_binned_forest` fori-loop, which is
  what makes the packed path bit-identical to the per-model device
  path (and, through the dyadic-booster trick, to host predict);
- `tree_model[t]` maps each packed tree to its member slot; each slot
  owns one `row_block`-row block of the batch at offset
  ``slot * row_block``, so per-row traversal cost is independent of
  how many members are resident;
- slots, trees, nodes and features are padded to powers of two and the
  member count rides a pow-2 slot axis, so a pack REBUILD (member
  evicted / hot-swapped) usually reuses the exact compiled program —
  and the per-dispatch `row_block` goes through the engine's pow-2
  bucket ladder, keeping compiles bounded at
  ``ceil(log2(max_bucket)) + 1`` per *pack*, not per model.

Pad trees are skipped with `lax.cond` (no add at all, not an add of
+0.0) so tree-axis padding cannot perturb signed zeros; pad rows are
masked inert by `row_valid` exactly as in the single-model engine.

`dispatch_pack` is the fused dispatch boundary: a registered fault
site (``serving_pack_predict``) inside the replica retry bracket, so
the chaos harness can kill the fused path and watch the breaker /
failover / host-fallback ladder hold for every member at once.
`PackBatcher` extends the continuous-batching `MicroBatcher` with a
slot-grouped dispatch so one queue (one SLO admission model, one
scheduler) serves the whole pack.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.log import LightGBMError
from ..utils.timer import global_timer
from .batcher import MicroBatcher, _Request
from .engine import next_bucket
from .forest import DeviceForest

__all__ = ["ForestPack", "PackEntry", "PackBatcher", "build_forest_pack",
           "predict_packed_forest", "dispatch_pack"]


def _pow2(n: int) -> int:
    """Smallest power of two >= max(n, 1)."""
    p = 1
    while p < n:
        p <<= 1
    return p


@dataclasses.dataclass
class ForestPack:
    """Several DeviceForests padded into one slot-grouped device layout.

    Presents the same surface the replica fleet needs from a
    DeviceForest (`supported`, `place_on`, `nbytes_device`), so
    `ReplicaSet.build` replicates a pack exactly like a single model.
    """
    name: str
    stacked: object                # TreeArrays, fields [Tp, m1p, ...]
    tree_model: object             # jnp [Tp] i32: packed tree -> slot
    tree_class: object             # jnp [Tp] i32: output column
    num_bins: object               # jnp [Mp, Fp] i32, per-slot tables
    missing_is_nan: object         # jnp [Mp, Fp] bool
    member_names: Tuple[str, ...]  # slot order
    forests: Dict[str, DeviceForest]
    num_slots: int                 # Mp (pow-2 padded member count)
    num_outputs: int               # Kp (pow-2 padded max member outputs)
    num_features: int              # Fp (pow-2 padded max member features)
    num_trees: int                 # real (unpadded) packed tree count

    #: packs only ever contain device-servable members (build_forest_pack
    #: rejects unsupported forests), so the fleet always places them
    supported: bool = True

    def slot_of(self, name: str) -> int:
        return self.member_names.index(name)

    def place_on(self, device) -> "ForestPack":
        """The same logical pack with its device arrays pinned to
        `device` (replica placement; arrays are immutable so replicas
        share nothing mutable)."""
        import jax
        return dataclasses.replace(
            self,
            stacked=jax.device_put(self.stacked, device),
            tree_model=jax.device_put(self.tree_model, device),
            tree_class=jax.device_put(self.tree_class, device),
            num_bins=jax.device_put(self.num_bins, device),
            missing_is_nan=jax.device_put(self.missing_is_nan, device))

    def nbytes_device(self) -> int:
        import jax
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a in jax.tree_util.tree_leaves(self.stacked))


def build_forest_pack(members: Sequence[Tuple[str, DeviceForest]],
                      name: str = "pack") -> ForestPack:
    """Pad + concatenate member forests into one ForestPack.

    Tree order inside the pack is member-major in slot order, each
    member's own tree order preserved — the accumulation-order
    invariant behind the bit-identity contract. Raises on empty or
    host-fallback (unsupported) members: those keep serving solo.
    """
    import jax.numpy as jnp
    from ..learner.grower import TreeArrays

    if not members:
        raise LightGBMError("build_forest_pack needs at least one member")
    names = [nm for nm, _ in members]
    if len(set(names)) != len(names):
        raise LightGBMError(f"pack '{name}' has duplicate member names")
    for nm, forest in members:
        if not forest.supported:
            raise LightGBMError(
                f"pack member '{nm}' is not device-servable "
                f"({forest.unsupported_reason}); load it unpacked")

    m = len(members)
    mp = _pow2(m)
    hosts = []            # per member: dict of host numpy tree fields
    for nm, forest in members:
        hosts.append({f: np.asarray(getattr(forest.stacked, f))
                      for f in TreeArrays._fields})
    t_real = sum(h["leaf_value"].shape[0] for h in hosts)
    tp = _pow2(t_real)
    m1p = _pow2(max(h["leaf_value"].shape[1] for h in hosts))
    wp = _pow2(max(h["cat_bitset"].shape[2] for h in hosts))
    fp = _pow2(max(forest.num_features for _, forest in members))
    kp = _pow2(max(forest.num_outputs for _, forest in members))

    def field(fname: str, fill, dtype) -> np.ndarray:
        sample = hosts[0][fname]
        shape = (tp, m1p, wp) if sample.ndim == 3 else \
            ((tp, m1p) if sample.ndim == 2 else (tp,))
        out = np.full(shape, fill, dtype)
        t0 = 0
        for h in hosts:
            a = h[fname]
            t1 = t0 + a.shape[0]
            if a.ndim == 3:
                out[t0:t1, :a.shape[1], :a.shape[2]] = a
            elif a.ndim == 2:
                out[t0:t1, :a.shape[1]] = a
            else:
                out[t0:t1] = a
            t0 = t1
        return out

    # pad trees are single-leaf (split_feature -1 everywhere) AND
    # cond-skipped in the kernel; pad nodes of real trees are
    # unreachable (no child edge points at them)
    stacked = TreeArrays(
        split_feature=field("split_feature", -1, np.int32),
        threshold_bin=field("threshold_bin", 0, np.int32),
        default_left=field("default_left", False, bool),
        is_cat=field("is_cat", False, bool),
        cat_bitset=field("cat_bitset", 0, np.uint32),
        left=field("left", -1, np.int32),
        right=field("right", -1, np.int32),
        parent=field("parent", -1, np.int32),
        leaf_value=field("leaf_value", 0.0, np.float32),
        sum_grad=field("sum_grad", 0.0, np.float32),
        sum_hess=field("sum_hess", 0.0, np.float32),
        count=field("count", 0.0, np.float32),
        gain=field("gain", 0.0, np.float32),
        depth=field("depth", 0, np.int32),
        is_leaf=field("is_leaf", True, bool),
        num_nodes=field("num_nodes", 0, np.int32),
        num_leaves=field("num_leaves", 0, np.int32))
    stacked = TreeArrays(*[jnp.asarray(a) for a in stacked])

    tree_model = np.zeros(tp, np.int32)
    tree_class = np.zeros(tp, np.int32)
    t0 = 0
    for slot, (nm, forest) in enumerate(members):
        t1 = t0 + forest.num_trees
        tree_model[t0:t1] = slot
        tree_class[t0:t1] = np.asarray(forest.tree_class)
        t0 = t1

    # per-slot binning tables; pad slots/features get num_bin 1 (bin 0
    # is their only value, never a NaN bin) and are unreferenced anyway
    num_bins = np.ones((mp, fp), np.int32)
    missing = np.zeros((mp, fp), bool)
    for slot, (nm, forest) in enumerate(members):
        f = forest.num_features
        num_bins[slot, :f] = np.asarray(forest.num_bins)
        missing[slot, :f] = np.asarray(forest.missing_is_nan)

    return ForestPack(
        name=name, stacked=stacked,
        tree_model=jnp.asarray(tree_model),
        tree_class=jnp.asarray(tree_class),
        num_bins=jnp.asarray(num_bins),
        missing_is_nan=jnp.asarray(missing),
        member_names=tuple(names),
        forests={nm: forest for nm, forest in members},
        num_slots=mp, num_outputs=kp, num_features=fp,
        num_trees=t_real)


def _predict_packed_impl(stacked, tree_model, tree_class, t_real,
                         bins, num_bins, missing_is_nan,
                         num_outputs: int, row_block: int, row_valid):
    import jax
    import jax.numpy as jnp

    from ..learner.predict import predict_binned_tree

    tp = stacked.leaf_value.shape[0]
    total = bins.shape[0]
    fp = bins.shape[1]
    valid = row_valid if row_valid is not None else \
        jnp.ones(total, bool)

    def body(i, acc):
        def add(acc):
            tree = jax.tree_util.tree_map(lambda a: a[i], stacked)
            s = tree_model[i]
            off = s * row_block
            rb = jax.lax.dynamic_slice(bins, (off, 0), (row_block, fp))
            rv = jax.lax.dynamic_slice(valid, (off,), (row_block,))
            vals = predict_binned_tree(
                tree, rb, num_bins[s], missing_is_nan[s], row_valid=rv)
            blk = jax.lax.dynamic_slice(
                acc, (off, 0), (row_block, num_outputs))
            blk = blk.at[:, tree_class[i]].add(vals)
            return jax.lax.dynamic_update_slice(acc, blk, (off, 0))

        return jax.lax.cond(i < t_real, add, lambda a: a, acc)

    acc = jnp.zeros((total, num_outputs), jnp.float32)
    return jax.lax.fori_loop(0, tp, body, acc)


_packed_jit = None


def _packed_fn():
    """The jitted fused predictor, built on first use (serving modules
    never import JAX at module load). Tests read `_cache_size()` off
    the returned function for the shape-leak guard."""
    global _packed_jit
    if _packed_jit is None:
        import jax
        _packed_jit = jax.jit(
            _predict_packed_impl,
            static_argnames=("num_outputs", "row_block"))
    return _packed_jit


def predict_packed_forest(stacked, tree_model, tree_class, t_real,
                          bins, num_bins, missing_is_nan,
                          num_outputs: int = 1, row_block: int = 16,
                          row_valid=None):
    """Fused multi-model forest sum: one launch, every resident model.

    bins: [Mp * row_block, Fp] — slot s owns rows
    ``[s*row_block, (s+1)*row_block)``. Each packed tree dynamic-slices
    its slot's row block, traverses it against the SLOT's binning
    tables (exact missing/categorical semantics per member), and
    accumulates into the slot's block of the output — per-member
    accumulation order is the member's own tree order, so every real
    row is bit-identical to the member's solo device predict. Pad
    trees (``i >= t_real``) are `lax.cond`-skipped: no add at all, so
    padding cannot flip signed zeros. `t_real` is a device scalar (not
    a static arg) so rebuilt packs with the same padded shapes reuse
    the compiled program. Returns [Mp * row_block, num_outputs] raw
    f32 scores.
    """
    return _packed_fn()(stacked, tree_model, tree_class, t_real, bins,
                        num_bins, missing_is_nan,
                        num_outputs=num_outputs, row_block=row_block,
                        row_valid=row_valid)


def dispatch_pack(engine, pack: ForestPack,
                  requests: Sequence[Tuple[int, np.ndarray]],
                  metrics_by_slot: Optional[Dict[int, object]] = None,
                  pack_metrics=None) -> np.ndarray:
    """One fused device dispatch answering a mixed (slot, bins) batch.

    Rows are grouped per slot, chunked through the engine's pow-2
    bucket ladder (`row_block` = next_bucket of the largest slot's
    rows this round; a slot with more rows than `max_bucket` takes
    extra rounds), assembled into the slot-grouped layout and scored
    by ONE `predict_packed_forest` launch per round. Returns the raw
    [sum(rows), num_outputs] scores in request order. Compile
    accounting rides the engine's bucket cache keyed on the pack, so
    the ladder bound applies per pack, not per member.
    """
    import jax.numpy as jnp

    from ..observability import registry as _obs
    from ..reliability import faults

    # registered fault site: the fused multi-model dispatch boundary
    # (replica retry/failover bracket lives in replicas.dispatch)
    faults.inject("serving_pack_predict")

    if not requests:
        return np.zeros((0, pack.num_outputs), np.float32)
    with global_timer.timeit("serve_pack_predict"):
        by_slot: Dict[int, List[np.ndarray]] = {}
        spans: List[Tuple[int, int, int]] = []   # (slot, start, rows)
        for slot, bins in requests:
            chunks = by_slot.setdefault(slot, [])
            start = sum(c.shape[0] for c in chunks)
            chunks.append(np.asarray(bins, np.int32))
            spans.append((slot, start, bins.shape[0]))
        slot_bins = {s: (c[0] if len(c) == 1 else np.concatenate(c))
                     for s, c in by_slot.items()}
        done: Dict[int, List[np.ndarray]] = {s: [] for s in slot_bins}
        offs = {s: 0 for s in slot_bins}
        while True:
            this_round = {
                s: min(len(b) - offs[s], engine.max_bucket)
                for s, b in slot_bins.items() if offs[s] < len(b)}
            if not this_round:
                break
            block = next_bucket(max(this_round.values()),
                                engine.min_bucket, engine.max_bucket)
            packed = np.zeros((pack.num_slots * block,
                               pack.num_features), np.int32)
            valid = np.zeros(pack.num_slots * block, bool)
            for s, r in this_round.items():
                chunk = slot_bins[s][offs[s]:offs[s] + r]
                packed[s * block:s * block + r, :chunk.shape[1]] = chunk
                valid[s * block:s * block + r] = True
            hit = engine._record(pack, block)
            if metrics_by_slot:
                for s in this_round:
                    m = metrics_by_slot.get(s)
                    if m is not None:
                        m.record_batch(bucket_hit=hit, compiled=not hit)
            _t0 = time.perf_counter()
            raw = predict_packed_forest(
                pack.stacked, pack.tree_model, pack.tree_class,
                jnp.int32(pack.num_trees), jnp.asarray(packed),
                pack.num_bins, pack.missing_is_nan,
                num_outputs=pack.num_outputs, row_block=block,
                row_valid=jnp.asarray(valid))
            raw = np.asarray(raw)        # device -> host sync
            _dt = time.perf_counter() - _t0
            if _obs.enabled:
                # a pack bucket-cache miss IS an XLA compilation of the
                # fused predictor for this block shape
                _obs.compiles.record(f"serving_pack_b{block}", _dt,
                                     compiled=not hit)
                _obs.trace.add("serve_pack_predict", _t0, _dt,
                               block=block, slots=len(this_round),
                               rows=sum(this_round.values()))
            if pack_metrics is not None:
                pack_metrics.record_dispatch(
                    rows=sum(this_round.values()),
                    capacity=pack.num_slots * block,
                    slots=len(this_round), compiled=not hit)
            for s, r in this_round.items():
                done[s].append(raw[s * block:s * block + r])
                offs[s] += r
        slot_raw = {s: (c[0] if len(c) == 1 else np.concatenate(c))
                    for s, c in done.items()}
        return np.concatenate(
            [slot_raw[s][start:start + rows]
             for s, start, rows in spans], axis=0)


class PackBatcher(MicroBatcher):
    """One continuous-batching queue for a whole ForestPack.

    Requests carry their member's slot; each coalesced batch becomes
    ONE fused dispatch (`run_pack([(slot, bins), ...]) -> raw rows in
    request order`) instead of one launch per member. Inherits the
    scheduler, SLO admission (rows-aware service model — essential
    here, where members of very different sizes share the queue) and
    drain semantics unchanged.
    """

    def __init__(self, run_pack, **kwargs):
        self._run_pack = run_pack
        super().__init__(run_batch=None, **kwargs)

    def _dispatch(self, batch: List[_Request]) -> None:
        raw = self._run_pack([(r.slot, r.bins) for r in batch])
        lo = 0
        for req in batch:
            hi = lo + len(req.bins)
            req.future.set_result(raw[lo:hi])
            lo = hi


@dataclasses.dataclass
class PackEntry:
    """Shared serving machinery for one resident ForestPack: the fused
    device layout, its replica fleet, the slot-aware batcher and the
    pack-level metrics. Member `ModelEntry`s point here; a rebuild
    (member evict / hot-swap) publishes a NEW PackEntry and drains the
    old batcher through the host path — same semantics as a
    single-model hot swap."""
    name: str
    pack: ForestPack
    replicas: object               # ReplicaSet over the pack
    batcher: Optional[PackBatcher]
    metrics: object                # metrics.PackMetrics
    version: int = 1
    #: slot -> the member ModelEntry's ModelMetrics, filled by the
    #: registry as it publishes member entries (the fused dispatch
    #: records per-member batch/compile counts through it)
    slot_metrics: Dict[int, object] = dataclasses.field(
        default_factory=dict)

    def member_names(self) -> Tuple[str, ...]:
        return self.pack.member_names
