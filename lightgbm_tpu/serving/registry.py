"""Model registry: load a Booster once, serve it many times.

Each entry pins one model's `DeviceForest` (stacked TreeArrays + host
binners) in device memory so the request hot path never re-stacks tree
arrays or re-parses a model file. Lifecycle is explicit:

- `load(name, ...)`   Booster / model file / model string -> resident
- `refresh(name, ...)` atomically swap in a new version (in-flight
  requests finish against the old arrays — JAX arrays are immutable,
  so the swap is just a reference move)
- `evict(name)`       drop the entry; device memory frees with the
  last array reference

An entry owns everything a request needs — forest, replica set, micro
batcher — so the server fetches ONE reference and serves the request
against a consistent snapshot: a refresh can never pair the new forest
with the old queue (no torn model). The registry builds the entry
fully (replicas placed, batcher worker running) *before* publishing
it, then hands the previous entry back to the caller, which drains the
old batcher outside the lock.

Health is derived, not sticky: `entry.degraded` is computed from the
replica breakers (`serving/breaker.py`) and heals itself when a probe
dispatch closes a breaker — the PR-1 manual-refresh flag is gone.

Capacity is bounded: loading past `max_models` evicts the least
recently *used* entry (use = a `get`), mirroring the bucket cache's
"bounded resources, predictable behavior" contract.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..utils.log import Log, LightGBMError
from .forest import DeviceForest, build_device_forest
from .metrics import ModelMetrics
from .replicas import ReplicaSet

__all__ = ["ModelRegistry", "ModelEntry"]


@dataclass
class ModelEntry:
    name: str
    forest: DeviceForest
    booster: object                     # the source Booster (host fallback)
    metrics: ModelMetrics
    loaded_at: float
    version: int = 1
    last_used: float = field(default=0.0)
    # device-side replica fleet (empty for unsupported forests); the
    # breakers inside it carry this entry's health
    replicas: Optional[ReplicaSet] = None
    # micro-batching queue bound to THIS entry's forest+replicas; the
    # server submits to entry.batcher so a refresh can never route old
    # queued bins to a new forest
    batcher: object = None

    @property
    def degraded(self) -> bool:
        """Device path unavailable right now. Derived from breaker
        state — heals itself when a replica's half-open probe closes
        its breaker (contrast PR 1's sticky flag, cleared only by a
        manual refresh)."""
        if not self.forest.supported:
            return True
        if self.replicas is None or len(self.replicas) == 0:
            return True
        return not self.replicas.any_available()


def _forest_from_source(booster=None, model_file: Optional[str] = None,
                        model_str: Optional[str] = None):
    from ..basic import Booster
    if booster is None:
        if model_file is None and model_str is None:
            raise LightGBMError(
                "registry.load needs a booster, model_file or model_str")
        booster = Booster(model_file=model_file, model_str=model_str)
    forest = booster.device_forest()
    return booster, forest


class ModelRegistry:
    """Thread-safe name -> ModelEntry map with LRU capacity.

    `replica_factory(forest, name) -> ReplicaSet` and
    `batcher_factory(entry) -> MicroBatcher` are injected by the
    server so the registry stays free of routing policy; both may be
    None (registry-only tests get bare entries).
    """

    def __init__(self, max_models: int = 8,
                 replica_factory: Optional[Callable] = None,
                 batcher_factory: Optional[Callable] = None):
        if max_models < 1:
            raise ValueError("max_models must be >= 1")
        self.max_models = int(max_models)
        self._entries: Dict[str, ModelEntry] = {}
        self._lock = threading.RLock()
        self.replica_factory = replica_factory
        self.batcher_factory = batcher_factory
        self.swap_count = 0

    # ------------------------------------------------------------------
    def load(self, name: str, booster=None,
             model_file: Optional[str] = None,
             model_str: Optional[str] = None) -> ModelEntry:
        """Build + pin the device forest for `name`. Idempotent per
        name: loading an existing name is a hot-swap (the previous
        entry's batcher is drained through the host path, see
        `Server.hot_swap`)."""
        entry, prev = self._load_prepared(name, booster, model_file,
                                          model_str)
        # a plain load of an existing name still must not strand the
        # old entry's queue; drain it here (hot_swap does its own
        # drain + accounting before calling _load_prepared)
        self._drain_replaced(prev)
        return entry

    def _load_prepared(self, name, booster=None, model_file=None,
                       model_str=None):
        """Build the full entry (forest, replicas, running batcher),
        publish it atomically, return (entry, previous_entry)."""
        booster, forest = _forest_from_source(booster, model_file,
                                              model_str)
        replicas = (self.replica_factory(forest, name)
                    if self.replica_factory else None)
        with self._lock:
            prev = self._entries.get(name)
            entry = ModelEntry(
                name=name, forest=forest, booster=booster,
                metrics=prev.metrics if prev else ModelMetrics(),
                loaded_at=time.monotonic(),
                version=(prev.version + 1) if prev else 1,
                last_used=time.monotonic(),
                replicas=replicas)
            if self.batcher_factory is not None:
                entry.batcher = self.batcher_factory(entry)
            self._entries[name] = entry
            if prev is not None:
                self.swap_count += 1
            evicted = self._evict_over_capacity_locked()
        for old in evicted:
            self._drain_replaced(old)
        if not forest.supported:
            Log.warning(
                f"serving model '{name}' on the host fallback path: "
                f"{forest.unsupported_reason}")
        Log.info(f"serving: loaded model '{name}' v{entry.version} "
                 f"({forest.num_trees} trees, "
                 f"{forest.num_features} features)")
        return entry, prev

    @staticmethod
    def _drain_replaced(prev: Optional[ModelEntry]) -> int:
        """Close a replaced/evicted entry's batcher. Queued requests
        resolve with `BatcherClosed`; the server re-answers each via
        the OLD entry's host path (its `_finish` closed over the
        entry), so nothing is dropped or served by a torn model."""
        if prev is None or prev.batcher is None:
            return 0
        drained = prev.batcher.close(drain_queued=False)
        if drained:
            prev.metrics.record_swap_drain(drained)
        return drained

    def refresh(self, name: str, booster=None,
                model_file: Optional[str] = None,
                model_str: Optional[str] = None) -> ModelEntry:
        """Atomic swap to a new model version under the same name."""
        with self._lock:
            if name not in self._entries:
                raise LightGBMError(f"model '{name}' is not loaded")
        return self.load(name, booster=booster, model_file=model_file,
                         model_str=model_str)

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise LightGBMError(f"model '{name}' is not loaded")
            entry.last_used = time.monotonic()
            return entry

    def evict(self, name: str) -> bool:
        """Drop `name`; returns False when it was not loaded. Queued
        requests drain through the host path, none dropped."""
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is not None:
            self._drain_replaced(entry)
            Log.info(f"serving: evicted model '{name}'")
        return entry is not None

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # ------------------------------------------------------------------
    def _evict_over_capacity_locked(self) -> List[ModelEntry]:
        # `_locked` suffix: caller holds the lock (docs/StaticAnalysis.md)
        evicted: List[ModelEntry] = []
        while len(self._entries) > self.max_models:
            lru = min(self._entries.values(), key=lambda e: e.last_used)
            del self._entries[lru.name]
            evicted.append(lru)
            Log.warning(f"serving: capacity {self.max_models} reached, "
                        f"evicted LRU model '{lru.name}'")
        return evicted
