"""Model registry: load a Booster once, serve it many times.

Each entry pins one model's `DeviceForest` (stacked TreeArrays + host
binners) in device memory so the request hot path never re-stacks tree
arrays or re-parses a model file. Lifecycle is explicit:

- `load(name, ...)`   Booster / model file / model string -> resident
- `refresh(name, ...)` atomically swap in a new version (in-flight
  requests finish against the old arrays — JAX arrays are immutable,
  so the swap is just a reference move)
- `evict(name)`       drop the entry; device memory frees with the
  last array reference

Capacity is bounded: loading past `max_models` evicts the least
recently *used* entry (use = a `get`), mirroring the bucket cache's
"bounded resources, predictable behavior" contract.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.log import Log, LightGBMError
from .forest import DeviceForest, build_device_forest
from .metrics import ModelMetrics

__all__ = ["ModelRegistry", "ModelEntry"]


@dataclass
class ModelEntry:
    name: str
    forest: DeviceForest
    booster: object                     # the source Booster (host fallback)
    metrics: ModelMetrics
    loaded_at: float
    version: int = 1
    last_used: float = field(default=0.0)
    # set by the server after a device failure: subsequent requests for
    # this entry take the host path until the model is refreshed
    degraded: bool = False


def _forest_from_source(booster=None, model_file: Optional[str] = None,
                        model_str: Optional[str] = None):
    from ..basic import Booster
    if booster is None:
        if model_file is None and model_str is None:
            raise LightGBMError(
                "registry.load needs a booster, model_file or model_str")
        booster = Booster(model_file=model_file, model_str=model_str)
    forest = booster.device_forest()
    return booster, forest


class ModelRegistry:
    """Thread-safe name -> ModelEntry map with LRU capacity."""

    def __init__(self, max_models: int = 8):
        if max_models < 1:
            raise ValueError("max_models must be >= 1")
        self.max_models = int(max_models)
        self._entries: Dict[str, ModelEntry] = {}
        self._lock = threading.RLock()

    # ------------------------------------------------------------------
    def load(self, name: str, booster=None,
             model_file: Optional[str] = None,
             model_str: Optional[str] = None) -> ModelEntry:
        """Build + pin the device forest for `name`. Idempotent per
        name: loading an existing name is a refresh."""
        booster, forest = _forest_from_source(booster, model_file,
                                              model_str)
        with self._lock:
            prev = self._entries.get(name)
            entry = ModelEntry(
                name=name, forest=forest, booster=booster,
                metrics=prev.metrics if prev else ModelMetrics(),
                loaded_at=time.monotonic(),
                version=(prev.version + 1) if prev else 1,
                last_used=time.monotonic())
            self._entries[name] = entry
            self._evict_over_capacity_locked()
        if not forest.supported:
            Log.warning(
                f"serving model '{name}' on the host fallback path: "
                f"{forest.unsupported_reason}")
        Log.info(f"serving: loaded model '{name}' v{entry.version} "
                 f"({forest.num_trees} trees, "
                 f"{forest.num_features} features)")
        return entry

    def refresh(self, name: str, booster=None,
                model_file: Optional[str] = None,
                model_str: Optional[str] = None) -> ModelEntry:
        """Atomic swap to a new model version under the same name."""
        with self._lock:
            if name not in self._entries:
                raise LightGBMError(f"model '{name}' is not loaded")
        return self.load(name, booster=booster, model_file=model_file,
                         model_str=model_str)

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise LightGBMError(f"model '{name}' is not loaded")
            entry.last_used = time.monotonic()
            return entry

    def evict(self, name: str) -> bool:
        """Drop `name`; returns False when it was not loaded."""
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is not None:
            Log.info(f"serving: evicted model '{name}'")
        return entry is not None

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # ------------------------------------------------------------------
    def _evict_over_capacity_locked(self) -> None:
        # `_locked` suffix: caller holds the lock (docs/StaticAnalysis.md)
        while len(self._entries) > self.max_models:
            lru = min(self._entries.values(), key=lambda e: e.last_used)
            del self._entries[lru.name]
            Log.warning(f"serving: capacity {self.max_models} reached, "
                        f"evicted LRU model '{lru.name}'")
