"""Model registry: load a Booster once, serve it many times.

Each entry pins one model's `DeviceForest` (stacked TreeArrays + host
binners) in device memory so the request hot path never re-stacks tree
arrays or re-parses a model file. Lifecycle is explicit:

- `load(name, ...)`   Booster / model file / model string -> resident
- `refresh(name, ...)` atomically swap in a new version (in-flight
  requests finish against the old arrays — JAX arrays are immutable,
  so the swap is just a reference move)
- `evict(name)`       drop the entry; device memory frees with the
  last array reference

An entry owns everything a request needs — forest, replica set, micro
batcher — so the server fetches ONE reference and serves the request
against a consistent snapshot: a refresh can never pair the new forest
with the old queue (no torn model). The registry builds the entry
fully (replicas placed, batcher worker running) *before* publishing
it, then hands the previous entry back to the caller, which drains the
old batcher outside the lock.

Health is derived, not sticky: `entry.degraded` is computed from the
replica breakers (`serving/breaker.py`) and heals itself when a probe
dispatch closes a breaker — the PR-1 manual-refresh flag is gone.

Capacity is bounded: loading past `max_models` evicts the least
recently *used* entry (use = a `get`), mirroring the bucket cache's
"bounded resources, predictable behavior" contract.

Multi-model packs (serving/multimodel.py): `load_pack` loads several
models into ONE fused device layout; each member still gets its own
`ModelEntry` (own metrics, own host fallback booster) but `pack` /
`pack_slot` point at the shared `PackEntry` that owns the ForestPack,
its replica fleet and the slot-aware batcher. Membership is sticky
through lifecycle events, each of which REBUILDS the pack off-lock and
publishes atomically with hot-swap drain semantics:

- LRU-evicting one member republishes the pack without it; the other
  members keep serving (briefly against the old pack) and queued
  futures on the old batcher — including the evicted member's —
  resolve `BatcherClosed` and re-answer through each member's host
  path, exactly once.
- Refreshing (hot-swapping) one member republishes the pack with the
  member's new forest in the same slot layout.
- Evicting the last member drops the whole PackEntry.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..utils.log import Log, LightGBMError
from .forest import DeviceForest, build_device_forest
from .metrics import ModelMetrics
from .replicas import ReplicaSet

__all__ = ["ModelRegistry", "ModelEntry"]


@dataclass
class ModelEntry:
    name: str
    forest: DeviceForest
    booster: object                     # the source Booster (host fallback)
    metrics: ModelMetrics
    loaded_at: float
    version: int = 1
    last_used: float = field(default=0.0)
    # device-side replica fleet (empty for unsupported forests); the
    # breakers inside it carry this entry's health
    replicas: Optional[ReplicaSet] = None
    # micro-batching queue bound to THIS entry's forest+replicas; the
    # server submits to entry.batcher so a refresh can never route old
    # queued bins to a new forest
    batcher: object = None
    # pack membership (serving/multimodel.py): the shared PackEntry
    # whose fused dispatch serves this model, and this model's slot in
    # it. Pack members have replicas=None/batcher=None — the pack owns
    # both.
    pack: object = None
    pack_slot: int = -1

    @property
    def degraded(self) -> bool:
        """Device path unavailable right now. Derived from breaker
        state — heals itself when a replica's half-open probe closes
        its breaker (contrast PR 1's sticky flag, cleared only by a
        manual refresh). Pack members derive health from the PACK's
        replica fleet."""
        if not self.forest.supported:
            return True
        replicas = self.pack.replicas if self.pack is not None \
            else self.replicas
        if replicas is None or len(replicas) == 0:
            return True
        return not replicas.any_available()


def _forest_from_source(booster=None, model_file: Optional[str] = None,
                        model_str: Optional[str] = None):
    from ..basic import Booster
    if booster is None:
        if model_file is None and model_str is None:
            raise LightGBMError(
                "registry.load needs a booster, model_file or model_str")
        booster = Booster(model_file=model_file, model_str=model_str)
    forest = booster.device_forest()
    return booster, forest


class ModelRegistry:
    """Thread-safe name -> ModelEntry map with LRU capacity.

    `replica_factory(forest, name) -> ReplicaSet` and
    `batcher_factory(entry) -> MicroBatcher` are injected by the
    server so the registry stays free of routing policy; both may be
    None (registry-only tests get bare entries).
    """

    def __init__(self, max_models: int = 8,
                 replica_factory: Optional[Callable] = None,
                 batcher_factory: Optional[Callable] = None,
                 pack_batcher_factory: Optional[Callable] = None):
        if max_models < 1:
            raise ValueError("max_models must be >= 1")
        self.max_models = int(max_models)
        self._entries: Dict[str, ModelEntry] = {}
        self._lock = threading.RLock()
        self.replica_factory = replica_factory
        self.batcher_factory = batcher_factory
        # pack_batcher_factory(pack_entry) -> PackBatcher; the replica
        # factory is reused as-is (ReplicaSet.build is polymorphic
        # over DeviceForest / ForestPack)
        self.pack_batcher_factory = pack_batcher_factory
        self.swap_count = 0
        self.pack_rebuilds = 0

    # ------------------------------------------------------------------
    def load(self, name: str, booster=None,
             model_file: Optional[str] = None,
             model_str: Optional[str] = None) -> ModelEntry:
        """Build + pin the device forest for `name`. Idempotent per
        name: loading an existing name is a hot-swap (the previous
        entry's batcher is drained through the host path, see
        `Server.hot_swap`)."""
        entry, prev = self._load_prepared(name, booster, model_file,
                                          model_str)
        # a plain load of an existing name still must not strand the
        # old entry's queue; drain it here (hot_swap does its own
        # drain + accounting before calling _load_prepared)
        self._drain_replaced(prev)
        return entry

    def _load_prepared(self, name, booster=None, model_file=None,
                       model_str=None):
        """Build the full entry (forest, replicas, running batcher),
        publish it atomically, return (entry, previous_entry).

        When `name` is currently a PACK member and the new forest is
        device-servable, the whole pack is rebuilt with the member's
        new forest (same hot-swap semantics, pack-wide); an
        unsupported replacement leaves the pack and serves solo."""
        booster, forest = _forest_from_source(booster, model_file,
                                              model_str)
        with self._lock:
            prior = self._entries.get(name)
        if prior is not None and prior.pack is not None and \
                forest.supported:
            self._rebuild_pack(prior.pack,
                               replace={name: (booster, forest)})
            with self._lock:
                entry = self._entries[name]
                self.swap_count += 1
                evicted = self._evict_over_capacity_locked()
            self._handle_evicted(evicted)
            Log.info(f"serving: loaded model '{name}' v{entry.version} "
                     f"into pack '{entry.pack.name}' "
                     f"({forest.num_trees} trees)")
            return entry, prior
        if prior is not None and prior.pack is not None:
            # member turned host-only: drop it from the pack first so
            # the remaining members keep their fused path
            self._rebuild_pack(prior.pack, drop={name})
        replicas = (self.replica_factory(forest, name)
                    if self.replica_factory else None)
        with self._lock:
            prev = self._entries.get(name)
            prev = prior if prior is not None else prev
            entry = ModelEntry(
                name=name, forest=forest, booster=booster,
                metrics=prev.metrics if prev else ModelMetrics(),
                loaded_at=time.monotonic(),
                version=(prev.version + 1) if prev else 1,
                last_used=time.monotonic(),
                replicas=replicas)
            if self.batcher_factory is not None:
                entry.batcher = self.batcher_factory(entry)
            self._entries[name] = entry
            if prev is not None:
                self.swap_count += 1
            evicted = self._evict_over_capacity_locked()
        self._handle_evicted(evicted)
        if not forest.supported:
            Log.warning(
                f"serving model '{name}' on the host fallback path: "
                f"{forest.unsupported_reason}")
        Log.info(f"serving: loaded model '{name}' v{entry.version} "
                 f"({forest.num_trees} trees, "
                 f"{forest.num_features} features)")
        return entry, prev

    @staticmethod
    def _drain_replaced(prev: Optional[ModelEntry]) -> int:
        """Close a replaced/evicted entry's batcher. Queued requests
        resolve with `BatcherClosed`; the server re-answers each via
        the OLD entry's host path (its `_finish` closed over the
        entry), so nothing is dropped or served by a torn model. For a
        replaced PACK member the drain target is the old PackEntry's
        batcher (the rebuild already republished the survivors)."""
        if prev is None:
            return 0
        if prev.pack is not None:
            return ModelRegistry._drain_pack(prev.pack)
        if prev.batcher is None:
            return 0
        drained = prev.batcher.close(drain_queued=False)
        if drained:
            prev.metrics.record_swap_drain(drained)
        return drained

    @staticmethod
    def _drain_pack(old_pe) -> int:
        """Close a replaced/dropped PackEntry's batcher with hot-swap
        drain semantics. Idempotent: a second close of an already
        closed batcher drains nothing and records nothing twice."""
        if old_pe.batcher is None:
            return 0
        drained = old_pe.batcher.close(drain_queued=False)
        old_pe.metrics.record_rebuild(drained)
        return drained

    # ------------------------------------------------------------------
    def load_pack(self, pack_name: str, members) -> List[ModelEntry]:
        """Load several models as ONE fused ForestPack.

        `members` is a sequence of ``(name, booster)`` pairs (or
        ``(name, {"model_file": ...})`` / ``{"model_str": ...}``
        dicts). Members whose forest cannot be served from the device
        load unpacked — a solo host-fallback entry with a warning — so
        one exotic model never blocks its pack-mates' fused path.
        Returns the member entries in input order."""
        from .metrics import PackMetrics
        from .multimodel import PackEntry, build_forest_pack
        built = []
        for nm, src in members:
            kw = dict(src) if isinstance(src, dict) else {"booster": src}
            booster, forest = _forest_from_source(**kw)
            built.append((nm, booster, forest))
        packable = [(nm, b, f) for nm, b, f in built if f.supported]
        unpackable = [(nm, b, f) for nm, b, f in built
                      if not f.supported]
        by_name: Dict[str, ModelEntry] = {}
        new_pe = None
        if packable:
            pack = build_forest_pack(
                [(nm, f) for nm, _b, f in packable], name=pack_name)
            replicas = (self.replica_factory(pack, pack_name)
                        if self.replica_factory else None)
            new_pe = PackEntry(name=pack_name, pack=pack,
                               replicas=replicas, batcher=None,
                               metrics=PackMetrics())
            if self.pack_batcher_factory is not None:
                new_pe.batcher = self.pack_batcher_factory(new_pe)
        prevs: List[Optional[ModelEntry]] = []
        now = time.monotonic()
        with self._lock:
            for slot, (nm, b, f) in enumerate(packable):
                prev = self._entries.get(nm)
                prevs.append(prev)
                entry = ModelEntry(
                    name=nm, forest=f, booster=b,
                    metrics=prev.metrics if prev else ModelMetrics(),
                    loaded_at=now,
                    version=(prev.version + 1) if prev else 1,
                    last_used=now, pack=new_pe, pack_slot=slot)
                new_pe.slot_metrics[slot] = entry.metrics
                self._entries[nm] = entry
                by_name[nm] = entry
                if prev is not None:
                    self.swap_count += 1
            evicted = self._evict_over_capacity_locked()
        # replaced entries drain off-lock; a member poached from
        # ANOTHER pack rebuilds that pack without it (grouped, once)
        self._handle_evicted(
            [p for p in prevs if p is not None] + evicted)
        for nm, b, f in unpackable:
            Log.warning(
                f"serving: pack member '{nm}' is not device-servable "
                f"({f.unsupported_reason}); loading unpacked on the "
                f"host path")
            by_name[nm] = self.load(nm, booster=b)
        if new_pe is not None:
            Log.info(f"serving: loaded pack '{pack_name}' with "
                     f"{len(packable)} members "
                     f"({new_pe.pack.num_trees} trees, "
                     f"{new_pe.pack.num_slots} slots)")
        return [by_name[nm] for nm, _b, _f in built]

    def _rebuild_pack(self, old_pe, drop=frozenset(), replace=None):
        """Republish `old_pe`'s pack without the `drop` members and/or
        with `replace`d forests ({name: (booster, forest)}), keeping
        the surviving slot ORDER. The device build runs OFF-lock; the
        member entries publish atomically; the OLD batcher keeps
        serving until the caller drains it (hot-swap semantics).
        Returns the new PackEntry, or None when no members remain
        (whole-pack drop)."""
        from .multimodel import PackEntry, build_forest_pack
        replace = replace or {}
        with self._lock:
            members = []
            for nm in old_pe.member_names():
                if nm in drop:
                    continue
                if nm in replace:
                    b, f = replace[nm]
                    members.append((nm, b, f))
                    continue
                e = self._entries.get(nm)
                if e is not None and e.pack is old_pe:
                    members.append((nm, e.booster, e.forest))
        if not members:
            return None
        pack = build_forest_pack(
            [(nm, f) for nm, _b, f in members], name=old_pe.name)
        replicas = (self.replica_factory(pack, old_pe.name)
                    if self.replica_factory else None)
        new_pe = PackEntry(name=old_pe.name, pack=pack,
                           replicas=replicas, batcher=None,
                           metrics=old_pe.metrics,
                           version=old_pe.version + 1)
        if self.pack_batcher_factory is not None:
            new_pe.batcher = self.pack_batcher_factory(new_pe)
        now = time.monotonic()
        with self._lock:
            for slot, (nm, b, f) in enumerate(members):
                prior = self._entries.get(nm)
                entry = ModelEntry(
                    name=nm, forest=f, booster=b,
                    metrics=prior.metrics if prior is not None
                    else ModelMetrics(),
                    loaded_at=now,
                    version=(prior.version + 1) if prior is not None
                    else 1,
                    last_used=prior.last_used if prior is not None
                    else now,
                    pack=new_pe, pack_slot=slot)
                new_pe.slot_metrics[slot] = entry.metrics
                self._entries[nm] = entry
            self.pack_rebuilds += 1
        Log.info(f"serving: rebuilt pack '{old_pe.name}' "
                 f"v{new_pe.version} ({len(members)} members)")
        return new_pe

    def _handle_evicted(self, stale: List[ModelEntry]) -> None:
        """Off-lock cleanup for replaced/LRU-victim entries. Solo
        entries drain their own batcher; a pack member's departure
        republishes its pack without it (whole-pack drop when it was
        the last member) and then drains the OLD pack batcher —
        queued futures, including the departed member's, resolve
        through each member's host path exactly once."""
        pack_groups: Dict[int, list] = {}
        for old in stale:
            if old.pack is None:
                self._drain_replaced(old)
            else:
                grp = pack_groups.setdefault(id(old.pack),
                                             [old.pack, set()])
                grp[1].add(old.name)
        for old_pe, names in pack_groups.values():
            # drop every departed name; members that were merely
            # REPLACED under a newer pack are excluded by the rebuild
            # itself (it only keeps entries still pointing at old_pe)
            self._rebuild_pack(old_pe, drop=names)
            self._drain_pack(old_pe)

    def packs(self) -> Dict[str, object]:
        """Live PackEntries keyed by pack name (no LRU touch)."""
        with self._lock:
            out: Dict[str, object] = {}
            for e in self._entries.values():
                if e.pack is not None:
                    out[e.pack.name] = e.pack
            return out

    # ------------------------------------------------------------------
    def refresh(self, name: str, booster=None,
                model_file: Optional[str] = None,
                model_str: Optional[str] = None) -> ModelEntry:
        """Atomic swap to a new model version under the same name."""
        with self._lock:
            if name not in self._entries:
                raise LightGBMError(f"model '{name}' is not loaded")
        return self.load(name, booster=booster, model_file=model_file,
                         model_str=model_str)

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise LightGBMError(f"model '{name}' is not loaded")
            entry.last_used = time.monotonic()
            return entry

    def evict(self, name: str) -> bool:
        """Drop `name`; returns False when it was not loaded. Queued
        requests drain through the host path, none dropped."""
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is not None:
            # pack members route through _handle_evicted so the pack
            # is republished without them (survivors keep the fused
            # path); solo entries just drain
            self._handle_evicted([entry])
            Log.info(f"serving: evicted model '{name}'")
        return entry is not None

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # ------------------------------------------------------------------
    def _evict_over_capacity_locked(self) -> List[ModelEntry]:
        # `_locked` suffix: caller holds the lock (docs/StaticAnalysis.md)
        evicted: List[ModelEntry] = []
        while len(self._entries) > self.max_models:
            lru = min(self._entries.values(), key=lambda e: e.last_used)
            del self._entries[lru.name]
            evicted.append(lru)
            Log.warning(f"serving: capacity {self.max_models} reached, "
                        f"evicted LRU model '{lru.name}'")
        return evicted
