"""Replica fleet: one model's DeviceForest on several local devices.

ROADMAP item 3 asks for serving that scales past a single chip and
survives one of them dying. A `ReplicaSet` replicates a loaded
`DeviceForest` across local devices (`jax.device_put` of the stacked
pytree — arrays are immutable, so replicas share nothing mutable) and
routes each coalesced batch to the least-loaded replica whose circuit
breaker grants the dispatch (`breaker.py`).

Failure handling is the degradation ladder's middle rungs: a replica
dispatch gets the standard capped-backoff retries; if it still fails
(or returns non-finite scores — a deterministic forest would reproduce
those on every retry, so they fail the replica immediately), the
replica's breaker records the failure and the batch FAILS OVER to the
next available replica. Only when every replica is open/refused does
`NoReplicaAvailable` escape to the server, which serves the batch via
host predict. An open breaker heals itself: after the cooldown the
next batch is routed to it as a half-open probe, and one clean device
dispatch closes it again.

The dispatch boundary is a registered fault site
(``serving_replica_predict``, docs/Reliability.md) so the chaos
harness can kill any replica's device path and watch the breaker
open, the traffic fail over, and the probe re-close it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..reliability import retry_call
from ..utils.log import Log
from ..utils.timer import global_timer
from .forest import DeviceForest

__all__ = ["Replica", "ReplicaSet", "NoReplicaAvailable",
           "NonFiniteScores"]


class NoReplicaAvailable(RuntimeError):
    """Every replica's breaker refused the dispatch (all open, or the
    half-open probes are taken). The server answers via host predict —
    the bottom rung of the degradation ladder."""


class NonFiniteScores(RuntimeError):
    """Device predict returned NaN/inf raw scores. Deterministic
    forests reproduce this on retry, so it fails the replica (breaker
    failure + failover) instead of burning the retry budget."""


class Replica:
    """One device-resident copy of the forest + its breaker + load."""

    def __init__(self, index: int, forest: DeviceForest, device,
                 breaker) -> None:
        self.index = index
        self.forest = forest
        self.device = device
        self.breaker = breaker
        self._lock = threading.Lock()
        self._inflight = 0
        self.dispatches = 0
        self.failures = 0

    def _acquire_slot(self) -> None:
        with self._lock:
            self._inflight += 1
            self.dispatches += 1

    def _release_slot(self, ok: bool) -> None:
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)
            if not ok:
                self.failures += 1

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def snapshot(self) -> Dict:
        snap = self.breaker.snapshot()
        with self._lock:
            snap.update(replica=self.index, device=str(self.device),
                        inflight=self._inflight,
                        dispatches=self.dispatches,
                        failures=self.failures)
        return snap


class ReplicaSet:
    """Least-loaded, breaker-gated routing across replicas.

    `forest` may be a single DeviceForest or a multimodel.ForestPack —
    anything carrying `supported` and `place_on(device)`; the fleet is
    agnostic to what one dispatch scores."""

    def __init__(self, replicas: List[Replica], name: str = "model"):
        self.name = name
        self._replicas = tuple(replicas)   # immutable after build
        self._lock = threading.Lock()
        self.failovers = 0

    # ------------------------------------------------------------------
    @classmethod
    def build(cls, forest: DeviceForest, n_replicas: int, *,
              name: str = "model", breaker_threshold: int = 3,
              breaker_cooldown_ms: float = 250.0,
              clock=time.monotonic) -> "ReplicaSet":
        """Replicate `forest` onto local devices. ``n_replicas <= 0``
        means one replica per local device. Unsupported forests get an
        empty set (the server never routes them to the device)."""
        from .breaker import CircuitBreaker
        if not forest.supported:
            return cls([], name=name)
        try:
            import jax
            devices = jax.local_devices()
        except Exception:       # no backend: single logical replica
            devices = [None]
        if n_replicas <= 0:
            n_replicas = len(devices)
        replicas: List[Replica] = []
        for i in range(max(int(n_replicas), 1)):
            dev = devices[i % len(devices)] if devices else None
            if i == 0 or dev is None or len(devices) == 1:
                # replica 0 keeps the already-built arrays; a 1-device
                # host shares them too (identical placement, and the
                # bucket cache stays warm across replicas)
                rep_forest = forest
            else:
                rep_forest = forest.place_on(dev)
            breaker = CircuitBreaker(threshold=breaker_threshold,
                                     cooldown_s=breaker_cooldown_ms / 1e3,
                                     clock=clock)
            replicas.append(Replica(i, rep_forest, dev, breaker))
        return cls(replicas, name=name)

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._replicas)

    def replicas(self) -> List[Replica]:
        return list(self._replicas)

    def any_available(self) -> bool:
        """Would a new request reach the device path right now? Non-
        consuming: breaker probes are only reserved at dispatch."""
        return any(r.breaker.available() for r in self._replicas)

    def open_count(self) -> int:
        return sum(1 for r in self._replicas
                   if r.breaker.state != "closed")

    def _pick_locked(self, exclude) -> Optional[Replica]:
        candidates = sorted(
            (r for r in self._replicas if r.index not in exclude),
            key=lambda r: (r.inflight(), r.index))
        for rep in candidates:
            if rep.breaker.try_acquire():
                return rep
        return None

    # ------------------------------------------------------------------
    def dispatch(self, engine, bins: np.ndarray, *, metrics=None,
                 retry_attempts: int = 3, retry_backoff_ms: float = 50.0,
                 retry_backoff_max_ms: float = 2000.0,
                 attempt_fn=None) -> np.ndarray:
        """Route one coalesced batch: least-loaded breaker-granted
        replica, capped-backoff retries on it, breaker bookkeeping,
        failover to the next replica on final failure. Raises
        `NoReplicaAvailable` when every replica refuses — the caller's
        host-fallback rung takes over.

        `attempt_fn(replica) -> raw` overrides what one attempt runs
        (the fused pack dispatch passes `multimodel.dispatch_pack`
        here); the default scores `bins` through the bucketed engine.
        Either way the attempt runs inside this retry/breaker/failover
        bracket and its per-dispatch fault site."""
        from ..reliability import faults

        tried: set = set()
        failed_over = False
        while True:
            with self._lock:
                rep = self._pick_locked(tried)
            if rep is None:
                raise NoReplicaAvailable(
                    f"serving model '{self.name}': no replica available "
                    f"({len(self._replicas)} total, "
                    f"{self.open_count()} breaker-open)")
            if failed_over:
                with self._lock:
                    self.failovers += 1
                if metrics is not None:
                    metrics.record_failover()
                Log.warning(
                    f"serving model '{self.name}': failing over to "
                    f"replica {rep.index}")
            rep._acquire_slot()
            ok = False
            try:
                site = f"serving_replica_predict[{self.name}:{rep.index}]"

                def _one_attempt(_rep=rep):
                    # registered fault site: the per-replica device
                    # dispatch boundary (chaos kills land here)
                    faults.inject("serving_replica_predict")
                    if attempt_fn is not None:
                        return attempt_fn(_rep)
                    return engine.predict_raw(_rep.forest, bins,
                                              metrics=metrics)

                with global_timer.timeit("serve_replica_dispatch"):
                    raw = retry_call(
                        _one_attempt,
                        attempts=retry_attempts,
                        backoff_ms=retry_backoff_ms,
                        backoff_max_ms=retry_backoff_max_ms,
                        site=site,
                        on_retry=(metrics.record_retry
                                  if metrics is not None else None))
                if not np.all(np.isfinite(raw)):
                    raise NonFiniteScores(
                        f"replica {rep.index} of '{self.name}' returned "
                        f"non-finite scores")
                ok = True
            except NonFiniteScores as exc:
                from ..reliability import counters
                counters.inc("guard_trips")
                if metrics is not None:
                    metrics.record_guard_trip()
                rep.breaker.record_failure()
                Log.warning(f"serving model '{self.name}': {exc}; "
                            f"breaker records failure on replica "
                            f"{rep.index}")
                tried.add(rep.index)
                failed_over = True
                continue
            except Exception as exc:
                rep.breaker.record_failure()
                Log.warning(
                    f"serving model '{self.name}': replica {rep.index} "
                    f"device predict failed ({exc}); breaker "
                    f"{rep.breaker.state}")
                tried.add(rep.index)
                failed_over = True
                continue
            finally:
                rep._release_slot(ok)
            rep.breaker.record_success()
            return raw

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            failovers = self.failovers
        reps = [r.snapshot() for r in self._replicas]
        return {
            "replicas": reps,
            "replica_count": len(self._replicas),
            "breaker_open_replicas": sum(
                1 for r in reps if r["state"] != "closed"),
            "failovers": failovers,
        }
