"""The serving facade: registry + bucket cache + micro-batcher + metrics.

    server = Server(max_batch_size=512, max_wait_ms=2.0)
    server.load_model("clf", booster=bst)          # one-time device load
    probs = server.predict("clf", X)               # == bst.predict(X)
    print(json.dumps(server.metrics_snapshot()))

Request path: `predict` bins the rows on the host (cheap integer
quantization), submits them to the model's `MicroBatcher`, and blocks
on the Future; the batcher worker coalesces concurrent requests into
one device dispatch through the shared `BucketedPredictor`. Responses
are converted to output space host-side, so results match
`Booster.predict` (device accumulation is f32; see tests for the
tolerance contract, and the padded-row test for the bit-identity of
bucket padding itself).

Degradation ladder: unsupported model -> host path from the start;
device dispatch raises -> that request is served by the host path, the
entry is marked degraded, and later requests skip the device until a
`refresh_model`. Overload -> `OverloadError` before any work is done.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from ..reliability import counters, retry_call
from ..utils.log import Log, LightGBMError
from ..utils.timer import global_timer
from .batcher import BatcherClosed, MicroBatcher, OverloadError
from .engine import BucketedPredictor, max_compilations
from .metrics import timer_totals
from .registry import ModelEntry, ModelRegistry

__all__ = ["Server", "OverloadError"]


class Server:
    """TPU-resident inference server for LightGBM boosters."""

    def __init__(self, *, max_batch_size: int = 1024,
                 max_wait_ms: float = 2.0, max_queue: int = 128,
                 min_bucket: int = 16, max_bucket: int = 1024,
                 max_models: int = 8, retry_attempts: int = 3,
                 retry_backoff_ms: float = 50.0,
                 retry_backoff_max_ms: float = 2000.0):
        self.registry = ModelRegistry(max_models=max_models)
        self.engine = BucketedPredictor(min_bucket=min_bucket,
                                        max_bucket=max_bucket)
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.retry_attempts = max(1, int(retry_attempts))
        self.retry_backoff_ms = float(retry_backoff_ms)
        self.retry_backoff_max_ms = float(retry_backoff_max_ms)
        self._batchers: Dict[str, MicroBatcher] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._metrics_server = None

    @classmethod
    def from_config(cls, config) -> "Server":
        """Build from a Config carrying the serve_*/retry_* parameters."""
        return cls(max_batch_size=config.serve_max_batch_size,
                   max_wait_ms=config.serve_max_wait_ms,
                   max_queue=config.serve_max_queue,
                   min_bucket=config.serve_min_bucket,
                   max_bucket=config.serve_max_bucket,
                   max_models=config.serve_max_models,
                   retry_attempts=config.retry_max_attempts,
                   retry_backoff_ms=config.retry_backoff_ms,
                   retry_backoff_max_ms=config.retry_backoff_max_ms)

    # ------------------------------------------------------------------
    # lifecycle
    def load_model(self, name: str, booster=None,
                   model_file: Optional[str] = None,
                   model_str: Optional[str] = None) -> ModelEntry:
        with global_timer.timeit("serve_model_load"):
            entry = self.registry.load(name, booster=booster,
                                       model_file=model_file,
                                       model_str=model_str)
        with self._lock:
            if self._closed:
                raise RuntimeError("server is closed")
            if name not in self._batchers:
                self._batchers[name] = MicroBatcher(
                    self._make_runner(name),
                    max_batch_size=self.max_batch_size,
                    max_wait_ms=self.max_wait_ms,
                    max_queue=self.max_queue, name=name)
        return entry

    def refresh_model(self, name: str, booster=None,
                      model_file: Optional[str] = None,
                      model_str: Optional[str] = None) -> ModelEntry:
        """Swap in a new model version; clears a degraded flag."""
        if name not in self.registry:
            raise LightGBMError(f"model '{name}' is not loaded")
        return self.load_model(name, booster=booster,
                               model_file=model_file, model_str=model_str)

    def evict_model(self, name: str) -> bool:
        with self._lock:
            batcher = self._batchers.pop(name, None)
        if batcher is not None:
            batcher.close()
        return self.registry.evict(name)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            batchers, self._batchers = dict(self._batchers), {}
            msrv, self._metrics_server = self._metrics_server, None
        if msrv is not None:
            msrv.close()
        for b in batchers.values():
            b.close()
        for name in self.registry.names():
            self.registry.evict(name)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    # request path
    def predict(self, name: str, X, raw_score: bool = False,
                timeout: Optional[float] = None) -> np.ndarray:
        """Score one request; blocks until its coalesced batch lands.

        Matches `Booster.predict(X, raw_score=raw_score)` output shape
        and values. Raises OverloadError when shed by admission
        control."""
        return self.predict_async(name, X, raw_score=raw_score) \
            .result(timeout=timeout)

    def predict_async(self, name: str, X,
                      raw_score: bool = False) -> Future:
        """Non-blocking predict: a Future of the converted scores."""
        entry = self.registry.get(name)
        t0 = time.perf_counter()
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        out: Future = Future()
        if not entry.forest.supported or entry.degraded:
            self._host_resolve(entry, X, raw_score, t0, out)
            return out
        with global_timer.timeit("serve_bin_rows"):
            bins = entry.forest.bin_rows(X)
        with self._lock:
            batcher = self._batchers.get(name)
        if batcher is None:
            # model evicted between registry.get and here: the entry is
            # still alive in our hands, serve it on the host path
            self._host_resolve(entry, X, raw_score, t0, out)
            return out
        try:
            raw_future = batcher.submit(bins)
        except OverloadError:
            entry.metrics.record_shed()
            raise
        def _finish(fut: Future) -> None:
            try:
                raw = fut.result()
            except BatcherClosed:
                # graceful shutdown drain: the queue is going away, the
                # model is fine — serve this request on the host path
                # without degrading the entry
                Log.info(
                    f"serving model '{name}': draining request through "
                    f"host predict on batcher shutdown")
                self._host_resolve(entry, X, raw_score, t0, out)
                return
            except Exception as exc:
                # device failure: degrade this entry to the host path
                entry.degraded = True
                entry.metrics.record_error()
                Log.warning(
                    f"serving model '{name}': device predict failed "
                    f"({exc}); falling back to host predict")
                self._host_resolve(entry, X, raw_score, t0, out)
                return
            if not np.all(np.isfinite(raw)):
                # numeric guard rail: non-finite device scores never
                # reach a caller — recompute on the host and degrade
                # the entry (a deterministic forest would reproduce
                # the bad output on every later dispatch)
                entry.degraded = True
                entry.metrics.record_guard_trip()
                counters.inc("guard_trips")
                Log.warning(
                    f"serving model '{name}': non-finite device scores; "
                    f"falling back to host predict")
                self._host_resolve(entry, X, raw_score, t0, out)
                return
            try:
                res = entry.forest.convert_raw(raw, raw_score=raw_score)
            except Exception as exc:
                out.set_exception(exc)
                return
            entry.metrics.record_request(len(X), time.perf_counter() - t0)
            out.set_result(res)
        raw_future.add_done_callback(_finish)
        return out

    def _host_resolve(self, entry: ModelEntry, X: np.ndarray,
                      raw_score: bool, t0: float, out: Future) -> None:
        """Serve via Booster/HostModel predict (CPU fallback path)."""
        try:
            with global_timer.timeit("serve_host_fallback"):
                res = entry.booster.predict(X, raw_score=raw_score)
        except Exception as exc:
            entry.metrics.record_error()
            out.set_exception(exc)
            return
        entry.metrics.record_request(len(X), time.perf_counter() - t0,
                                     fallback=True)
        counters.inc("fallbacks")
        out.set_result(res)

    def _make_runner(self, name: str):
        def run(bins: np.ndarray) -> np.ndarray:
            entry = self.registry.get(name)
            # transient device faults get capped-exponential-backoff
            # retries before the degradation ladder (host fallback)
            # takes over; each retry is visible in the model's metrics
            return retry_call(
                self.engine.predict_raw, entry.forest, bins,
                metrics=entry.metrics,
                attempts=self.retry_attempts,
                backoff_ms=self.retry_backoff_ms,
                backoff_max_ms=self.retry_backoff_max_ms,
                site=f"serving_device_predict[{name}]",
                on_retry=entry.metrics.record_retry)
        return run

    # test/ops hook: the model's queue (pause/resume/queue_depth)
    def batcher(self, name: str) -> MicroBatcher:
        with self._lock:
            return self._batchers[name]

    # ------------------------------------------------------------------
    # metrics
    def metrics_snapshot(self, name: Optional[str] = None) -> Dict:
        """JSON-able snapshot: per-model request metrics + engine-wide
        bucket-cache counters + serve_* timer phase totals."""
        names = [name] if name is not None else self.registry.names()
        models = {}
        for nm in names:
            entry = self.registry.get(nm)
            snap = entry.metrics.snapshot()
            snap.update(self.engine.counters_for(entry.forest))
            snap["version"] = entry.version
            snap["degraded"] = entry.degraded
            snap["device_resident"] = entry.forest.supported
            with self._lock:
                batcher = self._batchers.get(nm)
            if batcher is not None:
                snap["queue_depth"] = batcher.queue_depth()
                snap["coalesced_batches"] = batcher.batch_count
                snap["coalesced_requests"] = batcher.coalesced_requests
            models[nm] = snap
        return {
            "models": models,
            "engine": {
                "compile_count": self.engine.compile_count,
                "bucket_cache_hits": self.engine.hit_count,
                "device_batches": self.engine.device_batches,
                "min_bucket": self.engine.min_bucket,
                "max_bucket": self.engine.max_bucket,
                "max_compilations_per_model":
                    max_compilations(self.engine.max_bucket),
            },
            "timers": timer_totals(),
        }

    def save_metrics(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.metrics_snapshot(), fh, indent=2)
            fh.write("\n")

    def prometheus_text(self) -> str:
        """Prometheus text-exposition (0.0.4) body: per-model request
        metrics (label model="<name>"), engine-wide bucket-cache
        counters, serve timers, plus the process-global observability
        registry (training telemetry, compiles, MFU, reliability)."""
        from ..observability import registry as _obs
        from ..observability.export import render_prometheus
        snap = self.metrics_snapshot()
        sections = [(m, "lightgbm_tpu_serving_model", {"model": nm})
                    for nm, m in snap["models"].items()]
        sections.append((snap["engine"], "lightgbm_tpu_serving_engine",
                         None))
        return render_prometheus(sections) + _obs.prometheus_text()

    def start_metrics_server(self, port: int = 0,
                             host: str = "127.0.0.1"):
        """Expose GET /metrics (Prometheus text), /healthz and
        /snapshot (JSON metrics_snapshot) on a daemon thread; port 0
        binds an ephemeral port. Returns the MetricsHTTPServer (its
        `.port`/`.url` carry the bound address); closed with the
        Server. Idempotent — a second call returns the running one."""
        from ..observability.export import MetricsHTTPServer
        with self._lock:
            if self._closed:
                raise RuntimeError("server is closed")
            if self._metrics_server is None:
                self._metrics_server = MetricsHTTPServer(
                    self.prometheus_text, self.metrics_snapshot,
                    host=host, port=port)
                Log.info("serving metrics at %s",
                         self._metrics_server.url)
            srv = self._metrics_server
        return srv
