"""The serving facade: registry + replicas + micro-batcher + metrics.

    server = Server(max_batch_size=512, max_wait_ms=2.0, slo_ms=10.0)
    server.load_model("clf", booster=bst)          # one-time device load
    probs = server.predict("clf", X)               # == bst.predict(X)
    server.hot_swap("clf", booster=bst2)           # under live traffic
    print(json.dumps(server.metrics_snapshot()))

Request path: `predict` bins the rows on the host (cheap integer
quantization), submits them to the model entry's `MicroBatcher` with
the request's SLO deadline, and blocks on the Future; the batcher
worker coalesces concurrent requests into one dispatch that the
entry's `ReplicaSet` routes to the least-loaded healthy replica.
Responses are converted to output space host-side, so results match
`Booster.predict` (device accumulation is f32; see tests for the
tolerance contract, and the padded-row test for the bit-identity of
bucket padding itself).

Degradation ladder (docs/Serving.md): deadline shed at admission ->
per-replica capped-backoff retries -> breaker opens on consecutive
failures and traffic fails over to the next replica -> every replica
open means host predict answers. No rung drops a request, and the
breakers self-heal (half-open probe, auto-close) — there is no sticky
degraded flag anymore.

Hot-swap: `hot_swap` builds the new entry completely (replicas placed,
batcher running), publishes it atomically, then drains the OLD entry's
queue — each queued future resolves `BatcherClosed` and is re-answered
through the old entry's host path (same binning, no torn model, no
drop). In-flight device batches finish against the old arrays, which
JAX keeps alive until the last reference drops.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from ..reliability import counters, faults
from ..utils.log import Log, LightGBMError
from ..utils.timer import global_timer
from .batcher import (SCHEDULERS, BatcherClosed, DeadlineExceeded,
                      MicroBatcher, OverloadError)
from .engine import BucketedPredictor, max_compilations
from .metrics import timer_totals
from .registry import ModelEntry, ModelRegistry
from .replicas import NoReplicaAvailable, ReplicaSet

__all__ = ["Server", "OverloadError", "DeadlineExceeded"]

#: what the caller sees when a request's SLO budget cannot be met:
#: "fallback" answers it via host predict (still counted as a
#: deadline miss), "fail" raises DeadlineExceeded fast
DEADLINE_POLICIES = ("fallback", "fail")


class Server:
    """TPU-resident inference server for LightGBM boosters."""

    def __init__(self, *, max_batch_size: int = 1024,
                 max_wait_ms: float = 2.0, max_queue: int = 128,
                 min_bucket: int = 16, max_bucket: int = 1024,
                 max_models: int = 8, retry_attempts: int = 3,
                 retry_backoff_ms: float = 50.0,
                 retry_backoff_max_ms: float = 2000.0,
                 slo_ms: float = 0.0, deadline_policy: str = "fallback",
                 n_replicas: int = 1, breaker_threshold: int = 3,
                 breaker_cooldown_ms: float = 250.0,
                 scheduler: str = "slo", pack_size: int = 8):
        if deadline_policy not in DEADLINE_POLICIES:
            raise ValueError(
                f"deadline_policy must be one of {DEADLINE_POLICIES}")
        if scheduler not in SCHEDULERS:
            raise ValueError(f"scheduler must be one of {SCHEDULERS}")
        if pack_size < 1:
            raise ValueError("pack_size must be >= 1")
        self.engine = BucketedPredictor(min_bucket=min_bucket,
                                        max_bucket=max_bucket)
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.retry_attempts = max(1, int(retry_attempts))
        self.retry_backoff_ms = float(retry_backoff_ms)
        self.retry_backoff_max_ms = float(retry_backoff_max_ms)
        self.slo_ms = float(slo_ms)          # 0 disables deadlines
        self.deadline_policy = deadline_policy
        self.n_replicas = int(n_replicas)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_ms = float(breaker_cooldown_ms)
        self.scheduler = scheduler
        self.pack_size = int(pack_size)
        self.registry = ModelRegistry(
            max_models=max_models,
            replica_factory=self._build_replicas,
            batcher_factory=self._build_batcher,
            pack_batcher_factory=self._build_pack_batcher)
        self._lock = threading.Lock()
        self._closed = False
        self._metrics_server = None

    @classmethod
    def from_config(cls, config) -> "Server":
        """Build from a Config carrying the serve_*/retry_* parameters."""
        return cls(max_batch_size=config.serve_max_batch_size,
                   max_wait_ms=config.serve_max_wait_ms,
                   max_queue=config.serve_max_queue,
                   min_bucket=config.serve_min_bucket,
                   max_bucket=config.serve_max_bucket,
                   max_models=config.serve_max_models,
                   retry_attempts=config.retry_max_attempts,
                   retry_backoff_ms=config.retry_backoff_ms,
                   retry_backoff_max_ms=config.retry_backoff_max_ms,
                   slo_ms=config.serve_slo_ms,
                   deadline_policy=config.serve_deadline_policy,
                   n_replicas=config.serve_replicas,
                   breaker_threshold=config.serve_breaker_threshold,
                   breaker_cooldown_ms=config.serve_breaker_cooldown_ms,
                   scheduler=config.serve_scheduler,
                   pack_size=config.serve_pack_size)

    # ------------------------------------------------------------------
    # registry factories: each entry owns its replica fleet + batcher
    def _build_replicas(self, forest, name: str) -> ReplicaSet:
        return ReplicaSet.build(
            forest, self.n_replicas, name=name,
            breaker_threshold=self.breaker_threshold,
            breaker_cooldown_ms=self.breaker_cooldown_ms)

    def _build_batcher(self, entry: ModelEntry) -> MicroBatcher:
        return MicroBatcher(
            self._make_runner(entry),
            max_batch_size=self.max_batch_size,
            max_wait_ms=self.max_wait_ms,
            max_queue=self.max_queue, name=entry.name,
            scheduler=self.scheduler)

    def _build_pack_batcher(self, pe):
        from .multimodel import PackBatcher
        return PackBatcher(
            self._make_pack_runner(pe),
            max_batch_size=self.max_batch_size,
            max_wait_ms=self.max_wait_ms,
            max_queue=self.max_queue, name=pe.name,
            scheduler=self.scheduler)

    def _make_runner(self, entry: ModelEntry):
        # closes over the ENTRY, not the name: a hot-swap can never
        # route this batcher's queued bins to a different forest
        def run(bins: np.ndarray) -> np.ndarray:
            if entry.replicas is None or len(entry.replicas) == 0:
                raise NoReplicaAvailable(
                    f"model '{entry.name}' has no device replicas")
            return entry.replicas.dispatch(
                self.engine, bins, metrics=entry.metrics,
                retry_attempts=self.retry_attempts,
                retry_backoff_ms=self.retry_backoff_ms,
                retry_backoff_max_ms=self.retry_backoff_max_ms)
        return run

    def _make_pack_runner(self, pe):
        # closes over the PackEntry: a pack rebuild publishes a new
        # entry with a new batcher+runner, so queued (slot, bins) can
        # never score against a different pack layout
        from .multimodel import dispatch_pack

        def run(reqs) -> np.ndarray:
            if pe.replicas is None or len(pe.replicas) == 0:
                raise NoReplicaAvailable(
                    f"pack '{pe.name}' has no device replicas")

            def attempt(rep):
                return dispatch_pack(self.engine, rep.forest, reqs,
                                     metrics_by_slot=pe.slot_metrics,
                                     pack_metrics=pe.metrics)

            return pe.replicas.dispatch(
                self.engine, None, metrics=pe.metrics,
                attempt_fn=attempt,
                retry_attempts=self.retry_attempts,
                retry_backoff_ms=self.retry_backoff_ms,
                retry_backoff_max_ms=self.retry_backoff_max_ms)
        return run

    # ------------------------------------------------------------------
    # lifecycle
    def load_model(self, name: str, booster=None,
                   model_file: Optional[str] = None,
                   model_str: Optional[str] = None) -> ModelEntry:
        with self._lock:
            if self._closed:
                raise RuntimeError("server is closed")
        with global_timer.timeit("serve_model_load"):
            entry = self.registry.load(name, booster=booster,
                                       model_file=model_file,
                                       model_str=model_str)
        return entry

    def load_pack(self, pack_name: str, members):
        """Load several models as fused multi-model packs.

        `members` is a sequence of ``(name, booster)`` pairs (or
        ``(name, {"model_file": ...})`` dicts). Members are packed in
        chunks of at most `pack_size`; chunk ``i > 0`` gets the pack
        name ``f"{pack_name}/{i}"``. Each member still answers
        `predict(name, ...)` under its own name — packing only changes
        HOW the device dispatch happens (one fused launch for the
        whole pack instead of one per model). Returns the member
        entries in input order."""
        with self._lock:
            if self._closed:
                raise RuntimeError("server is closed")
        members = list(members)
        entries = []
        with global_timer.timeit("serve_model_load"):
            for i in range(0, len(members), self.pack_size):
                chunk = members[i:i + self.pack_size]
                nm = pack_name if i == 0 else \
                    f"{pack_name}/{i // self.pack_size}"
                entries.extend(self.registry.load_pack(nm, chunk))
        return entries

    def hot_swap(self, name: str, booster=None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None) -> ModelEntry:
        """Zero-downtime model swap under live traffic.

        Builds the replacement entry fully (device replicas placed,
        fresh breakers closed, batcher worker running), publishes it
        atomically, then closes the old entry's batcher WITHOUT
        dispatching its queue — those futures resolve `BatcherClosed`
        and the server re-answers each through the OLD entry's host
        path (`swap_drains` in metrics). New requests route to the new
        entry the moment it is published; in-flight device batches
        finish against the old arrays. No request is dropped or served
        by a torn model."""
        with self._lock:
            if self._closed:
                raise RuntimeError("server is closed")
        if name not in self.registry:
            raise LightGBMError(f"model '{name}' is not loaded")
        with global_timer.timeit("serve_hot_swap"):
            # registered fault site: a swap that dies mid-way must
            # leave the old entry serving (docs/Reliability.md)
            faults.inject("serving_hot_swap")
            entry, prev = self.registry._load_prepared(
                name, booster=booster, model_file=model_file,
                model_str=model_str)
            # registered fault site, the other side of the commit
            # point: the NEW entry is already published, so a kill here
            # must leave the new model serving with the old batcher's
            # queue drained by the recovery path, never a torn registry
            faults.inject("serving_hot_swap_commit")
            drained = self.registry._drain_replaced(prev)
        Log.info(f"serving: hot-swapped '{name}' to v{entry.version} "
                 f"({drained} queued requests drained via host)")
        return entry

    def refresh_model(self, name: str, booster=None,
                      model_file: Optional[str] = None,
                      model_str: Optional[str] = None) -> ModelEntry:
        """Swap in a new model version (alias of `hot_swap`; breakers
        start closed on the new entry's replicas)."""
        return self.hot_swap(name, booster=booster,
                             model_file=model_file, model_str=model_str)

    def evict_model(self, name: str) -> bool:
        return self.registry.evict(name)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            msrv, self._metrics_server = self._metrics_server, None
        if msrv is not None:
            msrv.close()
        for name in self.registry.names():
            self.registry.evict(name)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    # request path
    def predict(self, name: str, X, raw_score: bool = False,
                timeout: Optional[float] = None,
                slo_ms: Optional[float] = None) -> np.ndarray:
        """Score one request; blocks until its coalesced batch lands.

        Matches `Booster.predict(X, raw_score=raw_score)` output shape
        and values. Raises OverloadError when shed by admission
        control; DeadlineExceeded when the SLO budget is blown and the
        deadline policy is "fail"."""
        try:
            return self.predict_async(name, X, raw_score=raw_score,
                                      slo_ms=slo_ms) \
                .result(timeout=timeout)
        except (OverloadError, DeadlineExceeded, LightGBMError):
            raise                       # protocol outcomes, not crashes
        except Exception as exc:
            # serving fatal: an unhandled error escaping the request
            # path gets a post-mortem like training fatals do
            from ..observability.flightrec import recorder
            recorder.record_exception(f"serving.predict[{name}]", exc)
            recorder.flush("exception")
            raise

    def predict_async(self, name: str, X, raw_score: bool = False,
                      slo_ms: Optional[float] = None) -> Future:
        """Non-blocking predict: a Future of the converted scores.

        `slo_ms` overrides the server-wide SLO budget for this request
        (0 disables the deadline)."""
        entry = self.registry.get(name)
        t0 = time.perf_counter()
        budget_ms = self.slo_ms if slo_ms is None else float(slo_ms)
        deadline = (time.monotonic() + budget_ms / 1e3) \
            if budget_ms > 0 else None
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        out: Future = Future()
        if entry.degraded:
            # unsupported forest, or every replica breaker open with
            # cooldowns pending: the bottom rung answers directly
            self._host_resolve(entry, X, raw_score, t0, out)
            return out
        with global_timer.timeit("serve_bin_rows"):
            bins = entry.forest.bin_rows(X)
        # pack members share the PACK's slot-aware queue; solo models
        # keep their own
        batcher = entry.batcher if entry.pack is None \
            else entry.pack.batcher
        if batcher is None:
            self._host_resolve(entry, X, raw_score, t0, out)
            return out
        try:
            raw_future = batcher.submit(
                bins, deadline=deadline,
                slot=entry.pack_slot if entry.pack is not None else None)
        except OverloadError:
            entry.metrics.record_shed()
            raise
        except DeadlineExceeded:
            # admission projection says the queue cannot make the
            # budget: answer NOW per policy instead of queueing a
            # request that would expire
            entry.metrics.record_deadline_miss()
            if self.deadline_policy == "fail":
                raise
            self._host_resolve(entry, X, raw_score, t0, out)
            return out
        except BatcherClosed:
            # lost the race with a concurrent hot-swap/evict closing
            # this entry's batcher: the entry in hand still answers
            self._host_resolve(entry, X, raw_score, t0, out)
            return out

        def _finish(fut: Future) -> None:
            try:
                raw = fut.result()
            except BatcherClosed:
                # hot-swap/shutdown drain: the queue went away, the
                # model is fine — answer through THIS entry's host
                # path (same binning as the queued bins; no torn model)
                Log.info(
                    f"serving model '{name}': draining request through "
                    f"host predict on batcher shutdown")
                self._host_resolve(entry, X, raw_score, t0, out)
                return
            except DeadlineExceeded as exc:
                # expired while queued (service time spiked after
                # admission let it in)
                entry.metrics.record_deadline_miss()
                if self.deadline_policy == "fail":
                    out.set_exception(exc)
                    return
                self._host_resolve(entry, X, raw_score, t0, out)
                return
            except NoReplicaAvailable:
                # every replica breaker refused this batch: the host
                # answers while the cooldowns run; breakers will probe
                # and self-heal on the next dispatches
                self._host_resolve(entry, X, raw_score, t0, out)
                return
            except Exception as exc:
                # unexpected failure past retries+failover: the host
                # still answers, and it is counted as an error
                entry.metrics.record_error()
                Log.warning(
                    f"serving model '{name}': device predict failed "
                    f"({exc}); falling back to host predict")
                self._host_resolve(entry, X, raw_score, t0, out)
                return
            try:
                if entry.pack is not None:
                    # the fused kernel scores into the pack's padded
                    # output width; this member's columns come first
                    raw = raw[:, :entry.forest.num_outputs]
                res = entry.forest.convert_raw(raw, raw_score=raw_score)
            except Exception as exc:
                out.set_exception(exc)
                return
            entry.metrics.record_request(len(X), time.perf_counter() - t0)
            out.set_result(res)
        raw_future.add_done_callback(_finish)
        return out

    def _host_resolve(self, entry: ModelEntry, X: np.ndarray,
                      raw_score: bool, t0: float, out: Future) -> None:
        """Serve via Booster/HostModel predict (CPU fallback path)."""
        try:
            with global_timer.timeit("serve_host_fallback"):
                res = entry.booster.predict(X, raw_score=raw_score)
        except Exception as exc:
            entry.metrics.record_error()
            out.set_exception(exc)
            return
        entry.metrics.record_request(len(X), time.perf_counter() - t0,
                                     fallback=True)
        counters.inc("fallbacks")
        out.set_result(res)

    # test/ops hook: the model's queue (pause/resume/queue_depth);
    # pack members answer with the pack's shared queue
    def batcher(self, name: str) -> MicroBatcher:
        entry = self.registry.get(name)
        return entry.batcher if entry.pack is None \
            else entry.pack.batcher

    # test/ops hook: the model's replica fleet (breakers, failovers)
    def replicas(self, name: str) -> ReplicaSet:
        entry = self.registry.get(name)
        return entry.replicas if entry.pack is None \
            else entry.pack.replicas

    # ------------------------------------------------------------------
    # metrics
    def metrics_snapshot(self, name: Optional[str] = None) -> Dict:
        """JSON-able snapshot: per-model request metrics + per-replica
        breaker state + engine-wide bucket-cache counters + serve_*
        timer phase totals."""
        names = [name] if name is not None else self.registry.names()
        models = {}
        for nm in names:
            entry = self.registry.get(nm)
            snap = entry.metrics.snapshot()
            snap.update(self.engine.counters_for(entry.forest))
            snap["version"] = entry.version
            snap["degraded"] = entry.degraded
            snap["device_resident"] = entry.forest.supported
            if entry.pack is not None:
                snap["pack"] = entry.pack.name
                snap["pack_slot"] = entry.pack_slot
            if entry.replicas is not None:
                rsnap = entry.replicas.snapshot()
                snap["replica_count"] = rsnap["replica_count"]
                snap["breaker_open_replicas"] = \
                    rsnap["breaker_open_replicas"]
                snap["replicas"] = rsnap["replicas"]
            batcher = entry.batcher
            if batcher is not None:
                snap["queue_depth"] = batcher.queue_depth()
                snap["coalesced_batches"] = batcher.batch_count
                snap["coalesced_requests"] = batcher.coalesced_requests
                snap["deadline_shed_count"] = batcher.deadline_shed_count
                snap["deadline_expired_count"] = \
                    batcher.deadline_expired_count
            models[nm] = snap
        packs = {}
        for pname, pe in self.registry.packs().items():
            psnap = pe.metrics.snapshot()
            psnap["version"] = pe.version
            psnap["members"] = list(pe.member_names())
            psnap["num_slots"] = pe.pack.num_slots
            psnap["num_trees"] = pe.pack.num_trees
            if pe.replicas is not None:
                rsnap = pe.replicas.snapshot()
                psnap["replica_count"] = rsnap["replica_count"]
                psnap["breaker_open_replicas"] = \
                    rsnap["breaker_open_replicas"]
            if pe.batcher is not None:
                psnap["inflight"] = pe.batcher.queue_depth()
                psnap["coalesced_batches"] = pe.batcher.batch_count
                psnap["interleaves"] = pe.batcher.interleave_count
                psnap["deadline_shed_count"] = \
                    pe.batcher.deadline_shed_count
                psnap["deadline_expired_count"] = \
                    pe.batcher.deadline_expired_count
            packs[pname] = psnap
        return {
            "models": models,
            "packs": packs,
            "engine": {
                "pack_rebuilds": self.registry.pack_rebuilds,
                "compile_count": self.engine.compile_count,
                "bucket_cache_hits": self.engine.hit_count,
                "device_batches": self.engine.device_batches,
                "min_bucket": self.engine.min_bucket,
                "max_bucket": self.engine.max_bucket,
                "max_compilations_per_model":
                    max_compilations(self.engine.max_bucket),
            },
            "timers": timer_totals(),
        }

    def save_metrics(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.metrics_snapshot(), fh, indent=2)
            fh.write("\n")

    def prometheus_text(self) -> str:
        """Prometheus text-exposition (0.0.4) body: per-model request
        metrics (label model="<name>"), per-replica breaker gauges
        (labels model=, replica=), engine-wide bucket-cache counters,
        serve timers, plus the process-global observability registry
        (training telemetry, compiles, MFU, reliability)."""
        from ..observability import registry as _obs
        from ..observability.export import render_prometheus
        snap = self.metrics_snapshot()
        sections = []
        for nm, m in snap["models"].items():
            reps = m.pop("replicas", [])
            sections.append((m, "lightgbm_tpu_serving_model",
                             {"model": nm}))
            for rep in reps:
                sections.append((
                    {"breaker_state": rep["state_code"],
                     "breaker_opens": rep["opens"],
                     "breaker_closes": rep["closes"],
                     "breaker_probes": rep["probes"],
                     "inflight": rep["inflight"],
                     "dispatches": rep["dispatches"],
                     "failures": rep["failures"]},
                    "lightgbm_tpu_serving_replica",
                    {"model": nm, "replica": str(rep["replica"])}))
        for pname, p in snap.get("packs", {}).items():
            p = dict(p)
            p.pop("members", None)
            sections.append((p, "lightgbm_tpu_multimodel",
                             {"pack": pname}))
        sections.append((snap["engine"], "lightgbm_tpu_serving_engine",
                         None))
        return render_prometheus(sections) + _obs.prometheus_text()

    def start_metrics_server(self, port: int = 0,
                             host: str = "127.0.0.1"):
        """Expose GET /metrics (Prometheus text), /healthz and
        /snapshot (JSON metrics_snapshot) on a daemon thread; port 0
        binds an ephemeral port. Returns the MetricsHTTPServer (its
        `.port`/`.url` carry the bound address); closed with the
        Server. Idempotent — a second call returns the running one."""
        from ..observability.export import MetricsHTTPServer
        with self._lock:
            if self._closed:
                raise RuntimeError("server is closed")
            if self._metrics_server is None:
                self._metrics_server = MetricsHTTPServer(
                    self.prometheus_text, self.metrics_snapshot,
                    host=host, port=port)
                Log.info("serving metrics at %s",
                         self._metrics_server.url)
            srv = self._metrics_server
        return srv
