"""SHAP feature contributions (TreeSHAP).

Reference: Tree::PredictContrib / TreeSHAP recursion in src/io/tree.cpp
(Lundberg & Lee algorithm; `PredictContrib` path from c_api predict with
predict_contrib=true). Host NumPy implementation over HostTree — prediction
contributions are an offline/analysis path, not a training hot loop.
Output layout matches the reference: [n, (num_features + 1) * k] with the
expected value in the last slot per class.
"""

from __future__ import annotations

import numpy as np

__all__ = ["tree_shap_model", "tree_shap_single"]


def _tree_expected_value(tree) -> float:
    """Weighted average of leaf values (used as the base value)."""
    w = tree.leaf_weight if tree.leaf_weight.sum() > 0 else \
        np.maximum(tree.leaf_count, 1)
    return float((tree.leaf_value * w).sum() / w.sum())


def tree_shap_single(tree, x: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate SHAP values of one tree into phi [num_features + 1]."""
    # node cover (weight reaching each node)
    ni = tree.num_leaves - 1
    if ni <= 0:
        phi[-1] += float(tree.leaf_value[0])
        return

    leaf_w = tree.leaf_weight if tree.leaf_weight.sum() > 0 else \
        np.maximum(tree.leaf_count, 1).astype(np.float64)
    internal_w = np.zeros(ni)

    def node_weight(i):
        if i < 0:
            return float(leaf_w[~i])
        if internal_w[i] == 0:
            internal_w[i] = node_weight(int(tree.left_child[i])) + \
                node_weight(int(tree.right_child[i]))
        return internal_w[i]

    node_weight(0)

    def node_value(i):
        if i < 0:
            return float(tree.leaf_value[~i])
        wl = node_weight(int(tree.left_child[i]))
        wr = node_weight(int(tree.right_child[i]))
        return (node_value(int(tree.left_child[i])) * wl +
                node_value(int(tree.right_child[i])) * wr) / (wl + wr)

    # Path-dependent TreeSHAP (EXTEND/UNWIND recursion)
    class Path:
        __slots__ = ("d", "z", "o", "w")

        def __init__(self, depth):
            self.d = np.zeros(depth, np.int32)
            self.z = np.zeros(depth)
            self.o = np.zeros(depth)
            self.w = np.zeros(depth)

    def extend(p, length, pz, po, pi):
        p.d[length] = pi
        p.z[length] = pz
        p.o[length] = po
        p.w[length] = 1.0 if length == 0 else 0.0
        for i in range(length - 1, -1, -1):
            p.w[i + 1] += po * p.w[i] * (i + 1) / (length + 1)
            p.w[i] = pz * p.w[i] * (length - i) / (length + 1)

    def unwind(p, length, path_index):
        one = p.o[path_index]
        n = p.w[length]
        for j in range(length - 1, -1, -1):
            if one != 0:
                t = p.w[j]
                p.w[j] = n * (length + 1) / ((j + 1) * one)
                n = t - p.w[j] * p.z[path_index] * (length - j) / (length + 1)
            else:
                p.w[j] = p.w[j] * (length + 1) / \
                    (p.z[path_index] * (length - j))
        for j in range(path_index, length):
            p.d[j] = p.d[j + 1]
            p.z[j] = p.z[j + 1]
            p.o[j] = p.o[j + 1]

    def unwound_sum(p, length, path_index):
        one = p.o[path_index]
        total = 0.0
        n = p.w[length]
        for j in range(length - 1, -1, -1):
            if one != 0:
                t = n * (length + 1) / ((j + 1) * one)
                total += t
                n = p.w[j] - t * p.z[path_index] * (length - j) / (length + 1)
            else:
                total += p.w[j] / (p.z[path_index] * (length - j) /
                                   (length + 1))
        return total

    max_depth = tree.num_leaves + 2

    def decide_left(i, xv) -> bool:
        f = int(tree.split_feature[i])
        v = xv[f]
        dt = int(tree.decision_type[i])
        if dt & 1:  # categorical
            if not np.isfinite(v) or v < 0:
                return False
            iv = int(v)
            c = int(tree.threshold[i])
            lo, hi = tree.cat_boundaries[c], tree.cat_boundaries[c + 1]
            word = iv // 32
            if word < hi - lo:
                return bool((int(tree.cat_threshold[lo + word]) >>
                             (iv % 32)) & 1)
            return False
        missing_t = (dt >> 2) & 3
        if np.isnan(v):
            if missing_t == 2:
                return bool(dt & 2)
            v = 0.0
        if missing_t == 1 and abs(v) <= 1e-35:
            return bool(dt & 2)
        return v <= tree.threshold[i]

    def recurse(i, xv, p, length, pz, po, pf):
        p2 = Path(max_depth)
        p2.d[:length] = p.d[:length]
        p2.z[:length] = p.z[:length]
        p2.o[:length] = p.o[:length]
        p2.w[:length] = p.w[:length]
        extend(p2, length, pz, po, pf)
        length += 1
        if i < 0:
            for j in range(1, length):
                w = unwound_sum(p2, length - 1, j)
                phi[p2.d[j]] += w * (p2.o[j] - p2.z[j]) * \
                    float(tree.leaf_value[~i])
            return
        f = int(tree.split_feature[i])
        hot = int(tree.left_child[i]) if decide_left(i, xv) \
            else int(tree.right_child[i])
        cold = int(tree.right_child[i]) if decide_left(i, xv) \
            else int(tree.left_child[i])
        w_all = node_weight(i)
        iz, io = 1.0, 1.0
        # undo previous split on same feature
        path_index = -1
        for j in range(1, length):
            if p2.d[j] == f:
                path_index = j
                break
        if path_index >= 0:
            iz = p2.z[path_index]
            io = p2.o[path_index]
            unwind(p2, length - 1, path_index)
            length -= 1
        recurse(hot, xv, p2, length, iz * node_weight(hot) / w_all, io, f)
        recurse(cold, xv, p2, length, iz * node_weight(cold) / w_all, 0.0, f)

    phi[-1] += node_value(0)
    recurse(0, x, Path(max_depth), 0, 1.0, 1.0, -1)


def tree_shap_model(model, X: np.ndarray, start_iteration: int,
                    end_iteration: int) -> np.ndarray:
    k = max(model.num_tree_per_iteration, 1)
    n, nf_x = X.shape
    nf = max(model.max_feature_idx + 1, nf_x)
    out = np.zeros((n, k, nf + 1), np.float64)
    for ti in range(start_iteration * k, end_iteration * k):
        cls = model.tree_class[ti] if ti < len(model.tree_class) else ti % k
        tree = model.trees[ti]
        for r in range(n):
            phi = np.zeros(nf + 1)
            if tree.num_leaves > 1:
                tree_shap_single(tree, X[r], phi)
            else:
                phi[-1] = float(tree.leaf_value[0])
            out[r, cls] += phi
    return out.reshape(n, k * (nf + 1)) if k > 1 else out[:, 0, :]
