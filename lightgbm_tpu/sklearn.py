"""scikit-learn estimator API (reference python-package/lightgbm/sklearn.py).

LGBMModel/LGBMRegressor/LGBMClassifier/LGBMRanker with the same constructor
signature and fit/predict semantics as sklearn.py:347,973,1019,1173 —
eval_set handling, early stopping via callbacks, classes_/feature
importances, pandas passthrough.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset
from .callback import early_stopping as early_stopping_cb
from .callback import log_evaluation
from .engine import train as train_fn
from .utils.log import Log

__all__ = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]


class LGBMModel:
    """Base sklearn-style estimator (reference sklearn.py:347)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None,
                 class_weight: Optional[Union[Dict, str]] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_features: Optional[int] = None
        self._classes = None
        self._n_classes = 1
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._best_score: Dict = {}
        self.set_params(**kwargs)

    # ---- sklearn plumbing --------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves, "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective, "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha, "reg_lambda": self.reg_lambda,
            "random_state": self.random_state, "n_jobs": self.n_jobs,
            "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key) and not key.startswith("_"):
                setattr(self, key, value)
            self._other_params[key] = value
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _process_params(self, stage: str) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        obj = params.pop("objective", None) or self._default_objective()
        params["objective"] = obj
        alias_map = {"boosting_type": "boosting", "subsample": "bagging_fraction",
                     "subsample_freq": "bagging_freq",
                     "colsample_bytree": "feature_fraction",
                     "min_child_samples": "min_data_in_leaf",
                     "min_child_weight": "min_sum_hessian_in_leaf",
                     "min_split_gain": "min_gain_to_split",
                     "reg_alpha": "lambda_l1", "reg_lambda": "lambda_l2",
                     "subsample_for_bin": "bin_construct_sample_cnt",
                     "random_state": "seed", "n_jobs": "num_threads"}
        for src, dst in alias_map.items():
            if src in params:
                val = params.pop(src)
                if val is not None:
                    params[dst] = val
        if params.get("seed") is None:
            params.pop("seed", None)
        params.setdefault("verbosity", -1)
        return params

    # ---- fit ----------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=False,
            feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None) -> "LGBMModel":
        params = self._process_params("fit")
        if eval_metric is not None:
            params["metric"] = eval_metric if isinstance(eval_metric, str) \
                else ",".join(m for m in eval_metric if isinstance(m, str))
        y_arr = np.asarray(y).reshape(-1)
        sw = sample_weight
        if self.class_weight is not None and self._classes is not None:
            cw = self._compute_class_weight(y_arr)
            sw = cw if sw is None else np.asarray(sw) * cw
        train_set = Dataset(X, label=y_arr, weight=sw, group=group,
                            init_score=init_score,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=params)
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                else:
                    vw = eval_sample_weight[i] \
                        if eval_sample_weight is not None else None
                    vg = eval_group[i] if eval_group is not None else None
                    vi = eval_init_score[i] \
                        if eval_init_score is not None else None
                    vy_arr = self._transform_label(np.asarray(vy).reshape(-1))
                    valid_sets.append(Dataset(
                        vx, label=vy_arr, weight=vw, group=vg, init_score=vi,
                        reference=train_set, params=params))
                valid_names.append(
                    eval_names[i] if eval_names is not None else f"valid_{i}")
        cbs = list(callbacks or [])
        if early_stopping_rounds is not None and early_stopping_rounds > 0:
            cbs.append(early_stopping_cb(early_stopping_rounds,
                                         verbose=bool(verbose)))
        if verbose and isinstance(verbose, (int, bool)) and verbose is not False:
            period = 1 if verbose is True else int(verbose)
            cbs.append(log_evaluation(period))
        self._evals_result = {}
        from .callback import record_evaluation
        cbs.append(record_evaluation(self._evals_result))
        self._Booster = train_fn(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=valid_names or None, callbacks=cbs,
            init_model=init_model)
        self._n_features = np.asarray(X).shape[1] \
            if hasattr(X, "shape") else train_set.num_feature()
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        return self

    def _transform_label(self, y):
        return y

    def _compute_class_weight(self, y):
        if self.class_weight == "balanced":
            classes, counts = np.unique(y, return_counts=True)
            weights = len(y) / (len(classes) * counts)
            lut = dict(zip(classes, weights))
        else:
            lut = dict(self.class_weight)
        return np.asarray([lut.get(v, 1.0) for v in y], np.float32)

    # ---- predict ------------------------------------------------------
    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        if self._Booster is None:
            raise ValueError("Estimator not fitted, call fit first")
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib)

    # ---- attributes ---------------------------------------------------
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise ValueError("No booster found. Need to call fit beforehand.")
        return self._Booster

    @property
    def n_features_(self) -> int:
        return self._n_features

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def best_score_(self) -> Dict:
        return self._best_score

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        return self.booster_.feature_name()


class LGBMRegressor(LGBMModel):
    """Reference sklearn.py:1019 LGBMRegressor."""

    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(LGBMModel):
    """Reference sklearn.py:973 LGBMClassifier."""

    def _default_objective(self) -> str:
        return "binary" if self._n_classes <= 2 else "multiclass"

    def fit(self, X, y, **kwargs):
        y_arr = np.asarray(y).reshape(-1)
        # _classes_override: distributed fit (dask.py) supplies the
        # GLOBAL class set so ranks whose partitions miss a class still
        # encode identically
        override = getattr(self, "_classes_override", None)
        self._classes = np.unique(y_arr) if override is None \
            else np.asarray(override)
        self._n_classes = len(self._classes)
        self._label_map = {c: i for i, c in enumerate(self._classes)}
        y_enc = np.asarray([self._label_map[v] for v in y_arr], np.float32)
        if self._n_classes > 2:
            self._other_params["num_class"] = self._n_classes
        if "eval_set" in kwargs and kwargs["eval_set"] is not None:
            pass  # labels transformed via _transform_label in base fit
        return super().fit(X, y_enc, **kwargs)

    def _transform_label(self, y):
        return np.asarray([self._label_map.get(v, 0) for v in y], np.float32)

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        result = self.predict_proba(X, raw_score, start_iteration,
                                    num_iteration, pred_leaf, pred_contrib,
                                    **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes > 2:
            idx = np.argmax(result, axis=1)
        else:
            idx = (result[:, 1] > 0.5).astype(int) if result.ndim == 2 \
                else (result > 0.5).astype(int)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      **kwargs):
        result = super().predict(X, raw_score, start_iteration,
                                 num_iteration, pred_leaf, pred_contrib,
                                 **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes <= 2 and result.ndim == 1:
            return np.column_stack([1.0 - result, result])
        return result

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes


class LGBMRanker(LGBMModel):
    """Reference sklearn.py:1173 LGBMRanker."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        eval_group = kwargs.get("eval_group")
        if kwargs.get("eval_set") is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not "
                             "None")
        return super().fit(X, y, group=group, **kwargs)
