"""Out-of-core streaming ingestion (docs/Streaming.md).

Two-pass construction for datasets larger than host memory (Histogram
Sort with Sampling, arXiv:1803.01237): pass 1 streams row chunks from a
`ChunkSource` into a per-feature `ReservoirSketch` that freezes the bin
boundaries from a bounded uniform row sample; pass 2 re-streams and
quantizes each chunk straight into the preallocated uint8/16 bin
matrix, double-buffering the next chunk's host parse against the
current chunk's binning (the ingestion analogue of the pipeline
executor's dispatch/finalize overlap).

When the sketch capacity covers the whole stream
(`stream_sample_rows >= N`) the sample IS the dataset in stream order
and the frozen boundaries — and therefore the trained model — are
byte-identical to the in-memory path.
"""

from .loader import StreamStats, build_streamed_dataset
from .sketch import ReservoirSketch
from .sources import (ArraySource, ChunkSource, CSVSource, NpySource,
                      ParquetSource, WindowSource, source_from_path)

__all__ = [
    "ArraySource", "ChunkSource", "CSVSource", "NpySource",
    "ParquetSource", "ReservoirSketch", "StreamStats", "WindowSource",
    "build_streamed_dataset", "source_from_path",
]
