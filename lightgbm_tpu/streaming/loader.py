"""Two-pass out-of-core dataset construction over a ChunkSource.

Pass 1 streams chunks into a `ReservoirSketch` (sketch.py) and collects
stream-borne labels; the frozen sample then feeds the SAME
`find_bin_mappers` call the in-memory path makes, so a covering sketch
(`stream_sample_rows >= N`) yields bit-identical bin boundaries — and a
byte-identical model. Pass 2 re-streams and quantizes each chunk
straight into the preallocated uint8/16 bin matrix, double-buffering
the NEXT chunk's host parse (a worker thread) against the CURRENT
chunk's binning (main thread) — the ingestion analogue of the pipeline
executor's dispatch/finalize overlap. Peak host memory is
O(chunk + sketch + bin matrix), never the dense [N, F] float matrix.

Array-backed sources (`source.array` set: in-memory NumPy, `.npy`
memmap) skip the sketch pass entirely — bin finding samples the matrix
directly, exactly as `BinnedDataset.from_raw` would, and pass 2 bins
zero-copy row slices. This is also the route all-numeric in-memory
input takes (no whole-matrix float64 conversion).

Mid-stream durability: with a `checkpoint_dir`, pass 1 persists the
sketch + stream cursor (and pass-1 end freezes the mappers) via the
same tmp+rename atomicity as reliability/checkpoint.py bundles, in
side files a `latest_checkpoint` scan ignores. A killed ingest resumes
pass 1 at the saved chunk with the identical RNG stream; a kill in
pass 2 skips pass 1 entirely and re-quantizes (host-only work). The
`streaming_ingest` fault site makes the kill injectable.
"""

from __future__ import annotations

import json
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..binning import BinMapper, bin_columns, find_bin_mappers
from ..data import BinnedDataset, Metadata, _select_used_features
from ..observability import registry as _obs
from ..reliability.counters import counters
from ..reliability.faults import faults
from ..utils.log import Log, LightGBMError
from .sketch import ReservoirSketch
from .sources import ChunkSource

__all__ = ["StreamStats", "build_streamed_dataset"]

_STATE_JSON = "stream_state.json"
_STATE_NPZ = "stream_state.npz"
_STATE_VERSION = 2
#: pass-1 state saves are throttled: rewriting the sketch + labels is
#: O(rows seen), so saving only after rows grow by this factor keeps
#: total checkpoint I/O O(N) over the stream instead of O(N^2/chunk);
#: a time floor bounds lost work on slow streams regardless
_SAVE_GROWTH = 1.25
_SAVE_INTERVAL_S = 30.0


class StreamStats:
    """Per-ingest accounting, attached to the result as
    `dataset.stream_stats` unconditionally (bench.py reads it with
    observability off; registry.record_streaming_chunk mirrors chunk
    records into the unified snapshot when observability is on)."""

    def __init__(self, source_desc: str = ""):
        self.source = source_desc
        self.chunks = 0            # pass-2 chunks quantized
        self.rows = 0
        self.bytes = 0             # raw chunk bytes seen across passes
        self.sketch_chunks = 0     # pass-1 chunks sketched
        self.sample_rows = 0
        self.exact = False         # sketch held every row (parity mode)
        self.resumed_from_chunk = 0
        self.pass1_s = 0.0
        self.pass2_s = 0.0
        self.parse_s = 0.0         # overlapped host parse inside pass 2
        self.bin_s = 0.0

    @property
    def overlap_frac(self) -> float:
        """Fraction of the pass-2 wall covered by overlapped parsing of
        the next chunk — the double-buffering win (0 = fully serial)."""
        if self.pass2_s <= 0:
            return 0.0
        return min(1.0, self.parse_s / self.pass2_s)

    @property
    def rows_per_sec(self) -> float:
        wall = self.pass1_s + self.pass2_s
        return self.rows / wall if wall > 0 else 0.0

    def as_dict(self) -> dict:
        return {
            "source": self.source,
            "chunks": self.chunks,
            "rows": self.rows,
            "bytes": self.bytes,
            "sketch_chunks": self.sketch_chunks,
            "sample_rows": self.sample_rows,
            "exact": bool(self.exact),
            "resumed_from_chunk": self.resumed_from_chunk,
            "pass1_s": round(self.pass1_s, 6),
            "pass2_s": round(self.pass2_s, 6),
            "parse_s": round(self.parse_s, 6),
            "bin_s": round(self.bin_s, 6),
            "overlap_frac": round(self.overlap_frac, 4),
            "rows_per_sec": round(self.rows_per_sec, 1),
        }


def _ingest_chunk_step(chunk_index: int) -> None:
    """Per-chunk dispatch point for both passes; the injectable failure
    surface of streamed ingestion (reliability/faults.py site table)."""
    faults.inject("streaming_ingest")


# ---- stream-state side files (pass-1 durability) ----------------------
# Plain files, not ckpt_* bundles: latest_checkpoint() must keep
# resolving TRAINING state only, while ingestion keeps its own cursor.

def _state_paths(ckpt_dir: str):
    return (os.path.join(ckpt_dir, _STATE_JSON),
            os.path.join(ckpt_dir, _STATE_NPZ))


def _save_stream_state(ckpt_dir: str, state: Dict,
                       arrays: Dict[str, np.ndarray]) -> None:
    os.makedirs(ckpt_dir, exist_ok=True)
    jpath, npath = _state_paths(ckpt_dir)
    # the npz carries a copy of the json cursor: the two files are
    # renamed in separate os.replace calls, so a kill between them
    # leaves a torn pair that load detects and discards instead of
    # resuming with a cursor from chunk k over a sketch from chunk k+1
    seq = np.asarray([int(state["next_chunk"]), int(state["rows"])],
                     np.int64)
    tmp = npath + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, _seq=seq,
                 **{k: np.asarray(v) for k, v in arrays.items()})
    os.replace(tmp, npath)
    tmp = jpath + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"format_version": _STATE_VERSION, **state}, fh,
                  sort_keys=True)
    os.replace(tmp, jpath)


def _load_stream_state(ckpt_dir: str):
    jpath, npath = _state_paths(ckpt_dir)
    if not (os.path.isfile(jpath) and os.path.isfile(npath)):
        return None, None
    with open(jpath) as fh:
        state = json.load(fh)
    if state.get("format_version") != _STATE_VERSION:
        Log.warning("streaming: ignoring stream state with "
                    f"format_version={state.get('format_version')!r}")
        return None, None
    with np.load(npath) as z:
        arrays = {k: z[k] for k in z.files}
    seq = arrays.pop("_seq", None)
    if seq is None or int(seq[0]) != int(state["next_chunk"]) \
            or int(seq[1]) != int(state["rows"]):
        Log.warning(
            "streaming: stream state json/npz pair is inconsistent "
            "(torn save); discarding and restarting pass 1")
        return None, None
    return state, arrays


def _clear_stream_state(ckpt_dir: str) -> None:
    for p in _state_paths(ckpt_dir):
        try:
            os.remove(p)
        except OSError:
            pass


def build_streamed_dataset(
        source: ChunkSource, *,
        label: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        max_bin: int = 255, min_data_in_bin: int = 3,
        sample_cnt: int = 200000, use_missing: bool = True,
        zero_as_missing: bool = False,
        categorical_features: Optional[Sequence[int]] = None,
        seed: int = 1,
        feature_names: Optional[List[str]] = None,
        mappers: Optional[List[BinMapper]] = None,
        feature_pre_filter: bool = True,
        pre_filter_with_mappers: bool = False,
        used_override: Optional[np.ndarray] = None,
        sample_rows: int = 200000,
        bin_parity: bool = False,
        mapper_sync: Optional[Callable[[np.ndarray],
                                       List[BinMapper]]] = None,
        checkpoint_dir: Optional[str] = None) -> BinnedDataset:
    """Construct a BinnedDataset from a ChunkSource in two passes.

    `sample_cnt`/`seed` are the `bin_construct_sample_cnt` /
    `data_random_seed` the in-memory path would use — the sketch sample
    is fed to `find_bin_mappers` with exactly those, which is what makes
    the covering case bit-identical. `sample_rows` caps the reservoir;
    `bin_parity=True` turns a non-covering sketch into a hard error
    instead of an approximation. `mappers`/`used_override` align the
    result with a reference dataset's bins (validation sets).
    `mapper_sync`, when set (multihost pure streams), replaces the local
    `find_bin_mappers` call: it receives the pass-1 sketch sample and
    must return the mapper list every rank agrees on (a collective —
    every rank reaches it exactly once per ingest). A `None` sample
    means this rank's stream yielded no rows: the sync must still join
    the collective and then raise identically on every rank, so a
    lone empty partition fails the job loudly instead of hanging it.
    The returned dataset carries `stream_stats`.
    """
    if mapper_sync is not None and bin_parity:
        # parity is a single-process guarantee; multihost boundaries
        # come from the cross-host sample union, and letting the
        # per-rank coverage check raise would strand peer ranks in the
        # mapper collective — fail identically on every rank instead
        raise LightGBMError(
            "stream_bin_parity requires num_machines=1: multihost bin "
            "boundaries come from the cross-host sample union, not the "
            "local covering sketch")
    stats = StreamStats(source.describe())
    label_parts: List[np.ndarray] = []
    sk: Optional[ReservoirSketch] = None
    all_mappers = mappers
    num_features = source.num_features
    num_rows = source.num_rows
    start_chunk = 0

    # ---- resume -------------------------------------------------------
    saved, saved_arrays = (None, None)
    if checkpoint_dir:
        saved, saved_arrays = _load_stream_state(checkpoint_dir)
    if saved is not None and mapper_sync is not None \
            and saved.get("phase") != "sketch":
        # post-sketch state skips the mapper collective; a rank resuming
        # past it while its peers enter it would hang the allgather, so
        # multihost resume only trusts sketch-phase state (pass 1 then
        # ends in the collective on every rank)
        Log.warning("streaming: discarding post-sketch stream state "
                    "under multihost — re-running pass 1 so the bin "
                    "mapper collective runs on every rank")
        saved, saved_arrays = None, None
    if saved is not None and source.array is None:
        num_features = int(saved["num_features"])
        num_rows = int(saved["rows"])
        if len(saved_arrays.get("labels", ())):
            label_parts.append(np.asarray(saved_arrays["labels"],
                                          np.float32))
        if saved["phase"] == "sketch":
            sk = ReservoirSketch.from_state(
                {k[3:]: v for k, v in saved_arrays.items()
                 if k.startswith("sk_")})
            start_chunk = int(saved["next_chunk"])
        elif all_mappers is None:
            all_mappers = [BinMapper.from_dict(d)
                           for d in saved["mappers"]]
            stats.sample_rows = int(saved.get("sample_rows", 0))
            stats.exact = bool(saved.get("exact", False))
        stats.resumed_from_chunk = int(saved["next_chunk"])
        counters.inc("stream_resumes")
        Log.info(f"streaming: resuming {saved['phase']} pass at chunk "
                 f"{saved['next_chunk']}")

    # ---- pass 1: sketch the stream, freeze the bin boundaries ---------
    if all_mappers is None and source.array is not None:
        # array-backed fast path: the matrix is random-access, so bin
        # finding samples it directly — the very call from_raw makes —
        # and no sketch buffer ever exists
        t0 = time.perf_counter()
        all_mappers = find_bin_mappers(
            source.array, max_bin=max_bin,
            min_data_in_bin=min_data_in_bin, sample_cnt=sample_cnt,
            use_missing=use_missing, zero_as_missing=zero_as_missing,
            categorical_features=categorical_features, seed=seed)
        stats.pass1_s = time.perf_counter() - t0
        stats.sample_rows = min(int(num_rows), int(sample_cnt))
        stats.exact = True
        num_features = source.num_features
    elif all_mappers is None:
        t_pass1 = time.perf_counter()
        rows_before = 0 if sk is None else num_rows
        counted = 0
        ci = start_chunk
        next_save_rows = 0
        last_save_t = time.monotonic()
        for X, y in source.chunks(start_chunk=start_chunk):
            t0 = time.perf_counter()
            _ingest_chunk_step(ci)
            X = np.asarray(X)
            if num_features is None:
                num_features = X.shape[1]
            if sk is None:
                sk = ReservoirSketch(num_features, sample_rows, seed=seed)
            sk.add_chunk(X)
            if y is not None:
                label_parts.append(np.asarray(y, np.float32))
            counted += X.shape[0]
            stats.sketch_chunks += 1
            stats.bytes += X.nbytes
            ci += 1
            wall = time.perf_counter() - t0
            if _obs.enabled:
                _obs.record_streaming_chunk("sketch", ci - 1, t0, wall,
                                            X.shape[0], X.nbytes)
            rows_total = int((rows_before or 0) + counted)
            # a save rewrites the whole sketch + label buffer (O(rows)),
            # so only save after the stream grew by _SAVE_GROWTH (total
            # I/O stays O(N)) or the time floor elapsed
            if checkpoint_dir and (
                    rows_total >= next_save_rows or
                    time.monotonic() - last_save_t >= _SAVE_INTERVAL_S):
                arrays = {"sk_" + k: v for k, v in sk.state_dict().items()}
                arrays["labels"] = np.concatenate(label_parts) \
                    if label_parts else np.empty(0, np.float32)
                _save_stream_state(checkpoint_dir, {
                    "phase": "sketch", "next_chunk": ci,
                    "num_features": int(num_features),
                    "rows": rows_total,
                }, arrays)
                next_save_rows = int(rows_total * _SAVE_GROWTH) + 1
                last_save_t = time.monotonic()
        if sk is None:
            if mapper_sync is not None:
                # an empty local stream is rank-local state: join the
                # mapper collective with a None sample so every peer
                # raises the same error instead of hanging in the
                # allgather waiting for this rank (tpulint COLL002)
                mapper_sync(None)
            raise LightGBMError("streaming: source yielded no chunks")
        num_rows = (rows_before or 0) + counted
        stats.sample_rows = sk.sample_rows
        stats.exact = sk.is_exact
        if not sk.is_exact:
            Log.info(
                f"streaming: sketch sampled {sk.sample_rows} of "
                f"{sk.rows_seen} rows; bin boundaries are approximate "
                "(raise stream_sample_rows for exact parity)")
        if mapper_sync is not None:
            # multihost: the collective derives one mapper list from
            # every rank's sketch sample, so ranks streaming disjoint
            # partitions still bin against identical boundaries
            all_mappers = mapper_sync(sk.sample())
        else:
            # parity is checked on the local-binning arm only: the
            # mapper_sync+bin_parity combination was rejected at entry,
            # and a rank-local raise between sketching and the mapper
            # collective strands peers in the allgather (tpulint
            # COLL002 — the PR-7 multihost bug shape)
            if bin_parity and not sk.is_exact:
                raise LightGBMError(
                    f"stream_bin_parity: sketch capacity {sk.capacity} "
                    f"< {sk.rows_seen} rows seen — boundaries would be "
                    "approximate; raise stream_sample_rows to cover "
                    "the stream or drop stream_bin_parity")
            # identical call to the in-memory path: with a covering
            # sketch the sample IS the data in stream order, so
            # boundaries (and the model) are bit-identical;
            # non-covering, the reservoir stands in for the population
            all_mappers = find_bin_mappers(
                sk.sample(), max_bin=max_bin,
                min_data_in_bin=min_data_in_bin, sample_cnt=sample_cnt,
                use_missing=use_missing, zero_as_missing=zero_as_missing,
                categorical_features=categorical_features, seed=seed)
        sk = None   # sketch buffer is dead weight from here on
        stats.pass1_s = time.perf_counter() - t_pass1
        if _obs.enabled:
            _obs.record_streaming_sketch(stats.sample_rows, stats.exact)
        if checkpoint_dir:
            _save_stream_state(checkpoint_dir, {
                "phase": "bin", "next_chunk": 0,
                "num_features": int(num_features),
                "rows": int(num_rows),
                "sample_rows": int(stats.sample_rows),
                "exact": bool(stats.exact),
                "mappers": [m.to_dict() for m in all_mappers],
            }, {"labels": np.concatenate(label_parts)
                if label_parts else np.empty(0, np.float32)})
    elif saved is None:
        stats.exact = True   # boundaries supplied, nothing sketched

    if num_features is None:
        # unsized source binned against supplied mappers (aligned
        # validation data): the mapper list defines the width
        num_features = len(all_mappers)
    if len(all_mappers) != num_features:
        raise ValueError(f"got {len(all_mappers)} bin mappers for "
                         f"{num_features} features")

    # ---- feature selection (reference feature_pre_filter) -------------
    if used_override is not None:
        # align with a reference dataset's used set (validation data):
        # bin exactly its columns, skipping triviality re-selection
        used = np.asarray(used_override, dtype=np.int32)
        used_mappers = [all_mappers[f] for f in used]
        max_num_bin = max([m.num_bin for m in used_mappers], default=2)
        dtype = np.uint8 if max_num_bin <= 256 else np.uint16
    else:
        used, used_mappers, dtype = _select_used_features(
            all_mappers, feature_pre_filter and
            (mappers is None or pre_filter_with_mappers))

    # ---- pass 2: re-stream and quantize, parse overlapped with bin ----
    collect_labels = not label_parts and label is None and source.has_label
    sized = num_rows is not None
    binned = np.empty((num_rows, len(used)), dtype=dtype) if sized else None
    grow_parts: List[np.ndarray] = []
    t_pass2 = time.perf_counter()
    it = source.chunks()

    def _pull():
        t = time.perf_counter()
        c = next(it, None)
        return c, time.perf_counter() - t

    row0, ci = 0, 0
    with ThreadPoolExecutor(max_workers=1) as pool:
        fut = pool.submit(_pull)
        while True:
            chunk, parse_s = fut.result()
            if chunk is None:
                break
            # the worker parses chunk k+1 while this thread bins chunk k
            fut = pool.submit(_pull)
            X, y = chunk
            t0 = time.perf_counter()
            _ingest_chunk_step(ci)
            X = np.asarray(X)
            q = bin_columns(X, used, used_mappers, dtype)
            if binned is not None:
                binned[row0:row0 + X.shape[0]] = q
            else:
                grow_parts.append(q)
            if collect_labels and y is not None:
                label_parts.append(np.asarray(y, np.float32))
            bin_s = time.perf_counter() - t0
            stats.chunks += 1
            stats.rows += X.shape[0]
            stats.bytes += X.nbytes
            stats.bin_s += bin_s
            stats.parse_s += parse_s
            row0 += X.shape[0]
            ci += 1
            if _obs.enabled:
                _obs.record_streaming_chunk("bin", ci - 1, t0,
                                            bin_s + parse_s,
                                            X.shape[0], X.nbytes)
    if binned is None:
        if not grow_parts:
            raise LightGBMError("streaming: source yielded no chunks")
        binned = np.concatenate(grow_parts, axis=0)
    elif row0 != num_rows:
        raise LightGBMError(
            f"streaming: pass 2 saw {row0} rows but pass 1 counted "
            f"{num_rows} — the source is not restartable or the data "
            "changed between passes")
    stats.pass2_s = time.perf_counter() - t_pass2
    if checkpoint_dir:
        _clear_stream_state(checkpoint_dir)

    # ---- assemble -----------------------------------------------------
    if label is None and label_parts:
        label = np.concatenate(label_parts)
    md = Metadata(int(binned.shape[0]), label=label, weight=weight,
                  group=group, init_score=init_score)
    ds = BinnedDataset(binned, used_mappers, used,
                       int(num_features), md, feature_names)
    ds.stream_stats = stats
    # in-memory arrays ride this spine for every Dataset; only real
    # streams are worth a visible line
    (Log.debug if source.array is not None else Log.info)(
        f"streaming: ingested {stats.rows} rows x {num_features} "
        f"features in {stats.chunks} chunks "
        f"({stats.rows_per_sec:.0f} rows/s, overlap "
        f"{stats.overlap_frac:.0%}, sample {stats.sample_rows}"
        f"{' exact' if stats.exact else ''})")
    return ds
