"""Row-aligned reservoir sketch for streamed quantile binning.

The pass-1 statistic behind out-of-core bin finding (Histogram Sort
with Sampling, arXiv:1803.01237): a uniform row sample of bounded size
from which `binning.find_bin_mappers` derives the frozen boundaries.
The sketch is ROW-aligned (one reservoir of whole rows, not per-feature
value reservoirs) for two reasons:

- exact-path parity: while fewer rows than `capacity` have been seen,
  the buffer holds every row in stream order, so a covering sketch
  feeds `find_bin_mappers` the very matrix the in-memory path would —
  boundaries, and hence the trained model, are bit-identical
  (tests/test_streaming.py locks this);
- cross-feature consistency: row sampling keeps implicit-zero counts
  and NaN rates consistent across features the way the reference's
  sampled FindBin does (dataset_loader.cpp two-round sampling), which
  per-feature value sketches do not.

Beyond capacity it runs vectorized Algorithm R: row t (0-based) is kept
with probability capacity/(t+1), replacing a uniformly random slot.
`merge` concatenates while the union still fits (exactness preserved);
two overflowing sketches merge by count-weighted subsampling — the
per-host combine step distributed binning will reuse.

The full state serializes to plain arrays (`state_dict`/`from_state`)
so a mid-stream checkpoint (reliability/) can resume pass 1 with the
identical RNG stream and buffer.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

__all__ = ["ReservoirSketch"]


class ReservoirSketch:
    """Uniform row reservoir over a feature stream (Algorithm R)."""

    def __init__(self, num_features: int, capacity: int, seed: int = 1):
        if capacity < 1:
            raise ValueError("sketch capacity must be >= 1")
        self.num_features = int(num_features)
        self.capacity = int(capacity)
        self.rows_seen = 0
        # allocated lazily and grown geometrically toward capacity, so a
        # covering sketch over a small stream never allocates
        # capacity x F up front
        self._buf: Optional[np.ndarray] = None
        self._rng = np.random.RandomState(seed)

    # ---- ingest -------------------------------------------------------
    def _ensure(self, rows_needed: int) -> None:
        need = min(self.capacity, rows_needed)
        if self._buf is None:
            cap0 = min(self.capacity, max(need, 1024))
            self._buf = np.empty((cap0, self.num_features), np.float64)
        elif self._buf.shape[0] < need:
            grown = min(self.capacity, max(need, 2 * self._buf.shape[0]))
            self._buf = np.resize(self._buf, (grown, self.num_features))

    def add_chunk(self, X: np.ndarray) -> None:
        """Feed a [n, F] row chunk (any float dtype; cast is exact)."""
        X = np.asarray(X)
        if X.ndim != 2 or X.shape[1] != self.num_features:
            raise ValueError(
                f"chunk shape {X.shape} does not match "
                f"num_features={self.num_features}")
        n = X.shape[0]
        if n == 0:
            return
        fill = min(max(self.capacity - self.rows_seen, 0), n)
        if fill:
            self._ensure(self.rows_seen + fill)
            self._buf[self.rows_seen:self.rows_seen + fill] = X[:fill]
        if fill < n:
            # Algorithm R over the overflow rows: global index t keeps
            # with prob capacity/(t+1) into slot j ~ U[0, t]. Draws are
            # vectorized; the (few) accepted rows replay in stream order
            # so later acceptances overwrite earlier ones exactly as the
            # sequential algorithm would.
            t = self.rows_seen + fill + np.arange(n - fill, dtype=np.int64)
            slots = (self._rng.random_sample(n - fill) * (t + 1)).astype(
                np.int64)
            hit = np.nonzero(slots < self.capacity)[0]
            for i in hit:
                self._buf[slots[i]] = X[fill + int(i)]
        self.rows_seen += n

    # ---- combine ------------------------------------------------------
    @property
    def sample_rows(self) -> int:
        return min(self.rows_seen, self.capacity)

    @property
    def is_exact(self) -> bool:
        """True while the buffer holds every row seen, in stream order."""
        return self.rows_seen <= self.capacity

    def sample(self) -> np.ndarray:
        """The current [sample_rows, F] float64 sample (a view)."""
        if self._buf is None:
            return np.empty((0, self.num_features), np.float64)
        return self._buf[:self.sample_rows]

    def merge(self, other: "ReservoirSketch") -> "ReservoirSketch":
        """Fold `other` into self (per-chunk / per-host combine).

        While the union fits the capacity the merge is plain
        concatenation — exactness (and therefore in-memory parity) is
        preserved. Overflowing merges draw a count-weighted subsample of
        the two buffers, which keeps the union a uniform row sample of
        the combined stream."""
        if other.num_features != self.num_features:
            raise ValueError("cannot merge sketches over different "
                             "feature counts")
        total = self.rows_seen + other.rows_seen
        if total <= self.capacity:
            self._ensure(total)
            self._buf[self.rows_seen:total] = other.sample()
            self.rows_seen = total
            return self
        a, b = self.sample(), other.sample()
        take_b = int(round(self.capacity * other.rows_seen / total))
        take_b = min(take_b, len(b))
        take_a = min(self.capacity - take_b, len(a))
        ia = self._rng.choice(len(a), size=take_a, replace=False) \
            if take_a < len(a) else np.arange(len(a))
        ib = self._rng.choice(len(b), size=take_b, replace=False) \
            if take_b < len(b) else np.arange(len(b))
        merged = np.concatenate([a[np.sort(ia)], b[np.sort(ib)]], axis=0)
        self._buf = np.ascontiguousarray(merged, np.float64)
        self.rows_seen = total
        return self

    # ---- checkpoint ---------------------------------------------------
    def state_dict(self) -> Dict:
        s0, s1, s2, s3, s4 = self._rng.get_state()
        return {
            "num_features": np.int64(self.num_features),
            "capacity": np.int64(self.capacity),
            "rows_seen": np.int64(self.rows_seen),
            "buf": self.sample().copy(),
            "rng_keys": np.asarray(s1, np.uint32),
            "rng_pos": np.asarray([s2, s3, s4], np.float64),
        }

    @staticmethod
    def from_state(state: Dict) -> "ReservoirSketch":
        sk = ReservoirSketch(int(state["num_features"]),
                             int(state["capacity"]))
        sk.rows_seen = int(state["rows_seen"])
        buf = np.asarray(state["buf"], np.float64)
        if len(buf):
            sk._buf = np.ascontiguousarray(buf)
        pos = np.asarray(state["rng_pos"])
        sk._rng.set_state(("MT19937",
                           np.asarray(state["rng_keys"], np.uint32),
                           int(pos[0]), int(pos[1]), float(pos[2])))
        return sk
