"""Restartable row-chunk sources behind one `ChunkSource` protocol.

Every out-of-core input — CSV text, `.npy` memmap, Parquet (optional,
gated on pyarrow), an in-memory array, or a synthetic generator
(helpers/synth.py) — yields `[n, F]` row chunks through the same
iterator protocol, so the two-pass loader (loader.py) and the
in-memory fast path share one ingestion spine. A source must be
restartable: `chunks(start_chunk=k)` begins a fresh pass at chunk k,
which is what mid-stream checkpoint resume replays from.

Array-backed sources additionally expose `.array` (the zero-copy
random-access matrix) so bin finding can sample rows directly instead
of running a sketch pass — the route the in-memory NumPy path takes
(no whole-matrix float64 copy, satellite of docs/Streaming.md).
"""

from __future__ import annotations

import io
import os
from typing import Iterator, Optional, Tuple

import numpy as np

from ..utils.file_io import open_file

__all__ = ["ChunkSource", "ArraySource", "CSVSource", "NpySource",
           "ParquetSource", "WindowSource", "source_from_path"]

#: a pass yields (X_chunk [n, F] ndarray, y_chunk [n] or None)
Chunk = Tuple[np.ndarray, Optional[np.ndarray]]


class ChunkSource:
    """Restartable iterator of `[n, F]` row chunks.

    Subclasses implement `chunks(start_chunk)` and set `chunk_rows`.
    `num_rows`/`num_features` may be None for unsized sources (CSV)
    until a full pass has completed; the loader's pass 1 fills them in.
    `has_label` marks sources that carry the target inside the stream
    (CSV label column, synthetic generators)."""

    chunk_rows: int = 65536
    has_label: bool = False
    #: zero-copy random-access matrix when one exists (ArraySource,
    #: NpySource memmap); None for pure streams
    array: Optional[np.ndarray] = None

    def __init__(self, chunk_rows: int = 65536):
        if chunk_rows < 1:
            raise ValueError("chunk_rows must be >= 1")
        self.chunk_rows = int(chunk_rows)
        self.num_rows: Optional[int] = None
        self.num_features: Optional[int] = None

    def chunks(self, start_chunk: int = 0) -> Iterator[Chunk]:
        raise NotImplementedError  # pragma: no cover - interface

    def describe(self) -> str:
        return type(self).__name__


class ArraySource(ChunkSource):
    """Chunk view over an in-memory array or memmap — ZERO copy: each
    chunk is a slice of the underlying matrix, and `.array` lets bin
    finding sample rows directly. This is how all-numeric NumPy input
    rides the streaming spine without the legacy whole-matrix float64
    conversion."""

    def __init__(self, X: np.ndarray, chunk_rows: int = 65536,
                 label: Optional[np.ndarray] = None):
        super().__init__(chunk_rows)
        if X.ndim != 2:
            raise ValueError("ArraySource needs a 2-D matrix")
        self.array = X
        self.num_rows = int(X.shape[0])
        self.num_features = int(X.shape[1])
        self._label = label
        self.has_label = label is not None

    def chunks(self, start_chunk: int = 0) -> Iterator[Chunk]:
        step = self.chunk_rows
        for lo in range(start_chunk * step, self.num_rows, step):
            hi = min(lo + step, self.num_rows)
            y = None if self._label is None else self._label[lo:hi]
            yield self.array[lo:hi], y

    def describe(self) -> str:
        return (f"array[{self.num_rows}x{self.num_features} "
                f"{self.array.dtype}]")


class NpySource(ArraySource):
    """`.npy` file opened with mmap_mode='r': chunks fault in one
    window of pages at a time, so peak resident raw data stays one
    chunk regardless of file size."""

    def __init__(self, path: str, chunk_rows: int = 65536):
        X = np.load(path, mmap_mode="r")
        if X.ndim != 2:
            raise ValueError(f"{path}: expected a 2-D .npy matrix, got "
                             f"shape {X.shape}")
        super().__init__(X, chunk_rows)
        self.path = path

    def describe(self) -> str:
        return f"npy:{os.path.basename(self.path)}[{self.num_rows}]"


class WindowSource(ChunkSource):
    """A bounded window of `window_chunks` chunks over a base source,
    starting at base chunk `start_chunk` — the continuous loop's unit
    of refresh (continuous/trainer.py). The window is itself a full
    `ChunkSource`: restartable (`chunks(start_chunk=k)` re-opens the
    base at `start_chunk + k`, so mid-stream checkpoint resume replays
    within the window), and a window over an array-backed source stays
    a zero-copy `.array` view. A window past the end of the base yields
    no chunks — the loop's exhaustion probe — and a base that ends
    mid-window yields a clean partial pass, never a torn one."""

    def __init__(self, base: "ChunkSource", start_chunk: int = 0,
                 window_chunks: int = 1):
        super().__init__(base.chunk_rows)
        if start_chunk < 0:
            raise ValueError("start_chunk must be >= 0")
        if window_chunks < 1:
            raise ValueError("window_chunks must be >= 1")
        self.base = base
        self.start_chunk = int(start_chunk)
        self.window_chunks = int(window_chunks)
        self.has_label = base.has_label
        self.num_features = base.num_features
        if base.array is not None:
            lo = self.start_chunk * base.chunk_rows
            hi = lo + self.window_chunks * base.chunk_rows
            self.array = base.array[lo:hi]
            self.num_rows = int(self.array.shape[0])
        elif base.num_rows is not None:
            lo = min(self.start_chunk * base.chunk_rows, base.num_rows)
            hi = min(lo + self.window_chunks * base.chunk_rows,
                     base.num_rows)
            self.num_rows = hi - lo

    def chunks(self, start_chunk: int = 0) -> Iterator[Chunk]:
        budget = self.window_chunks - start_chunk
        rows = 0
        if budget > 0:
            for X, y in self.base.chunks(self.start_chunk + start_chunk):
                if self.num_features is None:
                    self.num_features = int(X.shape[1])
                rows += int(X.shape[0])
                yield X, y
                budget -= 1
                if budget == 0:
                    break
        if start_chunk == 0 and self.num_rows is None:
            self.num_rows = rows

    def describe(self) -> str:
        return (f"window[{self.start_chunk}:"
                f"{self.start_chunk + self.window_chunks}] of "
                f"{self.base.describe()}")


class CSVSource(ChunkSource):
    """Streamed CSV/TSV: reads `chunk_rows` lines at a time and parses
    them with np.loadtxt — the raw text and the parsed float block both
    stay chunk-sized. `label_col` (usually 0, the reference's
    label_column default) is split out of the feature block; None means
    the file carries features only."""

    def __init__(self, path: str, chunk_rows: int = 65536,
                 label_col: Optional[int] = 0, header: bool = False,
                 delimiter: Optional[str] = None):
        super().__init__(chunk_rows)
        self.path = path
        self.label_col = label_col
        self.header = bool(header)
        self.has_label = label_col is not None
        if delimiter is None:
            with open_file(path) as fh:
                if self.header:
                    fh.readline()
                first = fh.readline()
            delimiter = "\t" if "\t" in first else ","
        self.delimiter = delimiter

    def _parse_block(self, lines) -> Chunk:
        block = np.loadtxt(io.StringIO("".join(lines)),
                           delimiter=self.delimiter, ndmin=2)
        y = None
        if self.label_col is not None:
            y = block[:, self.label_col].astype(np.float32)
            block = np.delete(block, self.label_col, axis=1)
        if self.num_features is None:
            self.num_features = block.shape[1]
        return block, y

    def chunks(self, start_chunk: int = 0) -> Iterator[Chunk]:
        rows = 0
        with open_file(self.path) as fh:
            if self.header:
                fh.readline()
            skip = start_chunk * self.chunk_rows
            lines = []
            for line in fh:
                if not line.strip():
                    continue
                if skip > 0:
                    # resume cursor: chunk boundaries are line-counted,
                    # so skipping re-reads text but parses nothing
                    skip -= 1
                    rows += 1
                    continue
                lines.append(line)
                if len(lines) == self.chunk_rows:
                    rows += len(lines)
                    yield self._parse_block(lines)
                    lines = []
            if lines:
                rows += len(lines)
                yield self._parse_block(lines)
        if start_chunk == 0:
            self.num_rows = rows

    def describe(self) -> str:
        return f"csv:{os.path.basename(self.path)}"


class ParquetSource(ChunkSource):
    """Parquet via pyarrow, OPTIONAL: constructing one without pyarrow
    installed raises a clear error instead of importing at module load
    (the container does not ship pyarrow; nothing may pip install).

    `label_col` is the configured `label_column` spec: a column index
    (int or digit string), a `name:<column>` reference, or a bare
    column name. It resolves against the file schema at construction
    — an absent column raises instead of silently training without
    labels."""

    def __init__(self, path: str, chunk_rows: int = 65536,
                 label_col: Optional[object] = None):
        super().__init__(chunk_rows)
        try:
            import pyarrow.parquet as pq  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "ParquetSource requires pyarrow, which is not installed; "
                "convert the file to .npy or CSV, or install pyarrow"
            ) from exc
        self.path = path
        import pyarrow.parquet as pq
        meta = pq.ParquetFile(path)
        self.num_rows = int(meta.metadata.num_rows)
        names = list(meta.schema_arrow.names)
        self.label_col = self._resolve_label(label_col, names)
        self.has_label = self.label_col is not None
        self.num_features = len(names) - (1 if self.has_label else 0)

    @staticmethod
    def _resolve_label(spec, names) -> Optional[str]:
        if spec is None:
            return None
        if isinstance(spec, str) and spec.startswith("name:"):
            name = spec[len("name:"):]
        elif isinstance(spec, str) and not spec.lstrip("-").isdigit():
            name = spec
        else:
            idx = int(spec)
            if not 0 <= idx < len(names):
                raise ValueError(
                    f"label_column index {idx} out of range for Parquet "
                    f"schema with {len(names)} columns {names}")
            name = names[idx]
        if name not in names:
            raise ValueError(
                f"label column {name!r} not found in Parquet schema "
                f"{names}; set label_column=name:<column> or an index")
        return name

    def chunks(self, start_chunk: int = 0) -> Iterator[Chunk]:
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(self.path)
        ci = 0
        for batch in pf.iter_batches(batch_size=self.chunk_rows):
            if ci < start_chunk:
                ci += 1
                continue
            ci += 1
            cols = {n: np.asarray(batch.column(i))
                    for i, n in enumerate(batch.schema.names)}
            y = None
            if self.label_col is not None:
                if self.label_col not in cols:
                    raise ValueError(
                        f"{self.path}: batch schema lost label column "
                        f"{self.label_col!r}")
                y = cols.pop(self.label_col).astype(np.float32)
            X = np.column_stack(list(cols.values())).astype(
                np.float64, copy=False)
            yield X, y

    def describe(self) -> str:
        return f"parquet:{os.path.basename(self.path)}"


def source_from_path(path: str, chunk_rows: int = 65536,
                     label_col: Optional[object] = 0,
                     header: bool = False) -> ChunkSource:
    """Pick a source for a data path by extension: `.npy` memmap,
    `.parquet`/`.pq` (pyarrow-gated), else delimited text. `label_col`
    is the raw `label_column` spec (index, digit string, or `name:`),
    resolved per source format."""
    low = path.lower()
    if low.endswith(".npy"):
        return NpySource(path, chunk_rows)
    if low.endswith((".parquet", ".pq")):
        return ParquetSource(path, chunk_rows, label_col=label_col)
    if isinstance(label_col, str):
        if label_col.startswith("name:"):
            raise ValueError(
                "label_column=name: requires header parsing, which text "
                "sources do not do; use a column index")
        label_col = int(label_col)
    return CSVSource(path, chunk_rows, label_col=label_col, header=header)
