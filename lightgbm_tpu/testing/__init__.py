"""Test-support utilities (no runtime dependencies on the main API)."""
