"""Test-support utilities (no runtime dependencies on the main API).

- `subproc`: hang-safe multi-rank subprocess launcher (shared deadline,
  leaked children always killed) — the one spawn path for every
  two-process test and the chaos harness.
- `chaos`: rank-death chaos harness (kill one rank mid-collective,
  diagnose, resume) — docs/Reliability.md "Distributed fault model".
- `chaos_serve`: serving chaos + load harness (dyadic boosters for
  bit-identical device/host answers, closed/open-loop heavy-tailed
  load generation, chaos orchestration hooks) — docs/Serving.md
  "Degradation ladder".
- `dask_stub`: minimal dask-like cluster stand-in for dask.py tests.
"""
