"""Rank-death chaos harness for distributed survivability tests.

The scenario tests/test_chaos.py drives (docs/Reliability.md,
"Distributed fault model"):

1. a reference 2-rank run trains to completion, checkpointing on a
   period, and saves its model — the ground truth;
2. a chaos run arms ``faults.schedule("collective_psum",
   mode="rank_death")`` on ONE rank at a chosen iteration: that rank
   `os._exit`s mid-collective with no goodbye, and the survivor must
   abort within ~2x `collective_timeout_s` carrying a "rank k last
   seen Ns ago" diagnostic instead of hanging forever;
3. both ranks relaunch with ``resume_from`` pointed at the chaos run's
   checkpoint directory; the last COORDINATED bundle (COMMIT marker
   present) restores, and the finished model must be byte-identical to
   the reference — proving the watchdog + coordinated-checkpoint +
   resume pipeline loses nothing but wall-clock.

The worker below is self-contained source (no pytest imports inside
the subprocess) parameterized entirely through TEST_* env vars, built
on the same spawn pattern as tests/test_multihost.py via
`testing.subproc.run_ranks`.
"""

from __future__ import annotations

import os
import textwrap
from typing import Dict, List, Optional

from .subproc import RankResult, free_port, rank_env, run_ranks

__all__ = ["CHAOS_WORKER", "run_chaos_training", "run_elastic_training",
           "strip_rank_local_params"]

#: worker source for one rank of a (possibly chaos-injected) W-rank
#: training run. Env contract — TEST_WORLD (default 2; 1 skips the
#: multihost rendezvous entirely), TEST_PORTS, TEST_OUT, TEST_ROUNDS,
#: TEST_CKPT_DIR/TEST_CKPT_PERIOD (checkpointing), TEST_TIMEOUT_S
#: (collective watchdog; "0" disables), TEST_DEATH_RANK/TEST_DEATH_ITER
#: (rank_death arming; death rank < 0 disables), TEST_RESUME ("1" to
#: resume from TEST_CKPT_DIR), TEST_ELASTIC ("1" turns on
#: elastic_resize — the watchdog votes a shrink instead of aborting).
CHAOS_WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, os.environ["TEST_REPO"])
    rank = int(os.environ["LIGHTGBM_TPU_MACHINE_RANK"])
    world = int(os.environ.get("TEST_WORLD", "2"))
    ports = os.environ["TEST_PORTS"].split(",")
    import lightgbm_tpu as lgb
    from lightgbm_tpu.reliability import faults
    if world > 1:
        lgb.setup_multihost(
            world, ",".join(f"127.0.0.1:{p}" for p in ports),
            local_listen_port=int(ports[rank]))

    def make_data(n=4096, f=8, seed=7):
        r = np.random.RandomState(seed)
        X = r.randn(n, f)
        logit = X[:, 0] * 1.5 + 0.5 * X[:, 1] ** 2 - X[:, 2] + \\
            0.3 * r.randn(n)
        y = (logit > np.median(logit)).astype(np.float32)
        return X, y

    X, y = make_data()
    n = len(y)
    sl = slice(rank * n // world, (rank + 1) * n // world)
    ckpt_dir = os.environ["TEST_CKPT_DIR"]
    params = dict(objective="binary", tree_learner="data",
                  num_leaves=15, verbosity=-1, min_data_in_leaf=20,
                  enable_bundle=False, boost_from_average=False,
                  checkpoint_period=int(os.environ["TEST_CKPT_PERIOD"]),
                  checkpoint_dir=ckpt_dir,
                  collective_timeout_s=float(os.environ["TEST_TIMEOUT_S"]),
                  heartbeat_interval_s=0.25,
                  heartbeat_dir=os.path.join(ckpt_dir, "heartbeats"))
    if world > 1:
        params.update(
            num_machines=world,
            machines=",".join(f"127.0.0.1:{p}" for p in ports),
            local_listen_port=int(ports[rank]))
    if os.environ.get("TEST_ELASTIC", "0") == "1":
        params.update(elastic_resize=True, elastic_min_world=1,
                      elastic_epoch_timeout_s=20.0)

    death_rank = int(os.environ.get("TEST_DEATH_RANK", "-1"))
    death_iter = int(os.environ.get("TEST_DEATH_ITER", "-1"))
    callbacks = []
    if death_rank == rank and death_iter >= 0:
        def _arm(env):
            # arm at the START of the target iteration, so this rank
            # dies inside that iteration's first host collective while
            # its peer has already committed to the same collective
            if env.iteration == death_iter:
                faults.schedule("collective_psum", fail=1,
                                mode="rank_death")
        _arm.before_iteration = True
        _arm.order = 0
        callbacks.append(_arm)

    resume = os.environ.get("TEST_RESUME", "0") == "1"
    bst = lgb.train(params,
                    lgb.Dataset(X[sl], label=y[sl]),
                    int(os.environ["TEST_ROUNDS"]),
                    callbacks=callbacks,
                    resume_from=ckpt_dir if resume else None)
    bst.save_model(os.environ["TEST_OUT"])
    import jax
    print("CHAOS_WORKER_DEVICES", jax.device_count())
    print("CHAOS_WORKER_DONE rank", rank)
""")


def run_chaos_training(workdir: str, *, rounds: int,
                       ckpt_period: int, ckpt_dir: str,
                       timeout_s: float, death_rank: int = -1,
                       death_iter: int = -1, resume: bool = False,
                       harness_timeout: float = 420.0,
                       out_prefix: str = "model",
                       devices_per_rank: int = 4,
                       world: int = 2, elastic: bool = False,
                       extra_env: Optional[Dict[str, str]] = None
                       ) -> List[RankResult]:
    """Launch the W-rank chaos worker (default: the 2-rank scenario);
    returns per-rank results. Model files land at
    ``<workdir>/<out_prefix>_<rank>.txt``. `devices_per_rank` sets each
    rank's virtual host-device count — the default 2x4 geometry is the
    8-device global mesh the distributed acceptance scenario kills a
    rank out of. `world=1` runs a single process with no multihost
    rendezvous (the shape an elastic shrink reincarnates into);
    `elastic=True` arms elastic_resize in the worker's params."""
    from .subproc import repo_root
    os.makedirs(workdir, exist_ok=True)
    worker_py = os.path.join(workdir, "chaos_worker.py")
    with open(worker_py, "w") as f:
        f.write(CHAOS_WORKER)
    ports = [str(free_port()) for _ in range(world)]
    envs: List[Dict[str, str]] = []
    import sys
    argvs = []
    for rank in range(world):
        envs.append(rank_env(
            rank,
            XLA_FLAGS="--xla_force_host_platform_device_count=%d"
                      % devices_per_rank,
            TEST_REPO=repo_root(),
            TEST_WORLD=world,
            TEST_PORTS=",".join(ports),
            TEST_OUT=os.path.join(workdir, f"{out_prefix}_{rank}.txt"),
            TEST_ROUNDS=rounds,
            TEST_CKPT_DIR=ckpt_dir,
            TEST_CKPT_PERIOD=ckpt_period,
            TEST_TIMEOUT_S=timeout_s,
            TEST_DEATH_RANK=death_rank,
            TEST_DEATH_ITER=death_iter,
            TEST_RESUME="1" if resume else "0",
            TEST_ELASTIC="1" if elastic else "0",
            **(extra_env or {})))
        argvs.append([sys.executable, worker_py])
    return run_ranks(argvs, envs=envs, cwd=workdir,
                     timeout=harness_timeout)


def run_elastic_training(workdir: str, *, rounds: int,
                         ckpt_period: int, ckpt_dir: str,
                         timeout_s: float, death_rank: int,
                         death_iter: int, world: int = 2,
                         harness_timeout: float = 420.0,
                         devices_per_rank: int = 4,
                         max_relaunches: int = 3) -> Dict:
    """The shrink-and-finish supervisor (docs/Distributed.md
    "Elasticity"): launch a W-rank elastic run with a scheduled rank
    death; when survivors exit with ELASTIC_RESIZE_EXIT_CODE (75) after
    committing a membership record, snapshot the epoch's resume bundle
    (so a fixed-world parity run can resume from the IDENTICAL state)
    and relaunch them at the shrunken world with the committed epoch in
    LIGHTGBM_TPU_EPOCH — repeating until every rank exits 0. Any
    watchdog abort (113) or missing membership record fails the run:
    "zero aborts" is the acceptance bar, not best-effort.

    Returns {"history": [per-generation RankResult lists], "record":
    final MembershipRecord, "snapshot_dir": copied bundle dir or None,
    "out_prefix": prefix of the finishing generation's model files,
    "final_world": world of the finishing generation}."""
    import shutil
    from ..distributed.elastic import (ELASTIC_RESIZE_EXIT_CODE,
                                       load_membership)
    from ..reliability.watchdog import WATCHDOG_EXIT_CODE
    hb_dir = os.path.join(ckpt_dir, "heartbeats")
    out_prefix = "elastic_g0"
    results = run_chaos_training(
        workdir, rounds=rounds, ckpt_period=ckpt_period,
        ckpt_dir=ckpt_dir, timeout_s=timeout_s, death_rank=death_rank,
        death_iter=death_iter, world=world, elastic=True,
        harness_timeout=harness_timeout, out_prefix=out_prefix,
        devices_per_rank=devices_per_rank)
    history = [results]
    snapshot_dir: Optional[str] = None
    record = None
    epoch = 0
    cur_world = world
    relaunches = 0
    while any(r.returncode != 0 for r in results):
        rcs = [r.returncode for r in results]
        if WATCHDOG_EXIT_CODE in rcs:
            raise AssertionError(
                f"elastic run aborted instead of resizing: rcs={rcs}")
        if ELASTIC_RESIZE_EXIT_CODE not in rcs:
            raise AssertionError(
                f"no resize exit among failing ranks: rcs={rcs}")
        if relaunches >= max_relaunches:
            raise AssertionError(
                f"relaunch budget ({max_relaunches}) exhausted at "
                f"world={cur_world}")
        rec = load_membership(hb_dir)
        if rec is None or rec.epoch <= epoch:
            raise AssertionError(
                "resize exit without a newer membership record "
                f"(have epoch {epoch}, dir {hb_dir})")
        epoch, record, cur_world = rec.epoch, rec, rec.world
        if rec.resume_bundle and snapshot_dir is None:
            # copy BEFORE relaunching: the reincarnated run writes new
            # bundles into ckpt_dir, and the parity contract needs the
            # exact bundle this epoch resumed from
            snapshot_dir = os.path.join(
                workdir, f"snapshot_epoch_{rec.epoch}")
            os.makedirs(snapshot_dir, exist_ok=True)
            shutil.copytree(
                rec.resume_bundle,
                os.path.join(snapshot_dir,
                             os.path.basename(rec.resume_bundle)))
        relaunches += 1
        out_prefix = f"elastic_g{relaunches}"
        results = run_chaos_training(
            workdir, rounds=rounds, ckpt_period=ckpt_period,
            ckpt_dir=ckpt_dir, timeout_s=timeout_s,
            death_rank=-1, death_iter=-1, world=cur_world,
            elastic=True, resume=True,
            harness_timeout=harness_timeout, out_prefix=out_prefix,
            devices_per_rank=devices_per_rank,
            extra_env={"LIGHTGBM_TPU_EPOCH": str(epoch)})
        history.append(results)
    return {"history": history, "record": record,
            "snapshot_dir": snapshot_dir, "out_prefix": out_prefix,
            "final_world": cur_world}


def strip_rank_local_params(model_text: str) -> str:
    """Drop the dumped-parameter lines that legitimately differ between
    ranks and runs (each rank records its own listen port; checkpoint
    and heartbeat paths differ per tmp dir) so model byte-parity
    compares the trees and learned state, nothing else."""
    drop = ("local_listen_port", "machines", "checkpoint_dir",
            "heartbeat_dir", "checkpoint_period", "collective_timeout",
            "heartbeat_interval")
    return "\n".join(ln for ln in model_text.splitlines()
                     if not any(key in ln for key in drop))
