"""Rank-death chaos harness for distributed survivability tests.

The scenario tests/test_chaos.py drives (docs/Reliability.md,
"Distributed fault model"):

1. a reference 2-rank run trains to completion, checkpointing on a
   period, and saves its model — the ground truth;
2. a chaos run arms ``faults.schedule("collective_psum",
   mode="rank_death")`` on ONE rank at a chosen iteration: that rank
   `os._exit`s mid-collective with no goodbye, and the survivor must
   abort within ~2x `collective_timeout_s` carrying a "rank k last
   seen Ns ago" diagnostic instead of hanging forever;
3. both ranks relaunch with ``resume_from`` pointed at the chaos run's
   checkpoint directory; the last COORDINATED bundle (COMMIT marker
   present) restores, and the finished model must be byte-identical to
   the reference — proving the watchdog + coordinated-checkpoint +
   resume pipeline loses nothing but wall-clock.

The worker below is self-contained source (no pytest imports inside
the subprocess) parameterized entirely through TEST_* env vars, built
on the same spawn pattern as tests/test_multihost.py via
`testing.subproc.run_ranks`.
"""

from __future__ import annotations

import os
import textwrap
from typing import Dict, List, Optional

from .subproc import RankResult, free_port, rank_env, run_ranks

__all__ = ["CHAOS_WORKER", "run_chaos_training",
           "strip_rank_local_params"]

#: worker source for one rank of a (possibly chaos-injected) 2-rank
#: training run. Env contract — TEST_PORTS, TEST_OUT, TEST_ROUNDS,
#: TEST_CKPT_DIR/TEST_CKPT_PERIOD (checkpointing), TEST_TIMEOUT_S
#: (collective watchdog; "0" disables), TEST_DEATH_RANK/TEST_DEATH_ITER
#: (rank_death arming; death rank < 0 disables), TEST_RESUME ("1" to
#: resume from TEST_CKPT_DIR).
CHAOS_WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, os.environ["TEST_REPO"])
    rank = int(os.environ["LIGHTGBM_TPU_MACHINE_RANK"])
    ports = os.environ["TEST_PORTS"].split(",")
    import lightgbm_tpu as lgb
    from lightgbm_tpu.reliability import faults
    lgb.setup_multihost(
        2, ",".join(f"127.0.0.1:{p}" for p in ports),
        local_listen_port=int(ports[rank]))

    def make_data(n=4096, f=8, seed=7):
        r = np.random.RandomState(seed)
        X = r.randn(n, f)
        logit = X[:, 0] * 1.5 + 0.5 * X[:, 1] ** 2 - X[:, 2] + \\
            0.3 * r.randn(n)
        y = (logit > np.median(logit)).astype(np.float32)
        return X, y

    X, y = make_data()
    cut = len(y) // 2
    sl = slice(0, cut) if rank == 0 else slice(cut, None)
    ckpt_dir = os.environ["TEST_CKPT_DIR"]
    params = dict(objective="binary", tree_learner="data",
                  num_machines=2,
                  machines=",".join(f"127.0.0.1:{p}" for p in ports),
                  local_listen_port=int(ports[rank]),
                  num_leaves=15, verbosity=-1, min_data_in_leaf=20,
                  enable_bundle=False, boost_from_average=False,
                  checkpoint_period=int(os.environ["TEST_CKPT_PERIOD"]),
                  checkpoint_dir=ckpt_dir,
                  collective_timeout_s=float(os.environ["TEST_TIMEOUT_S"]),
                  heartbeat_interval_s=0.25,
                  heartbeat_dir=os.path.join(ckpt_dir, "heartbeats"))

    death_rank = int(os.environ.get("TEST_DEATH_RANK", "-1"))
    death_iter = int(os.environ.get("TEST_DEATH_ITER", "-1"))
    callbacks = []
    if death_rank == rank and death_iter >= 0:
        def _arm(env):
            # arm at the START of the target iteration, so this rank
            # dies inside that iteration's first host collective while
            # its peer has already committed to the same collective
            if env.iteration == death_iter:
                faults.schedule("collective_psum", fail=1,
                                mode="rank_death")
        _arm.before_iteration = True
        _arm.order = 0
        callbacks.append(_arm)

    resume = os.environ.get("TEST_RESUME", "0") == "1"
    bst = lgb.train(params,
                    lgb.Dataset(X[sl], label=y[sl]),
                    int(os.environ["TEST_ROUNDS"]),
                    callbacks=callbacks,
                    resume_from=ckpt_dir if resume else None)
    bst.save_model(os.environ["TEST_OUT"])
    import jax
    print("CHAOS_WORKER_DEVICES", jax.device_count())
    print("CHAOS_WORKER_DONE rank", rank)
""")


def run_chaos_training(workdir: str, *, rounds: int,
                       ckpt_period: int, ckpt_dir: str,
                       timeout_s: float, death_rank: int = -1,
                       death_iter: int = -1, resume: bool = False,
                       harness_timeout: float = 420.0,
                       out_prefix: str = "model",
                       devices_per_rank: int = 4) -> List[RankResult]:
    """Launch the 2-rank chaos worker; returns per-rank results. Model
    files land at ``<workdir>/<out_prefix>_<rank>.txt``.
    `devices_per_rank` sets each rank's virtual host-device count —
    the default 2x4 geometry is the 8-device global mesh the
    distributed acceptance scenario kills a rank out of."""
    from .subproc import repo_root
    os.makedirs(workdir, exist_ok=True)
    worker_py = os.path.join(workdir, "chaos_worker.py")
    with open(worker_py, "w") as f:
        f.write(CHAOS_WORKER)
    ports = [str(free_port()), str(free_port())]
    envs: List[Dict[str, str]] = []
    import sys
    argvs = []
    for rank in range(2):
        envs.append(rank_env(
            rank,
            XLA_FLAGS="--xla_force_host_platform_device_count=%d"
                      % devices_per_rank,
            TEST_REPO=repo_root(),
            TEST_PORTS=",".join(ports),
            TEST_OUT=os.path.join(workdir, f"{out_prefix}_{rank}.txt"),
            TEST_ROUNDS=rounds,
            TEST_CKPT_DIR=ckpt_dir,
            TEST_CKPT_PERIOD=ckpt_period,
            TEST_TIMEOUT_S=timeout_s,
            TEST_DEATH_RANK=death_rank,
            TEST_DEATH_ITER=death_iter,
            TEST_RESUME="1" if resume else "0"))
        argvs.append([sys.executable, worker_py])
    return run_ranks(argvs, envs=envs, cwd=workdir,
                     timeout=harness_timeout)


def strip_rank_local_params(model_text: str) -> str:
    """Drop the dumped-parameter lines that legitimately differ between
    ranks and runs (each rank records its own listen port; checkpoint
    and heartbeat paths differ per tmp dir) so model byte-parity
    compares the trees and learned state, nothing else."""
    drop = ("local_listen_port", "machines", "checkpoint_dir",
            "heartbeat_dir", "checkpoint_period", "collective_timeout",
            "heartbeat_interval")
    return "\n".join(ln for ln in model_text.splitlines()
                     if not any(key in ln for key in drop))
