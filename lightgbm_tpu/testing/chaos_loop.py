"""Continuous-loop chaos harness: kill the loop at every seam, prove
nothing breaks (docs/Continuous.md, "Chaos protocol").

What tests/test_loop_chaos.py drives:

1. a **dyadic publish transform** — every generation's model text is
   rewritten so leaf values are multiples of 2^-10 with bounded
   magnitude (chaos_serve.dyadic_booster's trick, applied per
   generation and idempotent under re-application), so served raw
   scores are *bit-identical* to host `Booster.predict` and "the
   survivor answered from a real generation" is `np.array_equal`
   against the per-generation reference predictions, not a tolerance;
2. a **reference run** — the same stream, config and seed with no
   faults armed, recording every published generation's model text;
3. **kill scenarios** — one per fault site on the cycle's path
   (`streaming_ingest`, `histogram_build`, `checkpoint_io`,
   `serving_hot_swap`, `serving_hot_swap_commit`, `loop_publish`):
   the site is armed mid-loop while closed-loop traffic hammers the
   served entry, the cycle dies, the trainer's recovery path rebuilds
   it, and the outcome must show zero dropped requests, every answer
   bit-identical to SOME published generation, every published
   generation byte-identical to the reference run's, and a flushed
   flight-recorder postmortem per failed cycle;
4. **poison + freshness** — a window whose every rebuild attempt dies
   is quarantined (visible from the freshness metric family alone),
   and a sub-nanosecond `loop_freshness_slo_s` raises the SLO alarm
   gauge without any other observable change.

The "kill" model is `InjectedFault` propagating out of the cycle: the
trainer's `run` catches it, flushes a postmortem, and re-enters
`_recover` — the exact code path a freshly restarted process runs, so
in-process crash-loops exercise restart recovery without fork cost.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from .chaos_serve import _LEAF_LINE, _quantize, LoadResult, run_closed_loop

__all__ = ["DEFAULT_TRAIN_PARAMS", "dyadic_model_transform",
           "write_stream_csv", "loop_params", "make_loop",
           "collect_generation_models", "verify_survivor_answers",
           "LoopChaosOutcome", "run_loop_scenario"]

#: deterministic small-model params: every rebuild of a killed cycle
#: must reproduce the reference bytes, so nothing here may depend on
#: wall clock, thread count or accumulated RNG state
DEFAULT_TRAIN_PARAMS = {
    "objective": "regression",
    "num_leaves": 7,
    "min_data_in_leaf": 5,
    "verbosity": -1,
    "boost_from_average": False,
    "deterministic": True,
    "seed": 3,
}


def dyadic_model_transform(model_str: str) -> str:
    """Quantize every leaf value to a multiple of 2^-10 with |v| <= 8.

    Idempotent by construction (a dyadic rational re-quantizes to
    itself), which the loop requires: a recovered cycle re-applies the
    transform to a model whose base trees were already transformed."""
    def _requantize(m):
        return m.group(1) + " ".join(_quantize(v)
                                     for v in m.group(2).split())
    return _LEAF_LINE.sub(_requantize, model_str)


def write_stream_csv(path: str, *, chunks: int = 6, chunk_rows: int = 48,
                     f: int = 6, seed: int = 11) -> np.ndarray:
    """Write a label-in-column-0 CSV stream of `chunks * chunk_rows`
    rows; returns the feature matrix (the serving probe pool). A text
    source (not an array view) keeps BOTH loader passes live, so
    `streaming_ingest` kills exercise real stream-state resume."""
    rng = np.random.RandomState(seed)
    X = rng.randn(chunks * chunk_rows, f)
    y = X[:, 0] * 1.5 - 0.7 * X[:, 1] + 0.3 * rng.randn(len(X))
    np.savetxt(path, np.column_stack([y, X]), delimiter=",",
               fmt="%.10g")
    return X


def loop_params(loop_dir: str, **overrides) -> Dict:
    """Train + loop params for one scenario. `loop_backoff_ms=0`
    keeps crash-loop retries instant (the policy still runs, the clock
    is just flat); chaos tests that assert the curve stub the sleep."""
    p = dict(DEFAULT_TRAIN_PARAMS)
    p.update({
        "loop_dir": loop_dir,
        "loop_rounds": 3,
        "loop_window_chunks": 2,
        "loop_keep": 100,        # retain every generation: the byte-
                                 # identity sweep reads them all back
        "loop_poison_retries": 3,
        "loop_backoff_ms": 0.0,
        "loop_freshness_slo_s": 0.0,
        "loop_model_name": "live",
    })
    p.update(overrides)
    return p


def make_loop(data_path: str, params: Dict, *, chunk_rows: int = 48,
              publish_transform: Optional[Callable] =
              dyadic_model_transform):
    """Build (trainer, server, config) for one scenario. The caller
    owns the server's lifetime (use `with server:` or close it)."""
    from ..config import Config
    from ..continuous import ContinuousTrainer
    from ..serving import Server
    from ..streaming import source_from_path
    cfg = Config(dict(params))
    server = Server.from_config(cfg)
    source = source_from_path(data_path, chunk_rows=chunk_rows,
                              label_col=0)
    trainer = ContinuousTrainer(cfg, source, server,
                                params=dict(params),
                                publish_transform=publish_transform,
                                sleep=lambda s: None)
    return trainer, server, cfg


def collect_generation_models(loop_dir: str) -> Dict[int, str]:
    """generation -> model text, read back from the gens bundles."""
    gens_dir = os.path.join(loop_dir, "gens")
    out: Dict[int, str] = {}
    from ..reliability.checkpoint import _bundle_iter
    try:
        names = os.listdir(gens_dir)
    except OSError:
        return out
    for name in names:
        it = _bundle_iter(name)
        if it is None:
            continue
        try:
            with open(os.path.join(gens_dir, name, "model.txt")) as fh:
                out[it] = fh.read()
        except OSError:
            continue
    return out


def verify_survivor_answers(load: LoadResult, gen_models: Dict[int, str],
                            X: np.ndarray) -> int:
    """Every 'ok' answer must be bit-identical to the host predict of
    the same rows under SOME published generation — a torn or
    half-swapped model matches none of them. Returns the number of
    records checked; raises AssertionError on the first orphan."""
    from ..basic import Booster
    refs = []
    for gen in sorted(gen_models):
        bst = Booster(model_str=gen_models[gen])
        refs.append((gen, bst.predict(X, raw_score=True)))
    assert refs, "no generations were published; nothing to verify"
    checked = 0
    for rec in load.ok_records():
        got = np.asarray(rec.value)
        if not any(np.array_equal(got, ref[rec.lo:rec.hi])
                   for _, ref in refs):
            raise AssertionError(
                f"request {rec.idx} rows [{rec.lo},{rec.hi}) matches "
                f"no published generation {sorted(gen_models)} bit-"
                f"for-bit — a torn model answered it")
        checked += 1
    return checked


# ----------------------------------------------------------------------
@dataclass
class LoopChaosOutcome:
    """Everything one kill scenario asserts on, in one record."""
    published: int                    # generations published post-boot
    bootstrap_published: int
    load: Optional[LoadResult]
    gen_models: Dict[int, str] = field(default_factory=dict)
    final_model: Optional[str] = None
    freshness: Dict = field(default_factory=dict)
    cycle_failures: int = 0           # loop_cycle_failures delta
    trips: int = 0                    # fault firings at the armed site
    quarantined: List[int] = field(default_factory=list)
    postmortems: List[str] = field(default_factory=list)


def _postmortem_files(loop_dir: str) -> List[str]:
    out = []
    root = os.path.join(loop_dir, "postmortems")
    for dirpath, _dirs, names in os.walk(root):
        out.extend(os.path.join(dirpath, n) for n in names
                   if n.startswith("postmortem_"))
    return sorted(out)


def run_loop_scenario(data_path: str, loop_dir: str, probe_X: np.ndarray,
                      *, windows: int, site: Optional[str] = None,
                      fail: int = 1, skip: int = 0, bootstrap: int = 1,
                      n_requests: int = 0, traffic_workers: int = 3,
                      chunk_rows: int = 48,
                      params_overrides: Optional[Dict] = None,
                      ) -> LoopChaosOutcome:
    """Run one kill scenario: bootstrap `bootstrap` windows clean (so
    the serving entry exists), arm `site` with a skip/fail schedule,
    then run the remaining windows — under closed-loop traffic when
    `n_requests` > 0 (the loop runs in a helper thread while the
    traffic ledger fills in the caller's)."""
    from ..observability import registry as _obs
    from ..reliability import counters
    from ..reliability.faults import faults
    params = loop_params(loop_dir, **(params_overrides or {}))
    trainer, server, cfg = make_loop(data_path, params,
                                     chunk_rows=chunk_rows)
    failures0 = counters.get("loop_cycle_failures")
    trips0 = faults.trips(site) if site else 0
    with server:
        boot = trainer.run(max_windows=bootstrap) if bootstrap else 0
        if site is not None:
            faults.schedule(site, fail=fail, skip=skip)
        try:
            load = None
            remaining = windows - bootstrap
            if n_requests > 0:
                published_box = []
                th = threading.Thread(
                    target=lambda: published_box.append(
                        trainer.run(max_windows=remaining)),
                    daemon=True)
                th.start()
                load = run_closed_loop(
                    server, cfg.loop_model_name, probe_X,
                    n_requests=n_requests, workers=traffic_workers,
                    max_rows=32, raw_score=True, seed=5)
                th.join(timeout=300)
                assert not th.is_alive(), "loop thread wedged"
                published = published_box[0] if published_box else 0
            else:
                published = trainer.run(max_windows=remaining)
        finally:
            if site is not None:
                faults.schedule(site, fail=0, skip=0)
    return LoopChaosOutcome(
        published=published,
        bootstrap_published=boot,
        load=load,
        gen_models=collect_generation_models(loop_dir),
        final_model=trainer._live_model_str,
        freshness=_obs.freshness_snapshot(),
        cycle_failures=counters.get("loop_cycle_failures") - failures0,
        trips=(faults.trips(site) - trips0) if site else 0,
        quarantined=list(trainer.quarantined),
        postmortems=_postmortem_files(loop_dir),
    )
