"""Serving chaos + load harness: prove the degradation ladder under fire.

What tests/test_serve_chaos.py and bench_serve.py drive
(docs/Serving.md, "Degradation ladder"):

1. a **dyadic booster** — a real trained model whose leaf values are
   rewritten to multiples of 2^-10 with bounded magnitude, so every
   partial sum is exactly representable in BOTH f32 (device) and f64
   (host). Raw scores from the device path are then *bit-identical* to
   `Booster.predict(X, raw_score=True)`, which turns "no torn model,
   no wrong answer under chaos" into `np.array_equal`, not a
   tolerance;
2. **load generation** — closed-loop (k workers, back-to-back) and
   open-loop (target-QPS arrival schedule, rampable across stages),
   both with heavy-tailed request sizes (bounded Pareto), hammering
   `Server.predict` / `predict_async` from many threads;
3. **chaos** — while the load runs, the fault registry kills replica
   dispatches (`serving_replica_predict`), a breaker is forced open,
   and the model is hot-swapped mid-ramp; the ledger then proves zero
   requests dropped or hung, every answer bit-identical to host
   predict, and the breaker observed opening, probing and re-closing.

Every request lands in a `RequestRecord` ledger row — outcome, row
slice, latency, answer — so assertions are exact accounting, not
sampling.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["dyadic_booster", "heavy_tailed_sizes", "RequestRecord",
           "LoadResult", "run_closed_loop", "run_open_loop",
           "verify_bit_identical", "DYADIC_BITS"]

#: leaf values are quantized to multiples of 2**-DYADIC_BITS; with
#: magnitudes < 2**4 and < 2**10 trees, every partial raw-score sum
#: needs at most 4+10+10 = 24 mantissa bits — exact in f32 AND f64,
#: so accumulation order cannot change a single bit
DYADIC_BITS = 10

_LEAF_LINE = re.compile(r"^(leaf_value=)(.*)$", re.M)


def _quantize(tok: str) -> str:
    q = 2.0 ** -DYADIC_BITS
    v = np.clip(round(float(tok) / q) * q, -8.0, 8.0)
    return repr(float(v))


def dyadic_booster(n: int = 1200, f: int = 8, trees: int = 12,
                   seed: int = 3, num_leaves: int = 15):
    """Train a regression booster, then rewrite its leaf values to
    dyadic rationals (multiples of 2^-10, |v| <= 8) and reload it.

    Returns (booster, X): device raw scores for any subset of X are
    bit-identical to `booster.predict(..., raw_score=True)` — f32 vs
    f64 accumulation both being exact — so chaos assertions can demand
    equality instead of tolerance."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X[:, 0] * 1.5 - 0.7 * X[:, 1] + 0.3 * rng.randn(n)
    bst = lgb.train({"objective": "regression", "num_leaves": num_leaves,
                     "verbosity": -1, "boost_from_average": False,
                     "min_data_in_leaf": 10},
                    lgb.Dataset(X, label=y), num_boost_round=trees)
    txt = bst.model_to_string()

    def _requantize(m: re.Match) -> str:
        vals = m.group(2).split()
        return m.group(1) + " ".join(_quantize(v) for v in vals)

    from lightgbm_tpu.basic import Booster
    return Booster(model_str=_LEAF_LINE.sub(_requantize, txt)), X


def heavy_tailed_sizes(rng: np.random.RandomState, count: int,
                       max_rows: int = 64) -> np.ndarray:
    """Bounded-Pareto request sizes: mostly tiny, occasionally near
    `max_rows` — the batch mix that stresses coalescing + bucketing."""
    sizes = 1 + (rng.pareto(1.3, size=count) * 2.0).astype(np.int64)
    return np.clip(sizes, 1, max_rows)


# ----------------------------------------------------------------------
@dataclass
class RequestRecord:
    idx: int
    lo: int                        # row slice [lo, hi) into the X pool
    hi: int
    outcome: str = "pending"       # ok | shed | deadline | error | hang
    latency_ms: float = 0.0
    value: Optional[np.ndarray] = None
    error: str = ""
    model: str = ""                # multi-model load: which name served it


@dataclass
class LoadResult:
    records: List[RequestRecord] = field(default_factory=list)
    wall_s: float = 0.0

    def by_outcome(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.records:
            out[r.outcome] = out.get(r.outcome, 0) + 1
        return out

    @property
    def issued(self) -> int:
        return len(self.records)

    @property
    def completed(self) -> int:
        """Requests that got a definitive answer or protocol error —
        anything except pending/hang counts as accounted for."""
        return sum(1 for r in self.records
                   if r.outcome not in ("pending", "hang"))

    @property
    def dropped(self) -> int:
        """Requests left hanging or unresolved: the chaos tests demand
        exactly zero of these."""
        return self.issued - self.completed

    def ok_records(self) -> List[RequestRecord]:
        return [r for r in self.records if r.outcome == "ok"]

    def qps(self) -> float:
        return (len(self.ok_records()) / self.wall_s) \
            if self.wall_s > 0 else 0.0

    def latency_percentiles(self) -> Dict[str, float]:
        lats = [r.latency_ms for r in self.ok_records()]
        if not lats:
            return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
        arr = np.asarray(lats)
        return {f"p{p}_ms": round(float(np.percentile(arr, p)), 3)
                for p in (50, 95, 99)}


def _issue(server, name: str, X: np.ndarray, rec: RequestRecord,
           raw_score: bool, timeout_s: float) -> None:
    from ..serving import DeadlineExceeded, OverloadError
    t0 = time.perf_counter()
    try:
        rec.value = server.predict(rec.model or name, X[rec.lo:rec.hi],
                                   raw_score=raw_score,
                                   timeout=timeout_s)
        rec.outcome = "ok"
    except OverloadError:
        rec.outcome = "shed"
    except DeadlineExceeded:
        rec.outcome = "deadline"
    except TimeoutError:
        rec.outcome = "hang"       # the one outcome chaos must forbid
    except Exception as exc:       # noqa: BLE001 — ledger, not handler
        rec.outcome = "error"
        rec.error = f"{type(exc).__name__}: {exc}"
    rec.latency_ms = (time.perf_counter() - t0) * 1e3


def run_closed_loop(server, name: str, X: np.ndarray, *,
                    n_requests: int = 200, workers: int = 4,
                    max_rows: int = 64, raw_score: bool = True,
                    timeout_s: float = 30.0, seed: int = 0,
                    mid_run=None) -> LoadResult:
    """`workers` threads issue back-to-back predicts until `n_requests`
    are done. `mid_run(k)` (optional) is called once by the driver
    thread after ~k/2 requests — the chaos hook (force a breaker open,
    hot-swap, arm faults) runs while traffic is live."""
    rng = np.random.RandomState(seed)
    sizes = heavy_tailed_sizes(rng, n_requests, max_rows)
    starts = rng.randint(0, max(len(X) - max_rows, 1), size=n_requests)
    records = [RequestRecord(i, int(starts[i]),
                             int(starts[i] + sizes[i]))
               for i in range(n_requests)]
    next_idx = [0]
    lock = threading.Lock()
    fired = threading.Event()

    def _worker():
        while True:
            with lock:
                if next_idx[0] >= n_requests:
                    return
                i = next_idx[0]
                next_idx[0] += 1
            if mid_run is not None and i >= n_requests // 2 and \
                    not fired.is_set():
                if not fired.is_set():
                    fired.set()
                    mid_run(i)
            _issue(server, name, X, records[i], raw_score, timeout_s)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=_worker, daemon=True)
               for _ in range(max(workers, 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s * 2)
    res = LoadResult(records=records,
                     wall_s=time.perf_counter() - t0)
    return res


def run_open_loop(server, name: str, X: np.ndarray, *,
                  stages: Sequence[Tuple[float, float]],
                  max_rows: int = 64, raw_score: bool = True,
                  timeout_s: float = 30.0, seed: int = 0,
                  mid_run=None,
                  names: Optional[Sequence[str]] = None) -> LoadResult:
    """Open-loop load: requests arrive on a fixed schedule regardless
    of completion (the honest way to measure tail latency — a closed
    loop self-throttles when the server slows). `stages` is a QPS ramp
    of (qps, duration_s) pairs. `mid_run(stage_index)` fires at each
    stage boundary past the first. `names` spreads the load uniformly
    over several served models (multi-model/pack benches); each
    record's `model` field says which one answered it."""
    rng = np.random.RandomState(seed)
    records: List[RequestRecord] = []
    threads: List[threading.Thread] = []
    t_start = time.perf_counter()
    idx = 0
    for si, (qps, duration_s) in enumerate(stages):
        if si and mid_run is not None:
            mid_run(si)
        n = max(int(qps * duration_s), 1)
        gaps = np.full(n, 1.0 / max(qps, 1e-9))
        sizes = heavy_tailed_sizes(rng, n, max_rows)
        starts = rng.randint(0, max(len(X) - max_rows, 1), size=n)
        picks = rng.randint(0, len(names), size=n) \
            if names else np.zeros(n, np.int64)
        stage_t0 = time.perf_counter()
        for k in range(n):
            rec = RequestRecord(idx, int(starts[k]),
                                int(starts[k] + sizes[k]),
                                model=names[picks[k]] if names else "")
            idx += 1
            records.append(rec)
            th = threading.Thread(
                target=_issue, args=(server, name, X, rec, raw_score,
                                     timeout_s), daemon=True)
            th.start()
            threads.append(th)
            # pace arrivals against the wall clock, not per-request
            # sleep drift
            target = stage_t0 + float(np.sum(gaps[:k + 1]))
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
    for th in threads:
        th.join(timeout=timeout_s * 2)
    return LoadResult(records=records,
                      wall_s=time.perf_counter() - t_start)


def verify_bit_identical(result: LoadResult, booster,
                         X: np.ndarray, boosters=None) -> int:
    """Every 'ok' answer must equal the host predict of the same rows,
    bit for bit (requires a `dyadic_booster` model and raw_score=True
    load). Multi-model loads pass `boosters` ({name: booster}) so each
    record checks against ITS model. Returns how many records were
    checked; raises AssertionError with the first mismatch otherwise."""
    checked = 0
    for rec in result.ok_records():
        ref_bst = boosters[rec.model] if boosters and rec.model \
            else booster
        ref = ref_bst.predict(X[rec.lo:rec.hi], raw_score=True)
        assert np.array_equal(np.asarray(rec.value), ref), (
            f"request {rec.idx} rows [{rec.lo},{rec.hi}) "
            f"model '{rec.model}' diverged from host predict")
        checked += 1
    return checked
