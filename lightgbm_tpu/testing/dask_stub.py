"""A minimal in-process stand-in for the dask/distributed surface that
``lightgbm_tpu.dask`` consumes, so the Dask orchestration (partition
grouping, who_has worker assignment, machines injection, rendezvous,
rank-0 model return) EXECUTES in CI without dask installed.

The reference backs its dask.py with 1,848 LoC of tests that run on real
``distributed.LocalCluster`` workers (python-package/lightgbm/dask.py:
68-184 and tests/python_package_test/test_dask.py). This environment has
no dask and no package index (VERDICT r3 item 4), so this stub
implements the narrow client API the integration touches — submit /
run / compute / gather / who_has / scheduler_info, delayed objects,
chunked arrays — over real SPAWNED WORKER PROCESSES (multiprocessing),
which is exactly what the orchestration needs to be true end-to-end:
each worker joins a genuine ``jax.distributed`` rendezvous and trains
its own partitions. ``tests/test_dask.py`` still targets real dask for
environments that have it.

Functions cross the process boundary via cloudpickle (as in real
distributed), so dask.py's lambdas work unmodified.

Usage::

    from lightgbm_tpu.testing import dask_stub
    dask_stub.install()            # sys.modules: dask, distributed, ...
    client = dask_stub.StubClient(n_workers=2)
    X = dask_stub.array_from(np.ndarray, chunk_rows=500)
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["install", "StubClient", "Array", "Delayed", "array_from",
           "delayed", "wait", "get_client"]


# ---------------------------------------------------------------------
# delayed / future graph pieces
class Delayed:
    """A value, or a deferred fn(*args) over nested Delayed/_FutureRef."""

    def __init__(self, fn=None, args=(), value=None, has_value=False):
        self.fn = fn
        self.args = args
        self.value = value
        self.has_value = has_value


def delayed(fn):
    def wrap(*args):
        return Delayed(fn=fn, args=args)
    return wrap


class _FutureRef:
    """Wire form of a Future: resolved from the worker's local store."""

    def __init__(self, key):
        self.key = key


class Future:
    def __init__(self, key: str, worker: str):
        self.key = key
        self.worker = worker
        self._event = threading.Event()
        self._value = None
        self._error: Optional[str] = None

    def _resolve(self, ok: bool, payload):
        if ok:
            self._value = payload
        else:
            self._error = payload
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"future {self.key} timed out")
        if self._error is not None:
            raise RuntimeError(
                f"worker task {self.key} failed:\n{self._error}")
        return self._value


def wait(futures):
    for f in futures:
        f.result()
    return futures


def get_client():
    raise ValueError("no global stub client; pass client= explicitly")


def _flatten(obj):
    if isinstance(obj, (list, tuple)):
        return [x for o in obj for x in _flatten(o)]
    if isinstance(obj, dict):
        return [x for o in obj.values() for x in _flatten(o)]
    return [obj]


def _strip_futures(obj):
    """Replace Future instances with picklable _FutureRef (recursively)."""
    if isinstance(obj, Future):
        return _FutureRef(obj.key)
    if isinstance(obj, Delayed):
        return Delayed(fn=obj.fn, args=_strip_futures(obj.args),
                       value=obj.value, has_value=obj.has_value)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_strip_futures(x) for x in obj)
    if isinstance(obj, dict):
        return {k: _strip_futures(v) for k, v in obj.items()}
    return obj


def _materialize(obj, store):
    """Worker-side: evaluate Delayed trees and dereference futures."""
    if isinstance(obj, _FutureRef):
        return store[obj.key]
    if isinstance(obj, Delayed):
        if obj.has_value:
            return obj.value
        return obj.fn(*[_materialize(a, store) for a in obj.args])
    if isinstance(obj, (list, tuple)):
        return type(obj)(_materialize(x, store) for x in obj)
    if isinstance(obj, dict):
        return {k: _materialize(v, store) for k, v in obj.items()}
    return obj


# ---------------------------------------------------------------------
# chunked array (the dask.array surface _concat_to_local/_delayed_parts/
# _predict_impl touch)
class Array:
    def __init__(self, chunks: List[np.ndarray]):
        self._chunks = [np.asarray(c) for c in chunks]

    @property
    def shape(self):
        first = self._chunks[0]
        rows = sum(c.shape[0] for c in self._chunks)
        return (rows,) + first.shape[1:]

    @property
    def ndim(self):
        return self._chunks[0].ndim

    @property
    def chunks(self):
        rows = tuple(c.shape[0] for c in self._chunks)
        first = self._chunks[0]
        return (rows,) + tuple((d,) for d in first.shape[1:])

    def to_delayed(self):
        d = np.empty(len(self._chunks), object)
        for i, c in enumerate(self._chunks):
            d[i] = Delayed(value=c, has_value=True)
        return d

    def compute(self):
        return np.concatenate(self._chunks, axis=0) \
            if len(self._chunks) > 1 else self._chunks[0]

    def map_blocks(self, fn, drop_axis=None, chunks=None, dtype=None):
        # eager per-chunk apply — enough for the predict path
        return Array([np.asarray(fn(c)) for c in self._chunks])


def array_from(arr: np.ndarray, chunk_rows: int) -> Array:
    arr = np.asarray(arr)
    return Array([arr[i:i + chunk_rows]
                  for i in range(0, arr.shape[0], chunk_rows)])


class _StubDataFrame:          # isinstance targets only
    pass


class _StubSeries:
    pass


# ---------------------------------------------------------------------
# worker process
def _worker_main(task_q, res_q):
    """Runs in a SPAWNED process with an untouched JAX backend, so
    _train_part's setup_multihost can do a real jax.distributed
    rendezvous (mesh.py:99)."""
    import cloudpickle
    store: Dict[str, Any] = {}
    while True:
        msg = task_q.get()
        if msg is None:
            return
        key, blob, send_back = msg
        try:
            fn, args, kwargs = cloudpickle.loads(blob)
            args = _materialize(args, store)
            kwargs = _materialize(kwargs, store)
            val = fn(*args, **kwargs)
            store[key] = val
            res_q.put((key, True, val if send_back else None))
        except BaseException:
            import traceback
            res_q.put((key, False, traceback.format_exc()))


class StubClient:
    """distributed.Client stand-in over spawned worker processes."""

    def __init__(self, n_workers: int = 2):
        import multiprocessing
        import socket
        ctx = multiprocessing.get_context("spawn")
        self._counter = itertools.count()
        self._futures: Dict[str, Future] = {}
        self._workers: Dict[str, tuple] = {}
        self._rr = itertools.cycle(range(n_workers))
        # keep worker backends small and untouched (test_multihost.py's
        # env hygiene): CPU platform, and no site hook that would
        # initialize the backend at interpreter start
        patch = {"JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
        saved = {k: os.environ.get(k) for k in
                 list(patch) + ["PALLAS_AXON_POOL_IPS",
                                "LIGHTGBM_TPU_MACHINE_RANK"]}
        os.environ.update(patch)
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ.pop("LIGHTGBM_TPU_MACHINE_RANK", None)
        try:
            for _ in range(n_workers):
                s = socket.socket()
                s.bind(("127.0.0.1", 0))
                addr = "tcp://127.0.0.1:%d" % s.getsockname()[1]
                s.close()
                tq, rq = ctx.Queue(), ctx.Queue()
                proc = ctx.Process(target=_worker_main, args=(tq, rq),
                                   daemon=True)
                proc.start()
                drain = threading.Thread(target=self._drain,
                                         args=(rq, addr, proc),
                                         daemon=True)
                drain.start()
                self._workers[addr] = (proc, tq, rq, drain)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    # -- client API used by lightgbm_tpu.dask -------------------------
    def scheduler_info(self):
        return {"workers": {w: {} for w in self._workers}}

    def submit(self, fn, *args, workers=None, pure=False, **kwargs):
        import cloudpickle
        addrs = sorted(self._workers)
        if workers:
            w = workers[0]
        else:
            # locality: run where an argument future's value lives (the
            # real scheduler's data-locality placement)
            arg_futs = [a for a in _flatten(args) + _flatten(kwargs)
                        if isinstance(a, Future)]
            w = arg_futs[0].worker if arg_futs else \
                addrs[next(self._rr) % len(addrs)]
        key = f"task-{next(self._counter)}"
        fut = Future(key, w)
        self._futures[key] = fut
        blob = cloudpickle.dumps(
            (fn, _strip_futures(args), _strip_futures(kwargs)))
        self._workers[w][1].put((key, blob, True))
        return fut

    def compute(self, delayeds):
        # schedule partition tuples round-robin; values stay worker-side
        import cloudpickle
        addrs = sorted(self._workers)
        futs = []
        for d in delayeds:
            w = addrs[next(self._rr) % len(addrs)]
            key = f"task-{next(self._counter)}"
            fut = Future(key, w)
            self._futures[key] = fut
            blob = cloudpickle.dumps(
                (_materialize, (_strip_futures(d), {}), {}))
            self._workers[w][1].put((key, blob, False))
            futs.append(fut)
        return futs

    def who_has(self, futures):
        wait(futures)
        return {f.key: [f.worker] for f in futures}

    def run(self, fn, workers=None):
        targets = workers if workers is not None else sorted(self._workers)
        futs = {w: self.submit(fn, workers=[w]) for w in targets}
        return {w: f.result() for w, f in futs.items()}

    def gather(self, futures):
        return [f.result() for f in futures]

    def close(self):
        for proc, tq, _rq, _d in self._workers.values():
            tq.put(None)
        for proc, _tq, _rq, _d in self._workers.values():
            proc.join(timeout=10)
            if proc.is_alive():
                proc.terminate()

    def _drain(self, rq, addr, proc):
        while True:
            try:
                key, ok, payload = rq.get(timeout=1.0)
            except queue.Empty:
                if not proc.is_alive():
                    # a dead worker (segfault, hard exit) must FAIL its
                    # pending futures, not hang result() forever
                    for f in list(self._futures.values()):
                        if f.worker == addr and not f._event.is_set():
                            f._resolve(False,
                                       f"worker process {addr} died "
                                       f"(exitcode {proc.exitcode})")
                    return
                continue
            except (EOFError, OSError):
                return
            fut = self._futures.get(key)
            if fut is not None:
                fut._resolve(ok, payload)


# ---------------------------------------------------------------------
_SAVED_MODULES: Optional[Dict[str, Any]] = None
_STUB_NAMES = ("dask", "dask.array", "dask.dataframe", "distributed")


def uninstall():
    """Undo install(): restore the real dask/distributed modules (or
    their absence) and re-resolve lightgbm_tpu.dask against them, so
    stub-based tests don't leak into real-dask tests that run later."""
    global _SAVED_MODULES
    import importlib
    import sys
    if _SAVED_MODULES is None:
        return
    for name in _STUB_NAMES:
        if _SAVED_MODULES[name] is None:
            sys.modules.pop(name, None)
        else:
            sys.modules[name] = _SAVED_MODULES[name]
    _SAVED_MODULES = None
    import lightgbm_tpu.dask as lgb_dask
    importlib.reload(lgb_dask)


def install():
    """Register stub modules so ``import dask.array`` /
    ``from distributed import wait`` inside lightgbm_tpu.dask resolve to
    this stub. Reloads lightgbm_tpu.dask if it was imported without
    dask. Returns the (reloaded) lightgbm_tpu.dask module; call
    uninstall() to restore the previous module state."""
    global _SAVED_MODULES
    import importlib
    import sys
    import types

    if _SAVED_MODULES is None:
        _SAVED_MODULES = {name: sys.modules.get(name)
                          for name in _STUB_NAMES}
    dask_mod = types.ModuleType("dask")
    dask_mod.delayed = delayed
    array_mod = types.ModuleType("dask.array")
    array_mod.Array = Array
    array_mod.from_array = array_from
    df_mod = types.ModuleType("dask.dataframe")
    df_mod.DataFrame = _StubDataFrame
    df_mod.Series = _StubSeries
    dask_mod.array = array_mod
    dask_mod.dataframe = df_mod
    dist_mod = types.ModuleType("distributed")
    dist_mod.wait = wait
    dist_mod.get_client = get_client
    dist_mod.Client = StubClient
    sys.modules["dask"] = dask_mod
    sys.modules["dask.array"] = array_mod
    sys.modules["dask.dataframe"] = df_mod
    sys.modules["distributed"] = dist_mod

    import lightgbm_tpu.dask as lgb_dask
    return importlib.reload(lgb_dask)
