"""Hang-safe multi-process launch helper for multihost tests.

tests/test_multihost.py grew four near-identical Popen blocks — spawn N
rank processes, drain their output, time them out together, kill
whatever leaks. The chaos harness (testing/chaos.py) needs the same
shape plus per-rank wall-clock timing (its watchdog assertions compare
rank exit times), so the pattern lives here once.

Guarantees:

- every spawned process is killed before `run_ranks` returns, no
  matter which assertion or exception fires (leaked children are how a
  single red test wedges a whole CI run);
- each rank's stdout+stderr is drained CONCURRENTLY (a rank blocked on
  a full pipe deadlocks against a sequential reader);
- per-rank wall durations are measured from a common start, so "the
  survivor exited within 2x the deadline of the death" is assertable.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

__all__ = ["RankResult", "free_port", "rank_env", "run_ranks",
           "repo_root", "python_argv"]


@dataclass
class RankResult:
    """Outcome of one rank process."""
    rank: int
    returncode: Optional[int]        # None only when timed_out
    output: str                      # merged stdout+stderr
    duration_s: float                # spawn -> exit (or kill)
    timed_out: bool = False

    def tail(self, n: int = 3000) -> str:
        return self.output[-n:]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def rank_env(rank: int, **extra: str) -> Dict[str, str]:
    """Environment for one CPU-backed rank process: virtual 4-device
    host platform, the rank marker the conftest-free workers read, and
    any TEST_* extras. A site hook in some environments initializes the
    JAX backend at interpreter start, which forbids
    jax.distributed.initialize; its trigger is dropped so workers start
    with an untouched backend."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               LIGHTGBM_TPU_MACHINE_RANK=str(rank))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for key, val in extra.items():
        env[key] = str(val)
    return env


def run_ranks(argvs: Sequence[Sequence[str]], *,
              envs: Sequence[Dict[str, str]],
              cwd: Optional[str] = None,
              timeout: float = 420.0) -> List[RankResult]:
    """Run one process per rank to completion under a SHARED deadline.

    `argvs[i]` is rank i's command line, `envs[i]` its environment
    (build with `rank_env`). On deadline expiry every still-running
    process is killed and its result marked `timed_out`; on any
    exception the finally clause kills the lot — children cannot
    outlive the call."""
    if len(argvs) != len(envs):
        raise ValueError("argvs and envs must pair up rank by rank")
    procs: List[subprocess.Popen] = []
    results: List[Optional[RankResult]] = [None] * len(argvs)
    start = time.monotonic()

    def _drain(i: int, p: subprocess.Popen) -> None:
        out, _ = p.communicate()        # blocks until process exit
        results[i] = RankResult(
            rank=i, returncode=p.returncode,
            output=(out or b"").decode(errors="replace"),
            duration_s=time.monotonic() - start)

    threads: List[threading.Thread] = []
    try:
        for i, argv in enumerate(argvs):
            p = subprocess.Popen(list(argv), env=envs[i], cwd=cwd,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
            procs.append(p)
            th = threading.Thread(target=_drain, args=(i, p),
                                  daemon=True)
            th.start()
            threads.append(th)
        deadline = start + timeout
        for th in threads:
            th.join(max(0.0, deadline - time.monotonic()))
        if any(th.is_alive() for th in threads):
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for th in threads:          # communicate() returns post-kill
                th.join(timeout=15.0)
    finally:
        for p in procs:                  # belt and braces: never leak
            if p.poll() is None:
                p.kill()
    out: List[RankResult] = []
    for i in range(len(argvs)):
        r = results[i]
        if r is None:                    # drain never finished: timeout
            r = RankResult(rank=i, returncode=None, output="",
                           duration_s=time.monotonic() - start,
                           timed_out=True)
        out.append(r)
    return out


def repo_root() -> str:
    """Repository root (the directory holding the package), for worker
    scripts that sys.path-insert it."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def python_argv(script_path: str) -> List[str]:
    return [sys.executable, script_path]
