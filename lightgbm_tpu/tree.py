"""Host-side model representation + LightGBM-compatible text serialization.

Mirrors the reference model text format exactly (GBDT::SaveModelToString
src/boosting/gbdt_model_text.cpp:311, Tree::ToString src/io/tree.cpp:339,
load path gbdt_model_text.cpp:421) so models serialized here can be
cross-checked/loaded by the reference's predictor and vice versa:

  header: version=v3, num_class, num_tree_per_iteration, label_index,
          max_feature_idx, objective, feature_names, feature_infos,
          tree_sizes
  per tree: num_leaves/num_cat/split_feature/split_gain/threshold/
          decision_type/left_child/right_child/leaf_value/leaf_weight/
          leaf_count/internal_value/internal_weight/internal_count/
          [cat_boundaries/cat_threshold]/is_linear/shrinkage

Node numbering follows the reference Tree: internal nodes 0..num_leaves-2,
leaves addressed as `~leaf_index` (negative) in child arrays (tree.h:25).
decision_type packs {categorical:1, default_left:2, missing_type<<2}
(tree.h decision-type masks; missing: None=0, Zero=1, NaN=2).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from .utils.log import Log

__all__ = ["HostTree", "HostModel"]

_CAT_BIT = 1
_DEFAULT_LEFT_BIT = 2
_MISSING_SHIFT = 2
_ZERO_THRESHOLD = 1e-35


def _fmt(x: float) -> str:
    """Double formatting akin to Common::ArrayToString<true> (%.17g-ish)."""
    return np.format_float_positional(
        np.float64(x), precision=17, unique=True, trim="0") \
        if np.isfinite(x) else ("1e+300" if x > 0 else "-1e+300")


def _join(arr, fmt=str) -> str:
    return " ".join(fmt(v) for v in arr)


@dataclasses.dataclass
class HostTree:
    """One tree in reference numbering (internal idx / ~leaf idx)."""
    num_leaves: int
    split_feature: np.ndarray      # [ni] original feature idx
    split_gain: np.ndarray         # [ni]
    threshold: np.ndarray          # [ni] double (or cat_boundaries index)
    decision_type: np.ndarray      # [ni] uint8
    left_child: np.ndarray         # [ni]
    right_child: np.ndarray        # [ni]
    leaf_value: np.ndarray         # [nl]
    leaf_weight: np.ndarray        # [nl]
    leaf_count: np.ndarray         # [nl]
    internal_value: np.ndarray     # [ni]
    internal_weight: np.ndarray    # [ni]
    internal_count: np.ndarray     # [ni]
    cat_boundaries: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(1, np.int32))
    cat_threshold: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.uint32))
    shrinkage: float = 1.0
    is_linear: bool = False
    # linear leaves (reference tree.h leaf_const_/leaf_coeff_/leaf_features_)
    leaf_const: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.float64))   # [nl]
    leaf_coeff: List[np.ndarray] = dataclasses.field(default_factory=list)
    leaf_features: List[np.ndarray] = dataclasses.field(default_factory=list)

    @property
    def num_cat(self) -> int:
        return len(self.cat_boundaries) - 1

    # ---- prediction (reference tree.h:335-412 decisions) -------------
    def predict_rows(self, X: np.ndarray) -> np.ndarray:
        leaf = self.leaf_index_rows(X)
        if not self.is_linear:
            return self.leaf_value[leaf]
        # linear leaves: const + coeff . x, NaN in any model feature falls
        # back to the constant leaf_value (tree.cpp:133-150).
        # Rows grouped by leaf with one argsort, not a scan per leaf.
        out = np.empty(len(leaf), np.float64)
        order = np.argsort(leaf, kind="stable")
        bounds = np.searchsorted(leaf[order], np.arange(self.num_leaves + 1))
        for li in range(self.num_leaves):
            rows = order[bounds[li]:bounds[li + 1]]
            if rows.size == 0:
                continue
            feats = self.leaf_features[li] if li < len(self.leaf_features) \
                else np.zeros(0, np.int32)
            const = float(self.leaf_const[li]) if li < len(self.leaf_const) \
                else float(self.leaf_value[li])
            if len(feats) == 0:
                out[rows] = const
                continue
            xv = X[np.ix_(rows, feats)]
            v = const + xv @ np.asarray(self.leaf_coeff[li], np.float64)
            nanr = np.isnan(xv).any(axis=1)
            v[nanr] = self.leaf_value[li]
            out[rows] = v
        return out

    def leaf_index_rows(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, np.int32)
        node = np.zeros(n, np.int32)  # internal idx; leaves become ~leaf
        active = node >= 0
        while active.any():
            idx = node[active]
            feat = self.split_feature[idx]
            vals = X[active, feat]
            thr = self.threshold[idx]
            dt = self.decision_type[idx]
            is_cat = (dt & _CAT_BIT) != 0
            default_left = (dt & _DEFAULT_LEFT_BIT) != 0
            missing_t = (dt >> _MISSING_SHIFT) & 3
            isnan = np.isnan(vals)
            # None-missing: NaN -> 0 (tree.h NumericalDecision)
            vals = np.where(isnan & (missing_t != 2), 0.0, vals)
            is_zero = np.abs(vals) <= _ZERO_THRESHOLD
            use_default = ((missing_t == 1) & is_zero & ~is_cat) | \
                          ((missing_t == 2) & isnan & ~is_cat)
            go_left = np.where(use_default, default_left, vals <= thr)
            if is_cat.any():
                ci = np.where(is_cat)[0]
                cat_left = np.zeros(len(ci), bool)
                for k, j in enumerate(ci):
                    v = vals[j]
                    if not np.isfinite(v) or v < 0:
                        cat_left[k] = False
                        continue
                    iv = int(v)
                    c = int(thr[j])  # cat_boundaries index
                    lo, hi = self.cat_boundaries[c], self.cat_boundaries[c + 1]
                    word = iv // 32
                    if word < hi - lo:
                        cat_left[k] = bool(
                            (int(self.cat_threshold[lo + word]) >>
                             (iv % 32)) & 1)
                go_left[ci] = cat_left
            nxt = np.where(go_left, self.left_child[idx],
                           self.right_child[idx])
            node[active] = nxt
            active = node >= 0
        return ~node  # leaf index

    # ---- text io ------------------------------------------------------
    def to_string(self) -> str:
        ni = self.num_leaves - 1
        lines = [f"num_leaves={self.num_leaves}",
                 f"num_cat={self.num_cat}"]
        if self.num_leaves > 1:
            lines += [
                "split_feature=" + _join(self.split_feature),
                "split_gain=" + _join(self.split_gain, _fmt),
                "threshold=" + _join(self.threshold, _fmt),
                "decision_type=" + _join(self.decision_type),
                "left_child=" + _join(self.left_child),
                "right_child=" + _join(self.right_child),
                "leaf_value=" + _join(self.leaf_value, _fmt),
                "leaf_weight=" + _join(self.leaf_weight, _fmt),
                "leaf_count=" + _join(self.leaf_count),
                "internal_value=" + _join(self.internal_value, _fmt),
                "internal_weight=" + _join(self.internal_weight, _fmt),
                "internal_count=" + _join(self.internal_count),
            ]
        else:
            lines += ["leaf_value=" + _join(self.leaf_value, _fmt)]
        if self.num_cat > 0:
            lines += ["cat_boundaries=" + _join(self.cat_boundaries),
                      "cat_threshold=" + _join(self.cat_threshold)]
        lines += [f"is_linear={int(self.is_linear)}"]
        if self.is_linear:
            # reference Tree::ToString linear section (tree.cpp:377-399):
            # flattened per-leaf feature lists / coefficients
            nf = [len(self.leaf_features[li])
                  if li < len(self.leaf_features) else 0
                  for li in range(self.num_leaves)]
            lines += [
                "leaf_const=" + _join(self.leaf_const, _fmt),
                "num_features=" + _join(nf),
                "leaf_features=" + _join(
                    [f for fl in self.leaf_features for f in fl]),
                "leaf_coeff=" + _join(
                    [c for cl in self.leaf_coeff for c in cl], _fmt),
            ]
        lines += [f"shrinkage={_fmt(self.shrinkage)}"]
        del ni
        return "\n".join(lines) + "\n\n"

    @staticmethod
    def from_block(kv: Dict[str, str]) -> "HostTree":
        nl = int(kv["num_leaves"])

        def arr(key, dtype, default_len=0):
            if key not in kv or kv[key] == "":
                return np.zeros(default_len, dtype)
            return np.asarray(kv[key].split(" "), dtype=dtype)

        if nl > 1:
            t = HostTree(
                num_leaves=nl,
                split_feature=arr("split_feature", np.int32),
                split_gain=arr("split_gain", np.float64),
                threshold=arr("threshold", np.float64),
                decision_type=arr("decision_type", np.int32).astype(np.uint8),
                left_child=arr("left_child", np.int32),
                right_child=arr("right_child", np.int32),
                leaf_value=arr("leaf_value", np.float64),
                leaf_weight=arr("leaf_weight", np.float64, nl),
                leaf_count=arr("leaf_count", np.int64, nl),
                internal_value=arr("internal_value", np.float64, nl - 1),
                internal_weight=arr("internal_weight", np.float64, nl - 1),
                internal_count=arr("internal_count", np.int64, nl - 1),
                shrinkage=float(kv.get("shrinkage", 1)),
                is_linear=bool(int(kv.get("is_linear", 0))))
        else:
            t = HostTree(
                num_leaves=nl,
                split_feature=np.zeros(0, np.int32),
                split_gain=np.zeros(0, np.float64),
                threshold=np.zeros(0, np.float64),
                decision_type=np.zeros(0, np.uint8),
                left_child=np.zeros(0, np.int32),
                right_child=np.zeros(0, np.int32),
                leaf_value=arr("leaf_value", np.float64),
                leaf_weight=np.zeros(nl, np.float64),
                leaf_count=np.zeros(nl, np.int64),
                internal_value=np.zeros(0, np.float64),
                internal_weight=np.zeros(0, np.float64),
                internal_count=np.zeros(0, np.int64),
                shrinkage=float(kv.get("shrinkage", 1)),
                is_linear=bool(int(kv.get("is_linear", 0))))
        if "cat_boundaries" in kv:
            t.cat_boundaries = np.asarray(
                kv["cat_boundaries"].split(" "), np.int64)
            t.cat_threshold = np.asarray(
                kv["cat_threshold"].split(" "), np.uint64).astype(np.uint32)
        if t.is_linear and "leaf_const" in kv:
            t.leaf_const = arr("leaf_const", np.float64, nl)
            nf = arr("num_features", np.int64, nl)
            flat_f = arr("leaf_features", np.int64)
            flat_c = arr("leaf_coeff", np.float64)
            offs = np.concatenate([[0], np.cumsum(nf)]).astype(np.int64)
            t.leaf_features = [flat_f[offs[i]:offs[i + 1]].astype(np.int32)
                               for i in range(nl)]
            t.leaf_coeff = [flat_c[offs[i]:offs[i + 1]] for i in range(nl)]
        return t

    # ---- json (Tree::ToJSON, tree.cpp:414) ----------------------------
    def to_json(self) -> dict:
        def node(i):
            if i < 0:
                li = ~i
                d = {"leaf_index": int(li),
                     "leaf_value": float(self.leaf_value[li]),
                     "leaf_weight": float(self.leaf_weight[li]),
                     "leaf_count": int(self.leaf_count[li])}
                if self.is_linear:
                    d["leaf_const"] = float(self.leaf_const[li]) \
                        if li < len(self.leaf_const) else d["leaf_value"]
                    d["leaf_features"] = [int(f) for f in (
                        self.leaf_features[li]
                        if li < len(self.leaf_features) else [])]
                    d["leaf_coeff"] = [float(c) for c in (
                        self.leaf_coeff[li]
                        if li < len(self.leaf_coeff) else [])]
                return d
            dt = int(self.decision_type[i])
            out = {
                "split_index": int(i),
                "split_feature": int(self.split_feature[i]),
                "split_gain": float(self.split_gain[i]),
                "threshold": float(self.threshold[i]),
                "decision_type": "==" if dt & _CAT_BIT else "<=",
                "default_left": bool(dt & _DEFAULT_LEFT_BIT),
                "missing_type": ["None", "Zero", "NaN"][(dt >> 2) & 3],
                "internal_value": float(self.internal_value[i]),
                "internal_weight": float(self.internal_weight[i]),
                "internal_count": int(self.internal_count[i]),
                "left_child": node(int(self.left_child[i])),
                "right_child": node(int(self.right_child[i])),
            }
            return out
        if self.num_leaves <= 1:
            structure = {"leaf_value": float(self.leaf_value[0])}
        else:
            structure = node(0)
        return {"num_leaves": int(self.num_leaves),
                "num_cat": int(self.num_cat),
                "shrinkage": float(self.shrinkage),
                "tree_structure": structure}


class HostModel:
    """Full model: header + trees (reference GBDT model text)."""

    def __init__(self):
        self.trees: List[HostTree] = []
        self.tree_class: List[int] = []
        self.num_class = 1
        self.num_tree_per_iteration = 1
        self.label_index = 0
        self.max_feature_idx = 0
        self.objective = "regression"
        self.average_output = False
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.params: Dict[str, str] = {}
        # training-time pandas category lists per categorical column
        # (reference basic.py pandas_categorical round-trip)
        self.pandas_categorical = None

    @property
    def num_iterations(self) -> int:
        return len(self.trees) // max(self.num_tree_per_iteration, 1)

    # ---- native predictor (cext/predict.cpp; predictor.hpp:30) --------
    def _flatten_native(self):
        """Flatten the forest into the concatenated arrays the C
        predictor consumes; cached until the tree list changes."""
        cached = getattr(self, "_native_flat", None)
        if cached is not None and cached["num_trees"] == len(self.trees):
            return cached
        t_list = self.trees
        k = max(self.num_tree_per_iteration, 1)
        node_off = np.zeros(len(t_list) + 1, np.int64)
        leaf_off = np.zeros(len(t_list) + 1, np.int64)
        catb_off = np.zeros(len(t_list) + 1, np.int64)
        catt_off = np.zeros(len(t_list) + 1, np.int64)
        for i, t in enumerate(t_list):
            node_off[i + 1] = node_off[i] + max(t.num_leaves - 1, 0)
            leaf_off[i + 1] = leaf_off[i] + t.num_leaves
            catb_off[i + 1] = catb_off[i] + len(t.cat_boundaries)
            catt_off[i + 1] = catt_off[i] + len(t.cat_threshold)

        def cat(key, dtype):
            parts = [np.asarray(getattr(t, key), dtype) for t in t_list]
            return np.ascontiguousarray(np.concatenate(parts)) if parts \
                else np.zeros(0, dtype)

        nl_total = int(leaf_off[-1])
        lconst = np.zeros(nl_total, np.float64)
        lfeat_off = np.zeros(nl_total + 1, np.int64)
        lfeats: List[np.ndarray] = []
        lcoefs: List[np.ndarray] = []
        pos = 0
        for i, t in enumerate(t_list):
            for li in range(t.num_leaves):
                gi = int(leaf_off[i]) + li
                if t.is_linear and li < len(t.leaf_const):
                    lconst[gi] = t.leaf_const[li]
                    feats = t.leaf_features[li] \
                        if li < len(t.leaf_features) else []
                    pos += len(feats)
                    lfeats.append(np.asarray(feats, np.int32))
                    lcoefs.append(np.asarray(
                        t.leaf_coeff[li] if li < len(t.leaf_coeff) else [],
                        np.float64))
                lfeat_off[gi + 1] = pos
        flat = {
            "num_trees": len(t_list),
            "tree_class": np.ascontiguousarray(
                [self.tree_class[i] if i < len(self.tree_class) else i % k
                 for i in range(len(t_list))], np.int32),
            "node_off": node_off, "leaf_off": leaf_off,
            "split_feature": cat("split_feature", np.int32),
            "threshold": cat("threshold", np.float64),
            "decision_type": cat("decision_type", np.uint8),
            "left": cat("left_child", np.int32),
            "right": cat("right_child", np.int32),
            "leaf_value": cat("leaf_value", np.float64),
            "catb_off": catb_off, "catt_off": catt_off,
            "cat_boundaries": cat("cat_boundaries", np.int64),
            "cat_threshold": cat("cat_threshold", np.uint32),
            "is_linear": np.ascontiguousarray(
                [int(t.is_linear) for t in t_list], np.uint8),
            "leaf_const": lconst,
            "lfeat_off": lfeat_off,
            "leaf_features": np.ascontiguousarray(
                np.concatenate(lfeats), np.int32) if lfeats
            else np.zeros(0, np.int32),
            "leaf_coeff": np.ascontiguousarray(
                np.concatenate(lcoefs), np.float64) if lcoefs
            else np.zeros(0, np.float64),
        }
        self._native_flat = flat
        return flat

    # ------------------------------------------------------------------
    @staticmethod
    def from_gbdt(gbdt, train_dataset) -> "HostModel":
        """Convert device TreeArrays into reference numbering."""
        from .boosting.rf import RF
        model = HostModel()
        cfg = gbdt.config
        model.num_class = max(int(cfg.num_class), 1)
        model.num_tree_per_iteration = gbdt.num_tree_per_iteration
        model.objective = _objective_string(gbdt, cfg)
        model.average_output = isinstance(gbdt, RF)
        ds = train_dataset.binned if train_dataset is not None else None
        if ds is not None:
            model.max_feature_idx = ds.num_total_features - 1
            model.feature_names = list(ds.feature_names)
            model.feature_infos = _feature_infos(ds)
            model.pandas_categorical = getattr(ds, "pandas_categorical",
                                               None)
            used_to_orig = np.asarray(ds.used_features, np.int64)
            mappers = ds.mappers
        else:
            model.max_feature_idx = 0
            used_to_orig = None
            mappers = None
        model.params = {k: str(v) for k, v in cfg.raw_params.items()}
        lins = getattr(gbdt, "linear_models", [])
        for ti, (tarr, cls) in enumerate(zip(gbdt.trees, gbdt.tree_class)):
            lin = lins[ti] if ti < len(lins) else None
            model.trees.append(
                host_tree_from_arrays(tarr, used_to_orig, mappers,
                                      float(cfg.learning_rate), lin=lin))
            model.tree_class.append(cls)
        return model

    # ------------------------------------------------------------------
    def predict(self, X: np.ndarray, start_iteration: int = 0,
                num_iteration: Optional[int] = None, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0) -> np.ndarray:
        k = max(self.num_tree_per_iteration, 1)
        total_iters = self.num_iterations
        if num_iteration is None or num_iteration <= 0:
            num_iteration = total_iters - start_iteration
        end_iteration = min(start_iteration + num_iteration, total_iters)
        rng = range(start_iteration * k, end_iteration * k)
        n = X.shape[0]
        from .cext import predict_available
        use_native = predict_available()
        if pred_leaf:
            if use_native:
                from .cext import forest_predict_leaf
                return forest_predict_leaf(
                    self._flatten_native(), X, start_iteration * k,
                    end_iteration * k)
            out = np.zeros((n, len(rng)), np.int32)
            for j, ti in enumerate(rng):
                out[:, j] = self.trees[ti].leaf_index_rows(X)
            return out
        if pred_contrib:
            if any(t.is_linear for t in self.trees):
                # reference parity: predictor.hpp:90 Log::Fatal
                raise NotImplementedError(
                    "Predicting SHAP feature contributions is not "
                    "implemented for linear trees.")
            return self.predict_contrib(X, start_iteration, end_iteration)
        out = np.zeros((n, k), np.float64)
        # margin-based prediction early stop (reference
        # prediction_early_stop.cpp: binary margin = 2|p|, multiclass
        # margin = top1 - top2, checked every round_period trees; rows past
        # the margin stop accumulating further trees)
        obj = self.objective.split(" ")[0]
        use_early = (pred_early_stop and not self.average_output and
                     (k > 1 or obj in ("binary", "cross_entropy",
                                       "xentropy")))
        if use_native and not use_early:
            # native OMP predictor (cext/predict.cpp, predictor.hpp:30)
            from .cext import forest_predict
            out = forest_predict(self._flatten_native(), X, k,
                                 start_iteration * k, end_iteration * k)
            if self.average_output:
                out /= max(end_iteration - start_iteration, 1)
            if not raw_score:
                out = self._convert_output(out)
            return out[:, 0] if k == 1 else out
        # checks happen on iteration boundaries only, so every class has an
        # equal tree count when a row is retired; rows are re-sliced only
        # when the active set changes (at a check), not per tree
        check_every = max(pred_early_stop_freq, 1) * k
        act_idx = None          # None = all rows active
        Xa = X
        for j, ti in enumerate(rng):
            cls = self.tree_class[ti] if ti < len(self.tree_class) else ti % k
            if act_idx is None:
                out[:, cls] += self.trees[ti].predict_rows(X)
            else:
                out[act_idx, cls] += self.trees[ti].predict_rows(Xa)
            if use_early and (j + 1) % check_every == 0:
                if k == 1:
                    margin = 2.0 * np.abs(out[:, 0])
                else:
                    part = np.partition(out, k - 2, axis=1)
                    margin = part[:, k - 1] - part[:, k - 2]
                active = margin < pred_early_stop_margin
                if act_idx is not None:
                    keep = np.zeros(n, bool)
                    keep[act_idx] = True
                    active &= keep
                if not active.all() or act_idx is not None:
                    act_idx = np.flatnonzero(active)
                    if act_idx.size == 0:
                        break
                    Xa = X[act_idx]
        if self.average_output:
            out /= max(end_iteration - start_iteration, 1)
        if not raw_score:
            out = self._convert_output(out)
        return out[:, 0] if k == 1 else out

    def _convert_output(self, raw: np.ndarray) -> np.ndarray:
        obj = self.objective.split(" ")[0]
        if obj == "binary":
            sigmoid = _objective_param(self.objective, "sigmoid", 1.0)
            return 1.0 / (1.0 + np.exp(-sigmoid * raw))
        if obj in ("multiclass", "softmax"):
            e = np.exp(raw - raw.max(axis=1, keepdims=True))
            return e / e.sum(axis=1, keepdims=True)
        if obj in ("multiclassova", "multiclass_ova"):
            sigmoid = _objective_param(self.objective, "sigmoid", 1.0)
            return 1.0 / (1.0 + np.exp(-sigmoid * raw))
        if obj in ("poisson", "gamma", "tweedie"):
            return np.exp(raw)
        if obj in ("cross_entropy", "xentropy"):
            return 1.0 / (1.0 + np.exp(-raw))
        if obj in ("cross_entropy_lambda", "xentlambda"):
            return np.log1p(np.exp(raw))
        return raw

    def predict_contrib(self, X: np.ndarray, start_iteration: int,
                        end_iteration: int) -> np.ndarray:
        """SHAP values via the tree SHAP algorithm (reference
        Tree::PredictContrib / TreeSHAP in tree.cpp). Returns
        [n, (num_features+1) * k]."""
        from .shap import tree_shap_model
        return tree_shap_model(self, X, start_iteration, end_iteration)

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split"
                           ) -> np.ndarray:
        nf = self.max_feature_idx + 1
        imp = np.zeros(nf, np.float64)
        for t in self.trees:
            for i in range(t.num_leaves - 1):
                f = int(t.split_feature[i])
                if importance_type == "split":
                    imp[f] += 1.0
                else:
                    imp[f] += max(float(t.split_gain[i]), 0.0)
        if importance_type == "split":
            return imp.astype(np.int64) if False else imp
        return imp

    def refit(self, X: np.ndarray, label: np.ndarray, decay_rate: float,
              config) -> "HostModel":
        """Re-fit leaf values on new data (reference GBDT::RefitTree
        gbdt.cpp:287: new_output = FeatureHistogram leaf output on new
        grad/hess; leaf = decay*old + (1-decay)*new)."""
        import copy
        from .objectives import create_objective
        from .data import Metadata
        import jax.numpy as jnp
        new_model = copy.deepcopy(self)
        new_model._native_flat = None  # leaf values change in place below
        obj = create_objective(self.objective.split(" ")[0], config)
        md = Metadata(len(label), label=label)
        obj.init(md, len(label))
        k = max(self.num_tree_per_iteration, 1)
        score = np.zeros((len(label), k), np.float64)
        l2 = float(config.lambda_l2)
        l1 = float(config.lambda_l1)
        for ti, t in enumerate(new_model.trees):
            cls = self.tree_class[ti] if ti < len(self.tree_class) else ti % k
            sc = jnp.asarray(score[:, 0] if k == 1 else score)
            g, h = obj.get_gradients(sc)
            g = np.asarray(g).reshape(len(label), -1)[:, cls]
            h = np.asarray(h).reshape(len(label), -1)[:, cls]
            leaves = t.leaf_index_rows(X)
            sum_g = np.bincount(leaves, weights=g, minlength=t.num_leaves)
            sum_h = np.bincount(leaves, weights=h, minlength=t.num_leaves)
            thr_g = np.sign(sum_g) * np.maximum(np.abs(sum_g) - l1, 0)
            new_out = -thr_g / (sum_h + l2 + 1e-15)
            t.leaf_value = decay_rate * t.leaf_value + \
                (1.0 - decay_rate) * new_out * t.shrinkage
            if t.is_linear:
                # re-fit leaf linear models with decay (reference
                # CalculateLinear is_refit path,
                # linear_tree_learner.cpp:325-378)
                self._refit_linear_leaves(t, X, leaves, g, h, decay_rate,
                                          new_out, float(config.linear_lambda))
            score[:, cls] += t.predict_rows(X)
        return new_model

    @staticmethod
    def _refit_linear_leaves(t: "HostTree", X, leaves, g, h, decay,
                             new_out, lam) -> None:
        for li in range(t.num_leaves):
            feats = t.leaf_features[li] if li < len(t.leaf_features) \
                else np.zeros(0, np.int32)
            nfeat = len(feats)
            fb_const = decay * float(t.leaf_const[li]) + \
                (1.0 - decay) * new_out[li] * t.shrinkage
            if nfeat == 0:
                t.leaf_const[li] = fb_const
                continue
            rows = np.flatnonzero(leaves == li)
            xv = X[np.ix_(rows, feats)]
            okr = ~np.isnan(xv).any(axis=1)
            old_coef = np.asarray(t.leaf_coeff[li], np.float64)
            if okr.sum() < nfeat + 1:
                t.leaf_const[li] = fb_const
                t.leaf_coeff[li] = np.zeros(nfeat)
                continue
            xt = np.column_stack([xv[okr], np.ones(int(okr.sum()))])
            a = (xt * h[rows][okr][:, None]).T @ xt
            a[np.arange(nfeat), np.arange(nfeat)] += lam
            try:
                sol = -np.linalg.solve(a, xt.T @ g[rows][okr])
            except np.linalg.LinAlgError:
                t.leaf_const[li] = fb_const
                t.leaf_coeff[li] = np.zeros(nfeat)
                continue
            t.leaf_coeff[li] = decay * old_coef + \
                (1.0 - decay) * sol[:nfeat] * t.shrinkage
            t.leaf_const[li] = decay * float(t.leaf_const[li]) + \
                (1.0 - decay) * sol[nfeat] * t.shrinkage

    # ------------------------------------------------------------------
    def to_string(self, num_iteration: Optional[int] = None,
                  start_iteration: int = 0) -> str:
        k = max(self.num_tree_per_iteration, 1)
        total = self.num_iterations
        start_iteration = max(0, min(start_iteration, total))
        num_used = len(self.trees)
        if num_iteration is not None and num_iteration > 0:
            num_used = min((start_iteration + num_iteration) * k, num_used)
        start_model = start_iteration * k
        lines = ["tree", "version=v3",
                 f"num_class={self.num_class}",
                 f"num_tree_per_iteration={self.num_tree_per_iteration}",
                 f"label_index={self.label_index}",
                 f"max_feature_idx={self.max_feature_idx}",
                 f"objective={self.objective}"]
        if self.average_output:
            lines.append("average_output")
        lines.append("feature_names=" + " ".join(self.feature_names))
        lines.append("feature_infos=" + " ".join(self.feature_infos))
        tree_strs = []
        for i in range(start_model, num_used):
            s = f"Tree={i - start_model}\n" + self.trees[i].to_string()
            tree_strs.append(s)
        lines.append("tree_sizes=" + " ".join(
            str(len(s) + 1) for s in tree_strs))
        lines.append("")
        body = "\n".join(lines) + "\n"
        body += "\n".join(tree_strs)
        if tree_strs:
            body += "\n"
        body += "end of trees\n"
        imp = self.feature_importance("split")
        pairs = sorted(
            [(int(imp[i]), self.feature_names[i])
             for i in range(len(self.feature_names)) if imp[i] > 0],
            key=lambda p: -p[0])
        body += "\nfeature_importances:\n"
        for cnt, name in pairs:
            body += f"{name}={cnt}\n"
        if self.params:
            body += "\nparameters:\n"
            for kk, v in self.params.items():
                body += f"[{kk}: {v}]\n"
            body += "end of parameters\n"
        # pandas category lists (reference python basic.py:591-624): the
        # reference's _load_pandas_categorical reads only the file tail, so
        # this must be the LAST line of the model string.
        import json as _json
        body += "\npandas_categorical:%s\n" % _json.dumps(
            self.pandas_categorical, default=str)
        return body

    @staticmethod
    def from_string(s: str) -> "HostModel":
        model = HostModel()
        lines = s.split("\n")
        i = 0
        # header
        while i < len(lines):
            line = lines[i].strip()
            i += 1
            if line.startswith("Tree="):
                i -= 1
                break
            if line == "tree" or line == "":
                continue
            if line == "average_output":
                model.average_output = True
                continue
            if "=" in line:
                key, val = line.split("=", 1)
                if key == "num_class":
                    model.num_class = int(val)
                elif key == "num_tree_per_iteration":
                    model.num_tree_per_iteration = int(val)
                elif key == "label_index":
                    model.label_index = int(val)
                elif key == "max_feature_idx":
                    model.max_feature_idx = int(val)
                elif key == "objective":
                    model.objective = val
                elif key == "feature_names":
                    model.feature_names = val.split(" ") if val else []
                elif key == "feature_infos":
                    model.feature_infos = val.split(" ") if val else []
        # trees
        while i < len(lines):
            line = lines[i].strip()
            if line.startswith("end of trees"):
                break
            if not line.startswith("Tree="):
                i += 1
                continue
            i += 1
            kv: Dict[str, str] = {}
            while i < len(lines):
                tline = lines[i].strip()
                if tline == "" :
                    i += 1
                    if i < len(lines) and not lines[i].strip().startswith(
                            tuple(["Tree=", "end of trees"])):
                        continue
                    break
                if "=" in tline:
                    kk, vv = tline.split("=", 1)
                    kv[kk] = vv
                i += 1
            model.trees.append(HostTree.from_block(kv))
        k = max(model.num_tree_per_iteration, 1)
        model.tree_class = [ti % k for ti in range(len(model.trees))]
        if "pandas_categorical:" in s:
            import json as _json
            pline = s.split("pandas_categorical:", 1)[1].split("\n", 1)[0]
            try:
                model.pandas_categorical = _json.loads(pline)
            except ValueError:
                model.pandas_categorical = None
        # parameters tail (optional)
        if "parameters:" in s:
            tail = s.split("parameters:", 1)[1]
            for pline in tail.split("\n"):
                pline = pline.strip()
                if pline.startswith("[") and ": " in pline:
                    kk, vv = pline[1:-1].split(": ", 1)
                    model.params[kk] = vv
        return model

    def to_json(self, num_iteration: Optional[int] = None,
                start_iteration: int = 0) -> dict:
        k = max(self.num_tree_per_iteration, 1)
        total = self.num_iterations
        if num_iteration is None or num_iteration <= 0:
            num_iteration = total
        end = min(start_iteration + num_iteration, total)
        tree_infos = []
        for ti in range(start_iteration * k, end * k):
            tj = self.trees[ti].to_json()
            tj["tree_index"] = ti
            tree_infos.append(tj)
        return {
            "name": "tree",
            "version": "v3",
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": self.label_index,
            "max_feature_idx": self.max_feature_idx,
            "objective": self.objective,
            "average_output": self.average_output,
            "feature_names": self.feature_names,
            "feature_infos": self.feature_infos,
            "tree_info": tree_infos,
        }


# ---------------------------------------------------------------------------

def _objective_param(objective_str: str, key: str, default: float) -> float:
    """Parse `key:value` tokens from a serialized objective string."""
    for tok in objective_str.split(" ")[1:]:
        if tok.startswith(key + ":"):
            return float(tok.split(":", 1)[1])
    return default


def _objective_string(gbdt, cfg) -> str:
    obj = gbdt.objective
    if obj is None:
        return cfg.objective or "custom"
    name = obj.name
    if name == "binary":
        return f"binary sigmoid:{obj.sigmoid:g}"
    if name in ("multiclass", "multiclassova"):
        extra = f" num_class:{cfg.num_class}"
        if name == "multiclassova":
            extra += f" sigmoid:{obj.sigmoid:g}"
        return name + extra
    if name == "lambdarank":
        return "lambdarank"
    return name


def _feature_infos(ds) -> List[str]:
    infos = ["none"] * ds.num_total_features
    for j, f in enumerate(ds.used_features):
        m = ds.mappers[j]
        if m.is_categorical:
            cats = sorted(c for c in m.bin_2_categorical if c >= 0)
            infos[int(f)] = ":".join(str(c) for c in cats) if cats else "none"
        else:
            infos[int(f)] = f"[{m.min_val:g}:{m.max_val:g}]"
    return infos


def host_tree_from_arrays(tarr, used_to_orig: Optional[np.ndarray],
                          mappers, shrinkage: float, lin=None) -> HostTree:
    """Convert device TreeArrays (node-id space) to reference numbering."""
    nn = int(tarr.num_nodes)
    split_feature = np.asarray(tarr.split_feature)[:nn]
    is_leaf = split_feature < 0
    node_ids = np.arange(nn)
    internal_ids = node_ids[~is_leaf]
    leaf_ids = node_ids[is_leaf]
    internal_rank = np.full(nn, -1)
    internal_rank[internal_ids] = np.arange(len(internal_ids))
    leaf_rank = np.full(nn, -1)
    leaf_rank[leaf_ids] = np.arange(len(leaf_ids))

    left = np.asarray(tarr.left)[:nn]
    right = np.asarray(tarr.right)[:nn]
    thr_bin = np.asarray(tarr.threshold_bin)[:nn]
    default_left = np.asarray(tarr.default_left)[:nn]
    is_cat = np.asarray(tarr.is_cat)[:nn]
    cat_bitsets = np.asarray(tarr.cat_bitset)[:nn]
    value = np.asarray(tarr.leaf_value)[:nn]
    sum_hess = np.asarray(tarr.sum_hess)[:nn]
    count = np.asarray(tarr.count)[:nn]
    gain = np.asarray(tarr.gain)[:nn]

    nl = len(leaf_ids)
    ni = len(internal_ids)
    if nl == 0:
        nl = 1

    def child_ref(cid):
        if cid < 0:
            return 0
        return internal_rank[cid] if internal_rank[cid] >= 0 \
            else ~int(leaf_rank[cid])

    cat_boundaries = [0]
    cat_threshold: List[int] = []
    t_split_feature = np.zeros(ni, np.int32)
    t_threshold = np.zeros(ni, np.float64)
    t_decision = np.zeros(ni, np.uint8)
    t_left = np.zeros(ni, np.int32)
    t_right = np.zeros(ni, np.int32)
    for r, nid in enumerate(internal_ids):
        fu = int(split_feature[nid])
        forig = int(used_to_orig[fu]) if used_to_orig is not None else fu
        t_split_feature[r] = forig
        t_left[r] = child_ref(int(left[nid]))
        t_right[r] = child_ref(int(right[nid]))
        m = mappers[fu] if mappers is not None else None
        if is_cat[nid]:
            # decode the node's bin bitset -> category-value bitset
            # (reference SplitInfo::cat_threshold -> Tree cat storage,
            # tree.h:25 cat_boundaries_/cat_threshold_)
            words_bins = cat_bitsets[nid]
            catvals = []
            for b in range(len(words_bins) * 32):
                if (int(words_bins[b // 32]) >> (b % 32)) & 1:
                    catval = m.bin_2_categorical[b] if m is not None and \
                        b < len(m.bin_2_categorical) else b
                    catvals.append(max(int(catval), 0))
            if not catvals:
                catvals = [0]
            nwords = max(catvals) // 32 + 1
            words = [0] * nwords
            for catval in catvals:
                words[catval // 32] |= (1 << (catval % 32))
            t_threshold[r] = len(cat_boundaries) - 1
            cat_boundaries.append(cat_boundaries[-1] + nwords)
            cat_threshold.extend(words)
            missing_t = 2
            t_decision[r] = _CAT_BIT | (missing_t << _MISSING_SHIFT)
        else:
            if m is not None:
                t_threshold[r] = m.bin_to_threshold_value(int(thr_bin[nid]))
                missing_t = int(m.missing_type)
            else:
                t_threshold[r] = float(thr_bin[nid])
                missing_t = 0
            t_decision[r] = (_DEFAULT_LEFT_BIT if default_left[nid] else 0) \
                | (missing_t << _MISSING_SHIFT)

    tree = HostTree(
        num_leaves=nl,
        split_feature=t_split_feature,
        split_gain=gain[internal_ids].astype(np.float64),
        threshold=t_threshold,
        decision_type=t_decision,
        left_child=t_left,
        right_child=t_right,
        leaf_value=value[leaf_ids].astype(np.float64) if len(leaf_ids)
        else np.asarray([float(value[0])]),
        leaf_weight=sum_hess[leaf_ids].astype(np.float64) if len(leaf_ids)
        else np.zeros(1),
        leaf_count=count[leaf_ids].astype(np.int64) if len(leaf_ids)
        else np.zeros(1, np.int64),
        internal_value=value[internal_ids].astype(np.float64),
        internal_weight=sum_hess[internal_ids].astype(np.float64),
        internal_count=count[internal_ids].astype(np.int64),
        cat_boundaries=np.asarray(cat_boundaries, np.int64),
        cat_threshold=np.asarray(cat_threshold, np.uint32),
        shrinkage=shrinkage)
    if lin is not None:
        # linear leaves in leaf-rank order, original feature indices,
        # dropping near-zero coefficients like the reference
        # (linear_tree_learner.cpp:356-362)
        const = np.asarray(lin.const)[:nn]
        coeff = np.asarray(lin.coeff)[:nn]
        lfeat = np.asarray(lin.feat)[:nn]
        tree.is_linear = True
        if len(leaf_ids):
            tree.leaf_const = const[leaf_ids].astype(np.float64)
        else:
            tree.leaf_const = np.asarray([float(value[0])])
        lf_list: List[np.ndarray] = []
        lc_list: List[np.ndarray] = []
        for nid in (leaf_ids if len(leaf_ids) else [0]):
            fs: List[int] = []
            cs: List[float] = []
            for d in range(lfeat.shape[1]):
                fu = int(lfeat[nid, d])
                c = float(coeff[nid, d])
                if fu >= 0 and abs(c) > _ZERO_THRESHOLD:
                    fs.append(int(used_to_orig[fu])
                              if used_to_orig is not None else fu)
                    cs.append(c)
            lf_list.append(np.asarray(fs, np.int32))
            lc_list.append(np.asarray(cs, np.float64))
        tree.leaf_features = lf_list
        tree.leaf_coeff = lc_list
    return tree
