from .log import Log, register_logger
from .timer import Timer, global_timer

__all__ = ["Log", "register_logger", "Timer", "global_timer"]
