"""Pluggable file IO (reference VirtualFileReader/VirtualFileWriter,
src/io/file_io.cpp + utils/file_io.h, incl. the optional HDFS backend
behind USE_HDFS).

Local paths use plain open(). URI-style paths (scheme://...) dispatch to
a registered handler; `fsspec` is auto-used when importable (which
covers hdfs/s3/gs/... the way the reference's HDFS build does), and
custom schemes can be registered explicitly:

    lightgbm_tpu.utils.file_io.register_filesystem("myfs", opener)

where `opener(path, mode)` returns a file object. Every model-file,
dataset-binary and CLI read/write in the package goes through
open_file()."""

from __future__ import annotations

from typing import Callable, Dict

__all__ = ["open_file", "register_filesystem"]

_SCHEMES: Dict[str, Callable] = {}


def register_filesystem(scheme: str, opener: Callable) -> None:
    """Register `opener(path, mode)` for `scheme://` paths."""
    _SCHEMES[scheme] = opener


def _scheme_of(path) -> str:
    s = str(path)
    if "://" in s:
        return s.split("://", 1)[0]
    return ""


def open_file(path, mode: str = "r"):
    """open() for local paths; registered handler or fsspec for URIs."""
    scheme = _scheme_of(path)
    if not scheme:
        return open(path, mode)
    if scheme in _SCHEMES:
        return _SCHEMES[scheme](str(path), mode)
    try:
        import fsspec
    except ImportError:
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} and fsspec "
            f"is not installed; register one with "
            f"lightgbm_tpu.utils.file_io.register_filesystem") from None
    try:
        return fsspec.open(str(path), mode).open()
    except (ValueError, ImportError) as e:
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} and fsspec "
            f"cannot handle it ({e}); register one with "
            f"lightgbm_tpu.utils.file_io.register_filesystem") from e
