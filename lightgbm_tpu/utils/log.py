"""Leveled logger with pluggable sink.

Reference: include/LightGBM/utils/log.h:81 (Log class, LogLevel, callback
sink log.h:83-90; Python redirection basic.py:48-108). Here it is a thin
wrapper over the stdlib logging module with the same level semantics:
Fatal raises, Warning/Info/Debug gated by verbosity.
"""

from __future__ import annotations

import logging
import sys
from typing import Callable, Optional

_logger = logging.getLogger("lightgbm_tpu")
if not _logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("[LightGBM-TPU] [%(levelname)s] %(message)s"))
    _logger.addHandler(_h)
    _logger.setLevel(logging.INFO)

_custom_sink: Optional[Callable[[str], None]] = None


class LightGBMError(Exception):
    """Fatal error raised by Log.fatal (reference log.h:110 raises)."""


def register_logger(logger_or_callback) -> None:
    """Redirect log output (reference LGBM_RegisterLogCallback c_api.h:71)."""
    global _custom_sink, _logger
    if callable(logger_or_callback) and not isinstance(
            logger_or_callback, logging.Logger):
        _custom_sink = logger_or_callback
    elif isinstance(logger_or_callback, logging.Logger):
        _logger = logger_or_callback
        _custom_sink = None


class Log:
    verbosity: int = 1  # <0: fatal only, 0: +warn, 1: +info, >1: +debug

    @staticmethod
    def set_verbosity(v: int) -> None:
        Log.verbosity = v

    @staticmethod
    def _emit(level: int, msg: str) -> None:
        if _custom_sink is not None:
            _custom_sink(msg + "\n")
        else:
            _logger.log(level, msg)

    @staticmethod
    def debug(msg: str, *args) -> None:
        if Log.verbosity > 1:
            Log._emit(logging.DEBUG, msg % args if args else msg)

    @staticmethod
    def info(msg: str, *args) -> None:
        if Log.verbosity >= 1:
            Log._emit(logging.INFO, msg % args if args else msg)

    @staticmethod
    def warning(msg: str, *args) -> None:
        if Log.verbosity >= 0:
            Log._emit(logging.WARNING, msg % args if args else msg)

    @staticmethod
    def fatal(msg: str, *args) -> None:
        text = msg % args if args else msg
        Log._emit(logging.ERROR, text)
        raise LightGBMError(text)
