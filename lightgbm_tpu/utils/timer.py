"""Named-phase accumulating timers.

Reference: Common::Timer / FunctionTimer RAII profiling accumulators
(include/LightGBM/utils/common.h:973,1037; printed at exit under USE_TIMETAG)
plus one process-global registry `global_timer` (src/boosting/gbdt.cpp:20).
This host timer brackets whole phases the same way the reference brackets
CUDA phases (cuda_single_gpu_tree_learner.cpp:112-169). For the device
side, `lightgbm_tpu/observability/profile.py` brackets real
``jax.profiler`` captures around named spans (``profile_spans=`` globs,
e.g. ``pipeline_block,sharded_grow`` — the BENCH_r06 attribution
protocol in docs/Performance.md).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict


class Timer:
    def __init__(self) -> None:
        self._acc: Dict[str, float] = defaultdict(float)
        self._count: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def timeit(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] += time.perf_counter() - start
            self._count[name] += 1

    def add(self, name: str, seconds: float) -> None:
        self._acc[name] += seconds
        self._count[name] += 1

    def totals(self) -> Dict[str, float]:
        return dict(self._acc)

    def report(self) -> str:
        lines = ["LightGBM-TPU phase timings:"]
        for name in sorted(self._acc, key=self._acc.get, reverse=True):
            lines.append(f"  {name}: {self._acc[name]:.3f}s "
                         f"(x{self._count[name]})")
        return "\n".join(lines)

    def reset(self) -> None:
        self._acc.clear()
        self._count.clear()


global_timer = Timer()
