"""Dispatch entry points stripped of their fault sites (parsed, never
executed) — FAULT001 must flag each manifest row it can resolve."""


def train_many_dispatch(trees):
    # FAULT001: fused dispatch without the fused_dispatch site
    return list(trees)


def _grow(node):
    # FAULT001 twice: histogram_build and collective_psum both missing
    return node
