"""Fixture helper: host-syncs its parameter (callee side of the
cross-module JIT003 case)."""


def to_python_scalar(v):
    return float(v)
