"""Fixture: jitted body passes a traced value to a helper that
host-syncs it in another module — invisible to the lexical JIT003,
caught by the interprocedural engine."""

import jax

from .convert import to_python_scalar


@jax.jit
def scale(x):
    s = to_python_scalar(x)
    return x * s
