"""Fixture helper: performs a collective inside a callee (the
collective-in-callee side of the cross-module COLL001 case)."""

import jax


def sync_error_count(err):
    return jax.lax.psum(err, "ranks")
