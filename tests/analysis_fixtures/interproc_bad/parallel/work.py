"""Fixture: rank-divergent branch around a helper that psums inside —
the lexical COLL001 sees no collective here; the call graph does."""

import jax

from .comm_helper import sync_error_count


def report(err):
    r = jax.lax.axis_index("ranks")
    if r == 0:
        return sync_error_count(err)
    return err
