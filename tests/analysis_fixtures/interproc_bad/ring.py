"""Fixture: calls a `_locked` helper from another module without
holding any lock — the delegation edge only the call graph resolves."""

import threading

from .store import append_locked


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []

    def add(self, item):
        append_locked(self._buf, item)
