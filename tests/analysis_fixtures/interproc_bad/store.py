"""Fixture helper: `_locked`-suffixed mutator — the suffix contract
says every caller must hold the owning lock."""


def append_locked(buf, item):
    buf.append(item)
