"""Clean twin: helper only reads trace-static metadata, never
host-syncs its parameter."""


def leading_dim(v):
    return v.shape[0]
