"""Clean twin: the jitted body calls a helper, but the helper only
touches shape metadata — no host sync anywhere on the chain."""

import jax

from .convert import leading_dim


@jax.jit
def scale(x):
    n = leading_dim(x)
    return x * n
