"""Clean twin helper: same collective in a callee, reached from a
rank-uniform caller."""

import jax


def sync_error_count(err):
    return jax.lax.psum(err, "ranks")
