"""Clean twin: the collective-bearing helper runs unconditionally on
every rank — no divergent control flow guards it."""

import jax

from .comm_helper import sync_error_count


def report(err):
    total = sync_error_count(err)
    return jax.numpy.where(total > 0, total, err)
