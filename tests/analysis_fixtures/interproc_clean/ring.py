"""Clean twin: the `_locked` delegate is called with the lock held."""

import threading

from .store import append_locked


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []

    def add(self, item):
        with self._lock:
            append_locked(self._buf, item)
