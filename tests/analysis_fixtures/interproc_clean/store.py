"""Clean twin helper: identical `_locked` mutator; callers hold the
lock."""


def append_locked(buf, item):
    buf.append(item)
