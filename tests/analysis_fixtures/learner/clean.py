"""Look-alike patterns that are exempt by design — the analyzer must
report ZERO findings here. Each block mirrors a real idiom from the
package that a naive checker would false-positive on."""
import functools
import threading

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("num_outputs",))
def structural_dispatch(x, efb=None, row_valid=None, num_outputs=1):
    # `is None` branches select between pytrees: changing them retraces
    # anyway, so they are structural, not recompile hazards
    if efb is not None:
        x = x + efb
    if row_valid is not None:
        x = jnp.where(row_valid, x, 0.0)
    n = x.shape[0]               # .shape is static at trace time
    for i in range(x.ndim):      # range over a static attribute
        x = x + i
    return x * num_outputs + n


class CleanState:
    def __init__(self):
        self._lock = threading.Lock()
        self._worker = object()  # assigned only here: read-only after init
        self._jobs = []

    def push(self, item):
        with self._lock:
            self._jobs.append(item)

    def worker(self):
        return self._worker      # init-only attr needs no lock

    def _swap_locked(self):
        # `_locked` suffix: the caller holds the lock by contract
        jobs, self._jobs = self._jobs, []
        return jobs
