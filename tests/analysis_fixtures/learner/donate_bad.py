"""Known-bad buffer-donation fixtures. Never imported or executed —
parsed by tests/test_static_analysis.py, which pins the JIT004 line
numbers; the `ok_*` functions are the exempt idioms that must stay
silent."""
import functools

import jax


@functools.partial(jax.jit, donate_argnames=("score",))
def advance(score, delta):
    return score + delta


def use_after_keyword_donation(score, delta):
    out = advance(score=score, delta=delta)
    return out + score          # JIT004: score was donated on line 16


def _step(carry, dx):
    return carry * dx


step = jax.jit(_step, donate_argnames=("carry",))


def use_after_positional_donation(carry, dx):
    nxt = step(carry, dx)
    total = carry + 1.0         # JIT004: carry donated positionally
    return nxt, total


def ok_rebind_from_result(score, delta):
    score = advance(score=score, delta=delta)
    return score * 2.0          # rebound from the call's result: clean


class Holder:
    def ok_attribute_receiver(self, delta):
        # attribute-form donated args are deliberately not tracked —
        # attribute rebinding is object-ownership territory the
        # name-flow analysis cannot see
        out = advance(score=self.buf, delta=delta)
        return out + self.buf


def ok_store_then_use(carry, dx):
    nxt = step(carry, dx)
    carry = nxt
    return carry + 1.0          # rebound before the read: clean
