"""Known-bad dtype-discipline fixtures (parsed, never executed)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def f64_in_device_code(x):
    acc = jnp.zeros(8, dtype=jnp.float64)   # DTYPE001: f64 accumulator
    y = x.astype("float64")                 # DTYPE001: f64 string dtype
    z = np.float64(0.0)                     # DTYPE001: np.float64
    w = x.astype(float)                     # DTYPE002: implicit promotion
    v = jnp.asarray(x, dtype=float)         # DTYPE002: dtype=float kwarg
    return acc, y, z, w, v
