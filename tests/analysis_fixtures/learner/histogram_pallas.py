"""PERF001 fixture: `argsort` inside registered device hot-path
functions. The basename matches the real hot-path module so the
rules_perf.HOT_PATH_MANIFEST rows apply; host-side helpers that are
not in the manifest must stay exempt, and an explicit line
suppression must downgrade without hiding."""

import jax.numpy as jnp
import numpy as np


def partition_rows(row_slot, num_slots):
    order = jnp.argsort(row_slot)          # manifest entry point: fires
    return order[:num_slots]


def build_histograms_scatter(bins, row_slot):
    def sweep(s):
        return np.argsort(s)               # nested helper: covered
    return bins[sweep(row_slot)]


def _host_side_bin_boundaries(values):
    # NOT in the manifest: host-side setup (runs once per Dataset, not
    # once per level) may sort freely
    return np.argsort(values)


def build_histograms_pallas(bins, row_slot):
    # the sanctioned oracle shape: visible, auditable suppression
    order = jnp.argsort(row_slot)  # tpulint: disable=PERF001
    return bins[order]
