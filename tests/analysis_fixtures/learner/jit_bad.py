"""Known-bad jit-hygiene fixtures. Never imported or executed — parsed
by tests/test_static_analysis.py, which pins the rule ids and line
numbers each marked line must fire."""
import functools

import jax
import numpy as np


@jax.jit
def scalar_leak(x, lr: float):
    # JIT001 on the def: `lr` is a bare-scalar-annotated param not in
    # static_argnames — every new value recompiles
    return x * lr


@functools.partial(jax.jit, static_argnames=("n",))
def control_flow(x, n: int, depth=4):
    # JIT001 on the def: `depth` has a Python-scalar default
    if depth > 2:                # JIT002: Python branch on a traced value
        x = x + 1.0
    for _ in range(depth):       # JIT002: range() over a traced value
        x = x * 2.0
    return x * n


@jax.jit
def host_sync(x):
    total = float(x.sum())       # JIT003: float() forces a host sync
    arr = np.asarray(x)          # JIT003: numpy call on a traced value
    flag = bool(x[0])            # JIT003: bool() forces a host sync
    val = x.max().item()         # JIT003: .item() forces a host sync
    return total, arr, flag, val
