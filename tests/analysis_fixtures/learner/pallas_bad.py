"""PALLAS001 fixtures: undeclared block shapes + traced closures."""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _good_factory(nb):
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * nb
    return kernel


@functools.partial(jax.jit, static_argnames=("nb",))
def no_block_decls(x, *, nb):
    # line below: pallas_call without grid_spec or in_specs/out_specs
    return pl.pallas_call(
        _good_factory(nb),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


@functools.partial(jax.jit, static_argnames=("nb",))
def traced_closure(x, scale, *, nb):
    def kernel(x_ref, o_ref):
        # `scale` is a traced parameter of the jitted enclosing
        # function — a tracer at kernel-build time
        o_ref[...] = x_ref[...] * scale
    return pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec(x.shape, lambda i: (0, 0))],
        out_specs=pl.BlockSpec(x.shape, lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def _bad_factory(scale):
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * scale
    return kernel


@jax.jit
def traced_factory_arg(x, scale):
    return pl.pallas_call(
        _bad_factory(scale),  # traced arg baked into the kernel
        grid=(1,),
        in_specs=[pl.BlockSpec(x.shape, lambda i: (0, 0))],
        out_specs=pl.BlockSpec(x.shape, lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


@functools.partial(jax.jit, static_argnames=("nb",))
def clean(x, *, nb):
    # statics through the factory, traced data through operands: clean
    return pl.pallas_call(
        _good_factory(nb),
        grid=(1,),
        in_specs=[pl.BlockSpec(x.shape, lambda i: (0, 0))],
        out_specs=pl.BlockSpec(x.shape, lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
