"""Violations silenced by inline suppressions — each must be reported
with suppressed=True and not count against the exit status."""
import threading

import jax


@jax.jit
def quiet_sync(x):
    return float(x.sum())  # tpulint: disable=JIT003


class QuietState:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def peek(self):
        return self._n  # tpulint: disable=LOCK001
