"""Known-bad lock-discipline fixture (parsed, never executed)."""
import threading


class SharedState:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._count = 0

    def put(self, key, value):
        with self._lock:
            self._items[key] = value
            self._count += 1

    def peek(self, key):
        return self._items.get(key)   # LOCK001: read outside the lock

    def reset(self):
        self._count = 0               # LOCK001: write outside the lock

    def _drain_locked(self):
        return list(self._items)      # clean: `_locked` caller-holds contract
