"""Lock-order cycle fixture: Alpha calls into Beta's lock while holding
its own, and Beta does the reverse — LOCK002 must reject the cycle."""
import threading


class Alpha:
    def __init__(self, beta):
        self._lock = threading.Lock()
        self._beta = beta
        self._state = 0

    def poke_beta(self):
        with self._lock:
            self._state += 1
            self._beta.absorb_alpha()   # holds Alpha._lock -> Beta._lock

    def absorb_beta(self):
        with self._lock:
            self._state += 1


class Beta:
    def __init__(self, alpha):
        self._lock = threading.Lock()
        self._alpha = alpha
        self._state = 0

    def absorb_alpha(self):
        with self._lock:
            self._state += 1

    def poke_alpha(self):
        with self._lock:
            self._state += 1
            self._alpha.absorb_beta()   # holds Beta._lock -> Alpha._lock
