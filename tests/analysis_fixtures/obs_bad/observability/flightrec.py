"""Flight-recorder stub (parsed, never executed) — its presence under
an observability/ dir is the OBS001 gate: this fixture tree models a
package that HAS the crash flight recorder, so unbracketed manifest
sites are real findings."""
