"""Bracketed collective wrapper stripped of its observability bracket
(parsed, never executed) — OBS001 must flag guarded_allgather."""


def check_collective_fault(site):
    return site


def guarded_allgather(arr, label):
    # fault site present (FAULT001 quiet) but no collective_guard /
    # span / record_* bracket — OBS001 fires on the def line above
    check_collective_fault("collective_psum")
    return arr


def checkpoint_agree(value, label):
    # covered: delegates to the bracketed wrapper
    return guarded_allgather(value, label)
