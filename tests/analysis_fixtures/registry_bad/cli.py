"""Miniature drifted CLI (parsed, never executed)."""

SITE = "site_a"                              # wires site_a for REG004
OK_FAMILY = "lightgbm_tpu_documented_family"
BAD_FAMILY = "lightgbm_tpu_rogue_family"     # REG005: not in the doc


class Application:
    def __init__(self, cfg):
        self.config = cfg

    def run(self):
        task = self.config.task
        if task == "train":
            self.train()
        elif task == "fit":                  # REG002: config rejects "fit"
            self.train()

    def train(self):
        cfg = self.config
        faults.inject("site_zzz")            # REG004: unknown site  # noqa: F821
        return cfg.alpha + cfg.not_a_param   # REG003: unregistered attr
