"""Miniature drifted config registry (parsed, never executed). The
sibling docs/ dir at tests/analysis_fixtures/docs/ carries the
deliberately stale mirrors the REG rules must flag."""


def _p(name, type_, default, aliases=(), check=None):
    return (name, type_, default, tuple(aliases), check)


_PARAMS = [
    _p("task", str, "train", ("task_type",),
       lambda v: v in ("train", "predict")),
    _p("alpha", float, 0.5, ("alias_one",)),   # REG001: no doc row
    _p("beta", float, 0.5, ("alpha",)),        # REG001: alias hits a param name
]


class Config:
    def __init__(self, params=None):
        self.raw_params = {}
