"""Miniature fault-site registry (parsed, never executed)."""

KNOWN_SITES = (
    "site_a",   # wired (cli.py) + documented (docs/Reliability.md)
    "site_b",   # REG004 twice: unwired and undocumented
)
