"""Known-bad SPMD fixture: each COLL001/002/003 shape at a pinned line.

Host-side module (not under a device dir), so per-rank data extents
(len(...), .shape reads) seed the rank-taint — the conditions of the
streaming ingest path these rules were built for. Every function here
deadlocks or diverges a real multihost run.
"""
import jax
import numpy as np
from jax.experimental import multihost_utils


def branch_deadlock(x):
    r = jax.process_index()
    if r == 0:
        return jax.lax.psum(x, "data")
    return x


def loop_deadlock(chunks):
    total = 0
    for i in range(len(chunks)):
        total = total + jax.lax.psum(chunks[i], "data")
    return total


def cond_expr_deadlock(x):
    r = jax.process_index()
    return jax.lax.psum(x, "data") if r > 0 else x


def stranded_raise(rows):
    if len(rows) == 0:
        raise ValueError("empty shard on this rank")
    return multihost_utils.process_allgather(rows)


def pr7_bin_parity(sample, mapper_sync):
    # the PR-7 stream_bin_parity bug shape: rank-local validation with
    # a bare raise while peers proceed into the mapper collective
    if len(sample) > 100:
        return mapper_sync(sample)
    else:
        raise ValueError("bin parity check failed on this rank")


def ragged_gather(rows):
    n = len(rows)
    head = rows[:n]
    return multihost_utils.process_allgather(head)


def resize_epoch_vote(flag):
    # elastic-resize anti-pattern: only the coordinator gathers the
    # shrink vote while survivors skip the collective — the exact
    # deadlock the heartbeat-directory vote protocol exists to avoid
    r = jax.process_index()
    if r == 0:
        return multihost_utils.process_allgather(flag)
    return np.asarray(flag)
