"""Clean twins of coll_bad.py: the sanctioned multihost idioms.

Every function here is the repaired form of a coll_bad shape and must
stay silent — matching collectives on both arms, agreement-sync before
raising, participate-then-raise, pad-to-static-wire-shape, and
branches/loops on rank-uniform configuration.
"""
import jax
import numpy as np
from jax.experimental import multihost_utils


def branch_both_arms(x):
    r = jax.process_index()
    if r == 0:
        y = jax.lax.psum(x, "data")
    else:
        y = jax.lax.psum(x * 0, "data")
    return y


def agreement_sync_then_raise(sample):
    ok = 1 if len(sample) > 0 else 0
    oks = multihost_utils.process_allgather(ok)
    if min(oks) == 0:
        raise ValueError("a rank had no rows - all ranks abort together")
    return multihost_utils.process_allgather(sample)


def participate_then_raise(sample, mapper_sync):
    if len(sample) == 0:
        mapper_sync(None)
        raise ValueError("empty shard; peers were released first")
    return mapper_sync(sample)


def padded_gather(rows, per_rank):
    n = len(rows)
    if n < per_rank:
        rows = np.pad(rows, (0, per_rank - n))
    return multihost_utils.process_allgather(rows)


def uniform_config_branch(x, cfg):
    if cfg.force_row_wise:
        return jax.lax.psum(x, "data")
    return jax.lax.psum(x * 1, "data")


def uniform_loop(x, num_rounds):
    for _ in range(num_rounds):
        x = jax.lax.psum(x, "data")
    return x
