"""Package-root marker: its presence arms the project-wide registry
rules (COLL004 discovery) for this fixture directory. The docs tree is
deliberately absent here, so the parameter-docs rule is silenced —
a live file suppression SUP001 must accept."""
# tpulint: disable-file=REG001
