"""An unregistered collective entry point: COLL004 discovery target."""
from jax.experimental import multihost_utils


def rogue_sync(values):
    return multihost_utils.process_allgather(values)
