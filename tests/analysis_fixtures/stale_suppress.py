"""Stale-suppression fixture: SUP001 positives plus one live negative.

Three dead comments (unknown rule id, dead line suppression, dead
file-wide suppression) and one live LOCK001 suppression that must NOT
be flagged.
"""
import threading

import numpy as np

# tpulint: disable-file=LOCK002


def fine(x):
    return np.asarray(x)  # tpulint: disable=NOPE123


def also_fine(x):
    return x + 1  # tpulint: disable=JIT003


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def peek(self):
        return self._n  # tpulint: disable=LOCK001
