"""Fixture trace manifest: one contract violation per TRACE rule.

rules_trace loads this module (any scanned file named
``trace_manifest.py``) instead of the production manifest, so the
TRACE rules can be pinned against known-bad traced programs without
planting violations in the package. Every entry is a tiny
self-contained jax program; `line` anchors the expected finding.
"""

import functools

from lightgbm_tpu.analysis.tracecheck import (TraceEntry,
                                              retrace_stable)


def _shaped(shape, dtype="float32"):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _probe_sorting():
    import jax
    import jax.numpy as jnp

    def f(x):
        return jnp.sort(x) * 2.0

    return {"jaxpr": jax.make_jaxpr(f)(_shaped((16,)))}


def _probe_f64():
    import warnings

    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    def f(x):
        return x.astype(jnp.float64) * 2.0

    with warnings.catch_warnings():
        # the default-mode trace truncates f64 -> f32 with a warning;
        # the x64 trace below is the one the rule inspects
        warnings.simplefilter("ignore")
        out = {"jaxpr": jax.make_jaxpr(f)(_shaped((16,)))}
    with enable_x64():
        out["jaxpr_x64"] = jax.make_jaxpr(f)(_shaped((16,)))
    return out


def _probe_callback():
    import jax
    import jax.numpy as jnp

    def f(x):
        jax.debug.print("x sum {}", jnp.sum(x))
        return x * 2.0

    return {"jaxpr": jax.make_jaxpr(f)(_shaped((16,)))}


def _probe_dead_donation():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def f(scratch, x):
        # no output matches the donated buffer's shape/dtype: the
        # declared donation is unusable and silently dropped
        return (x * 2.0).astype(jnp.int32)

    traced = f.trace(_shaped((16,)), _shaped((16,)))
    return {"jaxpr": traced.jaxpr,
            "lowered_text": traced.lower().as_text()}


def _probe_baked_scalar():
    import jax

    @functools.partial(jax.jit, static_argnums=(1,))
    def f(x, k):
        return x * k

    traced = f.trace(_shaped((16,)), 2)
    # k is declared dispatch-stable below but marked static here: each
    # value recompiles, so the two traces differ
    stable = retrace_stable(f, [(_shaped((16,)), 2),
                                (_shaped((16,)), 3)])
    return {"jaxpr": traced.jaxpr, "stable": stable}


TRACE_MANIFEST = (
    TraceEntry(name="sorting_entry", target_file="trace_manifest.py",
               target_fn="_probe_sorting", build=_probe_sorting,
               line=94),
    TraceEntry(name="f64_entry", target_file="trace_manifest.py",
               target_fn="_probe_f64", build=_probe_f64,
               x64_mode=True, line=97),
    TraceEntry(name="callback_entry", target_file="trace_manifest.py",
               target_fn="_probe_callback", build=_probe_callback,
               line=100),
    TraceEntry(name="dead_donation_entry",
               target_file="trace_manifest.py",
               target_fn="_probe_dead_donation",
               build=_probe_dead_donation, donate=True, line=103),
    TraceEntry(name="baked_scalar_entry",
               target_file="trace_manifest.py",
               target_fn="_probe_baked_scalar",
               build=_probe_baked_scalar, stable_over="k", line=107),
)

#: one dispatch row with no covering entry and no waiver, plus one
#: waiver naming a row that does not exist (both TRACE006)
DISPATCH_ROWS = (
    ("gbdt.py", "train_many_dispatch", "fused_dispatch"),
)

WAIVERS = {
    ("removed.py", "old_entry", "stale_site"): "row no longer exists",
}
