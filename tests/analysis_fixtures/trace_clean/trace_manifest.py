"""Clean twin of trace_bad: every contract satisfied, zero findings.

One entry exercising every contract flag (sort-free, x64, callbacks,
donation, retrace stability) compliantly, covering the only dispatch
row — the TRACE rules must stay silent here.
"""

import functools

from lightgbm_tpu.analysis.tracecheck import (TraceEntry,
                                              retrace_stable)


def _shaped(shape, dtype="float32"):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _probe_clean():
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    @functools.partial(jax.jit, donate_argnums=(0,))
    def f(acc, x, k):
        # k stays traced (weak scalar): no per-value recompile; the
        # donated accumulator aliases the output
        return acc + x * k

    traced = f.trace(_shaped((16,)), _shaped((16,)), 2)
    stable = retrace_stable(f, [(_shaped((16,)), _shaped((16,)), 2),
                                (_shaped((16,)), _shaped((16,)), 3)])
    out = {"jaxpr": traced.jaxpr,
           "lowered_text": traced.lower().as_text(),
           "stable": stable}
    with enable_x64():
        out["jaxpr_x64"] = f.trace(
            _shaped((16,)), _shaped((16,)), 2).jaxpr
    return out


TRACE_MANIFEST = (
    TraceEntry(name="clean_entry", target_file="trace_manifest.py",
               target_fn="_probe_clean", build=_probe_clean,
               covers=(("gbdt.py", "train_many_dispatch",
                        "fused_dispatch"),),
               x64_mode=True, donate=True, stable_over="k", line=43),
)

DISPATCH_ROWS = (
    ("gbdt.py", "train_many_dispatch", "fused_dispatch"),
)

WAIVERS = {}
