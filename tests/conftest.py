"""Test env: CPU backend with 8 virtual devices for sharding tests.

Reference test strategy (SURVEY.md §4): distributed tests run N processes on
localhost sockets (tests/distributed/_test_distributed.py). The TPU-native
equivalent is a virtual multi-device CPU mesh — same collectives, no pod.
"""

from lightgbm_tpu.parallel.mesh import provision_virtual_devices

provision_virtual_devices(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def make_binary(n=2000, f=10, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    logit = X[:, 0] * 1.5 + 0.5 * X[:, 1] ** 2 - X[:, 2] + 0.3 * r.randn(n)
    y = (logit > np.median(logit)).astype(np.float32)
    return X, y


def make_regression(n=2000, f=10, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] * 2 + X[:, 1] ** 2 - 0.5 * X[:, 2] +
         0.1 * r.randn(n)).astype(np.float32)
    return X, y


def make_multiclass(n=3000, f=10, k=4, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    centers = r.randn(k, f) * 2
    logits = X @ centers.T + 0.5 * r.randn(n, k)
    y = logits.argmax(1).astype(np.float32)
    return X, y


def make_ranking(num_queries=100, docs_per_query=20, f=10, seed=0):
    r = np.random.RandomState(seed)
    n = num_queries * docs_per_query
    X = r.randn(n, f)
    rel = X[:, 0] + 0.5 * X[:, 1] + 0.5 * r.randn(n)
    # map to 0-4 labels by quantile
    qs = np.quantile(rel, [0.5, 0.75, 0.9, 0.97])
    y = np.digitize(rel, qs).astype(np.float32)
    group = np.full(num_queries, docs_per_query)
    return X, y, group
