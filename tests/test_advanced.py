"""Forced splits, CEGB penalties, prediction early-stop
(reference test_engine.py test_forced_split / test_cegb /
test_pred_early_stopping sections)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from conftest import make_binary, make_multiclass


class TestForcedSplits:
    def _train(self, tmp_path, spec, n_leaves=8, rounds=3):
        r = np.random.RandomState(0)
        X = r.randn(2000, 5).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
        fn = tmp_path / "forced.json"
        fn.write_text(json.dumps(spec))
        bst = lgb.train({"objective": "binary", "num_leaves": n_leaves,
                         "forcedsplits_filename": str(fn), "verbosity": -1,
                         "min_data_in_leaf": 5},
                        lgb.Dataset(X, label=y), rounds)
        return bst, X, y

    def test_root_split_forced(self, tmp_path):
        bst, _, _ = self._train(tmp_path,
                                {"feature": 2, "threshold": 0.0})
        for t in bst.dump_model()["tree_info"]:
            assert t["tree_structure"]["split_feature"] == 2

    def test_nested_forced_splits(self, tmp_path):
        spec = {"feature": 2, "threshold": 0.0,
                "left": {"feature": 3, "threshold": 0.5},
                "right": {"feature": 4, "threshold": -0.5}}
        bst, _, _ = self._train(tmp_path, spec)
        root = bst.dump_model()["tree_info"][0]["tree_structure"]
        assert root["split_feature"] == 2
        assert root["left_child"]["split_feature"] == 3
        assert root["right_child"]["split_feature"] == 4
        assert root["right_child"]["threshold"] == pytest.approx(-0.5,
                                                                 abs=0.2)

    def test_accuracy_not_destroyed(self, tmp_path):
        bst, X, y = self._train(tmp_path,
                                {"feature": 4, "threshold": 0.0},
                                n_leaves=16, rounds=20)
        acc = np.mean((bst.predict(X) > 0.5) == y)
        assert acc > 0.9

    def test_unused_feature_ignored(self, tmp_path):
        # feature 99 doesn't exist -> spec dropped, training proceeds
        bst, X, y = self._train(tmp_path, {"feature": 99, "threshold": 0.0})
        assert bst.num_trees() > 0


class TestCEGB:
    def _data(self):
        r = np.random.RandomState(1)
        X = r.randn(3000, 6).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] +
             0.1 * r.randn(3000) > 0).astype(np.float32)
        return X, y

    def test_coupled_penalty_blocks_feature(self):
        X, y = self._data()
        pen = [0.0, 1e6, 0.0, 0.0, 0.0, 0.0]
        bst = lgb.train({"objective": "binary", "num_leaves": 16,
                         "verbosity": -1, "cegb_tradeoff": 1.0,
                         "cegb_penalty_feature_coupled": pen},
                        lgb.Dataset(X, label=y), 5)
        assert bst.feature_importance()[1] == 0

    def test_split_penalty_shrinks_trees(self):
        X, y = self._data()
        base = {"objective": "binary", "num_leaves": 32, "verbosity": -1}
        b0 = lgb.train(base, lgb.Dataset(X, label=y), 5)
        b1 = lgb.train({**base, "cegb_penalty_split": 0.1},
                       lgb.Dataset(X, label=y), 5)
        n0 = sum(t["num_leaves"] for t in b0.dump_model()["tree_info"])
        n1 = sum(t["num_leaves"] for t in b1.dump_model()["tree_info"])
        assert n1 < n0

    def test_lazy_penalty_trains(self):
        X, y = self._data()
        bst = lgb.train({"objective": "binary", "num_leaves": 16,
                         "verbosity": -1,
                         "cegb_penalty_feature_lazy": [0.01] * 6},
                        lgb.Dataset(X, label=y), 5)
        acc = np.mean((bst.predict(X) > 0.5) == y)
        assert acc > 0.9

    def test_lazy_penalty_concentrates_features(self):
        # a uniform lazy penalty favors re-using already-charged features,
        # so the used-feature set should not grow vs the unpenalized model
        X, y = self._data()
        base = {"objective": "binary", "num_leaves": 16, "verbosity": -1}
        b0 = lgb.train(base, lgb.Dataset(X, label=y), 5)
        b1 = lgb.train({**base, "cegb_penalty_feature_lazy": [10.0] * 6},
                       lgb.Dataset(X, label=y), 5)
        used0 = np.sum(b0.feature_importance() > 0)
        used1 = np.sum(b1.feature_importance() > 0)
        assert used1 <= used0


class TestPredEarlyStop:
    def test_binary_matches_when_margin_huge(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        lgb.Dataset(X, label=y), 30)
        full = bst.predict(X)
        es = bst.predict(X, pred_early_stop=True,
                         pred_early_stop_freq=5,
                         pred_early_stop_margin=1e10)
        np.testing.assert_allclose(full, es, rtol=1e-6)

    def test_binary_approximates_with_margin(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        lgb.Dataset(X, label=y), 60)
        full = bst.predict(X)
        es = bst.predict(X, pred_early_stop=True,
                         pred_early_stop_freq=5,
                         pred_early_stop_margin=1.5)
        # hard-classification agreement stays high even though margins differ
        agree = np.mean((full > 0.5) == (es > 0.5))
        assert agree > 0.95

    def test_multiclass_early_stop(self):
        X, y = make_multiclass(k=3)
        bst = lgb.train({"objective": "multiclass", "num_class": 3,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 30)
        full = bst.predict(X).argmax(axis=1)
        es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=3,
                         pred_early_stop_margin=3.0).argmax(axis=1)
        assert np.mean(full == es) > 0.95
