"""Dataset/binning/config tests (reference tests/python_package_test/test_basic.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import BinMapper, MissingType, find_bin_mappers
from lightgbm_tpu.config import Config, parse_config_file
from lightgbm_tpu.data import BinnedDataset, Metadata


class TestConfig:
    def test_defaults(self):
        cfg = Config()
        assert cfg.num_leaves == 31
        assert cfg.learning_rate == 0.1
        assert cfg.max_bin == 255
        assert cfg.objective == "regression"

    def test_aliases(self):
        cfg = Config({"n_estimators": 50, "eta": 0.3, "min_child_samples": 5,
                      "reg_lambda": 1.5, "subsample": 0.8})
        assert cfg.num_iterations == 50
        assert cfg.learning_rate == 0.3
        assert cfg.min_data_in_leaf == 5
        assert cfg.lambda_l2 == 1.5
        assert cfg.bagging_fraction == 0.8

    def test_string_coercion(self):
        cfg = Config({"num_leaves": "63", "feature_fraction": "0.5",
                      "is_unbalance": "true"})
        assert cfg.num_leaves == 63
        assert cfg.feature_fraction == 0.5
        assert cfg.is_unbalance is True

    def test_invalid_value(self):
        with pytest.raises(ValueError):
            Config({"bagging_fraction": 1.5})

    def test_max_depth_caps_leaves(self):
        cfg = Config({"max_depth": 3, "num_leaves": 100})
        assert cfg.num_leaves == 8

    def test_goss_disables_bagging(self):
        cfg = Config({"boosting": "goss", "bagging_freq": 5,
                      "bagging_fraction": 0.5})
        assert cfg.bagging_freq == 0
        assert cfg.bagging_fraction == 1.0

    def test_config_file_parsing(self, tmp_path):
        p = tmp_path / "train.conf"
        p.write_text("task = train\n# comment\nnum_leaves=7 # inline\n\n")
        kv = parse_config_file(str(p))
        assert kv == {"task": "train", "num_leaves": "7"}

    def test_metric_list(self):
        cfg = Config({"metric": "auc,binary_logloss"})
        assert cfg.metric_list() == ["auc", "binary_logloss"]


class TestBinMapper:
    def test_simple_uniform(self):
        vals = np.linspace(-1, 1, 1000)
        m = BinMapper.from_sample(vals, 1000, max_bin=16, min_data_in_bin=1)
        assert 2 < m.num_bin <= 16
        bins = m.values_to_bins(vals)
        assert bins.min() == 0
        assert bins.max() == m.num_bin - 1
        # monotone: larger value -> same or larger bin
        assert np.all(np.diff(bins) >= 0)

    def test_few_distinct(self):
        vals = np.repeat([1.0, 2.0, 3.0], 100)
        m = BinMapper.from_sample(vals, 300, max_bin=255, min_data_in_bin=3)
        bins = m.values_to_bins(np.array([1.0, 2.0, 3.0]))
        assert len(set(bins.tolist())) == 3

    def test_zero_gets_own_bin(self):
        vals = np.concatenate([np.full(50, -1.0), np.full(100, 1.0)])
        m = BinMapper.from_sample(vals, 300, max_bin=16, min_data_in_bin=1)
        bz = m.values_to_bins(np.array([0.0]))[0]
        bneg = m.values_to_bins(np.array([-1.0]))[0]
        bpos = m.values_to_bins(np.array([1.0]))[0]
        assert bneg < bz < bpos
        assert m.default_bin == bz

    def test_nan_bin(self):
        vals = np.concatenate([np.random.RandomState(0).randn(500),
                               np.full(100, np.nan)])
        m = BinMapper.from_sample(vals, 600, max_bin=32, min_data_in_bin=1)
        assert m.missing_type == MissingType.NAN
        b = m.values_to_bins(np.array([np.nan]))[0]
        assert b == m.num_bin - 1

    def test_no_missing(self):
        vals = np.random.RandomState(0).randn(500)
        m = BinMapper.from_sample(vals, 500, max_bin=32, min_data_in_bin=1)
        assert m.missing_type == MissingType.NONE

    def test_categorical(self):
        r = np.random.RandomState(0)
        vals = r.choice([0, 1, 2, 5, 9], size=1000,
                        p=[0.4, 0.3, 0.2, 0.05, 0.05]).astype(float)
        m = BinMapper.from_sample(vals, 1000, max_bin=255,
                                  is_categorical=True)
        assert m.is_categorical
        # most frequent category -> bin 1 (bin 0 is the NaN dummy)
        assert m.categorical_2_bin[0] == 1
        bins = m.values_to_bins(np.array([0.0, 1.0, 777.0, np.nan]))
        assert bins[0] == 1
        assert bins[2] == 0  # unseen -> dummy
        assert bins[3] == 0  # nan -> dummy

    def test_serialization_roundtrip(self):
        vals = np.random.RandomState(0).randn(500)
        m = BinMapper.from_sample(vals, 500, max_bin=64, min_data_in_bin=1)
        m2 = BinMapper.from_dict(m.to_dict())
        test = np.random.RandomState(1).randn(100)
        np.testing.assert_array_equal(m.values_to_bins(test),
                                      m2.values_to_bins(test))

    def test_max_bin_respected(self):
        for mb in (3, 15, 63, 255):
            vals = np.random.RandomState(0).randn(10000)
            m = BinMapper.from_sample(vals, 10000, max_bin=mb,
                                      min_data_in_bin=1)
            assert m.num_bin <= mb


class TestBinnedDataset:
    def test_construct(self):
        X = np.random.RandomState(0).randn(500, 5)
        ds = BinnedDataset.from_raw(X, Metadata(500), max_bin=63)
        assert ds.num_data == 500
        assert ds.num_features == 5
        assert ds.bins.dtype == np.uint8
        assert ds.total_bins == ds.num_bins.sum()

    def test_trivial_feature_filtered(self):
        X = np.random.RandomState(0).randn(500, 3)
        X[:, 1] = 7.0  # constant
        ds = BinnedDataset.from_raw(X, Metadata(500), max_bin=63)
        assert ds.num_features == 2
        assert list(ds.used_features) == [0, 2]

    def test_subset(self):
        X = np.random.RandomState(0).randn(500, 5)
        y = np.random.RandomState(0).rand(500).astype(np.float32)
        ds = BinnedDataset.from_raw(X, Metadata(500, label=y), max_bin=63)
        sub = ds.subset(np.arange(100))
        assert sub.num_data == 100
        np.testing.assert_array_equal(sub.bins, ds.bins[:100])

    def test_metadata_validation(self):
        with pytest.raises(Exception):
            Metadata(100, label=np.zeros(50, np.float32))

    def test_query_boundaries(self):
        md = Metadata(100, label=np.zeros(100, np.float32),
                      group=np.full(10, 10))
        assert md.num_queries == 10
        assert md.query_boundaries[-1] == 100
        qids = md.query_ids()
        assert len(qids) == 100
        assert qids[0] == 0 and qids[-1] == 9


class TestDatasetAPI:
    def test_create_valid_and_set_categorical(self):
        rng = np.random.RandomState(3)
        X = rng.randn(400, 5)
        X[:, 2] = rng.randint(0, 8, size=400)
        y = (X[:, 0] > 0).astype(np.float32)
        d = lgb.Dataset(X, label=y)
        d.set_categorical_feature([2])
        v = d.create_valid(X[:80], label=y[:80])
        bst = lgb.train({"objective": "binary", "verbosity": -1}, d, 5,
                        valid_sets=[v], valid_names=["v"])
        assert bst.num_trees() == 5
        # after construction the categorical set is frozen
        with pytest.raises(Exception):
            d.set_categorical_feature([1])
        # unchanged set is a no-op, not an error
        d.set_categorical_feature([2])

    def test_lazy_construction(self):
        X = np.random.RandomState(0).randn(100, 4)
        y = np.zeros(100, np.float32)
        d = lgb.Dataset(X, label=y)
        assert d._binned is None
        d.construct()
        assert d._binned is not None
        assert d.num_data() == 100
        assert d.num_feature() == 4

    def test_reference_alignment(self):
        X = np.random.RandomState(0).randn(300, 4)
        y = np.zeros(300, np.float32)
        dtrain = lgb.Dataset(X[:200], label=y[:200])
        dvalid = lgb.Dataset(X[200:], label=y[200:], reference=dtrain)
        dtrain.construct()
        dvalid.construct()
        # same mappers => same bin boundaries
        for m1, m2 in zip(dtrain.binned.mappers, dvalid.binned.mappers):
            np.testing.assert_array_equal(m1.bin_upper_bound,
                                          m2.bin_upper_bound)

    def test_set_get_field(self):
        X = np.random.RandomState(0).randn(100, 4)
        d = lgb.Dataset(X, label=np.zeros(100))
        d.set_weight(np.ones(100))
        assert d.get_field("weight") is not None


class TestBinaryCache:
    """Dataset binary save/load (reference save_binary task +
    LoadFromBinFile fast path, dataset_loader.cpp:274)."""

    def test_roundtrip_identical(self, tmp_path):
        rng = np.random.RandomState(3)
        X = rng.randn(400, 6)
        X[rng.rand(400, 6) < 0.1] = np.nan
        X[:, 2] = rng.randint(0, 5, 400)
        y = (X[:, 0] > 0).astype(np.float32)
        w = rng.rand(400).astype(np.float32)
        d = lgb.Dataset(X, label=y, weight=w,
                        categorical_feature=[2])
        path = str(tmp_path / "data.bin")
        d.save_binary(path)
        assert BinnedDataset.is_binary_file(path)
        assert not BinnedDataset.is_binary_file(__file__)
        d2 = lgb.Dataset(path)
        d2.construct()
        b1, b2 = d.binned, d2.binned
        np.testing.assert_array_equal(b1.bins, b2.bins)
        np.testing.assert_array_equal(b1.used_features, b2.used_features)
        np.testing.assert_array_equal(b1.num_bins, b2.num_bins)
        np.testing.assert_array_equal(b1.metadata.label, b2.metadata.label)
        np.testing.assert_array_equal(b1.metadata.weight, b2.metadata.weight)
        for m1, m2 in zip(b1.mappers, b2.mappers):
            np.testing.assert_array_equal(m1.bin_upper_bound,
                                          m2.bin_upper_bound)
            assert m1.bin_2_categorical == m2.bin_2_categorical
            assert m1.missing_type == m2.missing_type

    def test_train_from_binary_matches(self, tmp_path):
        rng = np.random.RandomState(4)
        X = rng.randn(500, 5)
        y = (X[:, 0] + X[:, 1] ** 2 > 0.5).astype(np.float32)
        params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
                  "num_iterations": 10}
        b1 = lgb.train(params, lgb.Dataset(X, label=y))
        path = str(tmp_path / "t.bin")
        lgb.Dataset(X, label=y).save_binary(path)
        b2 = lgb.train(params, lgb.Dataset(path))
        np.testing.assert_allclose(b1.predict(X), b2.predict(X), rtol=1e-6)


class TestSparseInput:
    """scipy CSR/CSC ingest: binned without densifying the raw matrix
    (reference sparse_bin.hpp:73, basic.py __init_from_csr)."""

    @staticmethod
    def _sparse_data(n=3000, f=12, density=0.1, seed=0):
        scipy_sparse = pytest.importorskip("scipy.sparse")
        r = np.random.RandomState(seed)
        X = r.randn(n, f) * (r.rand(n, f) < density)
        y = (X[:, 0] - X[:, 1] + 0.5 * r.randn(n) > 0).astype(np.float32)
        return X, scipy_sparse.csr_matrix(X), y

    def test_sparse_matches_dense_bins(self):
        Xd, Xs, y = self._sparse_data()
        dd = lgb.Dataset(Xd, label=y)
        ds = lgb.Dataset(Xs, label=y)
        dd.construct()
        ds.construct()
        np.testing.assert_array_equal(dd._binned.bins, ds._binned.bins)
        assert all(a.to_dict() == b.to_dict() for a, b in
                   zip(dd._binned.mappers, ds._binned.mappers))

    def test_sparse_train_predict_matches_dense(self):
        Xd, Xs, y = self._sparse_data(seed=1)
        p = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
        bd = lgb.train(p, lgb.Dataset(Xd, label=y), 10)
        bs = lgb.train(p, lgb.Dataset(Xs, label=y), 10)
        np.testing.assert_allclose(bd.predict(Xd), bs.predict(Xs),
                                   rtol=1e-6, atol=1e-7)

    def test_csc_and_valid_alignment(self):
        scipy_sparse = pytest.importorskip("scipy.sparse")
        Xd, Xs, y = self._sparse_data(seed=2)
        dtrain = lgb.Dataset(scipy_sparse.csc_matrix(Xd), label=y)
        dvalid = lgb.Dataset(Xs[:500], label=y[:500], reference=dtrain)
        evals = {}
        lgb.train({"objective": "binary", "verbosity": -1,
                   "num_leaves": 15}, dtrain, 8, valid_sets=[dvalid],
                  callbacks=[lgb.record_evaluation(evals)])
        assert len(evals) > 0

    def test_sparse_linear_tree_rejected(self):
        _, Xs, y = self._sparse_data(seed=3)
        with pytest.raises(ValueError, match="dense"):
            lgb.train({"objective": "regression", "verbosity": -1,
                       "linear_tree": True}, lgb.Dataset(Xs, label=y), 3)

    def test_wide_sparse_memory_bounded(self):
        # 60k x 400 at 5% density: raw dense would be 192 MB f64; the
        # Dataset path must allocate only the ~24 MB uint8 bin matrix
        # (the 1M x 1000 <4 GB claim scaled down for CI)
        scipy_sparse = pytest.importorskip("scipy.sparse")
        import tracemalloc
        r = np.random.RandomState(5)
        n, f = 60_000, 400
        nnz = int(n * f * 0.05)
        rows = r.randint(0, n, nnz)
        cols = r.randint(0, f, nnz)
        vals = r.randn(nnz)
        Xs = scipy_sparse.csr_matrix((vals, (rows, cols)), shape=(n, f))
        y = (np.asarray(Xs[:, 0].todense()).ravel() +
             0.1 * r.randn(n) > 0).astype(np.float32)
        tracemalloc.start()
        d = lgb.Dataset(Xs, label=y)
        d.construct()
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert d._binned.bins.dtype == np.uint8
        # peak python allocations stay far under the dense-raw footprint
        assert peak < 120 * 1024 * 1024, f"peak {peak/1e6:.0f} MB"

    def test_sparse_duplicates_summed(self):
        scipy_sparse = pytest.importorskip("scipy.sparse")
        # duplicate COO entries mean SUM in scipy; binning must agree
        # with the dense equivalent
        rows = np.array([0, 0, 1, 2]); cols = np.array([1, 1, 0, 2])
        vals = np.array([2.0, 3.0, 1.0, -1.0])
        Xs = scipy_sparse.csr_matrix((vals, (rows, cols)), shape=(40, 3))
        Xd = np.asarray(Xs.todense())
        r = np.random.RandomState(0)
        Xd2 = Xd + 0.0; Xd2[3:] = r.randn(37, 3)
        Xs2 = scipy_sparse.csr_matrix(
            (np.concatenate([vals, Xd2[3:].ravel()]),
             (np.concatenate([rows, np.repeat(np.arange(3, 40), 3)]),
              np.concatenate([cols, np.tile(np.arange(3), 37)]))),
            shape=(40, 3))
        y = (Xd2[:, 0] > 0).astype(np.float32)
        dd = lgb.Dataset(Xd2, label=y, params={"min_data_in_bin": 1})
        ds = lgb.Dataset(Xs2, label=y, params={"min_data_in_bin": 1})
        dd.construct(); ds.construct()
        np.testing.assert_array_equal(dd._binned.bins, ds._binned.bins)

    def test_sparse_pred_contrib_returns_sparse(self):
        scipy_sparse = pytest.importorskip("scipy.sparse")
        Xd, Xs, y = self._sparse_data(seed=4)
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "num_leaves": 7}, lgb.Dataset(Xs, label=y), 5)
        contrib = bst.predict(Xs[:100], pred_contrib=True)
        assert scipy_sparse.issparse(contrib)
        dense_contrib = bst.predict(Xd[:100], pred_contrib=True)
        np.testing.assert_allclose(np.asarray(contrib.todense()),
                                   dense_contrib, rtol=1e-5, atol=1e-6)


class TestPandasCategorical:
    """pandas categorical-dtype handling + model-file round-trip
    (reference basic.py:541-624 _data_from_pandas, pandas_categorical
    JSON in the model text)."""

    @staticmethod
    def _frame(n=2000, seed=0):
        pd = pytest.importorskip("pandas")
        r = np.random.RandomState(seed)
        cats = ["red", "green", "blue", "violet"]
        df = pd.DataFrame({
            "x0": r.randn(n),
            "color": pd.Categorical(r.choice(cats, n), categories=cats),
            "x2": r.randn(n),
        })
        y = ((df["color"].cat.codes.values % 2 == 0) &
             (df["x0"].values > 0)).astype(np.float32)
        return df, y

    def test_auto_categorical_and_roundtrip(self, tmp_path):
        df, y = self._frame()
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "num_leaves": 15, "min_data_in_leaf": 5},
                        lgb.Dataset(df, label=y), 15)
        pred = bst.predict(df)
        assert ((pred > 0.5) == y).mean() > 0.95
        # model file stores the category lists; a reloaded model maps a
        # REORDERED categorical frame identically
        path = tmp_path / "m.txt"
        bst.save_model(str(path))
        assert "pandas_categorical:" in path.read_text()
        bst2 = lgb.Booster(model_file=str(path))
        pd = pytest.importorskip("pandas")
        df_re = df.copy()
        df_re["color"] = df_re["color"].cat.set_categories(
            ["violet", "blue", "green", "red"])
        np.testing.assert_allclose(bst2.predict(df_re), pred,
                                   rtol=1e-6, atol=1e-7)

    def test_unseen_category_routes_default(self):
        df, y = self._frame(seed=1)
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "num_leaves": 15, "min_data_in_leaf": 5},
                        lgb.Dataset(df, label=y), 8)
        pd = pytest.importorskip("pandas")
        df2 = df.head(50).copy()
        df2["color"] = pd.Categorical(["ultraviolet"] * 50)
        out = bst.predict(df2)  # unseen category -> NaN -> default path
        assert np.all(np.isfinite(out))


class TestSetCategoricalAfterConstruct:
    def test_reconstructs_when_raw_kept(self):
        r = np.random.RandomState(0)
        X = r.randn(1500, 4)
        X[:, 1] = r.randint(0, 6, 1500)
        y = (X[:, 0] > 0).astype(np.float32)
        d = lgb.Dataset(X, label=y, free_raw_data=False)
        d.construct()
        d.set_categorical_feature([1])  # drops + lazily rebuilds
        d.construct()
        assert bool(d._binned.is_categorical[
            list(d._binned.used_features).index(1)])

    def test_raises_when_raw_freed(self):
        r = np.random.RandomState(0)
        X = r.randn(500, 3)
        y = (X[:, 0] > 0).astype(np.float32)
        d = lgb.Dataset(X, label=y)
        d.construct()
        with pytest.raises(lgb.LightGBMError, match="free_raw_data"):
            d.set_categorical_feature([1])


def test_valid_set_uses_training_category_order():
    pd = pytest.importorskip("pandas")
    df, y = TestPandasCategorical._frame(seed=2)
    dtrain = lgb.Dataset(df, label=y)
    # valid frame with the same values but a REORDERED category dtype
    df_val = df.head(400).copy()
    df_val["color"] = df_val["color"].cat.set_categories(
        ["violet", "blue", "green", "red"])
    evals = {}
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5,
                     "metric": "binary_logloss"},
                    dtrain, 10,
                    valid_sets=[dtrain.create_valid(
                        df_val, label=y[:400])],
                    callbacks=[lgb.record_evaluation(evals)])
    # the valid rows are a subset of train rows: with correct
    # encoding the valid logloss tracks the train fit closely
    key = list(evals.values())[0]["binary_logloss"]
    pred = bst.predict(df.head(400))
    assert ((pred > 0.5) == y[:400]).mean() > 0.95
    assert key[-1] < 0.45

def test_int_categories_survive_save_load(tmp_path):
    pd = pytest.importorskip("pandas")
    r = np.random.RandomState(4)
    n = 1500
    df = pd.DataFrame({
        "x0": r.randn(n),
        "code": pd.Categorical(r.choice([3, 5, 11, 42], n)),
    })
    y = ((df["code"].values.astype(int) > 4) &
         (df["x0"].values > 0)).astype(np.float32)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5},
                    lgb.Dataset(df, label=y), 10)
    path = tmp_path / "m.txt"
    bst.save_model(str(path))
    bst2 = lgb.Booster(model_file=str(path))
    np.testing.assert_allclose(bst2.predict(df), bst.predict(df),
                               rtol=1e-6, atol=1e-7)
    assert ((bst2.predict(df) > 0.5) == y).mean() > 0.9


class TestStreamedConstruction:
    """Chunked / Sequence construction (reference ChunkedArray +
    LGBM_DatasetPushRows; python lightgbm.Sequence): the dense matrix
    never materializes, results equal one-shot construction."""

    def test_list_of_chunks_matches_dense(self):
        r = np.random.RandomState(0)
        X = r.randn(5000, 6)
        y = (X[:, 0] > 0).astype(np.float32)
        chunks = [X[:1500], X[1500:1600], X[1600:]]
        d1 = lgb.Dataset(X, label=y)
        d2 = lgb.Dataset(chunks, label=y)
        d1.construct()
        d2.construct()
        np.testing.assert_array_equal(d1._binned.bins, d2._binned.bins)
        b1 = lgb.train({"objective": "binary", "verbosity": -1}, d1, 5)
        b2 = lgb.train({"objective": "binary", "verbosity": -1},
                       lgb.Dataset(chunks, label=y), 5)
        np.testing.assert_allclose(b1.predict(X), b2.predict(X),
                                   rtol=1e-6, atol=1e-7)

    def test_sequence_streams(self):
        r = np.random.RandomState(1)
        X = r.randn(4000, 5)
        y = (X[:, 1] > 0).astype(np.float32)
        materialized = []

        class ArraySeq(lgb.Sequence):
            batch_size = 512

            def __len__(self):
                return X.shape[0]

            def __getitem__(self, idx):
                block = X[idx]
                materialized.append(
                    block.shape[0] if block.ndim == 2 else 1)
                return block

        d = lgb.Dataset(ArraySeq(), label=y)
        d.construct()
        dd = lgb.Dataset(X, label=y)
        dd.construct()
        np.testing.assert_array_equal(d._binned.bins, dd._binned.bins)
        # streamed: no single materialized block exceeded batch_size
        # (both the sampling pass and the quantize pass batch-walk)
        assert max(materialized) <= 512

    def test_linear_tree_rejected(self):
        r = np.random.RandomState(2)
        X = r.randn(1000, 3)
        y = X[:, 0].astype(np.float32)
        with pytest.raises(ValueError, match="dense"):
            lgb.train({"objective": "regression", "verbosity": -1,
                       "linear_tree": True},
                      lgb.Dataset([X[:500], X[500:]], label=y), 3)
