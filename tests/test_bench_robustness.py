"""The official bench must be un-crashable (VERDICT r3 item 1).

Round 3's BENCH record was rc=1: one JaxRuntimeError inside the first
fused dispatch killed the process. These tests inject faults at both
layers and assert the record survives:

- train_many catches a fused-dispatch fault and falls back to the
  per-iteration path with identical results (gbdt.py);
- bench.py's block driver catches faults ABOVE train_many (drain,
  rebuild), re-probes, rebuilds, and still emits a parseable JSON line
  with a nonzero value and rc=0.

Reference analog: tests/distributed/_test_distributed.py runs the
reference CLI in subprocesses so a crash is an assertion, not a lost
round.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.gbdt import _FAULT_ENV
from lightgbm_tpu.reliability import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _data(n=600, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


# retry_max_attempts=1 keeps the original contract under test: a single
# injected fault must reach the degradation ladder (per-iteration
# fallback), not be absorbed by the dispatch retry loop
PARAMS = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.2,
          "max_bin": 31, "verbosity": -1, "min_data_in_leaf": 5,
          "retry_max_attempts": 1}


def _mxu_booster(X, y):
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
    bst = lgb.Booster(params=dict(PARAMS), train_set=ds)
    bst.update()  # iteration 0 runs the normal (scatter) path
    g = bst.gbdt
    g._hist_impl = "mxu"  # force the fused-eligible path on CPU
    g._mxu_interpret = True
    g._fused_run = None
    return bst


@pytest.fixture(autouse=True)
def _clean_fault_env():
    faults.clear()
    yield
    os.environ.pop(_FAULT_ENV, None)
    os.environ.pop("BENCH_INJECT_BLOCK_FAULT", None)
    faults.clear()


class TestTrainManyFallback:
    def test_fused_fault_falls_back_per_iteration(self):
        X, y = _data(seed=4)
        a = _mxu_booster(X, y)
        b = _mxu_booster(X, y)
        os.environ[_FAULT_ENV] = "1"
        a.update_batch(3)  # fused dispatch raises -> per-iteration
        # the schedule lives in the in-process registry (the env var is
        # only its seed and is never mutated): fully consumed by now
        assert faults.remaining("fused_dispatch") == (0, 0)
        assert os.environ[_FAULT_ENV] == "1"
        for _ in range(3):
            b.update()
        assert a.current_iteration() == b.current_iteration() == 4
        np.testing.assert_array_equal(
            np.asarray(a.gbdt.train_score), np.asarray(b.gbdt.train_score))
        assert a.model_to_string() == b.model_to_string()
        # one failure does not disable the fused path...
        assert not getattr(a.gbdt, "_fused_disabled", False)

    def test_two_consecutive_faults_disable_fused(self):
        X, y = _data(seed=5)
        a = _mxu_booster(X, y)
        os.environ[_FAULT_ENV] = "2"
        a.update_batch(2)
        a.update_batch(2)
        assert a.gbdt._fused_disabled
        # ...and the disabled path still trains correctly
        a.update_batch(2)
        assert a.current_iteration() == 7


def _run_bench(extra_env, timeout=900):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "BENCH_ROWS": "1500", "BENCH_LEAVES": "7",
        "BENCH_MAX_BIN": "31", "BENCH_TREES": "4", "BENCH_BLOCK_TREES": "2",
        "BENCH_RETRY_WINDOW": "30", "BENCH_RETRY_INTERVAL": "5",
        # fault tests exercise the binary headline path only; the task
        # matrix has its own test below
        "BENCH_TASKS": ""})
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert lines, f"no JSON line in stdout: {proc.stdout!r}"
    return json.loads(lines[-1]), proc.stderr


@pytest.mark.slow
class TestBenchSurvivesFaults:
    def test_fault_at_warmup(self):
        # the exact round-3 failure: first fused dispatch dies
        parsed, err = _run_bench({_FAULT_ENV: "1"})
        assert parsed["metric"] == "higgs1m_trees_per_sec"
        assert parsed["value"] > 0, err[-2000:]
        # the record schema is stable even on degraded runs: every key
        # a round-over-round comparison indexes is present
        for key in ("vs_baseline", "vs_single_core", "unit",
                    "serve_qps", "serve_p50_ms", "serve_p95_ms",
                    "serve_p99_ms", "serve_rows_per_sec",
                    "serve_buckets_compiled", "serve_bucket_hits",
                    "achieved_tflops", "mfu_per_tree",
                    "device_peak_tflops", "tasks"):
            assert key in parsed, key
        # the serve path must have produced a live measurement too
        assert parsed["serve_qps"] > 0, err[-2000:]
        # CPU run: achieved TFLOP/s still computed from the analytic
        # MAC model (bench forces the MXU formula), peak unknown -> 0.0
        assert parsed["achieved_tflops"] > 0, err[-2000:]
        assert parsed["device_peak_tflops"] == 0.0

    def test_task_matrix_rows(self):
        # one per-task record (regression, smallest warm-up cost) rides
        # the same JSON line with the documented schema; tiny tree
        # counts can leave no measured block (value 0.0) — the metric
        # must still be real
        parsed, err = _run_bench({"BENCH_TASKS": "regression",
                                  "BENCH_TASK_TREES": "8"})
        assert len(parsed["tasks"]) == 1, err[-2000:]
        row = parsed["tasks"][0]
        for key in ("task", "value", "unit", "metric", "metric_value",
                    "vs_single_core"):
            assert key in row, key
        assert row["task"] == "regression"
        assert row["metric"] == "rmse"
        assert row["unit"] == "trees/sec"
        assert row["metric_value"] > 0, err[-2000:]

    def test_fault_above_train_many_mid_measurement(self):
        # fault that escapes train_many: bench must re-probe, rebuild
        # the booster, retry the block, and still record a value
        parsed, err = _run_bench({"BENCH_INJECT_BLOCK_FAULT": "2:1"})
        assert parsed["value"] > 0, err[-2000:]
        assert "block failed" in err
