"""Fused quantize pass (binning.py bin_columns NumPy path): exact
bin-id equality vs the original per-column searchsorted/dict-loop
implementation, which is inlined here verbatim as the reference.

The fused path changed three things — a single [F, N] float64 staging
buffer instead of per-column strided conversions, in-place NaN fixups
gated on NaNs actually being present, and a sorted-key LUT for
categoricals — none of which may move a single bin id.
"""

import numpy as np
import pytest

from lightgbm_tpu.binning import (BinMapper, MissingType, bin_columns,
                                  find_bin_mappers)


def _ref_values_to_bins(m: BinMapper, values: np.ndarray) -> np.ndarray:
    """Verbatim copy of the pre-fusion BinMapper.values_to_bins."""
    values = np.asarray(values, dtype=np.float64)
    if m.is_categorical:
        nan_mask = ~np.isfinite(values)
        ints = np.where(nan_mask, -1, values).astype(np.int64)
        lut = m.categorical_2_bin
        return np.array([lut.get(int(v), 0) for v in ints], dtype=np.int32)
    bounds = m.bin_upper_bound
    n_numeric = m.num_bin
    has_nan_bin = m.missing_type == MissingType.NAN
    if has_nan_bin:
        n_numeric -= 1
    search_bounds = bounds[:max(n_numeric - 1, 0)]
    vals = values.copy()
    if m.missing_type == MissingType.ZERO:
        vals = np.where(np.isnan(vals), 0.0, vals)
    out = np.searchsorted(search_bounds, vals, side="left").astype(np.int32)
    if has_nan_bin:
        out = np.where(np.isnan(values), m.num_bin - 1, out)
    else:
        out = np.where(np.isnan(values), m.default_bin, out)
    return out


def _make_X(n=3000, seed=7):
    """Columns engineered to hit every mapper flavor: dense gaussian,
    sparse with implicit zeros, NaN-bearing (NAN missing type),
    categorical with unseen/negative/NaN codes, and a constant."""
    rng = np.random.RandomState(seed)
    dense = rng.normal(size=n)
    sparse = np.where(rng.rand(n) < 0.8, 0.0, rng.normal(size=n) * 5)
    withnan = rng.normal(size=n)
    withnan[rng.rand(n) < 0.1] = np.nan
    cat = rng.choice([0, 1, 2, 3, 7, 50], size=n).astype(np.float64)
    cat[rng.rand(n) < 0.05] = np.nan
    cat[rng.rand(n) < 0.05] = -3        # negative -> NaN bucket
    cat[rng.rand(n) < 0.05] = 999       # unseen at high rate -> rare-dropped
    const = np.full(n, 2.5)
    return np.column_stack([dense, sparse, withnan, cat, const])


@pytest.mark.parametrize("zero_as_missing", [False, True])
@pytest.mark.parametrize("dtype", [np.uint8, np.uint16])
def test_bin_columns_matches_reference(zero_as_missing, dtype):
    X = _make_X()
    mappers = find_bin_mappers(X, max_bin=31,
                               zero_as_missing=zero_as_missing,
                               categorical_features=[3])
    used = list(range(X.shape[1]))
    got = bin_columns(X, used, mappers, dtype)
    ref = np.column_stack([
        _ref_values_to_bins(mappers[j], X[:, j]) for j in used
    ]).astype(dtype)
    np.testing.assert_array_equal(got, ref)


def test_bin_columns_float32_and_noncontiguous_input():
    X = _make_X().astype(np.float32)
    mappers = find_bin_mappers(np.asarray(X, np.float64), max_bin=15,
                               categorical_features=[3])
    used = list(range(X.shape[1]))
    view = X[::2]  # non-contiguous row view, float32 source
    got = bin_columns(view, used, mappers, np.uint8)
    ref = np.column_stack([
        _ref_values_to_bins(mappers[j], np.asarray(view[:, j], np.float64))
        for j in used
    ]).astype(np.uint8)
    np.testing.assert_array_equal(got, ref)


def test_bin_columns_does_not_mutate_input():
    # the ZERO-missing rewrite runs in place on the staging buffer —
    # never on the caller's matrix
    X = _make_X()
    before = X.copy()
    mappers = find_bin_mappers(X, max_bin=31, zero_as_missing=True,
                               categorical_features=[3])
    bin_columns(X, list(range(X.shape[1])), mappers, np.uint8)
    np.testing.assert_array_equal(X, before)


def test_values_to_bins_public_api_unchanged():
    X = _make_X(n=500)
    mappers = find_bin_mappers(X, max_bin=31, categorical_features=[3])
    for j, m in enumerate(mappers):
        got = m.values_to_bins(X[:, j])
        np.testing.assert_array_equal(got, _ref_values_to_bins(m, X[:, j]))
        assert got.dtype == np.int32


def test_sample_transpose_matches_numpy_chain():
    # fused native gather+transpose+f64 cast (lgbt_sample_transpose)
    # must be bit-identical to the NumPy chain it replaces
    from lightgbm_tpu import cext
    if not cext.available():
        pytest.skip("no compiler: native data layer unavailable")
    rng = np.random.RandomState(11)
    for dt in (np.float32, np.float64):
        X = rng.randn(5000, 6).astype(dt)
        X[rng.rand(5000, 6) < 0.05] = np.nan
        idx = np.sort(rng.choice(5000, 2000, replace=False))
        ref = np.ascontiguousarray(X[idx].T, dtype=np.float64)
        got = cext.sample_transpose(X, idx)
        assert got.dtype == ref.dtype and got.shape == ref.shape
        np.testing.assert_array_equal(got, ref)


def test_find_bin_mappers_sampled_paths_identical(monkeypatch):
    # the native fused-sample path and the NumPy fallback must build
    # identical mappers (same seeded index draw, same sample values)
    from lightgbm_tpu import cext
    if not cext.available():
        pytest.skip("no compiler: native data layer unavailable")
    rng = np.random.RandomState(12)
    X = rng.randn(9000, 5).astype(np.float32)
    X[rng.rand(9000, 5) < 0.03] = np.nan
    a = find_bin_mappers(X, max_bin=63, sample_cnt=4000)
    monkeypatch.setattr(cext, "available", lambda: False)
    b = find_bin_mappers(X, max_bin=63, sample_cnt=4000)
    for ma, mb in zip(a, b):
        assert ma.num_bin == mb.num_bin
        assert ma.missing_type == mb.missing_type
        np.testing.assert_array_equal(np.asarray(ma.bin_upper_bound),
                                      np.asarray(mb.bin_upper_bound))


def test_bin_columns_native_all_numeric_matches_numpy(monkeypatch):
    # above the native row threshold with every feature numeric,
    # bin_columns returns the kernel output directly (no fancy-index
    # copy) — ids and dtype must match the NumPy path exactly
    from lightgbm_tpu import cext
    if not cext.available():
        pytest.skip("no compiler: native data layer unavailable")
    rng = np.random.RandomState(13)
    X = np.ascontiguousarray(rng.randn(20001, 4).astype(np.float32))
    X[rng.rand(20001, 4) < 0.02] = np.nan
    mappers = find_bin_mappers(X, max_bin=255)
    used = list(range(4))
    nat = bin_columns(X, used, mappers, np.uint8)
    monkeypatch.setattr(cext, "available", lambda: False)
    ref = bin_columns(X, used, mappers, np.uint8)
    assert nat.dtype == ref.dtype and nat.shape == ref.shape
    np.testing.assert_array_equal(nat, ref)


def test_categorical_lut_semantics_exact():
    # float codes truncate like int(v); negatives, NaN, +/-inf and codes
    # absent from training all land in dummy bin 0 / the -1 bucket
    m = BinMapper.from_sample(
        np.asarray([1.0, 1.0, 2.0, 2.0, 2.0, 5.0], np.float64),
        total_sample_cnt=6, max_bin=10, is_categorical=True)
    probe = np.asarray([1.0, 2.0, 2.9, 5.0, 6.0, -1.0, -7.3,
                        np.nan, np.inf, -np.inf, 0.0], np.float64)
    np.testing.assert_array_equal(m.values_to_bins(probe),
                                  _ref_values_to_bins(m, probe))
