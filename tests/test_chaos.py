"""Rank-death chaos test: the ISSUE acceptance scenario end-to-end.

A 2-rank CPU-backed multihost run loses rank 1 to an injected
`rank_death` (`os._exit`, no goodbye) inside iteration 5's first host
collective. The survivor must NOT hang: the collective watchdog
deadline turns the silent peer into a "rank 1 last seen Ns ago"
diagnostic and a prompt abort. Relaunching both ranks with
`resume_from` restores the last COMMIT-marked coordinated bundle and
finishes to a model byte-identical to an unkilled reference run.

Slow (three 2-process training runs + one watchdog deadline wait):
excluded from tier-1 via the `slow` marker; run with `make chaos`.
"""

import json
import os

import pytest

from lightgbm_tpu.observability.flightrec import POSTMORTEM_PREFIX
from lightgbm_tpu.reliability.checkpoint import (COMMIT_MARKER,
                                                 latest_checkpoint)
from lightgbm_tpu.reliability.faults import RANK_DEATH_EXIT_CODE
from lightgbm_tpu.reliability.watchdog import WATCHDOG_EXIT_CODE
from lightgbm_tpu.testing.chaos import (run_chaos_training,
                                        strip_rank_local_params)

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

ROUNDS = 8
CKPT_PERIOD = 2
TIMEOUT_S = 30.0        # steady-state deadline; first bracket gets 4x
DEATH_ITER = 5          # last coordinated commit lands at iteration 4


def _read_model(workdir, rank):
    with open(os.path.join(workdir, f"model_{rank}.txt")) as f:
        return strip_rank_local_params(f.read())


def _assert_clean(results, what):
    for r in results:
        assert not r.timed_out, f"{what} rank {r.rank} hung:\n{r.tail()}"
        assert r.returncode == 0, \
            f"{what} rank {r.rank} rc={r.returncode}:\n{r.tail()}"
        assert "CHAOS_WORKER_DONE" in r.output


def test_rank_death_survivor_aborts_and_resume_is_byte_identical(
        tmp_path):
    # ---- 1. unkilled reference run: the ground-truth model ----------
    ref_dir = str(tmp_path / "ref")
    ref = run_chaos_training(
        ref_dir, rounds=ROUNDS, ckpt_period=CKPT_PERIOD,
        ckpt_dir=os.path.join(ref_dir, "ckpts"), timeout_s=TIMEOUT_S)
    _assert_clean(ref, "reference")
    ref_model = _read_model(ref_dir, 0)
    assert ref_model == _read_model(ref_dir, 1)   # SPMD: same model

    # ---- 2. chaos run: rank 1 dies inside iteration 5's collective --
    chaos_dir = str(tmp_path / "chaos")
    chaos_ckpts = os.path.join(chaos_dir, "ckpts")
    res = {r.rank: r for r in run_chaos_training(
        chaos_dir, rounds=ROUNDS, ckpt_period=CKPT_PERIOD,
        ckpt_dir=chaos_ckpts, timeout_s=TIMEOUT_S,
        death_rank=1, death_iter=DEATH_ITER)}

    dead, survivor = res[1], res[0]
    assert not dead.timed_out and not survivor.timed_out, (
        f"chaos run hung:\nrank0:\n{survivor.tail()}\n"
        f"rank1:\n{dead.tail()}")
    assert dead.returncode == RANK_DEATH_EXIT_CODE, dead.tail()
    assert "rank_death" in dead.output
    # the survivor must fail loudly — non-zero, with the watchdog's
    # named-culprit diagnostic — not hang and not "succeed"
    assert survivor.returncode not in (0, RANK_DEATH_EXIT_CODE), \
        survivor.tail()
    assert "rank 1 last seen" in survivor.output, survivor.tail()
    # ... and promptly: within 2x the steady-state deadline of the
    # moment its peer died (the rank-death exit timestamps that moment)
    assert survivor.duration_s - dead.duration_s <= 2 * TIMEOUT_S, (
        f"survivor outlived its peer by "
        f"{survivor.duration_s - dead.duration_s:.1f}s "
        f"(> 2x collective_timeout_s={TIMEOUT_S:g})")

    # ---- 3. the aftermath: last COMMITTED bundle is iteration 4 -----
    latest = latest_checkpoint(chaos_ckpts)
    assert latest is not None and latest.endswith("ckpt_0000004")
    assert os.path.isfile(os.path.join(latest, COMMIT_MARKER))

    # ---- 4. resume both ranks from the chaos checkpoints ------------
    resume_dir = str(tmp_path / "resume")
    resumed = run_chaos_training(
        resume_dir, rounds=ROUNDS, ckpt_period=CKPT_PERIOD,
        ckpt_dir=chaos_ckpts, timeout_s=TIMEOUT_S, resume=True)
    _assert_clean(resumed, "resume")
    # byte-parity with the unkilled run: the kill + watchdog abort +
    # coordinated-checkpoint resume lost nothing but wall-clock
    assert _read_model(resume_dir, 0) == ref_model
    assert _read_model(resume_dir, 1) == ref_model


def test_postmortem_bundles(tmp_path):
    """The flight-recorder acceptance scenario (`make postmortem`):
    the same 2-rank kill, but the assertion is the forensics — BOTH
    ranks leave a ``postmortem_<rank>.json`` in the shared checkpoint
    dir (flightrec_dir defaults to checkpoint_dir), and each bundle's
    last events name the collective the rank died in."""
    workdir = str(tmp_path / "chaos")
    ckpts = os.path.join(workdir, "ckpts")
    res = {r.rank: r for r in run_chaos_training(
        workdir, rounds=ROUNDS, ckpt_period=CKPT_PERIOD,
        ckpt_dir=ckpts, timeout_s=TIMEOUT_S,
        death_rank=1, death_iter=DEATH_ITER)}
    dead, survivor = res[1], res[0]
    assert dead.returncode == RANK_DEATH_EXIT_CODE, dead.tail()
    assert survivor.returncode == WATCHDOG_EXIT_CODE, survivor.tail()

    bundles = {}
    for rank in (0, 1):
        path = os.path.join(ckpts, f"{POSTMORTEM_PREFIX}{rank}.json")
        assert os.path.isfile(path), (
            f"rank {rank} left no postmortem bundle in {ckpts}: "
            f"{sorted(os.listdir(ckpts))}")
        with open(path) as f:
            bundles[rank] = json.load(f)
        assert bundles[rank]["rank"] == rank

    # the killed rank: flushed by the rank_death exit hook, last event
    # is the fault hit at the collective site it died inside
    assert bundles[1]["reason"] == "rank_death"
    last = bundles[1]["events"][-1]
    assert (last["kind"], last["name"], last["mode"]) == \
        ("fault", "collective_psum", "rank_death")

    # the survivor: flushed by the watchdog abort, last event carries
    # the named-culprit diagnostic; the hung bracket (an enter with no
    # matching exit) names the collective site it was stuck in
    assert bundles[0]["reason"] == "watchdog_abort"
    events = bundles[0]["events"]
    assert events[-1]["kind"] == "abort"
    assert "rank 1 last seen" in events[-1]["diag"]
    opens = [e["name"] for e in events if e["kind"] == "collective"
             and e.get("phase") == "enter"]
    closes = [e["name"] for e in events if e["kind"] == "collective"
              and e.get("phase") == "exit"]
    assert opens, "survivor recorded no collective brackets"
    hung = opens[len(closes):]
    assert hung, "survivor's last collective bracket closed cleanly"
    assert hung[0] in events[-1]["diag"]
