"""Checkpoint/resume: a killed run must resume to a model byte-identical
to an uninterrupted one (the ISSUE acceptance bar).

The "kill" is simulated by training run A to its checkpoint and then
throwing the process state away: run B starts from a fresh Dataset and
a fresh Booster and learns only through `resume_from`. Byte identity of
`model_to_string()` is the strongest possible equivalence — it covers
tree structure, leaf values, split gains, and the recorded params.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback as cb
from lightgbm_tpu.reliability import counters
from lightgbm_tpu.reliability.checkpoint import (latest_checkpoint,
                                                 load_checkpoint,
                                                 save_checkpoint)
from conftest import make_binary

PARAMS = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.2,
          "max_bin": 63, "verbosity": -1, "min_data_in_leaf": 5, "seed": 3}


def _data(seed=1):
    return make_binary(n=500, f=8, seed=seed)


def _ds(X, y):
    return lgb.Dataset(X.copy(), label=y.copy(), params={"max_bin": 63})


@pytest.fixture(autouse=True)
def _reset_counters():
    counters.reset()
    yield
    counters.reset()


# ----------------------------------------------------------------------
# the acceptance bar: kill-and-resume byte identity
RESUME_CASES = {
    "plain": ({}, 4, 8),
    # checkpoint at iter 4 lands mid bagging period (freq 3): the
    # cached bag mask must survive the resume
    "bagging_mid_period": ({"bagging_fraction": 0.8, "bagging_freq": 3,
                            "bagging_seed": 7}, 4, 9),
    # GOSS threads a stateful RNG key through every iteration
    "goss": ({"boosting": "goss", "top_rate": 0.3, "other_rate": 0.2},
             3, 8),
    "feature_fraction": ({"feature_fraction": 0.7,
                          "feature_fraction_seed": 5}, 4, 10),
    "multiclass": ({"objective": "multiclass", "num_class": 3}, 3, 7),
}


class TestResumeByteIdentity:
    @pytest.mark.parametrize("case", sorted(RESUME_CASES))
    def test_resume_matches_uninterrupted(self, case, tmp_path):
        extra, k, total = RESUME_CASES[case]
        X, y = _data()
        if extra.get("objective") == "multiclass":
            y = (np.abs(X[:, 0]) * 3 % 3).astype(np.int32).astype(
                np.float32)
        params = dict(PARAMS)
        params.update(extra)

        ref = lgb.train(dict(params), _ds(X, y), num_boost_round=total)
        ref_text = ref.model_to_string()

        # run A: train to k, checkpoint, "die"
        d = str(tmp_path / "ckpts")
        lgb.train(dict(params), _ds(X, y), num_boost_round=k,
                  callbacks=[cb.checkpoint(k, d)])
        # run B: fresh Dataset + Booster, resume
        found = latest_checkpoint(d)
        assert found is not None and found.endswith(f"ckpt_{k:07d}")
        resumed = lgb.train(dict(params), _ds(X, y),
                            num_boost_round=total, resume_from=found)
        assert resumed.model_to_string() == ref_text
        assert resumed.current_iteration() == total

    def test_resume_predictions_match(self, tmp_path):
        X, y = _data(seed=9)
        ref = lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=8)
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=4,
                  callbacks=[cb.checkpoint(4, d)])
        resumed = lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=8,
                            resume_from=d)
        np.testing.assert_array_equal(resumed.predict(X), ref.predict(X))

    def test_resume_with_valid_sets_and_eval_history(self, tmp_path):
        X, y = _data(seed=4)
        Xv, yv = _data(seed=5)
        p = dict(PARAMS, metric="binary_logloss")
        ref = lgb.train(p, _ds(X, y), num_boost_round=8,
                        valid_sets=[_ds(Xv, yv)], valid_names=["v"])
        d = str(tmp_path / "c")
        lgb.train(p, _ds(X, y), num_boost_round=4,
                  valid_sets=[_ds(Xv, yv)], valid_names=["v"],
                  callbacks=[cb.checkpoint(4, d)])
        resumed = lgb.train(p, _ds(X, y), num_boost_round=8,
                            valid_sets=[_ds(Xv, yv)], valid_names=["v"],
                            resume_from=d)
        assert resumed.model_to_string() == ref.model_to_string()

    def test_resume_conflicts_with_init_model(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        bst = lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=2,
                        callbacks=[cb.checkpoint(2, d)])
        bst.save_model(str(tmp_path / "m.txt"))
        with pytest.raises(ValueError):
            lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=4,
                      resume_from=d,
                      init_model=str(tmp_path / "m.txt"))


# ----------------------------------------------------------------------
# bundle mechanics
class TestBundleMechanics:
    def test_atomic_bundle_layout(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=3,
                  callbacks=[cb.checkpoint(3, d)])
        bundle = os.path.join(d, "ckpt_0000003")
        assert sorted(os.listdir(bundle)) == ["arrays.npz", "model.txt",
                                              "state.json"]
        state = json.loads(
            open(os.path.join(bundle, "state.json")).read())
        assert state["iteration"] == 3
        assert state["format_version"] == 1
        # no tmp turds left behind
        assert not [p for p in os.listdir(d) if p.startswith(".tmp-")]
        assert open(os.path.join(d, "LATEST")).read().strip() == \
            "ckpt_0000003"

    def test_keep_last_prunes(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=8,
                  callbacks=[cb.checkpoint(2, d, keep_last=2)])
        bundles = sorted(p for p in os.listdir(d) if p.startswith("ckpt_"))
        assert bundles == ["ckpt_0000006", "ckpt_0000008"]
        assert counters.get("checkpoint_saves") == 4

    def test_period_not_dividing_total_still_saves_final(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=5,
                  callbacks=[cb.checkpoint(3, d)])
        bundles = sorted(p for p in os.listdir(d) if p.startswith("ckpt_"))
        assert bundles == ["ckpt_0000003", "ckpt_0000005"]

    def test_latest_checkpoint_scan_fallback(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=4,
                  callbacks=[cb.checkpoint(2, d)])
        os.remove(os.path.join(d, "LATEST"))  # advisory only
        found = latest_checkpoint(d)
        assert found is not None and found.endswith("ckpt_0000004")

    def test_latest_checkpoint_empty_dir(self, tmp_path):
        assert latest_checkpoint(str(tmp_path)) is None
        assert latest_checkpoint(str(tmp_path / "missing")) is None

    def test_load_checkpoint_roundtrip(self, tmp_path):
        d = str(tmp_path / "c")
        save_checkpoint(d, 7, "model text", {"foo": 1},
                        {"a": np.arange(3, dtype=np.float32)})
        ck = load_checkpoint(d)  # parent dir resolves to latest bundle
        assert ck.iteration == 7
        assert ck.model_str == "model text"
        assert ck.state["foo"] == 1
        np.testing.assert_array_equal(ck.arrays["a"],
                                      np.arange(3, dtype=np.float32))

    def test_checkpoint_params_validated(self):
        with pytest.raises(ValueError):
            cb.checkpoint(0, "/tmp/x")
        with pytest.raises(ValueError):
            cb.checkpoint(2, "")

    def test_restore_rejects_mismatched_config(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=2,
                  callbacks=[cb.checkpoint(2, d)])
        p = dict(PARAMS, objective="multiclass", num_class=3)
        y3 = (np.abs(X[:, 0]) * 3 % 3).astype(np.float32)
        with pytest.raises(Exception):
            lgb.train(p, _ds(X, y3), num_boost_round=4, resume_from=d)


# ----------------------------------------------------------------------
# config + engine wiring
class TestConfigWiring:
    def test_checkpoint_period_requires_dir(self):
        X, y = _data()
        # period without dir: warned down to disabled, training fine
        bst = lgb.train(dict(PARAMS, checkpoint_period=2), _ds(X, y),
                        num_boost_round=2)
        assert bst.current_iteration() == 2

    def test_params_auto_attach_checkpoint_callback(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS, checkpoint_period=2, checkpoint_dir=d),
                  _ds(X, y), num_boost_round=4)
        bundles = sorted(p for p in os.listdir(d) if p.startswith("ckpt_"))
        assert bundles == ["ckpt_0000002", "ckpt_0000004"]


# ----------------------------------------------------------------------
# CLI auto-resume (task=train picks up the newest bundle)
class TestCliAutoResume:
    def _conf(self, tmp_path, num_trees, ckpt_dir):
        X, y = make_binary(n=600, f=6, seed=11)
        data = np.column_stack([y, X])
        np.savetxt(tmp_path / "train.tsv", data, delimiter="\t")
        (tmp_path / "train.conf").write_text(f"""
task = train
objective = binary
data = {tmp_path}/train.tsv
num_trees = {num_trees}
num_leaves = 7
learning_rate = 0.2
max_bin = 63
output_model = {tmp_path}/model.txt
checkpoint_period = 3
checkpoint_dir = {ckpt_dir}
verbosity = -1
seed = 3
""")
        return tmp_path / "train.conf"

    def test_auto_resume_from_latest(self, tmp_path):
        from lightgbm_tpu.cli import main
        ref_dir = tmp_path / "ref"
        ref_dir.mkdir()
        conf = self._conf(ref_dir, 9, ref_dir / "nockpt")
        main([f"config={conf}"])
        ref_text = (ref_dir / "model.txt").read_text()

        run_dir = tmp_path / "run"
        run_dir.mkdir()
        ckpt_dir = run_dir / "ckpts"
        # first invocation "dies" after 6 trees (two checkpoints)
        conf = self._conf(run_dir, 6, ckpt_dir)
        main([f"config={conf}"])
        assert latest_checkpoint(str(ckpt_dir)).endswith("ckpt_0000006")
        # re-launch asking for 9: auto-resumes from iteration 6
        conf = self._conf(run_dir, 9, ckpt_dir)
        main([f"config={conf}"])
        run_text = (run_dir / "model.txt").read_text()
        # recorded path params (data/output_model/checkpoint_dir/config)
        # legitimately differ between the two runs; the learned model —
        # everything after the params block — must be byte-identical
        assert run_text.split("end of parameters")[1] == \
            ref_text.split("end of parameters")[1]


# ----------------------------------------------------------------------
# the coordinated (multihost) commit protocol, driven in-process: two
# threads play two ranks, a barrier-backed agree() stands in for the
# one-int allgather (`parallel.comm.checkpoint_agree`)

import threading

from lightgbm_tpu.parallel.comm import checkpoint_agree
from lightgbm_tpu.reliability.checkpoint import (COMMIT_MARKER,
                                                 _prune, _sweep_tmp)
from lightgbm_tpu.reliability.faults import faults
from lightgbm_tpu.utils.log import LightGBMError


class _ThreadCoord:
    """CheckpointCoordinator stand-in: write slot, meet at the barrier,
    read all slots, meet again so no rank races ahead and overwrites
    the exchange for the next agree() round."""

    def __init__(self, rank, world, slots, barrier):
        self.rank, self.world = rank, world
        self._slots, self._barrier = slots, barrier

    def agree(self, value, label="checkpoint_agree"):
        self._slots[self.rank] = int(value)
        self._barrier.wait(timeout=30)
        out = np.asarray(list(self._slots), dtype=np.int64)
        self._barrier.wait(timeout=30)
        return out


def _coordinated_save(ckpt_dir, iterations, arrays_by_rank,
                      keep_last=0, model="tree-bytes\n"):
    """Run save_checkpoint on two rank-threads; returns per-rank
    ("ok", path) or ("err", exc)."""
    barrier = threading.Barrier(2)
    slots = [None, None]
    results = [None, None]

    def _run(rank):
        coord = _ThreadCoord(rank, 2, slots, barrier)
        try:
            results[rank] = ("ok", save_checkpoint(
                str(ckpt_dir), iterations[rank], model,
                {"note": "coord-test"}, arrays_by_rank[rank],
                keep_last=keep_last, coordinator=coord))
        except Exception as exc:            # noqa: BLE001 — recorded
            results[rank] = ("err", exc)

    threads = [threading.Thread(target=_run, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert all(not t.is_alive() for t in threads)
    return results


def _partial_coordinated_bundle(ckpt_dir, iteration, world=2):
    """Hand-build what a rank death mid-protocol leaves behind: shards
    and state.json present, COMMIT marker never cut."""
    bundle = os.path.join(str(ckpt_dir), f"ckpt_{iteration:07d}")
    os.makedirs(bundle, exist_ok=True)
    with open(os.path.join(bundle, "state.json"), "w") as f:
        json.dump({"format_version": 1, "iteration": iteration,
                   "world_size": world}, f)
    with open(os.path.join(bundle, "model.txt"), "w") as f:
        f.write("torn\n")
    np.savez(os.path.join(bundle, "shard_000.npz"), x=np.zeros(2))
    return bundle


class TestCoordinatedCheckpoint:
    def test_commit_protocol_layout_and_per_rank_load(self, tmp_path):
        arrays = {0: {"score": np.arange(3, dtype=np.float32)},
                  1: {"score": np.arange(3, 6, dtype=np.float32)}}
        results = _coordinated_save(tmp_path, (5, 5), arrays)
        assert [s for s, _ in results] == ["ok", "ok"]
        bundle = results[0][1]
        assert sorted(os.listdir(bundle)) == [
            COMMIT_MARKER, "model.txt", "shard_000.npz",
            "shard_001.npz", "state.json"]
        assert latest_checkpoint(str(tmp_path)) == bundle
        for rank in (0, 1):
            st = load_checkpoint(str(tmp_path), rank=rank, world=2)
            assert st.iteration == 5
            np.testing.assert_array_equal(
                st.arrays["score"], arrays[rank]["score"])

    def test_iteration_disagreement_raises_on_all_ranks(self, tmp_path):
        arrays = {0: {"a": np.zeros(1)}, 1: {"a": np.ones(1)}}
        results = _coordinated_save(tmp_path, (4, 6), arrays)
        for status, exc in results:
            assert status == "err"
            assert isinstance(exc, LightGBMError)
            assert "disagree" in str(exc)
        assert latest_checkpoint(str(tmp_path)) is None

    def test_one_rank_write_failure_leaves_no_commit(self, tmp_path):
        # exactly one thread trips the shared checkpoint_io schedule;
        # the failure is voted into the second agree, so BOTH ranks
        # raise together and the marker is never cut
        arrays = {0: {"a": np.zeros(1)}, 1: {"a": np.ones(1)}}
        faults.schedule("checkpoint_io", fail=1)
        try:
            results = _coordinated_save(tmp_path, (3, 3), arrays)
        finally:
            faults.clear("checkpoint_io")
        for status, exc in results:
            assert status == "err"
            assert "uncommitted" in str(exc)
        bundle = os.path.join(str(tmp_path), "ckpt_0000003")
        assert not os.path.isfile(os.path.join(bundle, COMMIT_MARKER))
        assert latest_checkpoint(str(tmp_path)) is None
        with pytest.raises(LightGBMError, match="no complete"):
            load_checkpoint(str(tmp_path), rank=0, world=2)

    def test_latest_skips_uncommitted_bundle(self, tmp_path):
        # regression: a committed bundle at iter 2, a torn one at iter 4
        arrays = {0: {"a": np.zeros(1)}, 1: {"a": np.ones(1)}}
        results = _coordinated_save(tmp_path, (2, 2), arrays)
        committed = results[0][1]
        _partial_coordinated_bundle(tmp_path, 4)
        assert latest_checkpoint(str(tmp_path)) == committed
        st = load_checkpoint(str(tmp_path), rank=0, world=2)
        assert st.iteration == 2 and st.path == committed

    def test_load_validates_topology(self, tmp_path):
        arrays = {0: {"a": np.zeros(1)}, 1: {"a": np.ones(1)}}
        bundle = _coordinated_save(tmp_path, (7, 7), arrays)[0][1]
        with pytest.raises(LightGBMError, match="coordinated"):
            load_checkpoint(bundle)                 # rank required
        with pytest.raises(LightGBMError, match="world_size"):
            load_checkpoint(bundle, rank=0, world=4)
        with pytest.raises(LightGBMError, match="out of range"):
            load_checkpoint(bundle, rank=5, world=2)

    def test_prune_removes_stale_uncommitted(self, tmp_path):
        arrays = {0: {"a": np.zeros(1)}, 1: {"a": np.ones(1)}}
        _partial_coordinated_bundle(tmp_path, 1)    # older than newest
        _coordinated_save(tmp_path, (2, 2), arrays)
        _coordinated_save(tmp_path, (4, 4), arrays)
        _partial_coordinated_bundle(tmp_path, 6)    # NEWER: in flight
        _prune(str(tmp_path), keep_last=1)
        names = sorted(n for n in os.listdir(str(tmp_path))
                       if n.startswith("ckpt_"))
        # iter-1 stale torn write and iter-2 over-quota bundle pruned;
        # the in-flight iter-6 bundle must never be eaten
        assert names == ["ckpt_0000004", "ckpt_0000006"]

    def test_prune_and_sweep_tolerate_missing_dir(self, tmp_path):
        gone = str(tmp_path / "never-created")
        _sweep_tmp(gone)                            # ENOENT: no raise
        _prune(gone, keep_last=2)
        # and a bundle vanishing mid-prune (racing rank) is tolerated:
        # _prune uses ignore_errors rmtree + tolerant scans
        _partial_coordinated_bundle(tmp_path, 1)
        _prune(str(tmp_path), keep_last=1)

    def test_checkpoint_agree_single_process_identity(self):
        # the real collective degenerates to identity on one process —
        # names checkpoint_agree for the COLLECTIVE_MANIFEST test wiring
        assert list(checkpoint_agree(9)) == [9]
