"""Checkpoint/resume: a killed run must resume to a model byte-identical
to an uninterrupted one (the ISSUE acceptance bar).

The "kill" is simulated by training run A to its checkpoint and then
throwing the process state away: run B starts from a fresh Dataset and
a fresh Booster and learns only through `resume_from`. Byte identity of
`model_to_string()` is the strongest possible equivalence — it covers
tree structure, leaf values, split gains, and the recorded params.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback as cb
from lightgbm_tpu.reliability import counters
from lightgbm_tpu.reliability.checkpoint import (latest_checkpoint,
                                                 load_checkpoint,
                                                 save_checkpoint)
from conftest import make_binary

PARAMS = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.2,
          "max_bin": 63, "verbosity": -1, "min_data_in_leaf": 5, "seed": 3}


def _data(seed=1):
    return make_binary(n=500, f=8, seed=seed)


def _ds(X, y):
    return lgb.Dataset(X.copy(), label=y.copy(), params={"max_bin": 63})


@pytest.fixture(autouse=True)
def _reset_counters():
    counters.reset()
    yield
    counters.reset()


# ----------------------------------------------------------------------
# the acceptance bar: kill-and-resume byte identity
RESUME_CASES = {
    "plain": ({}, 4, 8),
    # checkpoint at iter 4 lands mid bagging period (freq 3): the
    # cached bag mask must survive the resume
    "bagging_mid_period": ({"bagging_fraction": 0.8, "bagging_freq": 3,
                            "bagging_seed": 7}, 4, 9),
    # GOSS threads a stateful RNG key through every iteration
    "goss": ({"boosting": "goss", "top_rate": 0.3, "other_rate": 0.2},
             3, 8),
    "feature_fraction": ({"feature_fraction": 0.7,
                          "feature_fraction_seed": 5}, 4, 10),
    "multiclass": ({"objective": "multiclass", "num_class": 3}, 3, 7),
}


class TestResumeByteIdentity:
    @pytest.mark.parametrize("case", sorted(RESUME_CASES))
    def test_resume_matches_uninterrupted(self, case, tmp_path):
        extra, k, total = RESUME_CASES[case]
        X, y = _data()
        if extra.get("objective") == "multiclass":
            y = (np.abs(X[:, 0]) * 3 % 3).astype(np.int32).astype(
                np.float32)
        params = dict(PARAMS)
        params.update(extra)

        ref = lgb.train(dict(params), _ds(X, y), num_boost_round=total)
        ref_text = ref.model_to_string()

        # run A: train to k, checkpoint, "die"
        d = str(tmp_path / "ckpts")
        lgb.train(dict(params), _ds(X, y), num_boost_round=k,
                  callbacks=[cb.checkpoint(k, d)])
        # run B: fresh Dataset + Booster, resume
        found = latest_checkpoint(d)
        assert found is not None and found.endswith(f"ckpt_{k:07d}")
        resumed = lgb.train(dict(params), _ds(X, y),
                            num_boost_round=total, resume_from=found)
        assert resumed.model_to_string() == ref_text
        assert resumed.current_iteration() == total

    def test_resume_predictions_match(self, tmp_path):
        X, y = _data(seed=9)
        ref = lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=8)
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=4,
                  callbacks=[cb.checkpoint(4, d)])
        resumed = lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=8,
                            resume_from=d)
        np.testing.assert_array_equal(resumed.predict(X), ref.predict(X))

    def test_resume_with_valid_sets_and_eval_history(self, tmp_path):
        X, y = _data(seed=4)
        Xv, yv = _data(seed=5)
        p = dict(PARAMS, metric="binary_logloss")
        ref = lgb.train(p, _ds(X, y), num_boost_round=8,
                        valid_sets=[_ds(Xv, yv)], valid_names=["v"])
        d = str(tmp_path / "c")
        lgb.train(p, _ds(X, y), num_boost_round=4,
                  valid_sets=[_ds(Xv, yv)], valid_names=["v"],
                  callbacks=[cb.checkpoint(4, d)])
        resumed = lgb.train(p, _ds(X, y), num_boost_round=8,
                            valid_sets=[_ds(Xv, yv)], valid_names=["v"],
                            resume_from=d)
        assert resumed.model_to_string() == ref.model_to_string()

    def test_resume_conflicts_with_init_model(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        bst = lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=2,
                        callbacks=[cb.checkpoint(2, d)])
        bst.save_model(str(tmp_path / "m.txt"))
        with pytest.raises(ValueError):
            lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=4,
                      resume_from=d,
                      init_model=str(tmp_path / "m.txt"))


# ----------------------------------------------------------------------
# bundle mechanics
class TestBundleMechanics:
    def test_atomic_bundle_layout(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=3,
                  callbacks=[cb.checkpoint(3, d)])
        bundle = os.path.join(d, "ckpt_0000003")
        assert sorted(os.listdir(bundle)) == ["arrays.npz", "model.txt",
                                              "state.json"]
        state = json.loads(
            open(os.path.join(bundle, "state.json")).read())
        assert state["iteration"] == 3
        assert state["format_version"] == 1
        # no tmp turds left behind
        assert not [p for p in os.listdir(d) if p.startswith(".tmp-")]
        assert open(os.path.join(d, "LATEST")).read().strip() == \
            "ckpt_0000003"

    def test_keep_last_prunes(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=8,
                  callbacks=[cb.checkpoint(2, d, keep_last=2)])
        bundles = sorted(p for p in os.listdir(d) if p.startswith("ckpt_"))
        assert bundles == ["ckpt_0000006", "ckpt_0000008"]
        assert counters.get("checkpoint_saves") == 4

    def test_period_not_dividing_total_still_saves_final(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=5,
                  callbacks=[cb.checkpoint(3, d)])
        bundles = sorted(p for p in os.listdir(d) if p.startswith("ckpt_"))
        assert bundles == ["ckpt_0000003", "ckpt_0000005"]

    def test_latest_checkpoint_scan_fallback(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=4,
                  callbacks=[cb.checkpoint(2, d)])
        os.remove(os.path.join(d, "LATEST"))  # advisory only
        found = latest_checkpoint(d)
        assert found is not None and found.endswith("ckpt_0000004")

    def test_latest_checkpoint_empty_dir(self, tmp_path):
        assert latest_checkpoint(str(tmp_path)) is None
        assert latest_checkpoint(str(tmp_path / "missing")) is None

    def test_load_checkpoint_roundtrip(self, tmp_path):
        d = str(tmp_path / "c")
        save_checkpoint(d, 7, "model text", {"foo": 1},
                        {"a": np.arange(3, dtype=np.float32)})
        ck = load_checkpoint(d)  # parent dir resolves to latest bundle
        assert ck.iteration == 7
        assert ck.model_str == "model text"
        assert ck.state["foo"] == 1
        np.testing.assert_array_equal(ck.arrays["a"],
                                      np.arange(3, dtype=np.float32))

    def test_checkpoint_params_validated(self):
        with pytest.raises(ValueError):
            cb.checkpoint(0, "/tmp/x")
        with pytest.raises(ValueError):
            cb.checkpoint(2, "")

    def test_restore_rejects_mismatched_config(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS), _ds(X, y), num_boost_round=2,
                  callbacks=[cb.checkpoint(2, d)])
        p = dict(PARAMS, objective="multiclass", num_class=3)
        y3 = (np.abs(X[:, 0]) * 3 % 3).astype(np.float32)
        with pytest.raises(Exception):
            lgb.train(p, _ds(X, y3), num_boost_round=4, resume_from=d)


# ----------------------------------------------------------------------
# config + engine wiring
class TestConfigWiring:
    def test_checkpoint_period_requires_dir(self):
        X, y = _data()
        # period without dir: warned down to disabled, training fine
        bst = lgb.train(dict(PARAMS, checkpoint_period=2), _ds(X, y),
                        num_boost_round=2)
        assert bst.current_iteration() == 2

    def test_params_auto_attach_checkpoint_callback(self, tmp_path):
        X, y = _data()
        d = str(tmp_path / "c")
        lgb.train(dict(PARAMS, checkpoint_period=2, checkpoint_dir=d),
                  _ds(X, y), num_boost_round=4)
        bundles = sorted(p for p in os.listdir(d) if p.startswith("ckpt_"))
        assert bundles == ["ckpt_0000002", "ckpt_0000004"]


# ----------------------------------------------------------------------
# CLI auto-resume (task=train picks up the newest bundle)
class TestCliAutoResume:
    def _conf(self, tmp_path, num_trees, ckpt_dir):
        X, y = make_binary(n=600, f=6, seed=11)
        data = np.column_stack([y, X])
        np.savetxt(tmp_path / "train.tsv", data, delimiter="\t")
        (tmp_path / "train.conf").write_text(f"""
task = train
objective = binary
data = {tmp_path}/train.tsv
num_trees = {num_trees}
num_leaves = 7
learning_rate = 0.2
max_bin = 63
output_model = {tmp_path}/model.txt
checkpoint_period = 3
checkpoint_dir = {ckpt_dir}
verbosity = -1
seed = 3
""")
        return tmp_path / "train.conf"

    def test_auto_resume_from_latest(self, tmp_path):
        from lightgbm_tpu.cli import main
        ref_dir = tmp_path / "ref"
        ref_dir.mkdir()
        conf = self._conf(ref_dir, 9, ref_dir / "nockpt")
        main([f"config={conf}"])
        ref_text = (ref_dir / "model.txt").read_text()

        run_dir = tmp_path / "run"
        run_dir.mkdir()
        ckpt_dir = run_dir / "ckpts"
        # first invocation "dies" after 6 trees (two checkpoints)
        conf = self._conf(run_dir, 6, ckpt_dir)
        main([f"config={conf}"])
        assert latest_checkpoint(str(ckpt_dir)).endswith("ckpt_0000006")
        # re-launch asking for 9: auto-resumes from iteration 6
        conf = self._conf(run_dir, 9, ckpt_dir)
        main([f"config={conf}"])
        run_text = (run_dir / "model.txt").read_text()
        # recorded path params (data/output_model/checkpoint_dir/config)
        # legitimately differ between the two runs; the learned model —
        # everything after the params block — must be byte-identical
        assert run_text.split("end of parameters")[1] == \
            ref_text.split("end of parameters")[1]
