"""CLI tests — mirrors the reference's examples-driven consistency tests
(tests/cpp_tests/test.py runs CLI train.conf/predict.conf; the binary
classification example layout from examples/binary_classification)."""

import numpy as np
import pytest

from lightgbm_tpu.cli import main
from conftest import make_binary


@pytest.fixture
def example_dir(tmp_path):
    X, y = make_binary(n=1200, f=8)
    train = np.column_stack([y[:1000], X[:1000]])
    test = np.column_stack([y[1000:], X[1000:]])
    np.savetxt(tmp_path / "train.tsv", train, delimiter="\t")
    np.savetxt(tmp_path / "test.tsv", test, delimiter="\t")
    (tmp_path / "train.conf").write_text(f"""
task = train
objective = binary
metric = auc
data = {tmp_path}/train.tsv
valid = {tmp_path}/test.tsv
num_trees = 15
num_leaves = 15
learning_rate = 0.2
output_model = {tmp_path}/model.txt
verbosity = -1
""")
    (tmp_path / "predict.conf").write_text(f"""
task = predict
data = {tmp_path}/test.tsv
input_model = {tmp_path}/model.txt
output_result = {tmp_path}/preds.txt
verbosity = -1
""")
    return tmp_path


def test_cli_train_then_predict(example_dir):
    main([f"config={example_dir}/train.conf"])
    assert (example_dir / "model.txt").exists()
    model_text = (example_dir / "model.txt").read_text()
    assert model_text.startswith("tree\nversion=v3")
    main([f"config={example_dir}/predict.conf"])
    preds = np.loadtxt(example_dir / "preds.txt")
    assert len(preds) == 200
    assert np.all((preds >= 0) & (preds <= 1))
    # predictions should be informative
    test = np.loadtxt(example_dir / "test.tsv", delimiter="\t")
    y = test[:, 0]
    from lightgbm_tpu.metrics import AUCMetric
    assert AUCMetric._auc_fast(preds, y > 0, np.ones(len(y))) > 0.9


def test_cli_override_beats_config(example_dir, capsys):
    main([f"config={example_dir}/train.conf", "num_trees=3",
          f"output_model={example_dir}/model3.txt"])
    text = (example_dir / "model3.txt").read_text()
    assert text.count("Tree=") == 3


def test_cli_convert_model(example_dir):
    main([f"config={example_dir}/train.conf"])
    main([f"task=convert_model", f"input_model={example_dir}/model.txt",
          f"convert_model={example_dir}/model.cpp"])
    code = (example_dir / "model.cpp").read_text()
    assert "double PredictTree0" in code
    assert "double Predict(" in code


def _run_generated_cpp(tmp_path, cpp_path, X):
    """Compile the generated if-else model and run it over rows of X."""
    import subprocess
    n, f = X.shape
    main_src = f"""
#include <cstdio>
#include "model.cpp"
int main() {{
  double arr[{f}];
  while (std::scanf("%lf", &arr[0]) == 1) {{
    for (int j = 1; j < {f}; ++j) std::scanf("%lf", &arr[j]);
    std::printf("%.17g\\n", Predict(arr));
  }}
  return 0;
}}
"""
    (tmp_path / "main.cpp").write_text(main_src)
    exe = tmp_path / "model_exe"
    subprocess.run(["g++", "-O1", "-o", str(exe),
                    str(tmp_path / "main.cpp")],
                   check=True, cwd=tmp_path)
    feed = "\n".join(" ".join(f"{v:.17g}" for v in row) for row in X)
    out = subprocess.run([str(exe)], input=feed, text=True,
                         capture_output=True, check=True)
    return np.asarray([float(t) for t in out.stdout.split()])


@pytest.mark.skipif(__import__("shutil").which("g++") is None,
                    reason="g++ not available")
def test_cli_convert_model_cpp_matches_predict(tmp_path):
    """Generated C++ reproduces raw scores, incl. categorical bitset
    splits and NaN default directions (reference SaveModelToIfElse,
    gbdt_model_text.cpp:286)."""
    import lightgbm_tpu as lgb
    r = np.random.RandomState(3)
    n = 1500
    cat = r.randint(0, 10, n).astype(np.float64)
    x1 = r.randn(n)
    y = ((cat.astype(int) % 3 == 0) ^ (x1 > 0.4)).astype(np.float32)
    X = np.column_stack([cat, x1])
    X[r.rand(n) < 0.05, 1] = np.nan  # exercise NaN default direction
    bst = lgb.train({"objective": "binary", "num_leaves": 8,
                     "max_cat_to_onehot": 4, "min_data_in_leaf": 5,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y, categorical_feature=[0]), 10)
    model_file = tmp_path / "model.txt"
    bst.save_model(str(model_file))
    main(["task=convert_model", f"input_model={model_file}",
          f"convert_model={tmp_path}/model.cpp"])
    Xt = np.column_stack([r.randint(0, 12, 300).astype(np.float64),
                          r.randn(300)])
    Xt[r.rand(300) < 0.1, 1] = np.nan
    # hostile categorical values: negative, inf, huge — all route right
    Xt[:4, 0] = [-0.5, np.inf, 3e9, -7.0]
    got = _run_generated_cpp(tmp_path, tmp_path / "model.cpp", Xt)
    want = bst.predict(Xt, raw_score=True)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_cli_refit(example_dir):
    main([f"config={example_dir}/train.conf"])
    main([f"task=refit", f"data={example_dir}/train.tsv",
          f"input_model={example_dir}/model.txt",
          f"output_model={example_dir}/model_refit.txt"])
    assert (example_dir / "model_refit.txt").exists()


def test_cli_save_binary_then_train(example_dir):
    """task=save_binary writes <data>.bin; training from it matches text
    (reference application.cpp save_binary task + binary fast path)."""
    conf = example_dir / "savebin.conf"
    conf.write_text(f"""
task = save_binary
data = {example_dir}/train.tsv
verbosity = -1
""")
    main([f"config={conf}"])
    bin_path = example_dir / "train.tsv.bin"
    assert bin_path.exists()
    main([f"config={example_dir}/train.conf"])
    preds_text = (example_dir / "model.txt").read_text()
    main([f"config={example_dir}/train.conf", f"data={bin_path}",
          "valid=", f"output_model={example_dir}/model_bin.txt"])
    preds_bin = (example_dir / "model_bin.txt").read_text()
    # identical trees; only the echoed parameters block may differ (paths)
    assert preds_text.split("\nparameters")[0] == \
        preds_bin.split("\nparameters")[0]
