"""CLI tests — mirrors the reference's examples-driven consistency tests
(tests/cpp_tests/test.py runs CLI train.conf/predict.conf; the binary
classification example layout from examples/binary_classification)."""

import numpy as np
import pytest

from lightgbm_tpu.cli import main
from conftest import make_binary


@pytest.fixture
def example_dir(tmp_path):
    X, y = make_binary(n=1200, f=8)
    train = np.column_stack([y[:1000], X[:1000]])
    test = np.column_stack([y[1000:], X[1000:]])
    np.savetxt(tmp_path / "train.tsv", train, delimiter="\t")
    np.savetxt(tmp_path / "test.tsv", test, delimiter="\t")
    (tmp_path / "train.conf").write_text(f"""
task = train
objective = binary
metric = auc
data = {tmp_path}/train.tsv
valid = {tmp_path}/test.tsv
num_trees = 15
num_leaves = 15
learning_rate = 0.2
output_model = {tmp_path}/model.txt
verbosity = -1
""")
    (tmp_path / "predict.conf").write_text(f"""
task = predict
data = {tmp_path}/test.tsv
input_model = {tmp_path}/model.txt
output_result = {tmp_path}/preds.txt
verbosity = -1
""")
    return tmp_path


def test_cli_train_then_predict(example_dir):
    main([f"config={example_dir}/train.conf"])
    assert (example_dir / "model.txt").exists()
    model_text = (example_dir / "model.txt").read_text()
    assert model_text.startswith("tree\nversion=v3")
    main([f"config={example_dir}/predict.conf"])
    preds = np.loadtxt(example_dir / "preds.txt")
    assert len(preds) == 200
    assert np.all((preds >= 0) & (preds <= 1))
    # predictions should be informative
    test = np.loadtxt(example_dir / "test.tsv", delimiter="\t")
    y = test[:, 0]
    from lightgbm_tpu.metrics import AUCMetric
    assert AUCMetric._auc_fast(preds, y > 0, np.ones(len(y))) > 0.9


def test_cli_override_beats_config(example_dir, capsys):
    main([f"config={example_dir}/train.conf", "num_trees=3",
          f"output_model={example_dir}/model3.txt"])
    text = (example_dir / "model3.txt").read_text()
    assert text.count("Tree=") == 3


def test_cli_convert_model(example_dir):
    main([f"config={example_dir}/train.conf"])
    main([f"task=convert_model", f"input_model={example_dir}/model.txt",
          f"convert_model={example_dir}/model.cpp"])
    code = (example_dir / "model.cpp").read_text()
    assert "double PredictTree0" in code
    assert "double Predict(" in code


def test_cli_refit(example_dir):
    main([f"config={example_dir}/train.conf"])
    main([f"task=refit", f"data={example_dir}/train.tsv",
          f"input_model={example_dir}/model.txt",
          f"output_model={example_dir}/model_refit.txt"])
    assert (example_dir / "model_refit.txt").exists()
