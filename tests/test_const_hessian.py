"""Constant-hessian fast path (reference IsConstantHessian,
objective_function.h:42): for objectives whose per-row hessian is
exactly 1 x the count weight (L2/L1/quantile, unweighted), the MXU
kernels drop the hessian channel and reconstruct hessian histograms as
const x count — exact, one fewer dot channel (quantized 3 -> 2,
exact 5 -> 3)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.data import BinnedDataset, Metadata
from lightgbm_tpu.learner.grower_mxu import grow_tree_mxu
from lightgbm_tpu.learner.split import SplitHyperParams


def _reg_setup(n=800, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 +
         0.1 * rng.randn(n)).astype(np.float32)
    ds = BinnedDataset.from_raw(X, Metadata(n, label=y), max_bin=31)
    grad = -(jnp.asarray(y) - float(y.mean()))
    args = (jnp.asarray(ds.bins), grad, jnp.ones(n, jnp.float32),
            jnp.ones(n, jnp.float32),
            jnp.ones(ds.num_features, jnp.float32),
            jnp.asarray(ds.num_bins), jnp.asarray(ds.missing_types == 2),
            jnp.asarray(ds.is_categorical))
    return X, y, args, int(ds.num_bins.max())


@pytest.mark.slow
class TestConstHessian:
    def test_exact_mode_identical_trees(self):
        # hess == 1 everywhere: the reconstructed const x count channel
        # must reproduce the summed-ones channel bit-for-bit
        _, _, args, bmax = _reg_setup()
        kw = dict(num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
                  bmax=bmax, interpret=True, overshoot=2.0)
        t0, r0 = grow_tree_mxu(*args, const_hessian=0.0, **kw)
        t1, r1 = grow_tree_mxu(*args, const_hessian=1.0, **kw)
        nn = int(t0.num_nodes)
        assert int(t1.num_nodes) == nn
        for fld in ("split_feature", "threshold_bin", "left", "right"):
            np.testing.assert_array_equal(
                np.asarray(getattr(t0, fld)[:nn]),
                np.asarray(getattr(t1, fld)[:nn]), err_msg=fld)
        np.testing.assert_allclose(np.asarray(t0.leaf_value[:nn]),
                                   np.asarray(t1.leaf_value[:nn]),
                                   rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(r0), np.asarray(r1))

    def test_quantized_mode_grows_and_sums_exact(self):
        # quantized + const: hessian sums are exact counts (no rounding
        # noise), so each leaf's sum_hess equals its count exactly
        _, _, args, bmax = _reg_setup(seed=3)
        tree, row_node = grow_tree_mxu(
            *args, const_hessian=1.0, quantized_grad=True,
            rng_key=jax.random.PRNGKey(0), num_leaves=15, max_depth=-1,
            hp=SplitHyperParams(), bmax=bmax, interpret=True,
            overshoot=2.0)
        assert int(tree.num_leaves) == 15
        lf = np.asarray(tree.is_leaf)
        np.testing.assert_allclose(np.asarray(tree.sum_hess)[lf],
                                   np.asarray(tree.count)[lf], rtol=1e-6)

    def test_booster_regression_const_path_identical_models(self):
        # end-to-end: an unweighted L2 booster on the MXU path engages
        # the gate (gbdt._mxu_grow_kwargs) and trains a model identical
        # to the same MXU booster with the fast path disabled (scatter
        # comparison is out of scope here — the overgrow-and-prune
        # growth ORDER differs from the portable leafwise grower
        # independently of this feature)
        import lightgbm_tpu.boosting.gbdt as gbdt_mod
        X, y, _, _ = _reg_setup(seed=5)
        params = {"objective": "regression", "num_leaves": 15,
                  "max_bin": 31, "learning_rate": 0.2, "verbosity": -1,
                  "min_data_in_leaf": 5}

        def build(force_const_off=False):
            ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
            bst = lgb.Booster(params=dict(params), train_set=ds)
            bst.gbdt._hist_impl = "mxu"
            bst.gbdt._mxu_interpret = True
            if force_const_off:
                orig = bst.gbdt._mxu_grow_kwargs

                def no_const():
                    kw = orig()
                    kw["const_hessian"] = 0.0
                    return kw

                bst.gbdt._mxu_grow_kwargs = no_const
            return bst

        a, b = build(), build(force_const_off=True)
        assert a.gbdt._mxu_grow_kwargs()["const_hessian"] == 1.0
        assert b.gbdt._mxu_grow_kwargs()["const_hessian"] == 0.0
        for _ in range(3):
            a.update()
            b.update()
        np.testing.assert_array_equal(
            np.asarray(a.gbdt.train_score),
            np.asarray(b.gbdt.train_score))
        assert a.model_to_string() == b.model_to_string()
        # weighted data must gate the fast path off (h != const x cnt)
        dsw = lgb.Dataset(X, label=y,
                          weight=np.abs(X[:, 0]).astype(np.float32) + 0.5,
                          params={"max_bin": 31})
        bw = lgb.Booster(params=dict(params), train_set=dsw)
        assert bw.gbdt._mxu_grow_kwargs()["const_hessian"] == 0.0

    def test_nonunit_constant_hessian_value_respected(self):
        # an objective promising hess == 2 x row must reach the kernels
        # as const_hessian=2.0 (not the old hardcoded 1.0, which would
        # reconstruct hessian sums as 1 x count and silently halve every
        # leaf's H) — fast path on vs off must agree exactly
        from lightgbm_tpu.objectives import RegressionL2

        class ScaledL2(RegressionL2):
            name = "scaled_l2"
            constant_hessian_value = 2.0

            def get_gradients(self, score):
                grad = 2.0 * (score - self.trans_label)
                hess = 2.0 * jnp.ones_like(score)
                return self._weighted(grad, hess)

        X, y, _, _ = _reg_setup(n=300, f=4, seed=11)
        params = {"objective": "regression", "num_leaves": 7,
                  "max_bin": 31, "learning_rate": 0.2, "verbosity": -1,
                  "min_data_in_leaf": 5}

        def build(force_const_off=False):
            ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
            bst = lgb.Booster(params=dict(params), train_set=ds)
            gb = bst.gbdt
            swapped = ScaledL2(gb.config)
            swapped.label = gb.objective.label
            swapped.trans_label = gb.objective.trans_label
            swapped.weight = None
            swapped.num_data = gb.objective.num_data
            gb.objective = swapped
            gb._fused_run = None  # drop closure baked over the old obj
            gb._hist_impl = "mxu"
            gb._mxu_interpret = True
            if force_const_off:
                orig = gb._mxu_grow_kwargs

                def no_const():
                    kw = orig()
                    kw["const_hessian"] = 0.0
                    return kw

                gb._mxu_grow_kwargs = no_const
            return bst

        a, b = build(), build(force_const_off=True)
        assert a.gbdt._const_hessian() == 2.0
        assert a.gbdt._mxu_grow_kwargs()["const_hessian"] == 2.0
        assert b.gbdt._mxu_grow_kwargs()["const_hessian"] == 0.0
        for _ in range(2):
            a.update()
            b.update()
        np.testing.assert_array_equal(np.asarray(a.gbdt.train_score),
                                      np.asarray(b.gbdt.train_score))
        assert a.model_to_string() == b.model_to_string()

    def test_sharded_learner_keeps_const_hessian_off(self, monkeypatch):
        # the sharded learner's mxu kwargs are baked before
        # objective.init() binds weights, so the gate must stay OFF
        # there (a weighted dataset would otherwise silently train
        # wrong hessians — round-5 review finding)
        import lightgbm_tpu.parallel.learner as plearner
        captured = {}
        orig = plearner.make_sharded_grower

        def spy(*args, **kw):
            captured.update(kw.get("mxu_kwargs") or {})
            return orig(*args, **kw)

        monkeypatch.setattr(plearner, "make_sharded_grower", spy)
        X, y, _, _ = _reg_setup(seed=9)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        lgb.Booster(params={"objective": "regression", "num_leaves": 7,
                            "verbosity": -1, "tree_learner": "data",
                            "num_machines": 1, "use_quantized_grad": True},
                    train_set=ds)
        # the 8-virtual-device conftest guarantees the sharded path
        # engages; an empty capture would mean the gate under test never
        # ran — fail loudly rather than pass vacuously
        assert captured, "sharded learner did not engage"
        assert captured.get("const_hessian", 1.0) == 0.0
