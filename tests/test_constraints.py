"""Monotone/interaction constraints, extra-trees, bynode sampling tests
(reference test_engine.py monotone/interaction sections)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from conftest import make_binary, make_regression


def _is_monotone_increasing(bst, feature_idx, X, n_grid=25):
    """Check prediction is non-decreasing in the given feature."""
    base = np.median(X, axis=0)
    grid = np.linspace(X[:, feature_idx].min(), X[:, feature_idx].max(),
                      n_grid)
    rows = np.tile(base, (n_grid, 1))
    rows[:, feature_idx] = grid
    pred = bst.predict(rows, raw_score=True)
    return np.all(np.diff(pred) >= -1e-9)


class TestMonotone:
    def test_increasing_constraint_enforced(self):
        r = np.random.RandomState(0)
        n = 4000
        X = r.randn(n, 4)
        # feature 0 has non-monotone true effect; constraint must flatten it
        y = (np.sin(2 * X[:, 0]) + X[:, 1] +
             0.1 * r.randn(n)).astype(np.float32)
        mc = [1, 0, 0, 0]
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": mc, "num_leaves": 31},
                        lgb.Dataset(X, label=y), 30)
        assert _is_monotone_increasing(bst, 0, X)

    def test_decreasing_constraint_enforced(self):
        r = np.random.RandomState(1)
        n = 4000
        X = r.randn(n, 3)
        y = (np.cos(2 * X[:, 0]) - X[:, 2] +
             0.1 * r.randn(n)).astype(np.float32)
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": [-1, 0, 0],
                         "num_leaves": 31}, lgb.Dataset(X, label=y), 30)
        base = np.median(X, axis=0)
        grid = np.linspace(X[:, 0].min(), X[:, 0].max(), 25)
        rows = np.tile(base, (25, 1))
        rows[:, 0] = grid
        pred = bst.predict(rows, raw_score=True)
        assert np.all(np.diff(pred) <= 1e-9)

    def test_distributed_honors_monotone(self):
        # monotone constraints must survive tree_learner=data (they were
        # silently dropped by the sharded-grower factory at one point)
        import jax
        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device")
        r = np.random.RandomState(0)
        n = 4096
        X = r.randn(n, 4)
        y = (np.sin(2 * X[:, 0]) + X[:, 1] +
             0.1 * r.randn(n)).astype(np.float32)
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": [1, 0, 0, 0],
                         "tree_learner": "data", "num_leaves": 31},
                        lgb.Dataset(X, label=y), 20)
        assert _is_monotone_increasing(bst, 0, X)

    def test_unconstrained_differs(self):
        r = np.random.RandomState(0)
        n = 4000
        X = r.randn(n, 4)
        y = (np.sin(2 * X[:, 0]) + X[:, 1] +
             0.1 * r.randn(n)).astype(np.float32)
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "num_leaves": 31}, lgb.Dataset(X, label=y), 30)
        # sanity: without constraint the sine effect is non-monotone
        assert not _is_monotone_increasing(bst, 0, X)

    def test_monotone_penalty_runs(self):
        X, y = make_regression()
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": [1] + [0] * (X.shape[1] - 1),
                         "monotone_penalty": 2.0},
                        lgb.Dataset(X, label=y), 10)
        assert bst.num_trees() == 10


class TestInteractionConstraints:
    def test_groups_respected(self):
        X, y = make_binary(n=3000)
        groups = [[0, 1], [2, 3, 4]]
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "interaction_constraints": groups,
                         "num_leaves": 15}, lgb.Dataset(X, label=y), 15)
        model = bst._host_model()
        allowed = [set(g) for g in groups]
        for t in model.trees:
            # collect per-path feature sets via recursion
            def paths(node, used):
                if node < 0:
                    if used:
                        ok = any(used <= a for a in allowed) or len(used) == 1
                        assert ok, f"path features {used} violate constraints"
                    return
                fset = used | {int(t.split_feature[node])}
                paths(int(t.left_child[node]), fset)
                paths(int(t.right_child[node]), fset)
            if t.num_leaves > 1:
                paths(0, set())

    def test_accuracy_retained(self):
        X, y = make_binary(n=3000)
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "interaction_constraints": [[0, 1, 2],
                                                     [3, 4, 5, 6, 7, 8, 9]]},
                        lgb.Dataset(X, label=y), 20)
        from lightgbm_tpu.metrics import AUCMetric
        auc = AUCMetric._auc_fast(bst.predict(X), y > 0, np.ones(len(y)))
        assert auc > 0.9


class TestExtraTrees:
    def test_extra_trees_trains(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "extra_trees": True}, lgb.Dataset(X, label=y), 20)
        from lightgbm_tpu.metrics import AUCMetric
        auc = AUCMetric._auc_fast(bst.predict(X), y > 0, np.ones(len(y)))
        assert auc > 0.85  # random thresholds still learn

    def test_differs_from_exact(self):
        X, y = make_binary()
        b1 = lgb.train({"objective": "binary", "verbosity": -1},
                       lgb.Dataset(X, label=y), 5)
        b2 = lgb.train({"objective": "binary", "verbosity": -1,
                        "extra_trees": True}, lgb.Dataset(X, label=y), 5)
        assert not np.allclose(b1.predict(X), b2.predict(X))


class TestFeatureFractionByNode:
    def test_runs_and_learns(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "feature_fraction_bynode": 0.5},
                        lgb.Dataset(X, label=y), 20)
        from lightgbm_tpu.metrics import AUCMetric
        auc = AUCMetric._auc_fast(bst.predict(X), y > 0, np.ones(len(y)))
        assert auc > 0.9


class TestMonotoneMethods:
    """intermediate/advanced constraint methods: whole-tree bound
    recompute + all-leaves rescan (reference monotone_constraints.hpp
    IntermediateLeafConstraints :514 / AdvancedLeafConstraints :856)."""

    @staticmethod
    def _data(seed=0, n=3000):
        r = np.random.RandomState(seed)
        X = r.randn(n, 4)
        y = (np.sin(2 * X[:, 0]) + 0.8 * X[:, 1] - 0.5 * X[:, 2] +
             0.1 * r.randn(n)).astype(np.float32)
        return X, y

    @pytest.mark.parametrize("method", ["basic", "intermediate",
                                        "advanced"])
    def test_constraint_enforced(self, method):
        X, y = self._data()
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": [1, 0, -1, 0],
                         "monotone_constraints_method": method,
                         "num_leaves": 15}, lgb.Dataset(X, label=y), 15)
        assert _is_monotone_increasing(bst, 0, X)
        base = np.median(X, axis=0)
        grid = np.linspace(X[:, 2].min(), X[:, 2].max(), 25)
        rows = np.tile(base, (25, 1))
        rows[:, 2] = grid
        pred = bst.predict(rows, raw_score=True)
        assert np.all(np.diff(pred) <= 1e-9)

    def test_methods_quality_ordering(self):
        # looser constraints should fit at least as well (the reason the
        # reference grew 1184 LoC of them); allow small slack for
        # greedy-order noise
        X, y = self._data(seed=3, n=4000)
        losses = {}
        for method in ("basic", "intermediate", "advanced"):
            bst = lgb.train({"objective": "regression", "verbosity": -1,
                             "monotone_constraints": [1, 0, 0, 0],
                             "monotone_constraints_method": method,
                             "num_leaves": 31},
                            lgb.Dataset(X, label=y), 25)
            pr = bst.predict(X)
            losses[method] = float(np.mean((pr - y) ** 2))
        assert losses["intermediate"] <= losses["basic"] * 1.02
        assert losses["advanced"] <= losses["basic"] * 1.02

    def test_methods_differ_from_basic(self):
        X, y = self._data(seed=4)
        preds = {}
        for method in ("basic", "intermediate", "advanced"):
            bst = lgb.train({"objective": "regression", "verbosity": -1,
                             "monotone_constraints": [1, 0, 0, 0],
                             "monotone_constraints_method": method,
                             "num_leaves": 31},
                            lgb.Dataset(X, label=y), 10)
            preds[method] = bst.predict(X)
        assert not np.allclose(preds["basic"], preds["intermediate"])
        assert not np.allclose(preds["basic"], preds["advanced"])

    def test_bynode_downgrades_with_warning(self):
        # reference config.cpp:386-390
        X, y = self._data(seed=5)
        cfg = lgb.Config({"objective": "regression",
                          "monotone_constraints": [1, 0, 0, 0],
                          "monotone_constraints_method": "advanced",
                          "feature_fraction_bynode": 0.5})
        assert cfg.monotone_constraints_method == "basic"

    def test_distributed_intermediate(self):
        # improvement over the reference (config.cpp:381-384 downgrades
        # distributed): psum'd histogram caches support the rescan
        import jax
        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device")
        X, y = self._data(seed=6, n=4096)
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": [1, 0, 0, 0],
                         "monotone_constraints_method": "intermediate",
                         "tree_learner": "data", "num_leaves": 15},
                        lgb.Dataset(X, label=y), 10)
        assert _is_monotone_increasing(bst, 0, X)
