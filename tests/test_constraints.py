"""Monotone/interaction constraints, extra-trees, bynode sampling tests
(reference test_engine.py monotone/interaction sections)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from conftest import make_binary, make_regression


def _is_monotone_increasing(bst, feature_idx, X, n_grid=25):
    """Check prediction is non-decreasing in the given feature."""
    base = np.median(X, axis=0)
    grid = np.linspace(X[:, feature_idx].min(), X[:, feature_idx].max(),
                      n_grid)
    rows = np.tile(base, (n_grid, 1))
    rows[:, feature_idx] = grid
    pred = bst.predict(rows, raw_score=True)
    return np.all(np.diff(pred) >= -1e-9)


class TestMonotone:
    def test_increasing_constraint_enforced(self):
        r = np.random.RandomState(0)
        n = 4000
        X = r.randn(n, 4)
        # feature 0 has non-monotone true effect; constraint must flatten it
        y = (np.sin(2 * X[:, 0]) + X[:, 1] +
             0.1 * r.randn(n)).astype(np.float32)
        mc = [1, 0, 0, 0]
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": mc, "num_leaves": 31},
                        lgb.Dataset(X, label=y), 30)
        assert _is_monotone_increasing(bst, 0, X)

    def test_decreasing_constraint_enforced(self):
        r = np.random.RandomState(1)
        n = 4000
        X = r.randn(n, 3)
        y = (np.cos(2 * X[:, 0]) - X[:, 2] +
             0.1 * r.randn(n)).astype(np.float32)
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": [-1, 0, 0],
                         "num_leaves": 31}, lgb.Dataset(X, label=y), 30)
        base = np.median(X, axis=0)
        grid = np.linspace(X[:, 0].min(), X[:, 0].max(), 25)
        rows = np.tile(base, (25, 1))
        rows[:, 0] = grid
        pred = bst.predict(rows, raw_score=True)
        assert np.all(np.diff(pred) <= 1e-9)

    def test_distributed_honors_monotone(self):
        # monotone constraints must survive tree_learner=data (they were
        # silently dropped by the sharded-grower factory at one point)
        import jax
        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device")
        r = np.random.RandomState(0)
        n = 4096
        X = r.randn(n, 4)
        y = (np.sin(2 * X[:, 0]) + X[:, 1] +
             0.1 * r.randn(n)).astype(np.float32)
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": [1, 0, 0, 0],
                         "tree_learner": "data", "num_leaves": 31},
                        lgb.Dataset(X, label=y), 20)
        assert _is_monotone_increasing(bst, 0, X)

    def test_unconstrained_differs(self):
        r = np.random.RandomState(0)
        n = 4000
        X = r.randn(n, 4)
        y = (np.sin(2 * X[:, 0]) + X[:, 1] +
             0.1 * r.randn(n)).astype(np.float32)
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "num_leaves": 31}, lgb.Dataset(X, label=y), 30)
        # sanity: without constraint the sine effect is non-monotone
        assert not _is_monotone_increasing(bst, 0, X)

    def test_monotone_penalty_runs(self):
        X, y = make_regression()
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "monotone_constraints": [1] + [0] * (X.shape[1] - 1),
                         "monotone_penalty": 2.0},
                        lgb.Dataset(X, label=y), 10)
        assert bst.num_trees() == 10


class TestInteractionConstraints:
    def test_groups_respected(self):
        X, y = make_binary(n=3000)
        groups = [[0, 1], [2, 3, 4]]
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "interaction_constraints": groups,
                         "num_leaves": 15}, lgb.Dataset(X, label=y), 15)
        model = bst._host_model()
        allowed = [set(g) for g in groups]
        for t in model.trees:
            # collect per-path feature sets via recursion
            def paths(node, used):
                if node < 0:
                    if used:
                        ok = any(used <= a for a in allowed) or len(used) == 1
                        assert ok, f"path features {used} violate constraints"
                    return
                fset = used | {int(t.split_feature[node])}
                paths(int(t.left_child[node]), fset)
                paths(int(t.right_child[node]), fset)
            if t.num_leaves > 1:
                paths(0, set())

    def test_accuracy_retained(self):
        X, y = make_binary(n=3000)
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "interaction_constraints": [[0, 1, 2],
                                                     [3, 4, 5, 6, 7, 8, 9]]},
                        lgb.Dataset(X, label=y), 20)
        from lightgbm_tpu.metrics import AUCMetric
        auc = AUCMetric._auc_fast(bst.predict(X), y > 0, np.ones(len(y)))
        assert auc > 0.9


class TestExtraTrees:
    def test_extra_trees_trains(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "extra_trees": True}, lgb.Dataset(X, label=y), 20)
        from lightgbm_tpu.metrics import AUCMetric
        auc = AUCMetric._auc_fast(bst.predict(X), y > 0, np.ones(len(y)))
        assert auc > 0.85  # random thresholds still learn

    def test_differs_from_exact(self):
        X, y = make_binary()
        b1 = lgb.train({"objective": "binary", "verbosity": -1},
                       lgb.Dataset(X, label=y), 5)
        b2 = lgb.train({"objective": "binary", "verbosity": -1,
                        "extra_trees": True}, lgb.Dataset(X, label=y), 5)
        assert not np.allclose(b1.predict(X), b2.predict(X))


class TestFeatureFractionByNode:
    def test_runs_and_learns(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "feature_fraction_bynode": 0.5},
                        lgb.Dataset(X, label=y), 20)
        from lightgbm_tpu.metrics import AUCMetric
        auc = AUCMetric._auc_fast(bst.predict(X), y > 0, np.ones(len(y)))
        assert auc > 0.9
