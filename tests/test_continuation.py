"""Continued training (init_model) and model snapshots
(reference gbdt.cpp:279-283 snapshots, application.cpp:91-94 input_model,
engine.py init_model)."""

import glob
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(seed=0, n=3000):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 6).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


PARAMS = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
          "learning_rate": 0.2}


class TestContinuation:
    def test_matches_straight_training(self):
        X, y = _data()
        b10 = lgb.train(PARAMS, lgb.Dataset(X, label=y,
                                            free_raw_data=False), 10)
        b_cont = lgb.train(PARAMS, lgb.Dataset(X, label=y,
                                               free_raw_data=False), 10,
                           init_model=b10)
        b20 = lgb.train(PARAMS, lgb.Dataset(X, label=y,
                                            free_raw_data=False), 20)
        m_cont = np.mean((b_cont.predict(X) - y) ** 2)
        m_20 = np.mean((b20.predict(X) - y) ** 2)
        assert b_cont.num_trees() == 20
        # identical growth policy + seeding => near-identical quality
        assert m_cont == pytest.approx(m_20, rel=0.2)

    def test_merged_model_round_trip(self, tmp_path):
        X, y = _data(1)
        b1 = lgb.train(PARAMS, lgb.Dataset(X, label=y,
                                           free_raw_data=False), 5)
        fn = str(tmp_path / "base.txt")
        b1.save_model(fn)
        b2 = lgb.train(PARAMS, lgb.Dataset(X, label=y,
                                           free_raw_data=False), 5,
                       init_model=fn)  # from file, like the CLI
        b3 = lgb.Booster(model_str=b2.model_to_string())
        np.testing.assert_allclose(b2.predict(X), b3.predict(X), rtol=1e-6)
        assert b3.num_trees() == 10

    def test_iterative_continuation_same_dataset(self):
        # b1 -> b2 -> b3 chained on ONE Dataset object; then a plain
        # train() on it must not inherit the stale seeded scores
        X, y = _data(4)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        b1 = lgb.train(PARAMS, ds, 5)
        b2 = lgb.train(PARAMS, ds, 5, init_model=b1)
        b3 = lgb.train(PARAMS, ds, 5, init_model=b2)
        assert b3.num_trees() == 15
        m1 = np.mean((b1.predict(X) - y) ** 2)
        m3 = np.mean((b3.predict(X) - y) ** 2)
        assert m3 < m1
        b_plain = lgb.train(PARAMS, ds, 5)
        np.testing.assert_allclose(b_plain.predict(X), b1.predict(X),
                                   rtol=1e-5)

    def test_user_init_score_conflict_raises(self):
        X, y = _data(5)
        b1 = lgb.train(PARAMS, lgb.Dataset(X, label=y,
                                           free_raw_data=False), 5)
        ds = lgb.Dataset(X, label=y, free_raw_data=False,
                         init_score=np.zeros(len(y)))
        with pytest.raises(ValueError):
            lgb.train(PARAMS, ds, 5, init_model=b1)

    def test_cli_input_model(self, tmp_path):
        from lightgbm_tpu.cli import main
        X, y = _data(2)
        np.savetxt(tmp_path / "train.csv",
                   np.column_stack([y, X]), delimiter=",", fmt="%.6f")
        main([f"task=train", f"data={tmp_path}/train.csv", "label_column=0",
              "objective=regression", "num_leaves=15", "num_iterations=6",
              f"output_model={tmp_path}/m.txt", "verbosity=-1"])
        main([f"task=train", f"data={tmp_path}/train.csv", "label_column=0",
              "objective=regression", "num_leaves=15", "num_iterations=4",
              f"input_model={tmp_path}/m.txt",
              f"output_model={tmp_path}/m2.txt", "verbosity=-1"])
        bst = lgb.Booster(model_file=str(tmp_path / "m2.txt"))
        assert bst.num_trees() == 10


class TestSnapshots:
    def test_cli_snapshot_freq(self, tmp_path):
        from lightgbm_tpu.cli import main
        X, y = _data(3)
        np.savetxt(tmp_path / "train.csv",
                   np.column_stack([y, X]), delimiter=",", fmt="%.6f")
        main([f"task=train", f"data={tmp_path}/train.csv", "label_column=0",
              "objective=regression", "num_leaves=15", "num_iterations=10",
              "snapshot_freq=4", f"output_model={tmp_path}/m.txt",
              "verbosity=-1"])
        snaps = sorted(glob.glob(str(tmp_path / "m.txt.snapshot_iter_*")))
        assert [os.path.basename(s) for s in snaps] == \
            ["m.txt.snapshot_iter_4", "m.txt.snapshot_iter_8"]
        # snapshots are loadable, truncated models
        b = lgb.Booster(model_file=snaps[0])
        assert b.num_trees() == 4
