"""Continuous train -> refresh -> serve loop: fast tier (docs/Continuous.md).

Unit + small-integration coverage of the loop's parts: `WindowSource`
semantics (windowing, exhaustion, clean partial windows, restart
within a window), the crash-loop `BackoffPolicy`, pin-by-generation
checkpoint retention, the `lightgbm_tpu_freshness` metric family,
torn-publish detection, poison-window quarantine bookkeeping,
mid-publish kills (`serving_hot_swap` / `serving_hot_swap_commit` /
`loop_publish`) with the survivor's answers pinned to a real
generation, streamed init_model seeding, and the task=loop CLI. The
full kill-matrix with live traffic is tests/test_loop_chaos.py
(`make loop-chaos`).
"""

import json
import os
import shutil

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.observability import registry as _obs
from lightgbm_tpu.reliability import (InjectedFault, counters, faults,
                                      pin_bundle, pinned_bundle)
from lightgbm_tpu.reliability.backoff import BackoffPolicy
from lightgbm_tpu.reliability.checkpoint import (latest_checkpoint,
                                                 save_checkpoint)
from lightgbm_tpu.streaming import ArraySource, CSVSource, WindowSource
from lightgbm_tpu.testing.chaos_loop import (collect_generation_models,
                                             dyadic_model_transform,
                                             loop_params, make_loop,
                                             write_stream_csv)

pytestmark = pytest.mark.loop


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _array_source(chunks=5, chunk_rows=8, f=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(chunks * chunk_rows, f)
    y = rng.randn(chunks * chunk_rows).astype(np.float32)
    return ArraySource(X, chunk_rows=chunk_rows, label=y), X, y


# ----------------------------------------------------------------------
# WindowSource
def test_window_source_slices_array_zero_copy():
    src, X, y = _array_source()
    w = WindowSource(src, start_chunk=1, window_chunks=2)
    assert w.num_rows == 16
    assert w.array.base is not None          # a view, not a copy
    np.testing.assert_array_equal(w.array, X[8:24])
    got = list(w.chunks())
    assert len(got) == 2
    np.testing.assert_array_equal(np.vstack([c for c, _ in got]), X[8:24])
    np.testing.assert_array_equal(np.concatenate([l for _, l in got]),
                                  y[8:24])


def test_window_source_partial_window_at_stream_end():
    """A base that ends mid-window yields a clean partial pass — fewer
    chunks, correct rows, never a torn one."""
    src, X, _ = _array_source(chunks=5)
    w = WindowSource(src, start_chunk=4, window_chunks=3)
    assert w.num_rows == 8                   # only one chunk left
    got = list(w.chunks())
    assert len(got) == 1
    np.testing.assert_array_equal(got[0][0], X[32:40])


def test_window_source_past_end_is_empty():
    src, _, _ = _array_source(chunks=5)
    w = WindowSource(src, start_chunk=5, window_chunks=2)
    assert w.num_rows == 0
    assert list(w.chunks()) == []


def test_window_source_restartable_within_window():
    """chunks(start_chunk=k) re-opens the base at window offset k —
    what mid-stream checkpoint resume replays from."""
    src, X, _ = _array_source(chunks=6)
    w = WindowSource(src, start_chunk=2, window_chunks=3)
    resumed = list(w.chunks(start_chunk=1))
    assert len(resumed) == 2
    np.testing.assert_array_equal(resumed[0][0], X[24:32])
    np.testing.assert_array_equal(resumed[1][0], X[32:40])
    assert list(w.chunks(start_chunk=3)) == []


def test_window_source_over_unsized_csv(tmp_path):
    """Text sources don't know their size up front: the window's
    num_rows starts None and a full pass fills it in; a window past
    the end of the file yields nothing."""
    path = str(tmp_path / "s.csv")
    write_stream_csv(path, chunks=3, chunk_rows=10, f=4)
    base = CSVSource(path, chunk_rows=10, label_col=0)
    w = WindowSource(base, start_chunk=2, window_chunks=2)
    assert w.num_rows is None
    got = list(w.chunks())
    assert len(got) == 1 and got[0][0].shape == (10, 4)
    assert w.num_rows == 10
    past = WindowSource(CSVSource(path, chunk_rows=10, label_col=0),
                        start_chunk=3, window_chunks=1)
    assert list(past.chunks()) == []
    assert "window[2:4]" in w.describe()


def test_window_source_validates_bounds():
    src, _, _ = _array_source()
    with pytest.raises(ValueError):
        WindowSource(src, start_chunk=-1)
    with pytest.raises(ValueError):
        WindowSource(src, window_chunks=0)


# ----------------------------------------------------------------------
# BackoffPolicy
def test_backoff_policy_capped_exponential():
    p = BackoffPolicy(base_ms=50.0, max_ms=400.0, sleep=lambda s: None)
    assert [p.delay_ms(a) for a in range(5)] == [50, 100, 200, 400, 400]
    slept = []
    p2 = BackoffPolicy(base_ms=10.0, max_ms=100.0, sleep=slept.append)
    assert p2.wait(2) == 40.0
    assert slept == [0.04]
    assert BackoffPolicy(base_ms=0.0).delay_ms(7) == 0.0


# ----------------------------------------------------------------------
# pin-by-generation checkpoint retention
def test_prune_never_deletes_pinned_live_generation(tmp_path):
    d = str(tmp_path / "ck")
    paths = {}
    for it in range(1, 4):
        paths[it] = save_checkpoint(d, it, f"model-{it}", {}, {},
                                    keep_last=2)
    # bundle 1 aged out of keep_last=2 normally
    assert not os.path.isdir(paths[1])
    pin_bundle(d, paths[2])
    assert pinned_bundle(d) == 2
    for it in range(4, 7):
        save_checkpoint(d, it, f"model-{it}", {}, {}, keep_last=2)
    # 2 is far past the quota but pinned: still there, readable
    assert os.path.isdir(paths[2])
    with open(os.path.join(paths[2], "model.txt")) as fh:
        assert fh.read() == "model-2"
    # unpin -> the next save's prune removes it
    pin_bundle(d, None)
    assert pinned_bundle(d) is None
    save_checkpoint(d, 7, "model-7", {}, {}, keep_last=2)
    assert not os.path.isdir(paths[2])


def test_pinned_bundle_enoent_discipline(tmp_path):
    d = str(tmp_path / "ck")
    assert pinned_bundle(d) is None          # dir doesn't even exist
    os.makedirs(d)
    assert pinned_bundle(d) is None          # no pin file
    with open(os.path.join(d, "PINNED"), "w") as fh:
        fh.write("not-a-bundle-name\n")
    assert pinned_bundle(d) is None          # garbled pin reads unpinned
    pin_bundle(d, "ckpt_0000005")
    assert pinned_bundle(d) == 5
    pin_bundle(d, None)
    pin_bundle(d, None)                      # double-unpin: ENOENT ok


# ----------------------------------------------------------------------
# freshness metric family
def test_freshness_family_snapshot_and_prometheus():
    _obs.reset()
    _obs.record_freshness_publish(3, 1.25, slo_s=10.0)
    f = _obs.freshness_snapshot()
    assert f["generation"] == 3 and f["publishes"] == 1
    assert f["data_to_serve_s"] == 1.25 and f["slo_alarm"] == 0
    _obs.record_freshness_publish(4, 20.0, slo_s=10.0)
    f = _obs.freshness_snapshot()
    assert f["slo_alarm"] == 1 and f["slo_breaches"] == 1
    assert f["max_data_to_serve_s"] == 20.0
    _obs.record_freshness_publish(5, 0.5, slo_s=10.0)
    assert _obs.freshness_snapshot()["slo_alarm"] == 0   # alarm clears
    _obs.record_freshness_torn_publish(6)
    _obs.record_freshness_quarantine(2)
    f = _obs.freshness_snapshot()
    assert f["torn_publishes"] == 1 and f["quarantined_windows"] == 1
    txt = _obs.prometheus_text()
    assert "lightgbm_tpu_freshness_generation 5" in txt
    assert "lightgbm_tpu_freshness_quarantined_windows 1" in txt
    assert "freshness" in _obs.snapshot()
    _obs.reset()
    assert _obs.freshness_snapshot()["publishes"] == 0


# ----------------------------------------------------------------------
# loop state machine
@pytest.fixture
def loop_env(tmp_path):
    data = str(tmp_path / "stream.csv")
    X = write_stream_csv(data, chunks=6, chunk_rows=32, f=5)
    return data, str(tmp_path / "loop"), X


def test_loop_refresh_and_exhaustion(loop_env):
    """Happy path: windows refresh the live model (trees accumulate),
    the stream's end stops the loop cleanly, and a rerun over the
    exhausted stream publishes nothing but restores the live model."""
    data, loop_dir, _X = loop_env
    trainer, server, _cfg = make_loop(data, loop_params(loop_dir),
                                      chunk_rows=32)
    with server:
        assert trainer.run() == 3            # 6 chunks / window of 2
        assert trainer.generation == 3 and trainer.next_chunk == 6
    first_model = trainer._live_model_str
    assert first_model.count("Tree=") == 9   # 3 gens x loop_rounds=3
    # restart over the exhausted stream: marker-driven recovery, no
    # new generations, live model intact
    t2, s2, _ = make_loop(data, loop_params(loop_dir), chunk_rows=32)
    with s2:
        assert t2.run() == 0
        assert t2.generation == 3
        assert t2._live_model_str == first_model
        assert "live" in s2.registry


def test_loop_source_ending_mid_window_publishes_partial(loop_env):
    """5-chunk stream with 2-chunk windows: the last window has one
    chunk — a clean partial refresh, then clean exhaustion."""
    data, loop_dir, _X = loop_env
    short = str(os.path.dirname(data) + "/short.csv")
    write_stream_csv(short, chunks=5, chunk_rows=32, f=5)
    trainer, server, _cfg = make_loop(short, loop_params(loop_dir),
                                      chunk_rows=32)
    with server:
        assert trainer.run() == 3
    assert trainer.next_chunk == 6           # cursor advances by window


def test_recovery_discards_torn_generation_bundle(loop_env):
    """A COMPLETE gens bundle newer than the marker is a torn publish:
    recovery removes it and counts it in the freshness family."""
    data, loop_dir, _X = loop_env
    _obs.reset()
    trainer, server, _cfg = make_loop(data, loop_params(loop_dir),
                                      chunk_rows=32)
    with server:
        trainer.run(max_windows=1)
        gens = os.path.join(loop_dir, "gens")
        torn = save_checkpoint(gens, 7, "half-built", {}, {})
        trainer._recover()
        assert not os.path.isdir(torn)
        assert _obs.freshness_snapshot()["torn_publishes"] == 1
        assert collect_generation_models(loop_dir) \
            and 7 not in collect_generation_models(loop_dir)
        # the committed generation stays pinned and serving
        assert pinned_bundle(gens) == 1
        assert trainer.generation == 1


@pytest.mark.parametrize("site", ["serving_hot_swap",
                                  "serving_hot_swap_commit",
                                  "loop_publish"])
def test_mid_publish_kill_survivor_serves_a_real_generation(loop_env,
                                                           site):
    """Kill inside the publish sequence; the survivor must answer from
    a real generation — the OLD one when the kill landed before the
    atomic registry swap, the NEW one after it — and the retried cycle
    must converge on the same bytes either way."""
    from lightgbm_tpu.basic import Booster
    data, loop_dir, X = loop_env
    trainer, server, _cfg = make_loop(data, loop_params(loop_dir),
                                      chunk_rows=32)
    with server:
        trainer.run(max_windows=1)
        gen1 = trainer._live_model_str
        ref1 = Booster(model_str=gen1).predict(X[:24], raw_score=True)
        faults.schedule(site, fail=1)
        with pytest.raises(InjectedFault):
            trainer._recover()
            trainer._run_cycle_once()
        # survivor still answers, bit-identical to gen 1 or gen 2
        got = np.asarray(server.predict("live", X[:24], raw_score=True))
        if site == "serving_hot_swap":
            # kill BEFORE the atomic swap: old generation serving
            np.testing.assert_array_equal(got, ref1)
        marker = json.load(open(os.path.join(loop_dir, "GENERATION")))
        assert marker["generation"] == 1     # commit never advanced
        # recovery + redo: generation 2 lands, identical either way
        trainer._recover()
        trainer._run_cycle_once()
        gen2 = trainer._live_model_str
        ref2 = Booster(model_str=gen2).predict(X[:24], raw_score=True)
        assert np.array_equal(got, ref1) or np.array_equal(got, ref2)
        now = np.asarray(server.predict("live", X[:24], raw_score=True))
        np.testing.assert_array_equal(now, ref2)
        marker = json.load(open(os.path.join(loop_dir, "GENERATION")))
        assert marker["generation"] == 2
    assert faults.trips(site) >= 1


def test_poison_window_is_quarantined_and_loop_continues(loop_env):
    """A window whose every rebuild attempt dies is skipped, logged,
    counted — same generation, cursor advanced — and later windows
    still publish."""
    data, loop_dir, _X = loop_env
    _obs.reset()
    q0 = counters.get("loop_quarantined_windows")
    trainer, server, _cfg = make_loop(data, loop_params(loop_dir),
                                      chunk_rows=32)
    with server:
        trainer.run(max_windows=1)
        # poison the second window: every construct dies, 3 attempts
        faults.schedule("streaming_ingest", fail=3)
        assert trainer.run() == 1            # window 3 still publishes
    assert trainer.quarantined == [2]
    assert counters.get("loop_quarantined_windows") == q0 + 1
    assert _obs.freshness_snapshot()["quarantined_windows"] == 1
    marker = json.load(open(os.path.join(loop_dir, "GENERATION")))
    assert marker["quarantined"] == [2]
    assert marker["generation"] == 2 and marker["next_chunk"] == 6


def test_dyadic_transform_is_idempotent():
    line = "leaf_value=0.123456789 -1.987654321 7.3\n"
    once = dyadic_model_transform(line)
    assert dyadic_model_transform(once) == once
    vals = [float(v) for v in once.split("=")[1].split()]
    assert all(abs(v * 1024 - round(v * 1024)) == 0 for v in vals)


# ----------------------------------------------------------------------
# streamed init_model seeding (engine-level satellite)
def test_init_model_continuation_over_streamed_dataset():
    """Continued boosting with a ChunkSource dataset seeds init scores
    chunk by chunk and matches the in-memory continuation exactly."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.basic import Dataset
    from lightgbm_tpu.engine import train
    rng = np.random.RandomState(4)
    X1, y1 = rng.randn(120, 5), rng.randn(120).astype(np.float32)
    X2, y2 = rng.randn(96, 5), rng.randn(96).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 7,
              "min_data_in_leaf": 5, "verbosity": -1,
              "deterministic": True, "seed": 3}
    base = train(dict(params), Dataset(X1, label=y1), num_boost_round=3)
    streamed = Dataset(ArraySource(X2, chunk_rows=32, label=y2),
                       params=dict(params), free_raw_data=False)
    cont_s = train(dict(params), streamed, num_boost_round=2,
                   init_model=base)
    cont_m = train(dict(params),
                   Dataset(X2, label=y2, params=dict(params)),
                   num_boost_round=2, init_model=base)
    assert cont_s.model_to_string() == cont_m.model_to_string()


def test_init_model_over_exhausted_stream_raises():
    from lightgbm_tpu.basic import Dataset
    from lightgbm_tpu.engine import train
    rng = np.random.RandomState(4)
    X1, y1 = rng.randn(80, 4), rng.randn(80).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 7,
              "min_data_in_leaf": 5, "verbosity": -1}
    base = train(dict(params), Dataset(X1, label=y1), num_boost_round=2)
    src, _, _ = _array_source(chunks=4, chunk_rows=8, f=4)
    empty = WindowSource(src, start_chunk=4, window_chunks=1)
    with pytest.raises(ValueError, match="exhausted stream"):
        train(dict(params),
              Dataset(empty, params=dict(params), free_raw_data=False),
              num_boost_round=1, init_model=base)


# ----------------------------------------------------------------------
# config + CLI
def test_config_registers_loop_task_and_params():
    cfg = Config({"task": "loop", "loop_dir": "/tmp/x",
                  "loop_state_dir": "/tmp/x",      # alias
                  "loop_rounds": 5, "loop_window_chunks": 2,
                  "loop_keep": 4, "loop_poison_retries": 2,
                  "loop_backoff_ms": 10.0, "loop_backoff_max_ms": 80.0,
                  "loop_freshness_slo_s": 30.0,
                  "loop_model_name": "prod"})
    assert cfg.task == "loop" and cfg.loop_rounds == 5
    assert cfg.loop_freshness_slo_s == 30.0
    assert cfg.loop_model_name == "prod"
    with pytest.raises(Exception):
        Config({"loop_rounds": 0})
    with pytest.raises(Exception):
        Config({"loop_poison_retries": 0})


def test_cli_task_loop_end_to_end_and_restart(tmp_path):
    """task=loop over a CSV stream: generations publish, the model and
    serve metrics land on disk, and a rerun of the same conf resumes
    from the GENERATION marker without retraining anything."""
    from lightgbm_tpu.cli import Application
    data = str(tmp_path / "stream.csv")
    write_stream_csv(data, chunks=4, chunk_rows=32, f=5)
    loop_dir = str(tmp_path / "loop")
    out_model = str(tmp_path / "live.txt")
    argv = [f"data={data}", "task=loop", f"loop_dir={loop_dir}",
            "loop_rounds=2", "loop_window_chunks=2",
            "stream_chunk_rows=32", f"output_model={out_model}",
            "objective=regression", "num_leaves=7",
            "min_data_in_leaf=5", "verbosity=-1",
            "deterministic=true", "seed=3", "boost_from_average=false"]
    _obs.reset()                 # the freshness family is process-global
    Application(argv).run()
    assert os.path.isfile(out_model)
    with open(out_model) as fh:
        first = fh.read()
    assert first.count("Tree=") == 4         # 2 windows x 2 rounds
    metrics = json.load(open(out_model + ".metrics.json"))
    assert metrics["freshness"]["generation"] == 2
    assert metrics["freshness"]["publishes"] == 2
    marker = json.load(open(os.path.join(loop_dir, "GENERATION")))
    assert marker["generation"] == 2 and marker["next_chunk"] == 4
    saves0 = counters.get("checkpoint_saves")
    _obs.reset()
    Application(argv).run()                  # restart: stream exhausted
    assert counters.get("checkpoint_saves") == saves0   # nothing redone
    with open(out_model) as fh:
        assert fh.read() == first
    # the zero-publish restart still reports the generation it serves
    metrics = json.load(open(out_model + ".metrics.json"))
    assert metrics["freshness"]["generation"] == 2
    assert metrics["freshness"]["publishes"] == 0
